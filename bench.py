"""Benchmark: libsvm parse-to-HBM GB/s/chip — the headline driver metric.

Measures the full single-chip pipeline on this host's accelerator:
criteo-shaped libsvm (one shard — per-chip throughput is the metric;
the multi-part/multi-host shard shape is bench_suite config 4, which
runs all parts with concurrent pipelines) → native C++ parse → zero-copy
CSR views → async jax.device_put into device memory, transfers riding
under parse via detached leases.

The measured config is BUILT from the declarative pipeline graph
(dmlc_tpu.pipeline): ``from_uri(...).parse(...).batch(pad=True)
.to_device(...)`` compiles to the parser + ABI-5 native batch assembly
(bucket-padded device-layout batches emitted straight from the parse
arena — ``assembly_path`` says which rung served) + windowed async
transfers through the reusable host staging pair, with a telemetry
probe at each stage boundary and the in-flight device window owned by
the between-epoch autotuner instead of a hard-coded constant.
``DMLC_TPU_BENCH_ASSEMBLY=none`` restores the pre-r7 raw-block config
for before/after attribution. A short hand-wired
reference run (DMLC_TPU_BENCH_HANDWIRED_EPOCHS, default 3) reports
"handwired_gbps" alongside so pipeline overhead stays visible.

CLI: ``python bench.py [--trace out.json]`` — with --trace the
measurement epochs run under the dmlc_tpu.obs trace recorder and a
Chrome/Perfetto trace-event JSON (per-stage pull spans, queue waits,
transfer drains, native-engine counter tracks) lands at the given path.

Prints exactly ONE JSON line: {"metric", "value", "unit",
"vs_baseline", "best_epoch", "epochs", "bound", "assembly_path",
"assemble_wait_s", "parse_cpu_gbps_core",
"sustained_gauge_ok", "gauge_ok_epochs", "gauge_ok_threshold",
"epoch_gauges", "gauge_bands", "run_band", "replay_gbps", "replay",
"replay_tier", "handwired_gbps", "pipeline", "metrics", "analysis",
"control", "trace"} —
"value" is the SUSTAINED rate (20%-trimmed mean of per-epoch GB/s over
>= 5 epochs / >= the time budget), "best_epoch" the fastest single
epoch, "parse_cpu_gbps_core" the thread-CPU parse rate (immune to this
burstable VM's credit scheduler), "sustained_gauge_ok" the same
trimmed mean restricted to epochs whose pre-epoch host-memcpy gauge
cleared "gauge_ok_threshold" (credit-healthy epochs only — the
cross-run-comparable number; per-epoch gauges ride in "epoch_gauges"),
"gauge_bands" the same statistic split per comparability class
(BASELINE.md's credit-recovery bands: drained < 1.0, plateau 1.0-1.6,
elevated 1.6-3.0, full >= 3.0 GB/s memcpy) with "run_band" the run's
modal band — numbers from runs on different credit days compare within
a band without rerunning, "replay" the parse-once/replay-epochs page
probe (>= 3 gauge-tagged replay epochs: replay_best / replay_sustained
text-equivalent GB/s + build cost; "replay_gbps" keeps the best rate
for older readers; "value" deliberately excludes replay),
"replay_tier" the page-SPILL steady-replay probe (ShardedRowBlockIter
forced over its cache budget: parse-epoch vs page-replay-epoch rates
and their speedup — the ISSUE-2 acceptance number), "bound" whether
the best epoch waited mainly on transfers or on parse, "pipeline" the
best epoch's per-stage stats snapshot + the autotune report, "metrics"
the obs metrics-registry snapshot taken at the best epoch (queue
collectors, engine counters, profiler aggregates — the versioned
obs.metrics schema), "trace" the --trace output path (null without
--trace), and vs_baseline is value / 2.0 (the BASELINE.json target of
2 GB/s/chip; the reference publishes no numbers of its own, see
BASELINE.md).

Secondary diagnostics go to stderr.
"""

import json
import os
import subprocess
import sys
import time

DATA = "/tmp/dmlc_tpu_bench.libsvm"
TARGET_GBPS = 2.0
SIZE_MB = int(os.environ.get("DMLC_TPU_BENCH_MB", "256"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def ensure_data() -> int:
    want = SIZE_MB << 20
    if os.path.exists(DATA) and abs(os.path.getsize(DATA) - want) < (want // 4):
        return os.path.getsize(DATA)
    import numpy as np
    rng = np.random.RandomState(0)
    rows = []
    for i in range(4000):  # criteo-ish: ~39 features/row, large index space
        nnz = rng.randint(25, 45)
        idx = np.sort(rng.choice(10 ** 6, nnz, replace=False))
        vals = rng.rand(nnz)
        rows.append(f"{i % 2} " + " ".join(
            f"{j}:{v:.6f}" for j, v in zip(idx, vals)))
    block = ("\n".join(rows) + "\n").encode()
    reps = max(1, want // len(block))
    with open(DATA, "wb") as f:
        for _ in range(reps):
            f.write(block)
    return os.path.getsize(DATA)


def ensure_native() -> bool:
    from dmlc_tpu import native
    if native.native_available():
        return True
    try:
        subprocess.run([sys.executable, "-m", "dmlc_tpu.native.build"],
                       check=True, capture_output=True, timeout=300)
        native._tried = False
        return native.native_available()
    except Exception as e:  # noqa: BLE001
        log(f"native build failed ({e}); falling back to python engine")
        return False


def main() -> None:
    # --trace out.json: validated FIRST — a missing path must fail in
    # milliseconds, not after minutes of warmup epochs
    trace_path = None
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 >= len(sys.argv):
            log("--trace requires an output path")
            sys.exit(2)
        trace_path = sys.argv[i + 1]
    size = ensure_data()
    have_native = ensure_native()
    # live telemetry opt-ins (no-ops without their env vars): with
    # DMLC_TPU_SERVE_PORT set the measurement epochs are scrapeable
    # (curl :PORT/metrics) while they run; with DMLC_TPU_FLIGHT_DIR a
    # crash mid-bench leaves a post-mortem bundle
    from dmlc_tpu.obs.aggregate import install_if_env as gang_if_env
    from dmlc_tpu.obs.flight import install_if_env
    from dmlc_tpu.obs.serve import serve_if_env
    from dmlc_tpu.obs.timeseries import install_if_env as history_if_env
    srv = serve_if_env()
    if srv is not None:
        log(f"obs status server: http://127.0.0.1:{srv.port}/metrics")
    # history BEFORE flight: flight joins an existing ring but installs
    # its own 15 s one when none is running — the operator's
    # DMLC_TPU_HISTORY_S/_BYTES must win
    history_if_env()  # DMLC_TPU_HISTORY_S: /history + bundle history
    install_if_env()
    gang_if_env()     # DMLC_TPU_GANG_POLL_S (rank 0): /gang timeline
    # the sampling profiler is DEFAULT-ON for bench runs (env still
    # wins: DMLC_TPU_PROFILE_HZ sets the rate, =0 disables): the
    # embedded "analysis" verdict then carries hot_frames — which
    # FUNCTION the bound stage burns in, not just which stage
    from dmlc_tpu.obs import profile as _profile
    if _profile.install_if_env() is None \
            and os.environ.get(_profile.ENV_PROFILE_HZ) is None:
        _profile.install()
    # the verdict-driven controller is DEFAULT-ON for bench runs (env
    # wins: DMLC_TPU_CONTROL=0 disables): the measurement pipeline's
    # knobs move against the /analyze verdict instead of the blind
    # hill-climber, and every decision lands in the ledger embedded
    # under "control" — campaigns record WHAT moved and WHY
    from dmlc_tpu.obs import control as _ctl
    if _ctl.install_if_env() is None \
            and os.environ.get(_ctl.ENV_CONTROL) is None:
        _ctl.install()
    import jax
    import numpy as np
    from dmlc_tpu.data.parser import Parser

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")
    log(f"data: {size / 1e6:.1f} MB, engine={'native' if have_native else 'python'}")

    # warmup (compile/caches)
    warm = Parser.create(DATA, 0, 64, format="libsvm",
                         engine="auto")
    warm.next()
    b = warm.value()
    jax.block_until_ready(jax.device_put(b.offset, dev))
    if hasattr(warm, "destroy"):
        warm.destroy()

    # chunks sized so each device_put stays under the tunnel's
    # large-transfer cliff: r3 measured the cliff is already severe at
    # 8 MB (device_chunks ~0.2 GB/s vs 1.28 at 4 MB; bench sustained
    # 0.40 vs 0.54 GB/s for 8 vs 4 MB chunks on the same chip)
    chunk_mb = int(os.environ.get("DMLC_TPU_BENCH_CHUNK_MB", "4"))

    # Hand-wired reference config (pre-r6 measurement loop): parser →
    # fixed 4-deep async device_put window with leased arenas. Run a
    # few epochs of it so the pipeline-built path below stays honest.
    def handwired_epoch(parser):
        parser.before_first()
        t0 = time.perf_counter()
        in_flight = []  # (future, lease): lease released after transfer
        while parser.next():
            block = parser.value()
            lease = parser.detach() if hasattr(parser, "detach") else None
            in_flight.append((jax.device_put(
                {"offset": block.offset, "label": block.label,
                 "index": block.index, "value": block.value}, dev), lease))
            if len(in_flight) > 4:
                fut, ls = in_flight.pop(0)
                jax.block_until_ready(fut)
                if ls is not None:
                    ls.release()
        for fut, ls in in_flight:
            jax.block_until_ready(fut)
            if ls is not None:
                ls.release()
        return time.perf_counter() - t0

    handwired_gbps = None
    hw_epochs = int(os.environ.get("DMLC_TPU_BENCH_HANDWIRED_EPOCHS", "3"))
    if hw_epochs > 0:
        hw_parser = Parser.create(DATA, 0, 1, format="libsvm",
                                  engine="auto", chunk_size=chunk_mb << 20)
        hw_walls = [handwired_epoch(hw_parser) for _ in range(hw_epochs)]
        if hasattr(hw_parser, "destroy"):
            hw_parser.destroy()
        handwired_gbps = round(size / min(hw_walls) / 1e9, 4)
        log(f"hand-wired reference: best of {hw_epochs} epochs = "
            f"{handwired_gbps} GB/s")

    # The measured config, built from the declarative graph: same
    # parser, same windowed async transfer — but probed per stage and
    # with the in-flight window an autotuner knob instead of the
    # constant 4 the hand-wired loop carried. Since r7 the steady path
    # also ASSEMBLES: batch(pad=True) emits fixed-shape device-layout
    # batches, fused into the engine's ABI-5 native assembly when the
    # native parser serves (assembly_path="native-padded"; the Python
    # fused golden otherwise), and to_device routes them through the
    # host staging pair so transfer N overlaps assembly N+1.
    # DMLC_TPU_BENCH_ASSEMBLY=none restores the pre-r7 raw-block
    # config for before/after attribution.
    from dmlc_tpu.pipeline import Pipeline
    assembly_mode = os.environ.get("DMLC_TPU_BENCH_ASSEMBLY", "auto")
    # DMLC_TPU_BENCH_SHARDS=N (N>1): split the ONE bench file across N
    # native parsers on aligned byte ranges (ISSUE 7 rung c) — the
    # single-file workload parallelizes its reader/parse stages like a
    # multi-file one, byte-identical ordering pinned by tests. Padded
    # assembly over a sharded parse runs the python-fused rung (a
    # padded batch may not straddle the shard boundary), so this knob
    # trades the native-assembly rung for read/parse parallelism —
    # the right trade whenever cores outnumber the one reader thread.
    shards = int(os.environ.get("DMLC_TPU_BENCH_SHARDS", "0") or 0)
    parse_kw = {"shards": shards} if shards > 1 else {}
    pl = (Pipeline.from_uri(DATA)
          .parse(format="libsvm", engine="auto",
                 chunk_size=chunk_mb << 20, **parse_kw))
    if assembly_mode != "none":
        rows_pb = int(os.environ.get("DMLC_TPU_BENCH_BATCH_ROWS",
                                     str(8 << 10)))
        # worst-case nnz bound: ensure_data rows carry < 45 features
        nnz_pb = int(os.environ.get("DMLC_TPU_BENCH_NNZ_BUCKET",
                                    str(rows_pb * 45)))
        pl = pl.batch(rows_pb, pad=True, nnz_bucket=nnz_pb)
    built = pl.to_device(dev, window="auto").build(autotune=True)

    def epoch():
        for _ in built:
            pass
        snap = built.stats()
        parse_st = snap["stages"][0]
        dev_st = snap["stages"][-1]
        t_pull = parse_st["wait_s"]
        dx = dev_st.get("extra") or {}
        t_xfer = dx.get("xfer_wait_s", 0.0)
        # assemble-wait: pad+stack memcpy seconds this epoch — the
        # engine's consumer-side assemble_ns on the fused native rung
        # (where parse+assemble are ONE stage), the measured pad_single
        # time on the python rung (its own stage), plus the host
        # staging copies (device.assemble spans) when staging runs.
        # Scanned across stages: the fused path folds assembly into
        # stages[0], the fallback carries it on its own stage.
        t_asm = dx.get("staging_assemble_s", 0.0)
        stats = None
        for st in snap["stages"]:
            x = st.get("extra") or {}
            t_asm += x.get("assemble_s", 0.0)
            if stats is None:
                stats = x.get("engine")
        return (snap["wall_s"], t_pull, t_xfer, t_asm, parse_st["rows"],
                parse_st["nnz"], stats, snap)

    # Sustained measurement (VERDICT r2 #2): run at least min_epochs
    # passes AND keep sampling for the full time budget, then report the
    # TRIMMED MEAN as the headline — a number that survives a cold re-run
    # on this burstable host — with the best epoch alongside as the
    # hardware-capability ceiling. (min_epochs >= 3 guarantees the byte
    # budget is >= 3x the data size.)
    budget_s = float(os.environ.get("DMLC_TPU_BENCH_BUDGET_S", "60"))
    min_epochs = max(3, int(os.environ.get("DMLC_TPU_BENCH_MIN_EPOCHS", "5")))
    # DMLC_TPU_TRACE=<dir>: dump a jax.profiler device timeline of one
    # epoch (obs.trace.jax_trace) for offline inspection
    trace_dir = os.environ.get("DMLC_TPU_TRACE")
    if trace_dir:
        from dmlc_tpu.obs.trace import jax_trace
        with jax_trace("bench_epoch", log_dir=trace_dir):
            epoch()
        log(f"jax.profiler trace written to {trace_dir}")

    # --trace (parsed at the top of main): record the measurement
    # epochs with the obs trace recorder and export Chrome/Perfetto
    # trace-event JSON — per-stage pull spans, queue waits, transfer
    # drains, and the native engine's counters as counter tracks
    from dmlc_tpu.obs import metrics as obs_metrics
    from dmlc_tpu.obs import trace as obs_trace
    if trace_path:
        obs_trace.start()

    # Every epoch is tagged with a host-memcpy credit gauge (~50 ms,
    # VERDICT r4 #5): this burstable VM's CPU credits swing wall rates
    # ~10x, and without the per-epoch gauge a reader cannot separate
    # "slow framework epoch" from "drained credit bucket". Epochs whose
    # gauge clears GAUGE_OK_GBPS feed sustained_gauge_ok.
    from dmlc_tpu.bench_transfer import memcpy_gauge
    GAUGE_OK_GBPS = float(os.environ.get("DMLC_TPU_BENCH_GAUGE_OK", "1.0"))
    times = []   # (wall_s, gauge_gbps) per epoch
    best = None
    best_stats = None
    best_waits = (0.0, 0.0, 0.0)
    best_snap = None
    best_metrics = None
    t_start = time.perf_counter()
    i = 0
    while True:
        gauge = memcpy_gauge()
        if _ctl.active() is not None:
            # the controller judges the climate from the same gauge
            # the bands are built on — a drained bucket FREEZES knobs
            _ctl.active().note_gauge(gauge)
        dt, t_pull, t_xfer, t_asm, rows, nnz, stats, snap = epoch()
        times.append((dt, gauge))
        log(f"epoch {i}: rows={rows} nnz={nnz} wall={dt:.2f}s "
            f"pull-wait={t_pull:.2f}s xfer-wait={t_xfer:.2f}s "
            f"assemble-wait={t_asm:.2f}s "
            f"gauge={gauge:.2f} -> {size / dt / 1e9:.3f} GB/s")
        if best is None or dt < best:
            best, best_stats = dt, stats
            best_waits = (t_pull, t_xfer, t_asm)
            best_snap = snap
            # the registry snapshot AT the best epoch: queue
            # collectors, engine counters, profiler aggregates — the
            # versioned obs.metrics schema, embedded in BENCH JSON
            best_metrics = obs_metrics.REGISTRY.snapshot()
        i += 1
        elapsed = time.perf_counter() - t_start
        if i >= min_epochs and elapsed > budget_s:
            break
    if trace_path:
        rec = obs_trace.stop()
        if rec is not None:
            from dmlc_tpu.obs.export import write_chrome
            write_chrome(rec, trace_path)
            log(f"obs trace: {len(rec.events())} events "
                f"({rec.dropped} dropped) -> {trace_path}")
    # 20%-per-side trimmed mean of per-epoch rates: robust to both burst
    # windows and throttle windows of the credit scheduler

    def trimmed_mean(vals):
        vals = sorted(vals)
        k = len(vals) // 5
        cut = vals[k:len(vals) - k]
        return sum(cut) / len(cut)

    sustained = trimmed_mean([size / t / 1e9 for t, _ in times])
    # the same statistic over credit-healthy epochs only: the number a
    # judge can compare across runs without rerunning on a better day
    ok_rates = [size / t / 1e9 for t, g in times if g >= GAUGE_OK_GBPS]
    sustained_gauge_ok = (round(trimmed_mean(ok_rates), 4)
                          if len(ok_rates) >= 3 else None)
    log(f"gauge-ok epochs: {len(ok_rates)}/{len(times)} "
        f"(threshold {GAUGE_OK_GBPS} GB/s memcpy)")

    # Band-split sustained rates (BASELINE.md "Credit-recovery
    # profile"): the memcpy gauge separates comparability classes —
    # drained (< 1.0), the post-recovery plateau (1.0-1.6), elevated
    # (1.6-3.0) and full-bucket (>= 3.0, a long-rested VM). Numbers
    # compare ACROSS runs only within one band; the run's modal band is
    # stamped so two BASELINE rows can be read side by side without
    # rerunning either. The band cut points live in obs.analyze (the
    # compare/attribution engine reads the same ones).
    from dmlc_tpu.obs.analyze import gauge_band

    band_rates = {}
    for t, g in times:
        band_rates.setdefault(gauge_band(g), []).append(size / t / 1e9)
    gauge_bands = {
        band: {"epochs": len(rates),
               # same >= 3-epoch rule as sustained_gauge_ok: fewer make
               # a trimmed mean meaningless
               "sustained": (round(trimmed_mean(rates), 4)
                             if len(rates) >= 3 else None)}
        for band, rates in sorted(band_rates.items())}
    run_band = max(band_rates, key=lambda b: len(band_rates[b]))
    log(f"gauge bands: " + ", ".join(
        f"{b}={v['epochs']}ep"
        + (f"@{v['sustained']}" if v["sustained"] else "")
        for b, v in gauge_bands.items()) + f"; run_band={run_band}")
    if best_stats:
        # per-stage breakdown (VERDICT r1 #7): where the best epoch's
        # time went (shared formatter with the bench suite)
        from dmlc_tpu.bench_suite import format_stages
        line = format_stages(best_stats, size)
        if line:
            log(line)
    autotune_report = built.autotune_report()
    if _ctl.active() is not None:
        # the controller subsumed the autotuner: knob moves belong to
        # the "control" ledger below — reporting them as autotuner
        # work would credit a tuner that never ran
        autotune_report = None
    built.close()
    if autotune_report:
        log(f"autotune: values={autotune_report['values']} "
            f"tuned={autotune_report['tuned']} "
            f"decisions={len(autotune_report['decisions'])}")

    # Page-replay rate (VERDICT r4 #2, defensible since r6): the
    # repeated-epoch training shape — parse once into binary pages,
    # replay pages → HBM on every later epoch (DiskRowIter;
    # ShardedRowBlockIter replays retained rounds the same way). >= 3
    # replay epochs, each gauge-tagged, with best AND sustained
    # reported: a single post-drain epoch undersold config 8 by ~5x
    # (r5 measured replay_gbps 0.26 vs config 8's 1.4-2.0). Reported
    # ALONGSIDE the headline: "value" stays the true parse rate,
    # replay must not inflate it.
    replay_gbps = None
    replay = None
    if os.environ.get("DMLC_TPU_BENCH_REPLAY", "1") != "0":
        try:
            from dmlc_tpu.bench_suite import bench_page_replay
            rp_epochs = int(os.environ.get("DMLC_TPU_BENCH_REPLAY_EPOCHS",
                                           "5"))
            rp = bench_page_replay(min(SIZE_MB, 64), epochs=rp_epochs,
                                   gauge_fn=memcpy_gauge)
            # unrounded-wall rates from the suite (the display-rounded
            # epoch_walls would quantize ~30 ms epochs by percents)
            rp_rates = rp["epoch_rates_text_gbps"]
            replay_gbps = rp["text_equiv_gbps"]  # best epoch
            replay = {
                "replay_best": replay_gbps,
                "replay_sustained": round(trimmed_mean(rp_rates), 4),
                "epoch_walls": rp["epoch_walls"],
                "epoch_gauges": rp["epoch_gauges"],
                "build_s": rp["build_s"],
                "page_gbps": round(rp["gbps"], 4),
            }
            log(f"page replay: best {replay_gbps} / sustained "
                f"{replay['replay_sustained']} GB/s text-equivalent "
                f"over {len(rp_rates)} epochs (gauges "
                f"{rp['epoch_gauges']}, build {rp['build_s']}s)")
        except Exception as e:  # noqa: BLE001 — diagnostics must not
            log(f"page replay measurement failed: {e}")  # kill the run

    # Page-SPILL steady replay (r6 tentpole, the ISSUE-2 acceptance
    # probe): a config-7-style iterator forced over its cache budget —
    # steady epochs must serve from spilled round pages at >= 2x the
    # parse-epoch rate.
    replay_tier = None
    if os.environ.get("DMLC_TPU_BENCH_SPILL", "1") != "0":
        try:
            from dmlc_tpu.bench_suite import bench_spill_replay
            sr = bench_spill_replay(min(SIZE_MB, 64),
                                    gauge_fn=memcpy_gauge)
            replay_tier = {
                "mode": sr["mode"],
                "parse_epoch_gbps": sr["parse_epoch_gbps"],
                "parse_epoch_gauge": sr["parse_epoch_gauge"],
                "spill_epoch_gbps": sr["spill_epoch_gbps"],
                "replay_gbps": round(sr["gbps"], 4),
                "replay_sustained_gbps": sr["replay_sustained_gbps"],
                "speedup_vs_parse": sr["speedup_vs_parse"],
                "epoch_gauges": sr["epoch_gauges"],
                "rounds": sr["rounds"],
            }
            log(f"page-spill steady replay: {sr['gbps']:.3f} GB/s "
                f"text-equivalent vs {sr['parse_epoch_gbps']} parse "
                f"({sr['speedup_vs_parse']}x, tier={sr['mode']})")
        except Exception as e:  # noqa: BLE001 — diagnostics must not
            log(f"page-spill replay measurement failed: {e}")

    best_gbps = size / best / 1e9
    # Credit-immune kernel rate (VERDICT r3 #4): thread-CPU time spent
    # parsing, immune to this burstable VM's credit scheduler and to
    # the consumer thread preempting workers on a 1-core host.
    parse_cpu_gbps = None
    if best_stats and best_stats.get("parse_cpu_ns"):
        parse_cpu_gbps = size / best_stats["parse_cpu_ns"]
    # Which side bounds the pipeline (VERDICT r3 #1): the consumer
    # either waits on the parser (parse-bound) or on device transfers
    # (transfer-bound). On this box the transfer side is the tunnel's
    # burst shaping — see dmlc_tpu.bench_transfer / BASELINE.md.
    pull_s, xfer_s, asm_s = best_waits
    bound = "transfer" if xfer_s > pull_s else "parse"
    # which rung assembled the measured batches: "native-padded"
    # (engine ABI-5), "python-fused" (pad_single golden) or "none"
    # (DMLC_TPU_BENCH_ASSEMBLY=none, the pre-r7 raw-block config)
    assembly_path = "none"
    if best_snap:
        assembly_path = next(
            (x["assembly_path"] for s in best_snap["stages"]
             if (x := s.get("extra") or {}).get("assembly_path")),
            "none")
    log(f"sustained (trimmed mean of {len(times)} epochs) = "
        f"{sustained:.3f} GB/s; best epoch = {best_gbps:.3f} GB/s; "
        f"bound={bound} (pull-wait {pull_s:.2f}s vs xfer-wait "
        f"{xfer_s:.2f}s vs assemble-wait {asm_s:.2f}s in best epoch); "
        f"assembly_path={assembly_path}")
    # The structured attribution verdict (obs.analyze): the best
    # epoch's stage waits + the registry snapshot + the run's credit
    # gauges, decomposed into a schema-pinned bound/evidence block —
    # every campaign self-attributes instead of waiting for a human to
    # read the stage numbers
    analysis = None
    if best_snap:
        from dmlc_tpu.obs.analyze import attribute
        analysis = attribute(best_snap, metrics=best_metrics,
                             epoch_gauges=[g for _, g in times],
                             run_band=run_band)
        log(f"analysis: bound={analysis['bound']} "
            f"({analysis['confidence']}) — "
            + "; ".join(analysis["evidence"][:3]))
    control_doc = None
    if _ctl.active() is not None:
        try:
            control_doc = _ctl.active().to_dict(last=32)
        except Exception as e:  # noqa: BLE001 — the campaign line
            log(f"control ledger excerpt failed: {e}")  # must survive
    print(json.dumps({
        "metric": "libsvm_parse_to_hbm_throughput",
        "value": round(sustained, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(sustained / TARGET_GBPS, 4),
        "best_epoch": round(best_gbps, 4),
        "epochs": len(times),
        "bound": bound,
        # which rung assembled the measured batches (r7): attributes
        # campaign wins to native-padded vs python-fused vs the pre-r7
        # raw-block config; assemble_wait_s is the best epoch's
        # pad+stack memcpy seconds (engine assemble_ns or pad_single
        # time, plus host staging copies)
        "assembly_path": assembly_path,
        "assemble_wait_s": round(asm_s, 4),
        # null when the engine exposes no thread-CPU stats (python
        # fallback) — the key is always present for consumers
        "parse_cpu_gbps_core": (round(parse_cpu_gbps, 4)
                                if parse_cpu_gbps is not None else None),
        # trimmed mean over epochs whose pre-epoch host-memcpy gauge
        # cleared the threshold — separates framework throughput from
        # this burstable VM's credit bucket; null when <3 such epochs
        "sustained_gauge_ok": sustained_gauge_ok,
        "gauge_ok_epochs": len(ok_rates),
        "gauge_ok_threshold": GAUGE_OK_GBPS,
        "epoch_gauges": [round(g, 2) for _, g in times],
        # per-comparability-class sustained rates + this run's modal
        # band (BASELINE.md credit-recovery bands): cross-run reads
        # compare within a band only
        "gauge_bands": gauge_bands,
        "run_band": run_band,
        # parse-once/replay-epochs rate in text-equivalent GB/s (the
        # repeated-epoch training shape); null if the probe failed.
        # replay_gbps keeps the BEST single epoch (older readers);
        # "replay" carries best + sustained + per-epoch gauges/walls
        "replay_gbps": replay_gbps,
        "replay": replay,
        # page-SPILL steady replay: the over-budget iterator serving
        # steady epochs from spilled round pages (mode/rates/speedup);
        # null if the probe failed
        "replay_tier": replay_tier,
        # the pre-r6 hand-wired loop's best-of-N reference (null when
        # DMLC_TPU_BENCH_HANDWIRED_EPOCHS=0): the pipeline-built path
        # above must not sit below it
        "handwired_gbps": handwired_gbps,
        # the pipeline-built config's best epoch, per stage (schema:
        # dmlc_tpu.pipeline.stats) + the between-epoch autotune report
        # — null when the verdict-driven controller owned the knobs
        # instead (its moves ride the "control" ledger below)
        "pipeline": {
            "stages": best_snap["stages"] if best_snap else None,
            "knobs": best_snap["knobs"] if best_snap else None,
            "autotune": autotune_report,
        },
        # obs metrics-registry snapshot taken at the best epoch
        # (schema: dmlc_tpu.obs.metrics.METRICS_SCHEMA)
        "metrics": best_metrics,
        # the bottleneck-attribution verdict over the best epoch
        # (schema: dmlc_tpu.obs.analyze.VERDICT_KEYS, lint-pinned):
        # bound/band/confidence/evidence/stage_waits — what obsctl
        # diagnose prints and the /analyze endpoint serves live
        "analysis": analysis,
        # the control plane's decision-ledger excerpt (schema:
        # dmlc_tpu.obs.control.CONTROL_SCHEMA): which knobs moved,
        # on which verdicts, with the evidence — what /control serves
        # live and obsctl control renders; null when the controller
        # was disabled (DMLC_TPU_CONTROL=0) or its payload failed
        # (to_dict runs knob closures; a raising one must not cost
        # the whole campaign line — the flight.py discipline)
        "control": control_doc,
        # Chrome/Perfetto trace of the measurement epochs (--trace)
        "trace": trace_path,
    }))


if __name__ == "__main__":
    main()
