"""Benchmark: libsvm parse-to-HBM GB/s/chip — the headline driver metric.

Measures the full single-chip pipeline on this host's accelerator:
criteo-shaped libsvm (one shard — per-chip throughput is the metric;
the multi-part/multi-host shard shape is bench_suite config 4, which
runs all parts with concurrent pipelines) → native C++ parse → zero-copy
CSR views → async jax.device_put into device memory, transfers riding
under parse via detached leases. Prints exactly ONE JSON line:
{"metric", "value", "unit", "vs_baseline"} — vs_baseline is value / 2.0
(the BASELINE.json target of 2 GB/s/chip; the reference publishes no
numbers of its own, see BASELINE.md).

Secondary diagnostics go to stderr.
"""

import json
import os
import subprocess
import sys
import time

DATA = "/tmp/dmlc_tpu_bench.libsvm"
TARGET_GBPS = 2.0
SIZE_MB = int(os.environ.get("DMLC_TPU_BENCH_MB", "256"))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def ensure_data() -> int:
    want = SIZE_MB << 20
    if os.path.exists(DATA) and abs(os.path.getsize(DATA) - want) < (want // 4):
        return os.path.getsize(DATA)
    import numpy as np
    rng = np.random.RandomState(0)
    rows = []
    for i in range(4000):  # criteo-ish: ~39 features/row, large index space
        nnz = rng.randint(25, 45)
        idx = np.sort(rng.choice(10 ** 6, nnz, replace=False))
        vals = rng.rand(nnz)
        rows.append(f"{i % 2} " + " ".join(
            f"{j}:{v:.6f}" for j, v in zip(idx, vals)))
    block = ("\n".join(rows) + "\n").encode()
    reps = max(1, want // len(block))
    with open(DATA, "wb") as f:
        for _ in range(reps):
            f.write(block)
    return os.path.getsize(DATA)


def ensure_native() -> bool:
    from dmlc_tpu import native
    if native.native_available():
        return True
    try:
        subprocess.run([sys.executable, "-m", "dmlc_tpu.native.build"],
                       check=True, capture_output=True, timeout=300)
        native._tried = False
        return native.native_available()
    except Exception as e:  # noqa: BLE001
        log(f"native build failed ({e}); falling back to python engine")
        return False


def main() -> None:
    size = ensure_data()
    have_native = ensure_native()
    import jax
    import numpy as np
    from dmlc_tpu.data.parser import Parser

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")
    log(f"data: {size / 1e6:.1f} MB, engine={'native' if have_native else 'python'}")

    # warmup (compile/caches)
    warm = Parser.create(DATA, 0, 64, format="libsvm",
                         engine="auto")
    warm.next()
    b = warm.value()
    jax.block_until_ready(jax.device_put(b.offset, dev))
    if hasattr(warm, "destroy"):
        warm.destroy()

    # chunks sized so each device_put stays well under the tunnel's
    # large-transfer cliff (throughput peaks near ~4-8 MB per transfer
    # and halves by ~32 MB) while amortizing per-chunk overhead
    chunk_mb = int(os.environ.get("DMLC_TPU_BENCH_CHUNK_MB", "8"))
    parser = Parser.create(DATA, 0, 1, format="libsvm", engine="auto",
                           chunk_size=chunk_mb << 20)

    def epoch():
        parser.before_first()
        t0 = time.perf_counter()
        rows = nnz = 0
        in_flight = []  # (future, lease): lease released after transfer
        t_pull = 0.0
        tp0 = time.perf_counter()
        while parser.next():
            t_pull += time.perf_counter() - tp0
            block = parser.value()
            rows += block.size
            nnz += block.nnz
            # parse-to-HBM: ship the CSR views to the device, async; the
            # lease keeps the arena alive until the transfer completes
            # (zero-copy: no astype/copy round on the ABI boundary)
            lease = parser.detach() if hasattr(parser, "detach") else None
            in_flight.append((jax.device_put(
                {"offset": block.offset, "label": block.label,
                 "index": block.index, "value": block.value}, dev), lease))
            if len(in_flight) > 4:
                fut, ls = in_flight.pop(0)
                jax.block_until_ready(fut)
                if ls is not None:
                    ls.release()
            tp0 = time.perf_counter()
        for fut, ls in in_flight:
            jax.block_until_ready(fut)
            if ls is not None:
                ls.release()
        stats = parser.stats() if hasattr(parser, "stats") else None
        return time.perf_counter() - t0, t_pull, rows, nnz, stats

    # repeated epochs, keep the best: this host's CPU is burstable and
    # varies 2-4x run-to-run; keep sampling until the best stops
    # improving (or a time budget runs out) so the recorded number is
    # the steady-state hardware rate, not a throttled window
    budget_s = float(os.environ.get("DMLC_TPU_BENCH_BUDGET_S", "60"))
    # DMLC_TPU_TRACE=<dir>: dump a jax.profiler device timeline of one
    # epoch (utils.profiler.trace) for offline inspection
    trace_dir = os.environ.get("DMLC_TPU_TRACE")
    if trace_dir:
        from dmlc_tpu.utils.profiler import trace
        with trace("bench_epoch", log_dir=trace_dir):
            epoch()
        log(f"jax.profiler trace written to {trace_dir}")

    best = None
    best_stats = None
    t_start = time.perf_counter()
    i = 0
    since_improved = 0
    while True:
        dt, t_pull, rows, nnz, stats = epoch()
        log(f"epoch {i}: rows={rows} nnz={nnz} wall={dt:.2f}s "
            f"pull-wait={t_pull:.2f}s -> {size / dt / 1e9:.3f} GB/s")
        improved_enough = best is None or dt < best * 0.98
        if best is None or dt < best:  # true minimum is what we report
            best, best_stats = dt, stats
        since_improved = 0 if improved_enough else since_improved + 1
        i += 1
        elapsed = time.perf_counter() - t_start
        # keep sampling at least ~1/3 of the budget: the burstable CPU
        # throttles in multi-second stretches, and converging inside one
        # would lock in a slow window
        if i >= 3 and ((since_improved >= 3 and elapsed > budget_s / 3)
                       or elapsed > budget_s):
            break
    dt = best
    if best_stats:
        # per-stage breakdown (VERDICT r1 #7): where the time goes
        rd = best_stats["reader_busy_ns"] / 1e9
        pb = best_stats["parse_busy_ns"] / 1e9
        log(f"stages: read={rd:.2f}s ({size / rd / 1e9:.2f} GB/s) "
            f"parse={pb:.2f}s ({size / pb / 1e9:.2f} GB/s summed) "
            f"wall={best_stats['wall_ns'] / 1e9:.2f}s "
            f"chunks={best_stats['chunks']} "
            f"depth(chunkq={best_stats['max_chunk_queue_depth']}, "
            f"reorder={best_stats['max_reorder_depth']})")
    if hasattr(parser, "destroy"):
        parser.destroy()

    gbps = size / dt / 1e9
    log(f"best wall={dt:.2f}s -> {gbps:.3f} GB/s")
    print(json.dumps({
        "metric": "libsvm_parse_to_hbm_throughput",
        "value": round(gbps, 4),
        "unit": "GB/s/chip",
        "vs_baseline": round(gbps / TARGET_GBPS, 4),
    }))


if __name__ == "__main__":
    main()
