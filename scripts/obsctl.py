#!/usr/bin/env python
"""obsctl — operator CLI over the live obs plane and BENCH archives.

Subcommands (all read-only; the plane stays in charge):

- ``top``      — live top-style per-stage table of a running rank's
                 pipeline (polls ``/metrics.json``; ``--once`` for a
                 single frame);
- ``diagnose`` — one-shot bottleneck verdict: from a live rank's
                 ``/analyze`` endpoint, or offline from a BENCH JSON
                 (prefers the run's own embedded ``"analysis"``);
- ``compare``  — band-aware diff of two BENCH JSONs (gauge bands from
                 BASELINE.md): in-band credit variance reports as
                 variance, only out-of-tolerance same-band deltas flag
                 as regressions (exit 3 when any do);
- ``history``  — a rank's ``/history`` time-series ring, summarized;
- ``gang``     — rank 0's ``/gang`` merged gang view (per-rank
                 reachability, gaps, rollups), summarized — including
                 each rank's data-plane byte split (wire vs
                 peer-served vs served-to-peers), so the objstore
                 peer tier's 1/N wire claim is visible on one
                 timeline;
- ``control``  — a rank's ``/control`` decision ledger (the
                 verdict-driven controller): knob state per family
                 and every decision — trial / accepted / reverted /
                 freeze / no-op — with the verdict evidence that
                 caused it, so "why is this knob at this value" is
                 answerable from the CLI; exit 2 with the server's
                 enable hint when no controller is installed;
- ``rpc``      — a rank's ``/rpc`` RPC edge table (distributed
                 tracing plane): per-(peer, verb) call counts and
                 client p50/p99 latency, decomposed into
                 server-reported handle time vs network+queue
                 residual — "is the wire slow or is the server slow"
                 answerable per edge from the CLI;
- ``slo``      — a rank's ``/slo`` declared objectives (obs.slo):
                 per-objective windowed attainment, error-budget
                 remaining, and fast/slow burn-alert state — "are we
                 keeping the promises we declared" answerable from
                 the CLI; exit 2 with the server's enable hint when
                 nothing is declared;
- ``shuffle``  — a rank's ``/shuffle`` global-shuffle row
                 (dmlc_tpu.shuffle): permutation identity (seed,
                 epoch, window budget), coverage watermark, and the
                 local/peer/wire split of exchanged records and
                 bytes — "is the gang actually exchanging through
                 the peer tier" answerable from the CLI; exit 2 with
                 the server's enable hint when no shuffle is active;
- ``profile``  — a rank's ``/profile`` merged Python+native
                 flamegraph: live burst (``--seconds N --hz M``) or
                 the continuous trie, summarized as a top-frame
                 table, or written with ``--out`` as collapsed
                 stacks / a speedscope JSON (``--format``); exit 2
                 with the server's enable hint when no profiler is
                 installed.

Port defaults to ``DMLC_TPU_SERVE_PORT`` so ``obsctl top`` inside a
gang worker's environment needs no flags.

Examples::

    python scripts/obsctl.py top --port 9100
    python scripts/obsctl.py diagnose --port 9100
    python scripts/obsctl.py diagnose BENCH_r07.json
    python scripts/obsctl.py compare BENCH_r06.json BENCH_r07.json
    python scripts/obsctl.py gang --port 9100
    python scripts/obsctl.py profile --port 9100 --seconds 5
    python scripts/obsctl.py profile --out prof.speedscope.json \\
        --format speedscope
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # runnable from anywhere, no install step
    sys.path.insert(0, REPO)


def _fetch(port: int, path: str, host: str = "127.0.0.1",
           timeout_s: float = 5.0) -> Dict[str, Any]:
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                    timeout=timeout_s) as resp:
            return json.load(resp)
    except urllib.error.HTTPError as e:
        # the server's 404s carry a JSON {error, hint} payload (e.g.
        # "no timeseries ring installed" + how to enable it) — return
        # it so the subcommands can show the hint instead of a bare
        # HTTP status line
        try:
            payload = json.load(e)
        except Exception:  # noqa: BLE001 — non-JSON body: original err
            raise e from None
        return payload


def _default_port(args) -> int:
    if args.port:
        return args.port
    env = os.environ.get("DMLC_TPU_SERVE_PORT")
    if env:
        return int(env)
    raise SystemExit("no --port given and DMLC_TPU_SERVE_PORT unset")


def _pipeline_of(snap: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    for k, v in sorted((snap.get("collectors") or {}).items()):
        if k.startswith("pipeline") and v:
            return v
    return None


def _fmt(v: Any, nd: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_stage_table(pl: Dict[str, Any]) -> str:
    """One pipeline stats snapshot -> an aligned per-stage table."""
    cols = ["stage", "kind", "items", "rows", "MB", "wait_s", "wait%",
            "GB/s", "q"]
    rows: List[List[str]] = []
    for st in pl.get("stages") or []:
        occ = st.get("queue_occupancy")
        q = (f"{st.get('queue_depth_mean')}/{st.get('queue_cap')}"
             if st.get("queue_cap") else "-")
        rows.append([
            str(st.get("name", "?")), str(st.get("kind", "?")),
            _fmt(st.get("items")), _fmt(st.get("rows")),
            _fmt((st.get("bytes") or 0) / 1e6, 1),
            _fmt(st.get("wait_s"), 3),
            (f"{st['wait_frac']:.0%}"
             if st.get("wait_frac") is not None else "-"),
            _fmt(st.get("throughput_gbps"), 3),
            q + (f" ({occ:.0%})" if occ is not None else ""),
        ])
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(cols)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append(f"epoch {pl.get('epoch')}  wall {pl.get('wall_s')}s  "
                 f"knobs {pl.get('knobs')}")
    return "\n".join(lines)


def render_verdict(v: Dict[str, Any]) -> str:
    lines = [f"bound: {v.get('bound')}   band: {v.get('band')}   "
             f"confidence: {v.get('confidence')}"
             # schema-4 verdicts carry the tenant whose epoch was
             # judged (multi-tenant scheduler); untenanted runs omit it
             + (f"   tenant: {v['tenant']}" if v.get("tenant")
                else "")
             # schema-3 verdicts are citable (the control ledger
             # references them by id); older BENCH docs lack the field
             + (f"   [{v['verdict_id']}]" if v.get("verdict_id")
                else "")]
    sw = v.get("stage_waits") or {}
    lines.append(
        f"waits: parse {_fmt(sw.get('parse_s'), 3)}s  assemble "
        f"{_fmt(sw.get('assemble_s'), 3)}s  xfer "
        f"{_fmt(sw.get('xfer_s'), 3)}s  (total "
        f"{_fmt(sw.get('total_wait_s'), 3)}s of wall "
        f"{_fmt(sw.get('wall_s'), 3)}s)")
    lines.append("evidence:")
    for e in v.get("evidence") or []:
        lines.append(f"  - {e}")
    hot = v.get("hot_frames") or []
    if hot:
        lines.append("hot frames (sampling profiler, on-CPU):")
        for h in hot:
            lines.append(f"  {h['frac']:>6.1%}  {h['frame']} "
                         f"({h['samples']} samples)")
    return "\n".join(lines)


def render_compare(r: Dict[str, Any]) -> str:
    lines = [f"tolerance ±{r['tolerance']:.0%} within a credit band "
             "(BASELINE.md bands; cross-band reads are incomparable)"]
    header = ["band", "epochs a/b", "a GB/s", "b GB/s", "delta",
              "status"]
    rows: List[List[str]] = []
    for band, row in (r.get("bands") or {}).items():
        ea, eb = (row.get("epochs") or [None, None])[:2]
        rows.append([
            band, f"{_fmt(ea)}/{_fmt(eb)}", _fmt(row.get("a"), 4),
            _fmt(row.get("b"), 4),
            (f"{row['delta_frac']:+.1%}"
             if row.get("delta_frac") is not None else "-"),
            row.get("status", "-")])
    cpu = r.get("parse_cpu")
    if cpu:
        rows.append(["cpu-core*", "-", _fmt(cpu["a"], 4),
                     _fmt(cpu["b"], 4), f"{cpu['delta_frac']:+.1%}",
                     cpu["status"]])
    widths = [max(len(c), *(len(x[i]) for x in rows)) if rows
              else len(c) for i, c in enumerate(header)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(header, widths)))
    for x in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(x, widths)))
    if cpu:
        lines.append("(* parse_cpu_gbps_core: credit-immune, compared "
                     "across bands)")
    for reg in r.get("regressions") or []:
        lines.append(f"REGRESSION: {reg}")
    for imp in r.get("improvements") or []:
        lines.append(f"improvement: {imp}")
    if not r.get("regressions"):
        lines.append("no regressions outside in-band variance")
    return "\n".join(lines)


def cmd_top(args) -> int:
    port = _default_port(args)
    while True:
        snap = _fetch(port, "/metrics.json", host=args.host)
        pl = _pipeline_of(snap)
        stamp = time.strftime("%H:%M:%S")
        who = (f"rank {snap.get('rank')}" if snap.get("rank") is not None
               else f"pid {snap.get('pid')}")
        print(f"— obsctl top · {who} · :{port} · {stamp} —")
        if pl is None:
            print("no pipeline collector yet (no CompiledPipeline has "
                  "completed an epoch in this process)")
        else:
            print(render_stage_table(pl))
        if args.once:
            return 0
        sys.stdout.flush()
        time.sleep(args.interval)


def cmd_diagnose(args) -> int:
    from dmlc_tpu.obs.analyze import diagnose_bench
    if args.bench:
        v = diagnose_bench(args.bench)
    else:
        port = _default_port(args)
        v = _fetch(port, "/analyze", host=args.host)
        if "bound" not in v:
            print(json.dumps(v))
            return 2
    if args.json:
        print(json.dumps(v))
    else:
        print(render_verdict(v))
    return 0


def cmd_compare(args) -> int:
    from dmlc_tpu.obs.analyze import compare_files
    r = compare_files(args.a, args.b, tolerance=args.tolerance)
    if args.json:
        print(json.dumps(r))
    else:
        print(render_compare(r))
    return 3 if r["regressions"] else 0


def cmd_history(args) -> int:
    port = _default_port(args)
    path = "/history" + (f"?seconds={args.seconds}" if args.seconds
                         else "")
    h = _fetch(port, path, host=args.host)
    if args.json or "samples" not in h:
        print(json.dumps(h))
        return 0 if "samples" in h else 2
    samples = h["samples"]
    span = (samples[-1]["t"] - samples[0]["t"]) if len(samples) > 1 \
        else 0.0
    print(f"{len(samples)} samples spanning {span:.1f}s at "
          f"~{h['resolution_s']}s resolution "
          f"({h['approx_bytes']}/{h['budget_bytes']} bytes, "
          f"{h['coarsenings']} coarsenings)")
    if samples:
        for key in sorted(samples[-1]["v"])[:args.keys]:
            first = next((s["v"][key] for s in samples
                          if key in s["v"]), None)
            print(f"  {key}: {first} -> {samples[-1]['v'][key]}")
    return 0


def render_membership(mem: Dict[str, Any]) -> str:
    """One /gang ``membership`` section -> roster rows (the elastic
    half: who is in, at which rank, under which membership epoch)."""
    lines = [f"membership: gang {mem.get('gang')!r} epoch "
             f"{mem.get('epoch')} · world {mem.get('world')} · "
             f"this member {mem.get('member')} (rank "
             f"{mem.get('rank')})"]
    for e in sorted(mem.get("roster") or [],
                    key=lambda e: e.get("rank", 0)):
        port = e.get("port")
        lines.append(f"  rank {e.get('rank')}  {e.get('member')}  "
                     f"{e.get('host')}" + (f":{port}" if port else "")
                     + f"  attempt {e.get('attempt')}")
    prog = mem.get("progress") or {}
    if prog:
        done = sum(int(v) for v in prog.values())
        lines.append(f"  progress: {len(prog)} part(s) started, "
                     f"{done} records committed gang-wide")
    return "\n".join(lines)


def cmd_gang(args) -> int:
    port = _default_port(args)
    g = _fetch(port, "/gang", host=args.host)
    has_ranks = "ranks" in g
    membership = g.get("membership")
    if args.json or (not has_ranks and not membership):
        print(json.dumps(g))
        return 0 if (has_ranks or membership) else 2
    if membership:
        print(render_membership(membership))
    if not has_ranks:
        return 0
    print(f"gang of {len(g['ports'])} (poll {g['period_s']}s, "
          f"{g['polls']} polls)")
    data_plane = False
    for label, m in sorted(g["ranks"].items()):
        state = "UNREACHABLE" if m["unreachable"] else "up"
        gaps = len(m["gaps"])
        kept = m["series"]["kept"]
        print(f"  {label} :{m['port']}  {state}  "
              f"{m['polls_ok']} ok / {m['polls_failed']} failed"
              + (f"  {gaps} gap(s)" if gaps else "")
              + f"  {kept} samples"
              + (f"  last error {m['last_error']}"
                 if m["last_error"] else ""))
        # the rank's data-plane byte split: wire GETs vs bytes served
        # BY peers to this rank vs bytes this rank served TO peers —
        # the peer tier's 1/N claim, readable on one timeline
        samples = m["series"].get("samples") or []
        v = samples[-1]["v"] if samples else {}
        wire = v.get("counters.objstore.bytes")
        peer = v.get("counters.objstore.peer.bytes")
        served = v.get("counters.objstore.peer.served_bytes")
        if any(x for x in (wire, peer, served)):
            data_plane = True
            print(f"    bytes: wire {_fmt(wire, 0)} · "
                  f"peer-served {_fmt(peer, 0)} · "
                  f"served-to-peers {_fmt(served, 0)}")
        # the rank's checkpoint-restore byte split: what restore()
        # materialized and which tier carried it — the fanout's ~1/N
        # wire claim, per rank
        ck = v.get("counters.checkpoint.restore_bytes")
        if ck:
            print(f"    restore: {_fmt(ck, 0)} bytes · local "
                  f"{_fmt(v.get('counters.checkpoint.restore.local_bytes'), 0)}"
                  " · peer "
                  f"{_fmt(v.get('counters.checkpoint.restore.peer_bytes'), 0)}"
                  " · wire "
                  f"{_fmt(v.get('counters.checkpoint.restore.wire_bytes'), 0)}")
        # the rank's control-plane cadence (collectors.control.* ride
        # the same gang timeline): decisions made, climate freezes,
        # reverted trials — the observe→act loop, visible per rank
        dec = v.get("collectors.control.decisions")
        if dec is not None:
            print(f"    control: {_fmt(dec, 0)} decisions · "
                  f"{_fmt(v.get('collectors.control.freezes'), 0)} "
                  "freezes · "
                  f"{_fmt(v.get('collectors.control.reverted'), 0)} "
                  "reverted")
    roll = g["rollup"]["samples"]
    if roll:
        last = roll[-1]["v"]
        print(f"  rollup: reachable {last.get('gang.reachable')}/"
              f"{last.get('gang.expected')} at last poll, "
              f"{len(roll)} rollup samples")
        if data_plane:
            gw = last.get("sum.counters.objstore.bytes")
            gp = last.get("sum.counters.objstore.peer.bytes")
            print(f"  rollup bytes: wire {_fmt(gw, 0)} · "
                  f"peer-served {_fmt(gp, 0)} across reachable ranks")
    return 0


def render_control(doc: Dict[str, Any], last: int = 12) -> str:
    """One /control payload -> knob state + the decision tail."""
    lines = [f"controller: epoch {doc.get('epoch')}  "
             + "  ".join(f"{k}={v}" for k, v in
                         (doc.get("counts") or {}).items() if v)]
    for name, k in sorted((doc.get("knobs") or {}).items()):
        lines.append(
            f"  knob {name} = {k['value']} (family {k['family']}, "
            f"[{k['lo']},{k['hi']}], initial {k['initial']}"
            + (", FROZEN" if k.get("frozen") else "") + ")")
    led = doc.get("ledger") or {}
    lines.append(f"ledger: {led.get('kept')} of {led.get('offered')} "
                 f"decisions kept "
                 f"({led.get('approx_bytes')}/{led.get('budget_bytes')} "
                 f"bytes, {led.get('coarsenings')} coarsenings)")
    for rec in (led.get("records") or [])[-last:]:
        move = (f" {rec['knob']} {rec['old']}→{rec['new']}"
                if rec.get("knob") else "")
        lines.append(
            f"  [e{rec.get('epoch')}] {rec.get('outcome', '?'):<10} "
            f"{rec.get('family') or '-':<9} bound={rec.get('bound')}"
            f"/{rec.get('band')}{move}  ({rec.get('verdict_id')})")
        for e in (rec.get("evidence") or [])[:2]:
            lines.append(f"      - {e}")
    return "\n".join(lines)


def cmd_control(args) -> int:
    port = _default_port(args)
    path = "/control" + (f"?last={args.last}" if args.last else "")
    doc = _fetch(port, path, host=args.host)
    if "ledger" not in doc:
        # the server's 404 payload ({error, hint}: no controller
        # installed) — surface the hint, exit 2 like history/gang
        print(json.dumps(doc))
        return 2
    if args.json:
        print(json.dumps(doc))
        return 0
    print(render_control(doc, last=args.keys))
    return 0


def render_tenants(doc: Dict[str, Any]) -> str:
    """One /tenants payload -> per-tenant table + plane header."""
    lines = [f"scheduler: quantum {doc.get('quantum')} · burst "
             f"{doc.get('burst')} · queue budget "
             f"{doc.get('queue_budget')} · {doc.get('rounds')} rounds"]
    hdr = (f"{'tenant':<12} {'pipes':>5} {'share':>5} {'credits':>7} "
           f"{'pulls':>8} {'p50 ms':>8} {'p99 ms':>8} {'occ':>5} "
           f"{'verdict':<18}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for name, t in sorted((doc.get("tenants") or {}).items()):
        v = t.get("last_verdict") or {}
        verdict = (f"{v.get('bound')}/{v.get('confidence')}"
                   if v else "-")
        if t.get("paused"):
            verdict = "PAUSED " + verdict
        ms = [t.get("batch_p50_s"), t.get("batch_p99_s")]
        ms = [f"{x * 1e3:.1f}" if x is not None else "-" for x in ms]
        pipes = f"{t.get('pipelines')}/{t.get('max_pipelines')}"
        lines.append(
            f"{name:<12} {pipes:>5} {_fmt(t.get('queue_share'), 0):>5} "
            f"{_fmt(t.get('deficit'), 1):>7} {t.get('pulls', 0):>8} "
            f"{ms[0]:>8} {ms[1]:>8} "
            f"{_fmt(t.get('queue_occupancy')):>5} {verdict:<18}")
        wm = t.get("watermark")
        if wm:
            lines.append(
                f"    stream {wm.get('uri')}: {wm.get('windows')} "
                f"windows, watermark {wm.get('watermark_records')} "
                f"records / {wm.get('watermark_bytes')} bytes "
                f"(advanced {wm.get('last_advance_s_ago')}s ago, "
                f"{wm.get('retries')} degraded polls)")
        if t.get("rejected"):
            lines.append(f"    admission: {t['admitted']} admitted, "
                         f"{t['rejected']} rejected, "
                         f"{t['queued']} queued")
    return "\n".join(lines)


def cmd_tenants(args) -> int:
    port = _default_port(args)
    doc = _fetch(port, "/tenants", host=args.host)
    if "tenants" not in doc:
        # the server's 404 payload ({error, hint}: no scheduler
        # installed) — surface the hint, exit 2 like history/gang
        print(json.dumps(doc))
        return 2
    if args.json:
        print(json.dumps(doc))
        return 0
    print(render_tenants(doc))
    return 0


def render_rpc(doc: Dict[str, Any]) -> str:
    """One /rpc payload -> per-(peer, verb) attribution table: where
    each edge's client-observed latency went (server handle vs
    network+queue residual)."""
    edges = doc.get("edges") or []
    hdr = ["peer", "verb", "count", "err", "cli p50us", "cli p99us",
           "srv p50us", "srv p99us", "net p50us", "srv%"]
    rows: List[List[str]] = []
    for e in sorted(edges, key=lambda e: (e["peer"], e["verb"])):
        cli = e.get("client_us") or {}
        srv = e.get("server_us") or {}
        net = e.get("residual_us") or {}
        attributed = e.get("server_total_us") is not None \
            and e.get("attributed")
        share = "-"
        if attributed:
            total = (e["server_total_us"] or 0.0) \
                + (e["residual_total_us"] or 0.0)
            if total > 0:
                share = f"{e['server_total_us'] / total:.0%}"
        rows.append([
            str(e["peer"]), str(e["verb"]), str(e["count"]),
            str(e["errors"]), _fmt(cli.get("p50"), 0),
            _fmt(cli.get("p99"), 0), _fmt(srv.get("p50"), 0),
            _fmt(srv.get("p99"), 0), _fmt(net.get("p50"), 0), share,
        ])
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(hdr)]
    lines = ["  ".join(c.ljust(w) for c, w in zip(hdr, widths))]
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append("(srv% = server handle share of attributed wire "
                 "wait; the rest is network+queue residual)")
    return "\n".join(lines)


def cmd_rpc(args) -> int:
    port = _default_port(args)
    doc = _fetch(port, "/rpc", host=args.host)
    if "edges" not in doc:
        print(json.dumps(doc))
        return 2
    if args.json:
        print(json.dumps(doc))
        return 0
    if not doc["edges"]:
        print("no RPC edges recorded yet (tracing off, or no "
              "cross-process calls since start)")
        return 0
    print(render_rpc(doc))
    return 0


def render_slo(doc: Dict[str, Any]) -> str:
    """One /slo payload -> per-objective judgment table: windowed
    attainment, error budget remaining, and which burn alert (if any)
    is firing right now."""
    lines = [f"slo: fast-burn >= {doc.get('fast_burn_rate')}x · "
             f"slow-burn >= {doc.get('slow_burn_rate')}x "
             "(both windows of a pair must exceed the rate)"]
    hdr = ["objective", "tenant", "metric", "target", "window",
           "attain", "budget left", "burn", "fast burn", "alert"]
    rows: List[List[str]] = []
    for name, o in sorted((doc.get("objectives") or {}).items()):
        w = o.get("windows") or {}
        alerts = o.get("alerts") or {}
        alert = ("FAST-BURN" if alerts.get("fast")
                 else "slow-burn" if alerts.get("slow") else "-")
        if o.get("incomplete"):
            alert += " (incomplete)"
        att = o.get("attainment")
        rem = o.get("budget_remaining")
        rows.append([
            str(name), str(o.get("tenant") or "-"),
            str(o.get("metric")),
            f"{o.get('target_s')}s", f"{o.get('window_s')}s",
            f"{att:.2%}" if att is not None else "-",
            f"{rem:.0%}" if rem is not None else "-",
            _fmt((w.get("long") or {}).get("burn"), 1),
            _fmt((w.get("fast_short") or {}).get("burn"), 1),
            alert,
        ])
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(hdr)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(hdr, widths)))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append("(burn = long-window burn rate; fast burn = the "
                 "short fast window — the alert's reset edge)")
    if doc.get("incomplete"):
        lines.append(f"INCOMPLETE gang rollup: unreachable "
                     f"{', '.join(doc.get('unreachable') or [])} — "
                     "attainment judged on a subset of the gang")
    return "\n".join(lines)


def cmd_slo(args) -> int:
    port = _default_port(args)
    doc = _fetch(port, "/slo", host=args.host)
    if "objectives" not in doc:
        # the server's 404 payload ({error, hint}: nothing declared)
        # — surface the hint, exit 2 like tenants/control
        print(json.dumps(doc))
        return 2
    if args.json:
        print(json.dumps(doc))
        return 0
    print(render_slo(doc))
    return 0


def _fmt_bytes(n: int) -> str:
    """1536 -> '1.5KiB' — compact byte counts for the row tables."""
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if v < 1024 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{int(n)}B"


def render_shuffle(doc: Dict[str, Any]) -> str:
    """One /shuffle payload -> the rank's global-shuffle row: the
    permutation identity (seed/epoch/window budget), the coverage
    watermark, and where the exchanged bytes actually came from
    (local page store vs peer /pages tier vs source wire)."""
    rec = doc.get("records_by_tier") or {}
    byt = doc.get("bytes_by_tier") or {}
    cov = doc.get("coverage")
    lines = [
        f"shuffle: seed {doc.get('seed')} · epoch {doc.get('epoch')} "
        f"· rank {doc.get('rank')}/{doc.get('world')} · "
        f"{doc.get('uri')} ({doc.get('split_type')})",
        f"  records {doc.get('records')} in {doc.get('windows')} "
        f"windows (budget {_fmt_bytes(doc.get('window_bytes') or 0)})",
        f"  position {doc.get('position')} · delivered "
        f"{doc.get('delivered')} · coverage "
        + (f"{cov:.2%}" if cov is not None else "-"),
    ]
    hdr = ["tier", "records", "bytes"]
    rows = [[t, str(rec.get(t, 0)), _fmt_bytes(byt.get(t, 0))]
            for t in ("local", "peer", "wire")]
    widths = [max(len(c), *(len(r[i]) for r in rows))
              for i, c in enumerate(hdr)]
    lines.append("  ".join(c.ljust(w) for c, w in zip(hdr, widths)))
    for r in rows:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    lines.append("(peer = window pages served by another rank's "
                 "/pages tier; wire = hydrated from the source)")
    return "\n".join(lines)


def cmd_shuffle(args) -> int:
    port = _default_port(args)
    doc = _fetch(port, "/shuffle", host=args.host)
    if "records_by_tier" not in doc:
        # the server's 404 payload ({error, hint}: no shuffle active)
        print(json.dumps(doc))
        return 2
    if args.json:
        print(json.dumps(doc))
        return 0
    print(render_shuffle(doc))
    return 0


def cmd_profile(args) -> int:
    port = _default_port(args)
    qs = []
    if args.seconds is not None:
        qs.append(f"seconds={args.seconds}")
    if args.hz is not None:
        qs.append(f"hz={args.hz}")
    path = "/profile" + ("?" + "&".join(qs) if qs else "")
    doc = _fetch(port, path, host=args.host,
                 timeout_s=max(10.0, (args.seconds or 0) + 10.0))
    if "threads" not in doc:
        # the server's 404 payload ({error, hint}: no profiler
        # installed) — surface the hint, exit 2 like history/gang
        print(json.dumps(doc))
        return 2
    if args.out:
        from dmlc_tpu.obs.export import write_collapsed, write_speedscope
        if args.format == "speedscope":
            write_speedscope(doc, args.out)
        else:
            write_collapsed(doc, args.out)
        print(f"{args.format} profile -> {args.out} "
              f"({doc['samples']} samples)")
        return 0
    if args.json:
        print(json.dumps(doc))
        return 0
    from dmlc_tpu.obs.profile import hot_frames
    total = doc["samples"]
    wait = doc.get("wait_samples", 0)
    kind = (f"burst {doc.get('duration_s')}s" if doc.get("burst")
            else f"continuous {doc.get('duration_s')}s")
    print(f"{total} samples ({kind} at {doc.get('hz')} Hz), "
          f"{wait} off-cpu"
          + (f" ({wait / total:.0%})" if total else "")
          + f", {doc.get('coarsenings', 0)} coarsenings")
    for h in hot_frames(doc, limit=args.keys):
        print(f"  {h['frac']:>6.1%}  {h['frame']} "
              f"({h['samples']} samples)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="obsctl", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    def common(p):
        p.add_argument("--port", type=int, default=0,
                       help="status-server port (default: "
                            "DMLC_TPU_SERVE_PORT)")
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--json", action="store_true",
                       help="raw JSON output")

    p = sub.add_parser("top", help="live per-stage pipeline table")
    common(p)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--once", action="store_true")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("diagnose",
                       help="bottleneck verdict (live rank or BENCH "
                            "JSON)")
    common(p)
    p.add_argument("bench", nargs="?", default=None,
                   help="BENCH JSON to diagnose offline")
    p.set_defaults(fn=cmd_diagnose)

    p = sub.add_parser("compare",
                       help="band-aware diff of two BENCH JSONs")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--tolerance", type=float, default=None)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_compare)

    p = sub.add_parser("history", help="a rank's time-series ring")
    common(p)
    p.add_argument("--seconds", type=float, default=None)
    p.add_argument("--keys", type=int, default=12,
                   help="series keys to summarize")
    p.set_defaults(fn=cmd_history)

    p = sub.add_parser("gang", help="rank 0's merged gang view")
    common(p)
    p.set_defaults(fn=cmd_gang)

    p = sub.add_parser("tenants",
                       help="a rank's /tenants rows (multi-tenant "
                            "pipeline scheduler)")
    common(p)
    p.set_defaults(fn=cmd_tenants)

    p = sub.add_parser("control",
                       help="a rank's /control decision ledger "
                            "(verdict-driven controller)")
    common(p)
    p.add_argument("--last", type=int, default=None,
                   help="fetch only the trailing N ledger records")
    p.add_argument("--keys", type=int, default=12,
                   help="ledger records to render in the summary")
    p.set_defaults(fn=cmd_control)

    p = sub.add_parser("rpc",
                       help="a rank's /rpc edge table (per-peer wire "
                            "latency attribution)")
    common(p)
    p.set_defaults(fn=cmd_rpc)

    p = sub.add_parser("slo",
                       help="a rank's /slo declared objectives "
                            "(attainment, error budget, burn alerts)")
    common(p)
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser("shuffle",
                       help="a rank's /shuffle global-shuffle row "
                            "(seed, epoch, window budget, coverage, "
                            "local/peer/wire exchange)")
    common(p)
    p.set_defaults(fn=cmd_shuffle)

    p = sub.add_parser("profile",
                       help="a rank's merged Python+native flamegraph")
    common(p)
    p.add_argument("--seconds", type=float, default=None,
                   help="burst-capture the next N seconds (default: "
                        "dump the continuous profile)")
    p.add_argument("--hz", type=float, default=None,
                   help="burst sample rate (default: the installed "
                        "profiler's rate)")
    p.add_argument("--out", default=None,
                   help="write the profile to a file instead of "
                        "summarizing")
    p.add_argument("--format", choices=("collapsed", "speedscope"),
                   default="collapsed",
                   help="--out format: collapsed stacks "
                        "(flamegraph.pl) or speedscope JSON")
    p.add_argument("--keys", type=int, default=12,
                   help="hot frames to list in the summary")
    p.set_defaults(fn=cmd_profile)

    args = ap.parse_args(argv)
    if args.cmd == "compare" and args.tolerance is None:
        from dmlc_tpu.obs.analyze import DEFAULT_TOLERANCE
        args.tolerance = DEFAULT_TOLERANCE
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130
    except (OSError, urllib.error.URLError) as e:
        print(f"obsctl: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
