#!/usr/bin/env python
"""Repo lint gate (SURVEY §4's scripts/lint.py analogue).

Two layers, so the gate degrades instead of disappearing on hosts
without the tools baked in:

- **Built-in checks** (always run, stdlib only): every tracked .py file
  must parse (ast), use spaces-only indentation, carry no trailing
  whitespace, no CR line endings, and end with exactly one newline.
- **Observability gate** (always run, AST-based): inside the
  ``dmlc_tpu`` package, bare ``print(`` calls and new ad-hoc ``def
  stats(`` dict surfaces are forbidden outside ``dmlc_tpu/obs/`` — new
  telemetry registers into ``dmlc_tpu.obs.metrics`` and logs through
  ``dmlc_tpu.obs.log``. Pre-obs surfaces are pinned in an allowlist;
  the list shrinks, it does not grow.
- **Metric-name gate** (always run, AST-based): every literal
  instrument name passed to ``.counter("...")``/``.gauge("...")``/
  ``.histogram("...")`` inside ``dmlc_tpu/`` must match
  ``[a-z0-9_.]+`` — anything else renders badly (or not at all) in
  the Prometheus exposition that ``obs/serve.py`` derives from the
  registry. And ``http.server`` may be used ONLY by ``obs/serve.py``:
  one status server per process, not one per module.
- **Resilience gate** (always run, AST-based): inside ``dmlc_tpu``,
  outside ``dmlc_tpu/resilience/``, hand-rolled retry loops (a loop
  whose body both sleeps and swallows OSError-family exceptions) and
  naked ``except OSError: continue`` handlers are forbidden — retries
  are policy (``dmlc_tpu.resilience.RetryPolicy`` via ``guarded()``),
  not ad-hoc control flow. The two pre-resilience skip-not-retry
  handlers are pinned in an allowlist; the list shrinks, it does not
  grow.
- **Verdict-schema gate** (always run, AST-based): the analysis
  verdict's key set (``dmlc_tpu/obs/analyze.py`` ``VERDICT_KEYS``) is
  pinned here, and any literal dict that claims to be a verdict
  (``"bound"`` + ``"evidence"`` keys) anywhere in ``dmlc_tpu/`` or
  ``scripts/`` must match it exactly — the ``/analyze`` endpoint,
  bench JSON ``"analysis"`` blocks, and ``scripts/obsctl.py`` can
  never drift apart.
- **Knob gate** (always run, AST-based): steady-state knob mutation —
  ``.set_capacity()`` calls, ``.prefetch_depth``/``.window`` attribute
  assignment, ``objstore.configure()`` with coalesce/parallel/
  codec_level — is confined to the exploration rails
  (``pipeline/autotune.py`` + ``obs/control.py``) plus the pinned
  modules that DEFINE the knobs, so every knob move lands in the
  control plane's decision ledger with the evidence that caused it.
- **Codec gate** (always run, AST-based): direct ``zlib``/``gzip``/
  ``bz2``/``lzma`` imports inside ``dmlc_tpu/`` are forbidden outside
  ``io/codec.py`` (the one compressed-page seam; the pinned exception:
  ``resilience/policy.py``'s ``zlib.crc32`` jitter hash) — page bytes
  compress through one self-describing frame, never ad-hoc streams.
- **Profile gate** (always run, AST-based): ``sys._current_frames``
  walks and ``cProfile``/``profile``/``pstats`` imports inside
  ``dmlc_tpu/`` are confined to ``obs/profile.py`` — the process has
  ONE sampling profiler (one trie, one budget, one /profile payload);
  a second frame-walker elsewhere would mint a parallel universe the
  watchdog, flight bundles and ``hot_frames`` evidence never see.
- **Http-client gate** (always run, AST-based): ``http.client`` and
  ``urllib.request`` imports inside ``dmlc_tpu/`` are confined to the
  objstore client modules (``io/objstore/http_client.py``,
  ``io/objstore/peer.py``) and ``obs/serve.py``'s scrape — outbound
  HTTP elsewhere would bypass the ``io.objstore.*``/``obs.scrape``
  retry seams, fault plans, and byte counters (the ``http.server``
  side is pinned to ``obs/serve.py`` by the metric gate).
- **SLO gate** (always run, AST-based): instruments named in the
  ``slo.*`` family and the burn-rate threshold floats (14.4 / 6.0)
  are confined to ``obs/slo.py`` — one home for the alert math; every
  other surface imports ``FAST_BURN_RATE``/``SLOW_BURN_RATE`` and
  lets the engine export the per-objective gauges (the pinned
  exception: ``resilience/supervise.py``'s ``6.0`` teardown drain
  margin).
- **Random gate** (always run, AST-based): ``random`` /
  ``numpy.random`` construction inside ``dmlc_tpu/io/`` and
  ``dmlc_tpu/data/`` is forbidden — seeded-permutation ownership has
  one home (``dmlc_tpu/shuffle/``): epoch randomness is drawn from
  ``dmlc_tpu.shuffle.permutation.epoch_rng`` so the determinism
  contract (same seed ⇒ same order, restart-stable resume) holds.
- **Steady-path gate** (always run, AST-based): inside
  ``dmlc_tpu/data/`` and ``dmlc_tpu/pipeline/``, per-row Python loops
  over block payloads (``for row in …`` or ``range(<x>.size)`` index
  loops) are forbidden outside the pinned golden-path allowlist — the
  per-row work belongs to the engine's ABI-5 padded emission
  (``dtp_parser_next_padded``) or the vectorized ``data.padding`` ops.
- **ruff** over the Python tree and **clang-format --dry-run -Werror**
  over native/src/ — run when the binaries are importable/installed,
  reported as skipped otherwise.

Wired into the pytest suite via tests/test_lint.py, so tier-1 fails on
a lint regression. CLI: ``python scripts/lint.py`` exits 0 clean / 1
with findings on stderr.
"""

from __future__ import annotations

import ast
import os
import re
import subprocess
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {".git", ".claude", "__pycache__", ".pytest_cache", "build"}
NATIVE_SRC = os.path.join(REPO, "dmlc_tpu", "native", "src")


def python_files(root: str = REPO) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for name in filenames:
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def native_files(root: str = NATIVE_SRC) -> List[str]:
    if not os.path.isdir(root):
        return []
    return sorted(os.path.join(root, n) for n in os.listdir(root)
                  if n.endswith((".cc", ".h", ".cpp", ".hpp")))


def builtin_lint(paths: List[str]) -> List[str]:
    """Stdlib-only findings: ["path:line: message"]."""
    findings: List[str] = []
    for path in paths:
        rel = os.path.relpath(path, REPO)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            findings.append(f"{rel}:0: unreadable ({e})")
            continue
        if b"\r" in raw:
            findings.append(f"{rel}:0: CR line endings")
        try:
            text = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            findings.append(f"{rel}:0: not UTF-8 ({e})")
            continue
        try:
            ast.parse(text, filename=rel)
        except SyntaxError as e:
            findings.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        if text and not text.endswith("\n"):
            findings.append(f"{rel}:0: missing trailing newline")
        if text.endswith("\n\n"):
            findings.append(f"{rel}:0: trailing blank lines at EOF")
        for i, line in enumerate(text.split("\n"), 1):
            stripped = line.rstrip("\n")
            if stripped != stripped.rstrip():
                findings.append(f"{rel}:{i}: trailing whitespace")
            indent = stripped[:len(stripped) - len(stripped.lstrip())]
            if "\t" in indent:
                findings.append(f"{rel}:{i}: tab in indentation")
    return findings


# pre-obs surfaces, pinned (package-relative paths). print(): the two
# CLI-style emitters whose stdout IS their interface and the build
# script. stats(): the five shapes that now REGISTER into
# dmlc_tpu.obs.metrics (the methods stay for their callers). New code
# uses obs.metrics / obs.log instead of growing this list.
PRINT_ALLOWED = {
    "dmlc_tpu/native/build.py",
    "dmlc_tpu/bench_transfer.py",
    "dmlc_tpu/bench_suite.py",
}
STATS_ALLOWED = {
    "dmlc_tpu/data/threaded_iter.py",
    "dmlc_tpu/native/bindings.py",
    "dmlc_tpu/pipeline/graph.py",
    "dmlc_tpu/utils/memory.py",
}


def _parse_package_trees(paths: List[str]) -> dict:
    """{path: (rel, ast)} for the dmlc_tpu/ files — parsed ONCE and
    shared by every AST gate (each gate re-parsing the tree tripled
    the lint cost per added gate). Unparseable files are absent;
    builtin_lint reports those."""
    trees = {}
    for path in paths:
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        if not rel.startswith("dmlc_tpu/"):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                trees[path] = (rel, ast.parse(f.read(), filename=rel))
        except (OSError, SyntaxError, UnicodeDecodeError):
            pass
    return trees


def obs_lint(paths: List[str], trees: Optional[dict] = None) -> List[str]:
    """The observability gate: no new bare print()/ad-hoc stats() dict
    shapes inside dmlc_tpu/ outside obs/ (see module docstring)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel.startswith("dmlc_tpu/obs/"):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and rel not in PRINT_ALLOWED):
                findings.append(
                    f"{rel}:{node.lineno}: bare print() in package code "
                    "— log through dmlc_tpu.obs.log / utils.logging")
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == "stats"
                    and rel not in STATS_ALLOWED):
                findings.append(
                    f"{rel}:{node.lineno}: new stats() surface — "
                    "register a collector with dmlc_tpu.obs.metrics."
                    "REGISTRY instead of inventing a dict shape")
    return findings


# registry instrument names must survive the Prometheus name mangling
# in obs/serve.py losslessly: lowercase words joined by '.' (or '_')
METRIC_NAME_RE = re.compile(r"^[a-z0-9_.]+$")
# the ONE module allowed to stand up an HTTP server (package-relative)
HTTP_SERVER_ALLOWED = {"dmlc_tpu/obs/serve.py"}
_INSTRUMENT_METHODS = ("counter", "gauge", "histogram")


def metric_lint(paths: List[str],
                trees: Optional[dict] = None) -> List[str]:
    """The metric-name + http.server gate (see module docstring).
    Literal names only: f-string/dynamic names are built from literal
    parts that the regex already vets at their other call sites."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _INSTRUMENT_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                if not METRIC_NAME_RE.match(name):
                    findings.append(
                        f"{rel}:{node.lineno}: metric name {name!r} — "
                        "registry instrument names must match "
                        "[a-z0-9_.]+ (Prometheus exposition contract, "
                        "obs/serve.py)")
            if rel in HTTP_SERVER_ALLOWED:
                continue
            if (isinstance(node, ast.Import)
                    and any(a.name == "http.server"
                            for a in node.names)) or \
               (isinstance(node, ast.ImportFrom)
                    and (node.module == "http.server"
                         or (node.module == "http"
                             and any(a.name == "server"
                                     for a in node.names)))):
                findings.append(
                    f"{rel}:{node.lineno}: http.server outside "
                    "obs/serve.py — the process status server lives "
                    "there (serve()/serve_if_env()), one per process")
    return findings


# Byte access goes through the FileSystem/stream seams in dmlc_tpu/io/
# — that is where retry policies and fault plans apply (guarded() at
# io.stream.*, io.filesys.*, io.objstore.*) and where the unified page
# store stamps/accounts bytes. A direct open()/os.stat on a data path
# elsewhere in the package silently bypasses all of it. The pinned
# exceptions are files whose bytes are NOT data-path bytes: telemetry
# output (trace exports, flight bundles, stall reports), bench corpus
# builders and result JSON, launcher log capture, and the config file.
# The list shrinks, it does not grow.
IO_SEAM_ALLOWED = {
    "dmlc_tpu/bench_mp_worker.py",   # gang-worker result JSON
    "dmlc_tpu/bench_suite.py",       # corpus builders / BENCH JSON
    "dmlc_tpu/native/build.py",      # build tooling (zlib link probe)
    "dmlc_tpu/obs/analyze.py",       # BENCH result JSON (compare)
    "dmlc_tpu/obs/export.py",        # trace JSON export
    "dmlc_tpu/obs/flight.py",        # crash flight bundles
    "dmlc_tpu/obs/watchdog.py",      # stall reports
    "dmlc_tpu/parallel/launch.py",   # per-rank log capture
    "dmlc_tpu/utils/config.py",      # config file loader
}


def io_seam_lint(paths: List[str],
                 trees: Optional[dict] = None) -> List[str]:
    """The io-seam gate: no direct ``open()`` / ``os.stat()`` calls in
    dmlc_tpu/ outside dmlc_tpu/io/ (see IO_SEAM_ALLOWED)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel.startswith("dmlc_tpu/io/") or rel in IO_SEAM_ALLOWED:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "open":
                findings.append(
                    f"{rel}:{node.lineno}: direct open() outside "
                    "dmlc_tpu/io/ — byte access goes through "
                    "create_stream/FileSystem (or PageStore) so retry "
                    "policies and fault plans apply")
            elif (isinstance(f, ast.Attribute) and f.attr == "stat"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("os", "_os")):
                findings.append(
                    f"{rel}:{node.lineno}: direct os.stat() outside "
                    "dmlc_tpu/io/ — stat through "
                    "io.pagestore.stat_uri / FileSystem.get_path_info "
                    "so remote schemes and fault plans apply")
    return findings


# Compression is a SEAM (dmlc_tpu/io/codec.py: one self-describing
# page frame, one level contract, one corruption story the retry seams
# rely on), not a per-call-site choice: a direct zlib/gzip/bz2/lzma
# import elsewhere in the package would mint a second on-disk/on-wire
# byte format the sweep, the sidecar stamps, and the chaos tests never
# see. The one pinned exception is resilience/policy.py's zlib.crc32 —
# a deterministic jitter HASH, not compression. The list shrinks, it
# does not grow.
CODEC_ALLOWED = {"dmlc_tpu/io/codec.py"}
CODEC_CRC_ALLOWED = {"dmlc_tpu/resilience/policy.py"}
_CODEC_MODULES = {"zlib", "gzip", "bz2", "lzma"}


def codec_lint(paths: List[str],
               trees: Optional[dict] = None) -> List[str]:
    """The codec gate: no direct compression-module imports in
    dmlc_tpu/ outside io/codec.py (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in CODEC_ALLOWED:
            continue
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module.split(".")[0]]
            hit = sorted(set(mods) & _CODEC_MODULES)
            if not hit:
                continue
            if rel in CODEC_CRC_ALLOWED and hit == ["zlib"]:
                continue  # the pinned crc32 jitter-hash use
            findings.append(
                f"{rel}:{node.lineno}: direct {'/'.join(hit)} import "
                "outside io/codec.py — page bytes compress through "
                "dmlc_tpu.io.codec (encode_page/decode_page) so the "
                "frame header, sidecar stamps and corruption handling "
                "stay one contract")
    return findings


# pyarrow is a BOUNDARY, not a dependency (ABI 8): the native engine
# decodes parquet pages itself, and the only package code allowed to
# lean on pyarrow is the frozen golden (data/parquet_parser.py — the
# byte-parity reference and the engine="auto" fallback) and
# bench_suite.py's corpus makers. A pyarrow import anywhere else would
# silently re-introduce the Python-bound decode wall the native lane
# exists to remove — and break the package on hosts without pyarrow.
# The list shrinks, it does not grow.
ARROW_ALLOWED = {"dmlc_tpu/data/parquet_parser.py",
                 "dmlc_tpu/bench_suite.py"}
_ARROW_MODULES = {"pyarrow"}


def arrow_lint(paths: List[str],
               trees: Optional[dict] = None) -> List[str]:
    """The pyarrow gate: imports confined to the parquet golden and
    the bench corpus makers (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in ARROW_ALLOWED:
            continue
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                mods = [node.module.split(".")[0]]
            hit = sorted(set(mods) & _ARROW_MODULES)
            if hit:
                findings.append(
                    f"{rel}:{node.lineno}: pyarrow import outside "
                    "data/parquet_parser.py — parquet decode goes "
                    "through the parser registry (format "
                    "'parquet_native': the ABI-8 native page decoder, "
                    "pyarrow-golden fallback), never an ad-hoc arrow "
                    "boundary")
    return findings


# Sampling/profiling is a SEAM (dmlc_tpu/obs/profile.py: one sampler
# thread, one byte-budgeted trie, one wait-classification, one
# /profile payload that watchdog reports, flight bundles and the
# hot_frames verdict evidence all read). A sys._current_frames walk or
# a cProfile/profile/pstats import elsewhere in the package would be a
# second profiler the plane never sees. The list shrinks, it does not
# grow.
PROFILE_ALLOWED = {"dmlc_tpu/obs/profile.py"}
_PROFILER_MODULES = {"cProfile", "profile", "pstats"}


def profile_lint(paths: List[str],
                 trees: Optional[dict] = None) -> List[str]:
    """The profile gate: sys._current_frames / profiler-module imports
    confined to obs/profile.py (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in PROFILE_ALLOWED:
            continue
        for node in ast.walk(tree):
            if ((isinstance(node, ast.Attribute)
                    and node.attr == "_current_frames"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in ("sys", "_sys"))
                    or (isinstance(node, ast.ImportFrom)
                        and node.module == "sys" and node.level == 0
                        and any(a.name == "_current_frames"
                                for a in node.names))):
                findings.append(
                    f"{rel}:{node.lineno}: sys._current_frames outside "
                    "obs/profile.py — the process has ONE sampling "
                    "profiler (obs.profile.install()/sample_now()); "
                    "read its trie, don't walk frames ad hoc")
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name.split(".")[0] for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                mods = [node.module.split(".")[0]]
            hit = sorted(set(mods) & _PROFILER_MODULES)
            if hit:
                findings.append(
                    f"{rel}:{node.lineno}: direct {'/'.join(hit)} "
                    "import outside obs/profile.py — profiling goes "
                    "through dmlc_tpu.obs.profile (StackProfiler / "
                    "hot_frames), one sampler per process")
    return findings


# Outbound HTTP is a SEAM: the objstore client modules
# (io/objstore/http_client.py — the real ranged-GET wire client —
# and io/objstore/peer.py — the gang /pages tier) plus obs/serve.py's
# scrape() are the ONLY package code that speaks http.client/
# urllib.request. Anywhere else, an ad-hoc urlopen would bypass the
# io.objstore.*/obs.scrape retry seams, the fault plans, and the
# byte counters that make remote traffic auditable. The list shrinks,
# it does not grow. (urllib.parse — pure string handling — is fine
# anywhere.)
HTTP_CLIENT_ALLOWED = {
    "dmlc_tpu/io/objstore/http_client.py",
    "dmlc_tpu/io/objstore/peer.py",
    "dmlc_tpu/obs/serve.py",
}
_HTTP_CLIENT_MODULES = {("http", "client"), ("urllib", "request")}


def http_client_lint(paths: List[str],
                     trees: Optional[dict] = None) -> List[str]:
    """The http-client gate: ``http.client``/``urllib.request``
    imports in dmlc_tpu/ confined to the objstore client modules and
    obs/serve.py (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in HTTP_CLIENT_ALLOWED:
            continue
        for node in ast.walk(tree):
            hits = []
            if isinstance(node, ast.Import):
                for a in node.names:
                    parts = tuple(a.name.split(".")[:2])
                    if parts in _HTTP_CLIENT_MODULES:
                        hits.append(a.name)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                parts = tuple(node.module.split(".")[:2])
                if parts in _HTTP_CLIENT_MODULES:
                    hits.append(node.module)
                elif node.module in ("http", "urllib"):
                    for a in node.names:
                        if (node.module, a.name) in \
                                _HTTP_CLIENT_MODULES:
                            hits.append(f"{node.module}.{a.name}")
            for hit in hits:
                findings.append(
                    f"{rel}:{node.lineno}: {hit} outside the objstore "
                    "client modules — outbound HTTP goes through "
                    "io/objstore/http_client.py, io/objstore/peer.py "
                    "or obs.serve.scrape() so retry seams, fault "
                    "plans and byte counters apply")
    return findings


# Raw sockets are a SEAM: dmlc_tpu/rendezvous/service.py is the ONE
# home for socket/socketserver construction (the TCP membership
# service, its line-protocol client transport, and the free-port
# probe parallel/launch.py re-exports), with obs/serve.py allowed for
# its HTTP plane (http.server builds on socketserver). Anywhere else,
# an ad-hoc socket would bypass the rendezvous wire protocol, the
# rendezvous.* retry seams, and the bounded-handler discipline. The
# list shrinks, it does not grow.
SOCKET_ALLOWED = {
    "dmlc_tpu/rendezvous/service.py",
    "dmlc_tpu/obs/serve.py",
}
_SOCKET_MODULES = {"socket", "socketserver"}


def socket_lint(paths: List[str],
                trees: Optional[dict] = None) -> List[str]:
    """The socket gate: ``socket``/``socketserver`` imports in
    dmlc_tpu/ confined to rendezvous/service.py and obs/serve.py
    (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in SOCKET_ALLOWED:
            continue
        for node in ast.walk(tree):
            hits = []
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] in _SOCKET_MODULES:
                        hits.append(a.name)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                if node.module.split(".")[0] in _SOCKET_MODULES:
                    hits.append(node.module)
            for hit in hits:
                findings.append(
                    f"{rel}:{node.lineno}: {hit} import outside "
                    "rendezvous/service.py — raw TCP goes through "
                    "the rendezvous wire protocol (service.call / "
                    "probe_free_ports) so the bounded-handler "
                    "discipline and rendezvous.* retry seams apply")
    return findings


# the two pre-resilience "skip this file and move on" handlers (spill
# sweeps): genuinely skip-not-retry, pinned. New code classifies and
# retries through dmlc_tpu.resilience instead.
OSERROR_CONTINUE_ALLOWED = {
    "dmlc_tpu/data/row_iter.py",
    "dmlc_tpu/parallel/sharded.py",
}
RETRY_LOOP_ALLOWED: set = set()
_IO_EXC_NAMES = {"OSError", "IOError", "EnvironmentError",
                 "ConnectionError", "TimeoutError"}


def _handler_catches_io(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in _IO_EXC_NAMES for n in names)


def _handler_swallows_io(handler: ast.ExceptHandler) -> bool:
    """Catches an I/O exception AND does not re-raise: a handler that
    converts to a typed error is classification, not a retry loop."""
    if not _handler_catches_io(handler):
        return False
    return not any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _walk_same_scope(stmts) -> List[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions — a sleep inside a worker closure defined in a loop is
    not that loop retrying."""
    out: List[ast.AST] = []
    stack = list(stmts)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _is_sleep_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("time", "_time")) or \
           (isinstance(f, ast.Name) and f.id == "sleep")


def resilience_lint(paths: List[str],
                    trees: Optional[dict] = None) -> List[str]:
    """The resilience gate: no hand-rolled sleep/retry loops and no
    naked ``except OSError: continue`` in dmlc_tpu/ outside
    dmlc_tpu/resilience/ (see module docstring)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel.startswith("dmlc_tpu/resilience/"):
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.ExceptHandler)
                    and _handler_catches_io(node)
                    and len(node.body) == 1
                    and isinstance(node.body[0], ast.Continue)
                    and rel not in OSERROR_CONTINUE_ALLOWED):
                findings.append(
                    f"{rel}:{node.lineno}: naked 'except OSError: "
                    "continue' — classify and retry through "
                    "dmlc_tpu.resilience (guarded()/RetryPolicy), or "
                    "log the skip")
            if (isinstance(node, (ast.While, ast.For))
                    and rel not in RETRY_LOOP_ALLOWED):
                body_nodes = _walk_same_scope(node.body)
                sleeps = any(_is_sleep_call(n) for n in body_nodes)
                catches = any(isinstance(n, ast.ExceptHandler)
                              and _handler_swallows_io(n)
                              for n in body_nodes)
                if sleeps and catches:
                    findings.append(
                        f"{rel}:{node.lineno}: hand-rolled sleep/"
                        "retry loop — use dmlc_tpu.resilience."
                        "RetryPolicy (guarded(site, fn)) so attempts/"
                        "backoff/classification are policy, not "
                        "control flow")
    return findings


# The steady path never iterates row payloads in Python (ISSUE 7: the
# engine's ABI-5 padded emission and the vectorized data.padding ops
# own the per-row work — PR 2 measured ~2× for eliminating one Python
# memcpy layer, and a `for row in block` loop is strictly worse).
# Inside dmlc_tpu/data/ and dmlc_tpu/pipeline/, a loop whose target is
# literally `row` or whose iterable is `range(<x>.size)`/
# `range(<x>.num_rows)` is per-row Python on the hot path. The golden
# Row protocol itself (RowBlock.__iter__/__getitem__ in
# data/rowblock.py — the DEBUGGING surface, not a steady-path stage)
# is pinned. The list shrinks, it does not grow.
ROW_LOOP_ALLOWED = {
    "dmlc_tpu/data/rowblock.py",
}
_ROW_LOOP_DIRS = ("dmlc_tpu/data/", "dmlc_tpu/pipeline/")


def _target_names(t: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(t) if isinstance(n, ast.Name)]


def _iter_is_per_row(it: ast.AST) -> bool:
    """range(X.size) / range(X.num_rows): a per-row index loop."""
    if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range"):
        for arg in it.args:
            for n in ast.walk(arg):
                if (isinstance(n, ast.Attribute)
                        and n.attr in ("size", "num_rows")):
                    return True
    return False


def row_loop_lint(paths: List[str],
                  trees: Optional[dict] = None) -> List[str]:
    """The steady-path gate: no per-row Python loops over block
    payloads in dmlc_tpu/data/ or dmlc_tpu/pipeline/ (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    msg = ("per-row Python loop on the steady path — rows are engine "
           "work (dtp_parser_next_padded) or vectorized numpy "
           "(data.padding); stages operate on whole blocks")
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if (not rel.startswith(_ROW_LOOP_DIRS)
                or rel in ROW_LOOP_ALLOWED):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.For):
                pairs = [(node.target, node.iter)]
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                pairs = [(g.target, g.iter) for g in node.generators]
            else:
                continue
            for tgt, it in pairs:
                if "row" in _target_names(tgt) or _iter_is_per_row(it):
                    findings.append(f"{rel}:{node.lineno}: {msg}")
    return findings


# The analysis-verdict schema (dmlc_tpu/obs/analyze.py VERDICT_KEYS):
# the /analyze endpoint, bench.py's embedded "analysis" block, config
# 13's acceptance assert, and scripts/obsctl.py all read THIS key set.
# The pin below is the one source of truth the gate checks everything
# against — change the schema by changing both, consciously.
VERDICT_KEYS = ("schema", "epoch", "verdict_id", "tenant", "bound",
                "band", "confidence", "evidence", "hot_frames",
                "stage_waits")
_ANALYZE_REL = "dmlc_tpu/obs/analyze.py"


def _const_str_keys(node: ast.Dict) -> Optional[List[str]]:
    """The dict's keys when ALL are string constants, else None (a
    dynamic key means the dict is not a literal verdict shape)."""
    keys = []
    for k in node.keys:
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.append(k.value)
    return keys


def verdict_lint(paths: List[str],
                 trees: Optional[dict] = None) -> List[str]:
    """The verdict-schema gate: every literal dict that claims to BE a
    verdict (carries both a "bound" and an "evidence" string key) must
    carry exactly the pinned VERDICT_KEYS, and obs/analyze.py's
    VERDICT_KEYS tuple must equal the pin. Scanned over dmlc_tpu/ and
    scripts/ — the CLI consumes the same schema."""
    if trees is None:
        trees = _parse_package_trees(paths)
    scan: List[tuple] = [trees[p] for p in paths if p in trees]
    scripts_dir = os.path.join(REPO, "scripts")
    for path in paths:
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        if not path.startswith(scripts_dir + os.sep):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                scan.append((rel, ast.parse(f.read(), filename=rel)))
        except (OSError, SyntaxError, UnicodeDecodeError):
            pass
    findings: List[str] = []
    pin_seen = False
    for rel, tree in scan:
        for node in ast.walk(tree):
            if (rel == _ANALYZE_REL and isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "VERDICT_KEYS"
                            for t in node.targets)):
                pin_seen = True
                v = node.value
                vals = (tuple(e.value for e in v.elts
                              if isinstance(e, ast.Constant))
                        if isinstance(v, (ast.Tuple, ast.List))
                        else None)
                if vals != VERDICT_KEYS:
                    findings.append(
                        f"{rel}:{node.lineno}: VERDICT_KEYS {vals!r} "
                        f"drifted from the lint pin {VERDICT_KEYS!r} "
                        "— the /analyze endpoint, bench JSON and "
                        "obsctl share this schema; change both "
                        "consciously")
            if isinstance(node, ast.Dict):
                keys = _const_str_keys(node)
                if (keys is not None and "bound" in keys
                        and "evidence" in keys
                        and "outcome" not in keys
                        and sorted(keys) != sorted(VERDICT_KEYS)):
                    # ("outcome" marks a control-plane DECISION record
                    # — it cites a verdict by id, it is not one; its
                    # shape is pinned by obs/control.py RECORD_KEYS)
                    findings.append(
                        f"{rel}:{node.lineno}: verdict-shaped dict "
                        f"with keys {sorted(keys)} != the pinned "
                        f"schema {sorted(VERDICT_KEYS)} — build "
                        "verdicts with dmlc_tpu.obs.analyze."
                        "attribute(), never by hand")
    if any(rel == _ANALYZE_REL for rel, _ in scan) and not pin_seen:
        findings.append(f"{_ANALYZE_REL}:0: VERDICT_KEYS tuple "
                        "missing (the verdict-schema gate pins it)")
    return findings


# Knob mutation is a PLANE, not a call-site choice: every steady-state
# tunable (queue capacities via set_capacity, the shard serve depth,
# the in-flight device window, the objstore coalesce/parallel/codec
# options) is moved ONLY by the exploration rails — the depth
# hill-climber (pipeline/autotune.py) and the verdict-driven
# controller (obs/control.py) — so every move lands in the decision
# ledger with the evidence that caused it. A direct set_capacity or
# configure(coalesce=...) elsewhere in the package would be a
# hand-tuned constant the /control surface never saw. Pinned
# exceptions: the modules that DEFINE the knobs (threaded_iter's
# set_capacity itself, graph.py's knob get/set closures and stage
# construction, sharded.py's initial depth) and pagestore's budget
# plumbing. The list shrinks, it does not grow.
KNOB_MUTATION_ALLOWED = {
    "dmlc_tpu/pipeline/autotune.py",   # the hill-climber (rails)
    "dmlc_tpu/obs/control.py",         # the verdict-driven controller
    "dmlc_tpu/pipeline/graph.py",      # knob closures defined here
    "dmlc_tpu/data/threaded_iter.py",  # set_capacity definition
    "dmlc_tpu/parallel/sharded.py",    # initial prefetch_depth
}
# configure(coalesce=/parallel=/codec_level=) additionally allowed
# where the option plane is DEFINED and where bench corpora set up
# measurement variants (a bench config comparing codec on/off is an
# experiment, not a hand-tuned steady-state constant)
KNOB_CONFIGURE_ALLOWED = KNOB_MUTATION_ALLOWED | {
    "dmlc_tpu/io/objstore/fs.py",      # configure() itself
    "dmlc_tpu/bench_suite.py",         # measurement variants
    "dmlc_tpu/bench_peer_worker.py",   # gang-bench wire setup
}
_KNOB_ATTRS = {"prefetch_depth", "window"}
_KNOB_CONFIGURE_KWARGS = {"coalesce", "parallel", "codec_level"}


def knob_lint(paths: List[str],
              trees: Optional[dict] = None) -> List[str]:
    """The knob gate: steady-state knob mutation (``.set_capacity()``
    calls, ``.prefetch_depth``/``.window`` attribute assignment,
    ``configure()`` with coalesce/parallel/codec_level) confined to
    the exploration rails (see KNOB_MUTATION_ALLOWED)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        for node in ast.walk(tree):
            if (rel not in KNOB_MUTATION_ALLOWED
                    and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "set_capacity"):
                findings.append(
                    f"{rel}:{node.lineno}: direct set_capacity() — "
                    "queue depths are knobs; move them through the "
                    "exploration rails (pipeline/autotune.py Autotuner "
                    "or obs/control.py Controller) so the decision "
                    "lands in the ledger")
            if (rel not in KNOB_MUTATION_ALLOWED
                    and isinstance(node, (ast.Assign, ast.AugAssign,
                                          ast.AnnAssign))):
                # every assignment form counts: plain, augmented
                # (`w.window += 8`), annotated, and tuple-unpack
                # targets. Only the ASSIGNED attribute itself matters
                # — a knob attribute READ inside a target (a subscript
                # index, an attribute-chain prefix) is not a mutation.
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                direct = []
                stack = list(targets)
                while stack:
                    t = stack.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        stack.extend(t.elts)
                    elif isinstance(t, ast.Starred):
                        stack.append(t.value)
                    elif isinstance(t, ast.Attribute):
                        direct.append(t)
                attrs = sorted({t.attr for t in direct
                                if t.attr in _KNOB_ATTRS})
                for attr in attrs:
                    findings.append(
                        f"{rel}:{node.lineno}: direct .{attr} "
                        "assignment — a steady-state knob moves "
                        "through the exploration rails "
                        "(autotune/control), never a hand-set "
                        "constant")
            if (rel not in KNOB_CONFIGURE_ALLOWED
                    and isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Attribute)
                          and node.func.attr == "configure")
                         or (isinstance(node.func, ast.Name)
                             and node.func.id == "configure"))):
                hit = sorted(kw.arg for kw in node.keywords
                             if kw.arg in _KNOB_CONFIGURE_KWARGS)
                if hit:
                    findings.append(
                        f"{rel}:{node.lineno}: configure("
                        f"{'/'.join(hit)}=...) outside the control "
                        "plane — the wire knobs (coalesce, parallel, "
                        "codec level) are moved by obs/control.py "
                        "against the /analyze verdict; see "
                        "docs/remote_io.md")
    return findings


def run_ruff(root: str = REPO) -> Optional[List[str]]:
    """ruff findings, or None when ruff is not installed."""
    cmd = None
    try:
        import ruff  # noqa: F401 — presence probe only
        cmd = [sys.executable, "-m", "ruff"]
    except ImportError:
        from shutil import which
        if which("ruff"):
            cmd = ["ruff"]
    if cmd is None:
        return None
    proc = subprocess.run(
        cmd + ["check", "--quiet", root],
        capture_output=True, text=True, timeout=300)
    if proc.returncode == 0:
        return []
    out = (proc.stdout + proc.stderr).strip()
    return [line for line in out.splitlines() if line.strip()]


def run_clang_format(root: str = NATIVE_SRC) -> Optional[List[str]]:
    """clang-format dry-run findings, or None when unavailable."""
    from shutil import which
    if which("clang-format") is None:
        return None
    files = native_files(root)
    if not files:
        return []
    proc = subprocess.run(
        ["clang-format", "--dry-run", "-Werror"] + files,
        capture_output=True, text=True, timeout=300)
    if proc.returncode == 0:
        return []
    return [line for line in proc.stderr.splitlines() if line.strip()]


# Thread construction in the pipeline layer is a BUDGET, not a
# call-site choice: the multi-tenant scheduler (pipeline/scheduler.py)
# owns the process's thread/queue budgets and time-slices them across
# tenants — a stage runner spawning its own threading.Thread or pool
# would be capacity the scheduler can neither see, bill, nor
# backpressure. Pipeline stages get concurrency by lowering onto the
# ALREADY-BUDGETED machinery (data/threaded_iter.ThreadedIter — the
# one audited producer-thread seam, whose capacities the scheduler
# rebalances — and the native engine's own pools). The list shrinks,
# it does not grow.
THREAD_ALLOWED = {
    "dmlc_tpu/pipeline/scheduler.py",  # the budget owner itself
}
_THREAD_DIR = "dmlc_tpu/pipeline/"
_POOL_NAMES = {"ThreadPoolExecutor", "ProcessPoolExecutor", "Pool"}


def thread_lint(paths: List[str],
                trees: Optional[dict] = None) -> List[str]:
    """The thread gate: threading.Thread / executor-pool construction
    in dmlc_tpu/pipeline/ confined to the scheduler module (see
    above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if not rel.startswith(_THREAD_DIR) or rel in THREAD_ALLOWED:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = None
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("threading", "_threading")
                    and f.attr == "Thread"):
                name = "threading.Thread"
            elif isinstance(f, ast.Name) and f.id == "Thread":
                name = "Thread"
            elif (isinstance(f, ast.Attribute)
                  and f.attr in _POOL_NAMES) \
                    or (isinstance(f, ast.Name)
                        and f.id in _POOL_NAMES):
                name = (f.attr if isinstance(f, ast.Attribute)
                        else f.id)
            if name:
                findings.append(
                    f"{rel}:{node.lineno}: {name} construction in the "
                    "pipeline layer outside scheduler.py — thread "
                    "capacity is a scheduler-owned budget; lower onto "
                    "ThreadedIter (data/threaded_iter.py) or the "
                    "native engine's pools instead")
    return findings


# Trace-context wire literals are a SEAM: dmlc_tpu/obs/rpc.py is the
# ONE home for the "X-Dmlc-Trace"/"X-Dmlc-Handle-Us" header names and
# the serialized context format. Every other module injects/extracts
# through rpc.inject()/rpc.extract() and the TRACE_HEADER/HANDLE_HEADER
# constants — a hand-spelled header string would silently fork the wire
# format the flow-linked gang timelines depend on. The list is one
# entry and stays one entry.
TRACE_HEADER_ALLOWED = {
    "dmlc_tpu/obs/rpc.py",
}
_TRACE_HEADER_LITERALS = {"X-Dmlc-Trace", "X-Dmlc-Handle-Us"}


def trace_header_lint(paths: List[str],
                      trees: Optional[dict] = None) -> List[str]:
    """The trace-header gate: the ``X-Dmlc-Trace``/``X-Dmlc-Handle-Us``
    wire literals in dmlc_tpu/ confined to obs/rpc.py (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in TRACE_HEADER_ALLOWED:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in _TRACE_HEADER_LITERALS:
                findings.append(
                    f"{rel}:{node.lineno}: {node.value!r} literal "
                    "outside obs/rpc.py — the trace-context wire "
                    "format is owned by dmlc_tpu.obs.rpc; use "
                    "rpc.TRACE_HEADER/rpc.HANDLE_HEADER and the "
                    "inject()/extract() helpers")
    return findings


# The slo.* metric family and the burn-rate alert thresholds belong to
# dmlc_tpu/obs/slo.py — ONE home for the alert math. A hand-spelled
# "slo.xxx" gauge elsewhere would fork the family the /slo surfaces
# render, and a re-spelled 14.4/6.0 would fork the SRE-workbook
# thresholds every consumer imports as FAST_BURN_RATE/SLOW_BURN_RATE.
SLO_ALLOWED = {
    "dmlc_tpu/obs/slo.py",
}
_BURN_RATE_LITERALS = {14.4, 6.0}
# non-alert uses of the bare numbers, pinned: supervise.py's 6.0 is a
# gang-teardown drain margin (deadline - 6.0), not burn-rate math
BURN_RATE_EXEMPT = {
    "dmlc_tpu/resilience/supervise.py",
}


def _slo_instrument_name(call: ast.Call) -> bool:
    """True when an instrument call's literal (or f-string) name sits
    in the slo.* family."""
    if not call.args:
        return False
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value.startswith("slo.")
    if isinstance(a, ast.JoinedStr) and a.values:
        first = a.values[0]
        return (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value.startswith("slo."))
    return False


def slo_lint(paths: List[str],
             trees: Optional[dict] = None) -> List[str]:
    """The SLO gate: ``slo.*`` instrument names and the burn-rate
    threshold floats confined to obs/slo.py (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if rel in SLO_ALLOWED:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _INSTRUMENT_METHODS
                    and _slo_instrument_name(node)):
                findings.append(
                    f"{rel}:{node.lineno}: slo.* instrument outside "
                    "obs/slo.py — the slo.* metric family is owned by "
                    "dmlc_tpu.obs.slo (the engine exports the "
                    "per-objective gauges itself)")
            elif (isinstance(node, ast.Constant)
                    and isinstance(node.value, float)
                    and node.value in _BURN_RATE_LITERALS
                    and rel not in BURN_RATE_EXEMPT):
                findings.append(
                    f"{rel}:{node.lineno}: burn-rate threshold "
                    f"{node.value!r} outside obs/slo.py — import "
                    "FAST_BURN_RATE/SLOW_BURN_RATE from "
                    "dmlc_tpu.obs.slo (one home for alert math)")
    return findings


# Seeded permutations are a SEAM: dmlc_tpu/shuffle/ (epoch_rng /
# GlobalShuffle) is the ONE home for RNG construction in the data
# path — ad-hoc `random` / `numpy.random` use inside dmlc_tpu/io/ or
# dmlc_tpu/data/ would mint a shuffle order the determinism contract
# (same seed ⇒ same global order at any world size, restart-stable
# resume) never sees. The list shrinks, it does not grow.
RANDOM_ALLOWED: set = set()
_RANDOM_DIRS = ("dmlc_tpu/io/", "dmlc_tpu/data/")


def random_lint(paths: List[str],
                trees: Optional[dict] = None) -> List[str]:
    """The random gate: ``random``/``numpy.random`` construction in
    dmlc_tpu/io/ + dmlc_tpu/data/ confined to dmlc_tpu/shuffle/
    (see above)."""
    if trees is None:
        trees = _parse_package_trees(paths)
    findings: List[str] = []
    for path in paths:
        if path not in trees:
            continue
        rel, tree = trees[path]
        if not rel.startswith(_RANDOM_DIRS) or rel in RANDOM_ALLOWED:
            continue
        for node in ast.walk(tree):
            hits = []
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    if root == "random" or a.name.startswith(
                            "numpy.random"):
                        hits.append(a.name)
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                mod = node.module or ""
                if mod == "random" or mod.startswith("numpy.random"):
                    hits.append(mod)
                elif mod == "numpy":
                    hits.extend(f"numpy.{a.name}" for a in node.names
                                if a.name == "random")
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "random"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in ("np", "numpy")):
                hits.append(f"{node.value.id}.random")
            for hit in hits:
                findings.append(
                    f"{rel}:{node.lineno}: {hit} in the data path — "
                    "seeded permutations have one home: draw epoch "
                    "randomness from dmlc_tpu.shuffle.permutation."
                    "epoch_rng (or lower onto GlobalShuffle) so the "
                    "determinism contract holds")
    return findings


def main() -> int:
    paths = python_files()
    findings = builtin_lint(paths)
    trees = _parse_package_trees(paths)  # one parse, both AST gates
    findings += obs_lint(paths, trees)
    findings += metric_lint(paths, trees)
    findings += resilience_lint(paths, trees)
    findings += io_seam_lint(paths, trees)
    findings += row_loop_lint(paths, trees)
    findings += verdict_lint(paths, trees)
    findings += knob_lint(paths, trees)
    findings += codec_lint(paths, trees)
    findings += arrow_lint(paths, trees)
    findings += profile_lint(paths, trees)
    findings += http_client_lint(paths, trees)
    findings += socket_lint(paths, trees)
    findings += thread_lint(paths, trees)
    findings += trace_header_lint(paths, trees)
    findings += slo_lint(paths, trees)
    findings += random_lint(paths, trees)
    ruff = run_ruff()
    if ruff is None:
        print("lint: ruff not installed — built-in checks only",
              file=sys.stderr)
    else:
        findings += ruff
    cf = run_clang_format()
    if cf is None:
        print("lint: clang-format not installed — native/src unchecked",
              file=sys.stderr)
    else:
        findings += cf
    for f in findings:
        print(f, file=sys.stderr)
    print(f"lint: {len(findings)} finding(s) over "
          f"{len(paths)} python files", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
