#!/usr/bin/env python
"""Repo lint gate (SURVEY §4's scripts/lint.py analogue).

Two layers, so the gate degrades instead of disappearing on hosts
without the tools baked in:

- **Built-in checks** (always run, stdlib only): every tracked .py file
  must parse (ast), use spaces-only indentation, carry no trailing
  whitespace, no CR line endings, and end with exactly one newline.
- **ruff** over the Python tree and **clang-format --dry-run -Werror**
  over native/src/ — run when the binaries are importable/installed,
  reported as skipped otherwise.

Wired into the pytest suite via tests/test_lint.py, so tier-1 fails on
a lint regression. CLI: ``python scripts/lint.py`` exits 0 clean / 1
with findings on stderr.
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {".git", ".claude", "__pycache__", ".pytest_cache", "build"}
NATIVE_SRC = os.path.join(REPO, "dmlc_tpu", "native", "src")


def python_files(root: str = REPO) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for name in filenames:
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def native_files(root: str = NATIVE_SRC) -> List[str]:
    if not os.path.isdir(root):
        return []
    return sorted(os.path.join(root, n) for n in os.listdir(root)
                  if n.endswith((".cc", ".h", ".cpp", ".hpp")))


def builtin_lint(paths: List[str]) -> List[str]:
    """Stdlib-only findings: ["path:line: message"]."""
    findings: List[str] = []
    for path in paths:
        rel = os.path.relpath(path, REPO)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            findings.append(f"{rel}:0: unreadable ({e})")
            continue
        if b"\r" in raw:
            findings.append(f"{rel}:0: CR line endings")
        try:
            text = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            findings.append(f"{rel}:0: not UTF-8 ({e})")
            continue
        try:
            ast.parse(text, filename=rel)
        except SyntaxError as e:
            findings.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        if text and not text.endswith("\n"):
            findings.append(f"{rel}:0: missing trailing newline")
        if text.endswith("\n\n"):
            findings.append(f"{rel}:0: trailing blank lines at EOF")
        for i, line in enumerate(text.split("\n"), 1):
            stripped = line.rstrip("\n")
            if stripped != stripped.rstrip():
                findings.append(f"{rel}:{i}: trailing whitespace")
            indent = stripped[:len(stripped) - len(stripped.lstrip())]
            if "\t" in indent:
                findings.append(f"{rel}:{i}: tab in indentation")
    return findings


def run_ruff(root: str = REPO) -> Optional[List[str]]:
    """ruff findings, or None when ruff is not installed."""
    cmd = None
    try:
        import ruff  # noqa: F401 — presence probe only
        cmd = [sys.executable, "-m", "ruff"]
    except ImportError:
        from shutil import which
        if which("ruff"):
            cmd = ["ruff"]
    if cmd is None:
        return None
    proc = subprocess.run(
        cmd + ["check", "--quiet", root],
        capture_output=True, text=True, timeout=300)
    if proc.returncode == 0:
        return []
    out = (proc.stdout + proc.stderr).strip()
    return [line for line in out.splitlines() if line.strip()]


def run_clang_format(root: str = NATIVE_SRC) -> Optional[List[str]]:
    """clang-format dry-run findings, or None when unavailable."""
    from shutil import which
    if which("clang-format") is None:
        return None
    files = native_files(root)
    if not files:
        return []
    proc = subprocess.run(
        ["clang-format", "--dry-run", "-Werror"] + files,
        capture_output=True, text=True, timeout=300)
    if proc.returncode == 0:
        return []
    return [line for line in proc.stderr.splitlines() if line.strip()]


def main() -> int:
    findings = builtin_lint(python_files())
    ruff = run_ruff()
    if ruff is None:
        print("lint: ruff not installed — built-in checks only",
              file=sys.stderr)
    else:
        findings += ruff
    cf = run_clang_format()
    if cf is None:
        print("lint: clang-format not installed — native/src unchecked",
              file=sys.stderr)
    else:
        findings += cf
    for f in findings:
        print(f, file=sys.stderr)
    print(f"lint: {len(findings)} finding(s) over "
          f"{len(python_files())} python files", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
