"""Pipeline quickstart: the declarative dataset-graph composition layer.

One chain replaces the hand-wired InputSplit → Parser → ThreadedIter →
device-transfer stack (dmlc_tpu.pipeline; docs/pipeline.md):

  1. declare:   from_uri → parse → batch → prefetch → to_device
  2. run:       iterate the built pipeline, one epoch per pass
  3. observe:   per-stage stats snapshot (throughput, wait, occupancy)
  4. tune:      the autotuner adjusts "auto" depths between epochs
  5. shard:     the same graph lowers to multi-device global batches
"""

import os

import numpy as np

from dmlc_tpu.pipeline import Pipeline


def make_data(path: str, rows: int = 20000) -> str:
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for i in range(rows):
            nnz = rng.randint(4, 12)
            idx = np.sort(rng.choice(1000, nnz, replace=False))
            f.write(f"{i % 2} " + " ".join(
                f"{j}:{v:.4f}" for j, v in zip(idx, rng.rand(nnz))) + "\n")
    return path


def main() -> None:
    import jax
    from dmlc_tpu.io.tempdir import TemporaryDirectory

    with TemporaryDirectory() as tmp:
        uri = make_data(os.path.join(tmp.path, "train.libsvm"))

        # 1-2. declare the graph, run two epochs on the default device
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm")
                 .batch(4096)
                 .prefetch(depth="auto")
                 .to_device(jax.devices()[0], window="auto")
                 .build(autotune=True))
        for epoch in range(2):
            batches = rows = 0
            for batch in built:
                batches += 1
                rows += int(batch["offset"].shape[0]) - 1
            print(f"epoch {epoch}: {batches} device batches, {rows} rows")

        # 3. per-stage telemetry of the last epoch
        snap = built.stats()
        for st in snap["stages"]:
            occ = (f" occupancy={st['queue_occupancy']:.2f}"
                   if st["queue_occupancy"] is not None else "")
            print(f"  stage {st['name']}: items={st['items']} "
                  f"rows={st['rows']} wait={st['wait_s']:.3f}s{occ}")

        # 4. the depths the autotuner owns (vs the old constants)
        report = built.autotune_report()
        print(f"autotuned knobs: {report['values']} "
              f"(changed: {report['tuned'] or 'none yet'})")
        built.close()

        # 5. the same declarative graph, sharded over every device
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()), ("data",))
        sharded = (Pipeline.from_uri(uri)
                   .parse(format="libsvm")
                   .shard(mesh, row_bucket=1 << 10, nnz_bucket=1 << 14)
                   .build())
        total = 0
        for batch in sharded:
            # one global jax.Array per field, device-sharded on dim 0
            assert batch["offset"].shape[0] == len(jax.devices())
            total += int(np.sum(np.asarray(batch["num_rows"])))
        print(f"sharded: {total} rows across {len(jax.devices())} devices")
        sharded.close()
        print("pipeline quickstart OK")


if __name__ == "__main__":
    main()
