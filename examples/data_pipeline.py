"""Data-pipeline tour: RecordIO, sharded splits, shuffle, prefetch, cache.

The IO layer on its own (no model, no mesh) — the TPU-native equivalents
of the reference's stream/split/record stack (reference: include/dmlc/io.h,
include/dmlc/recordio.h, src/io/*):

  1. write a multi-part RecordIO dataset (magic-escape framing)
  2. read it back sharded: every part_index sees a disjoint, complete
     slice of records regardless of how record boundaries straddle the
     byte-range cuts
  3. shuffled split: chunk-level shuffle with a derandomizable seed
  4. threaded split: background chunk prefetch (ThreadedIter semantics)
  5. #cache URIs: first pass writes a local replay cache
"""

import os

import numpy as np

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.input_split_shuffle import InputSplitShuffle
from dmlc_tpu.io.recordio import RecordIOWriter
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.io.tempdir import TemporaryDirectory


def main() -> None:
    rng = np.random.RandomState(0)
    with TemporaryDirectory() as tmp:
        # 1. multi-part RecordIO dataset
        paths = []
        payloads = []
        for part in range(3):
            p = os.path.join(tmp.path, f"data.part{part}.rec")
            paths.append(p)
            with create_stream(p, "w") as s:
                w = RecordIOWriter(s)
                for _ in range(200):
                    rec = rng.bytes(rng.randint(100, 3000))
                    payloads.append(rec)
                    w.write_record(rec)
        uri = ";".join(paths)

        # 2. sharded read: 4 workers, disjoint + complete
        seen = []
        for k in range(4):
            sp = InputSplit.create(uri, k, 4, "recordio")
            n = 0
            for rec in sp:
                seen.append(bytes(rec))
                n += 1
            print(f"worker {k}: {n} records")
        assert sorted(seen) == sorted(payloads), "coverage/no-overlap broken"

        # 3. chunk-shuffled split (same seed -> same order)
        a = [bytes(r) for r in InputSplitShuffle.create(
            uri, 0, 1, "recordio", num_shuffle_parts=8, seed=7)]
        b = [bytes(r) for r in InputSplitShuffle.create(
            uri, 0, 1, "recordio", num_shuffle_parts=8, seed=7)]
        assert a == b and sorted(a) == sorted(payloads)
        print(f"shuffled split: deterministic order of {len(a)} records")

        # 4. background prefetch wrapper
        from dmlc_tpu.io.threaded_split import ThreadedInputSplit
        sp = ThreadedInputSplit(InputSplit.create(uri, 0, 1, "recordio"))
        n = sum(1 for _ in sp)
        print(f"threaded split: {n} records prefetched on a reader thread")

        # 5. cache URI: replay from local cache on the second pass
        cache = os.path.join(tmp.path, "replay.cache")
        for _ in range(2):
            sp = InputSplit.create(f"{paths[0]}#{cache}", 0, 1, "recordio")
            sum(1 for _ in sp)
        # cache files are shard-namespaced (.pK-N) with a .done commit marker
        print(f"cached split: cache file exists="
              f"{os.path.exists(cache + '.p0-1')}")


if __name__ == "__main__":
    main()
