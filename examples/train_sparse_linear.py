"""End-to-end example: sharded libsvm ingest -> data-parallel training ->
sharded checkpoint -> resume.

The full dmlc_tpu stack in one script (the TPU-native analogue of the
reference's downstream usage: InputSplit -> Parser -> RowBlockIter feeding
a learner, reference: test/dataiter_test.cc + docs):

  1. generate a libsvm training file from a hidden linear rule
  2. ShardedRowBlockIter: every device reads its own InputSplit partition,
     blocks are padded/stacked/assembled into global sharded jax.Arrays
  3. SparseLinearModel under shard_map: per-device CSR SpMV forward,
     psum-reduced logistic loss, SGD on replicated params
  4. ShardedCheckpoint save / restore, then training resumes

Runs anywhere: on a CPU-only host it uses 8 virtual devices (set before
jax import). On a TPU slice, drop the XLA_FLAGS override and launch one
process per host (python -m dmlc_tpu.parallel.launch --help).
"""

import os
import time

# default to an 8-virtual-device CPU mesh when the environment hasn't
# picked a working accelerator platform itself (XLA_FLAGS is read at
# backend init, so setting it here still takes effect)
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    # env var alone can be overridden by an installed accelerator plugin;
    # the config update is authoritative (same pattern as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:  # preset platform unavailable -> CPU fallback
        jax.config.update("jax_platforms", "cpu")

from dmlc_tpu.models import SparseLinearModel  # noqa: E402
from dmlc_tpu.parallel import ShardedRowBlockIter  # noqa: E402
from dmlc_tpu.io.checkpoint import ShardedCheckpoint  # noqa: E402
from dmlc_tpu.io.tempdir import TemporaryDirectory  # noqa: E402

NUM_FEATURES = 2048
NUM_ROWS = 20_000
EPOCHS = 4


def make_dataset(path: str, seed: int = 0) -> np.ndarray:
    """libsvm file whose labels follow a hidden sparse linear rule."""
    rng = np.random.RandomState(seed)
    w_true = np.zeros(NUM_FEATURES, np.float32)
    hot = rng.choice(NUM_FEATURES, 64, replace=False)
    w_true[hot] = rng.randn(64)
    with open(path, "w") as f:
        for _ in range(NUM_ROWS):
            nnz = rng.randint(8, 40)
            idx = np.sort(rng.choice(NUM_FEATURES, nnz, replace=False))
            val = rng.rand(nnz).astype(np.float32)
            margin = float((val * w_true[idx]).sum())
            label = 1 if margin > 0.5 else 0
            f.write(f"{label} "
                    + " ".join(f"{j}:{v:.6f}" for j, v in zip(idx, val))
                    + "\n")
    return w_true


def main() -> None:
    devices = np.array(jax.devices())
    mesh = Mesh(devices.reshape(-1), ("data",))
    print(f"mesh: {len(devices)} devices on axis 'data'")

    with TemporaryDirectory() as tmp:
        data = os.path.join(tmp.path, "train.libsvm")
        make_dataset(data)

        model = SparseLinearModel(NUM_FEATURES, learning_rate=0.5)
        params = {"w": jnp.zeros(NUM_FEATURES, jnp.float32),
                  "b": jnp.zeros((), jnp.float32)}
        step_fn = model.make_sharded_train_step(mesh)

        ckpt = ShardedCheckpoint(os.path.join(tmp.path, "ckpt"))
        # ONE iterator for the whole run (recreating it per epoch would
        # re-parse and re-agree every time): single-process runs stream
        # epoch 0, re-parse + tee epoch 1, and REPLAY the retained
        # rounds from memory thereafter (steady_replay, r5) — watch the
        # per-epoch 'parsed'/'replayed' tag below
        train_iter = ShardedRowBlockIter(data, mesh, format="libsvm",
                                         row_bucket=256, nnz_bucket=8192)
        step = 0
        for epoch in range(EPOCHS):
            losses = []
            replays_before = train_iter.replay_epochs
            t0 = time.perf_counter()
            for batch in train_iter:
                params, loss = step_fn(params, batch)
                losses.append(float(loss))
                step += 1
            wall = time.perf_counter() - t0
            src = ("replayed" if train_iter.replay_epochs > replays_before
                   else "parsed")
            print(f"epoch {epoch}: mean loss {np.mean(losses):.4f} "
                  f"({step} steps, {wall:.2f}s, {src})")
            ckpt.save(step, params)

        # simulate a restart: restore latest checkpoint and take one step
        restored, _meta = ckpt.restore(like=params)
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.asarray(params["w"]))
        for batch in ShardedRowBlockIter(data, mesh, format="libsvm",
                                         row_bucket=256, nnz_bucket=8192):
            restored, loss = step_fn(restored, batch)
            break
        print(f"resumed from step {ckpt.latest_step()}, "
              f"next-step loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
