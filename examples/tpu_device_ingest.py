"""Device-resident ingest via the tpu:// URI scheme.

Demonstrates the north-star path end to end (BASELINE.json: "Stream/
SeekStream gain a tpu:// URI that DMAs RecordIO chunks straight to
device"):

1. write a RecordIO dataset (records containing aligned magic bytes, so
   the escape framing is exercised),
2. stream it into device memory as raw chunks (TPUSeekStream.device_chunks:
   async transfers with a lookahead window),
3. ingest it sharded as record batches straight to the device
   (recordio_device_batches: zero host-side record copy with the native
   engine), and reduce over the payload on device.

Runs on an 8-virtual-device CPU mesh by default; on a TPU host the same
code lands the batches in HBM.
"""

import os
import struct

if "JAX_PLATFORMS" not in os.environ:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"].split(",")[0])

from dmlc_tpu.io import RECORDIO_MAGIC, RecordIOWriter, create_stream
from dmlc_tpu.io.stream import create_seek_stream_for_read
from dmlc_tpu.io.tpu_fs import recordio_device_batches


def main() -> None:
    path = "/tmp/dmlc_tpu_example.rec"
    rng = np.random.RandomState(0)
    magic = struct.pack("<I", RECORDIO_MAGIC)
    records = []
    with open(path, "wb") as fh:
        w = RecordIOWriter(fh)
        for i in range(500):
            rec = (magic * 2 + rng.bytes(rng.randint(10, 400))
                   if i % 9 == 0 else rng.bytes(rng.randint(1, 2000)))
            records.append(rec)
            w.write_record(rec)
    print(f"wrote {len(records)} records "
          f"({os.path.getsize(path) / 1e6:.1f} MB, "
          f"{w.escaped_magic_count} escaped magics)")

    # --- raw device chunks through the tpu:// stream
    s = create_seek_stream_for_read(f"tpu://{path}")
    total = 0
    nchunks = 0
    for chunk in s.device_chunks(chunk_bytes=256 * 1024, lookahead=2):
        chunk = jax.block_until_ready(chunk)
        total += chunk.size
        nchunks += 1
    s.close()
    print(f"device_chunks: {nchunks} chunks, {total} bytes on "
          f"{jax.devices()[0].platform}")

    # --- sharded record batches straight to device + on-device reduce
    ndev = min(4, len(jax.devices()))
    checksum = 0  # host-side accumulation: per-part sums live on
    nrec = 0      # DIFFERENT devices and must not be added under jit
    for part in range(ndev):
        dev = jax.devices()[part]
        for batch in recordio_device_batches(f"tpu://{path}", part, ndev,
                                             device=dev):
            payload, starts, ends = (batch["payload"], batch["starts"],
                                     batch["ends"])
            nrec += int(starts.shape[0])
            # on-device reduction over the RECORD bytes only: the
            # payload buffer is the raw chunk, so frame headers sit
            # between record spans — mask them out with a +1/-1
            # scatter + cumsum coverage (spans never overlap)
            n = payload.shape[0]
            delta = (jnp.zeros(n + 1, jnp.int32)
                     .at[starts].add(1).at[ends].add(-1))
            covered = jnp.cumsum(delta[:-1]) > 0
            part_sum = jnp.sum(jnp.where(covered,
                                         payload.astype(jnp.uint32), 0))
            checksum = (checksum + int(part_sum)) % (1 << 32)
    expect = sum(sum(r) for r in records) % (1 << 32)
    got = checksum
    assert got == expect, (got, expect)
    assert nrec == len(records)
    print(f"recordio_device_batches: {nrec} records across {ndev} "
          f"device shards, on-device checksum OK ({got})")


if __name__ == "__main__":
    main()
