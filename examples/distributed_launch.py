"""Multi-process distributed training via the launcher + rendezvous.

The reference launches jobs with `dmlc-submit --cluster local -n N` and
a socket tracker; here the same shape is `launch_local` + the
`jax.distributed` coordinator (see dmlc_tpu.parallel.launch for the
reference-compatible `DMLC_*` env contract).

Run directly: this script re-executes ITSELF as 2 worker processes
(`--worker`), each holding 2 virtual CPU devices. The workers rendezvous,
build one global 4-device mesh, stream disjoint shards through
ShardedRowBlockIter, train a SparseLinearModel collectively (gradients
psum over the data axis by construction), checkpoint, and exit. The
parent then restores the checkpoint single-process and prints the loss.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
DATA = "/tmp/dmlc_tpu_example_dist.libsvm"
CKPT = "/tmp/dmlc_tpu_example_dist_ckpt"
NUM_FEATURES = 512


def make_data() -> None:
    import numpy as np
    rng = np.random.RandomState(0)
    with open(DATA, "w") as f:
        for i in range(4000):
            idx = np.sort(rng.choice(NUM_FEATURES, rng.randint(2, 10),
                                     replace=False))
            feats = " ".join(f"{j}:{rng.rand():.4f}" for j in idx)
            f.write(f"{i % 2} {feats}\n")


def worker() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from dmlc_tpu.io.checkpoint import ShardedCheckpoint
    from dmlc_tpu.models import SparseLinearModel
    from dmlc_tpu.parallel.launch import finalize, init_from_env
    from dmlc_tpu.parallel.sharded import ShardedRowBlockIter

    rank, world = init_from_env()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    model = SparseLinearModel(NUM_FEATURES, learning_rate=0.5)
    params = jax.device_put(model.init_params(), NamedSharding(mesh, P()))
    step = model.make_sharded_train_step(mesh)
    it = ShardedRowBlockIter(DATA, mesh, format="libsvm",
                             row_bucket=256, nnz_bucket=2048)
    loss = None
    for _epoch in range(3):
        for batch in it:
            params, loss = step(params, batch)
    ShardedCheckpoint(CKPT).save(1, params,
                                 metadata={"loss": float(loss)})
    print(f"[worker {rank}/{world}] devices={len(jax.devices())} "
          f"final loss={float(loss):.4f}", flush=True)
    finalize()


def main() -> None:
    from dmlc_tpu.parallel.launch import launch_local

    make_data()
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep)),
    }
    launch_local(2, [sys.executable, os.path.abspath(__file__), "--worker"],
                 env=env, timeout=600)

    # restore on the parent (different process count: resharding-legal)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dmlc_tpu.io.checkpoint import ShardedCheckpoint
    flat, meta = ShardedCheckpoint(CKPT).restore()
    print(f"parent restored params w[:4]={flat['w'][:4].tolist()} "
          f"trained loss={meta['loss']:.4f}")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        main()
