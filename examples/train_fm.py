"""End-to-end example: libfm ingest -> FM and field-aware FFM training.

The libfm format family closed into a loop: LibFMParser (reference:
src/data/libfm_parser.h) parses field:index:value text, and
SparseFMModel — the second-order FM that format family exists to feed —
trains on the resulting CSR batches under shard_map — followed by
SparseFFMModel, which additionally consumes the parsed field[] column
(fields flow text -> parser -> padded batch -> device). The training data
follows a pure INTERACTION rule (label = XOR over feature pairs), which
a linear model provably cannot fit and the FM's pairwise term can.

Runs anywhere: on a CPU-only host it uses 8 virtual devices.
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402
import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:  # preset platform unavailable -> CPU fallback
        jax.config.update("jax_platforms", "cpu")

from dmlc_tpu.models import SparseFFMModel, SparseFMModel  # noqa: E402
from dmlc_tpu.parallel import ShardedRowBlockIter  # noqa: E402
from dmlc_tpu.io.tempdir import TemporaryDirectory  # noqa: E402

NPAIRS = 4
NCOL = 2 * NPAIRS + 2   # pair features + 2 context features
ROWS = 320
EPOCHS = 60


def make_libfm(path: str) -> None:
    """label = XOR(which side of a pair fired, context bit): zero linear
    signal by construction."""
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(ROWS):
            a, b, cbit = rng.randint(NPAIRS), rng.randint(2), rng.randint(2)
            feats = sorted({2 * a + b, 2 * NPAIRS + cbit})
            y = 1 if b == cbit else 0
            # field:index:value — field 0 = pair features, 1 = context
            # (plain FM ignores fields; the FFM below consumes them)
            toks = " ".join(
                f"{0 if j < 2 * NPAIRS else 1}:{j}:1" for j in feats)
            f.write(f"{y} {toks}\n")


def main() -> None:
    with TemporaryDirectory() as tmp:
        data = os.path.join(tmp.path, "train.libfm")
        make_libfm(data)

        mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        print(f"mesh: {mesh.devices.size} devices on "
              f"{jax.devices()[0].platform}")

        it = ShardedRowBlockIter(data, mesh, format="libfm",
                                 row_bucket=64, nnz_bucket=256)
        batches = list(it)
        model = SparseFMModel(NCOL, num_factors=4, learning_rate=1.0)
        params = jax.device_put(model.init_params(seed=2))
        step = model.make_sharded_train_step(mesh)
        # field-aware FFM on the same batches: the field[] column the
        # libfm parser filled is consumed on device
        ffm = SparseFFMModel(NCOL, num_fields=2, num_factors=4,
                             learning_rate=1.0)
        fparams = jax.device_put(ffm.init_params(seed=2))
        fstep = ffm.make_sharded_train_step(mesh)

        ffm.validate_batch(batches[0])  # field ids fit num_fields

        # compile BOTH programs up front: on a starved shared host, a
        # multi-second XLA compile wedged between training loops can
        # stall one virtual device past the CPU collectives' rendezvous
        # timeout — front-loading the compiles keeps the loops' tiny
        # per-step executions as the only collective work
        _, loss0 = step(params, batches[0])
        _, f0 = fstep(fparams, batches[0])

        def train(step_fn, p, tag):
            for epoch in range(EPOCHS):
                for batch in batches:
                    p, loss = step_fn(p, batch)
                # per-epoch sync bounds the async dispatch backlog: on a
                # starved shared host, hundreds of queued 8-device
                # collectives can spread one collective's thread
                # arrivals past the CPU rendezvous watchdog
                loss = float(loss)
                if (epoch + 1) % 20 == 0:
                    print(f"{tag} epoch {epoch + 1}: loss {loss:.4f}")
            _, final = step_fn(p, batches[0])
            return float(final)

        loss1 = train(step, params, "FM")
        print(f"loss {float(loss0):.4f} -> {loss1:.4f} "
              f"(pure-interaction rule: a linear model stays ~0.69)")
        assert loss1 < 0.3, "FM failed to learn the XOR rule"

        f1 = train(fstep, fparams, "FFM")
        print(f"FFM: loss {float(f0):.4f} -> {f1:.4f} "
              f"(field[] parsed from text and consumed on device)")
        assert f1 < 0.3, "FFM failed to learn the XOR rule"
        print("OK")


if __name__ == "__main__":
    main()
