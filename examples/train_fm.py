"""End-to-end example: libfm ingest -> factorization-machine training.

The libfm format family closed into a loop: LibFMParser (reference:
src/data/libfm_parser.h) parses field:index:value text, and
SparseFMModel — the second-order FM that format family exists to feed —
trains on the resulting CSR batches under shard_map. The training data
follows a pure INTERACTION rule (label = XOR over feature pairs), which
a linear model provably cannot fit and the FM's pairwise term can.

Runs anywhere: on a CPU-only host it uses 8 virtual devices.
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402
import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:  # preset platform unavailable -> CPU fallback
        jax.config.update("jax_platforms", "cpu")

from dmlc_tpu.models import SparseFMModel  # noqa: E402
from dmlc_tpu.parallel import ShardedRowBlockIter  # noqa: E402
from dmlc_tpu.io.tempdir import TemporaryDirectory  # noqa: E402

NPAIRS = 4
NCOL = 2 * NPAIRS + 2   # pair features + 2 context features
ROWS = 320
EPOCHS = 60


def make_libfm(path: str) -> None:
    """label = XOR(which side of a pair fired, context bit): zero linear
    signal by construction."""
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(ROWS):
            a, b, cbit = rng.randint(NPAIRS), rng.randint(2), rng.randint(2)
            feats = sorted({2 * a + b, 2 * NPAIRS + cbit})
            y = 1 if b == cbit else 0
            # field:index:value — field 0 = pair features, 1 = context
            # (plain FM ignores fields; an FFM extension would use them)
            toks = " ".join(
                f"{0 if j < 2 * NPAIRS else 1}:{j}:1" for j in feats)
            f.write(f"{y} {toks}\n")


def main() -> None:
    with TemporaryDirectory() as tmp:
        data = os.path.join(tmp.path, "train.libfm")
        make_libfm(data)

        mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        print(f"mesh: {mesh.devices.size} devices on "
              f"{jax.devices()[0].platform}")

        it = ShardedRowBlockIter(data, mesh, format="libfm",
                                 row_bucket=64, nnz_bucket=256)
        batches = list(it)
        model = SparseFMModel(NCOL, num_factors=4, learning_rate=1.0)
        params = jax.device_put(model.init_params(seed=2))
        step = model.make_sharded_train_step(mesh)

        _, loss0 = step(params, batches[0])
        for epoch in range(EPOCHS):
            for batch in batches:
                params, loss = step(params, batch)
            if (epoch + 1) % 20 == 0:
                print(f"epoch {epoch + 1}: loss {float(loss):.4f}")
        _, loss1 = step(params, batches[0])
        print(f"loss {float(loss0):.4f} -> {float(loss1):.4f} "
              f"(pure-interaction rule: a linear model stays ~0.69)")
        assert float(loss1) < 0.3, "FM failed to learn the XOR rule"
        print("OK")


if __name__ == "__main__":
    main()
