"""End-to-end example: libsvm-with-qid ingest -> pairwise ranking.

The qid column closed into a loop: the libsvm parser (reference:
src/data/libsvm_parser.h ``qid:`` tokens) fills RowBlock.qid, the
sharded ingest pads it (-1) into device batches, and SparseRankingModel
— the rank:pairwise objective that column exists to feed — trains under
shard_map. The data is query-grouped with graded relevance from a
hidden scorer, so pairwise accuracy provably rises.

Runs anywhere: on a CPU-only host it uses 8 virtual devices.
"""

import os

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402
import jax  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:  # preset platform unavailable -> CPU fallback
        jax.config.update("jax_platforms", "cpu")

from dmlc_tpu.models import SparseRankingModel  # noqa: E402
from dmlc_tpu.parallel import ShardedRowBlockIter  # noqa: E402
from dmlc_tpu.io.tempdir import TemporaryDirectory  # noqa: E402

NCOL = 32
NQUERIES = 64
DOCS_PER_Q = 6
EPOCHS = 40


def make_ranking_libsvm(path: str) -> None:
    """Query-grouped rows with graded labels (0/1/2) from a hidden
    linear scorer — the signal pairwise training should recover."""
    rng = np.random.RandomState(0)
    w_true = np.random.RandomState(7).randn(NCOL)
    with open(path, "w") as f:
        for q in range(NQUERIES):
            for _ in range(DOCS_PER_Q):
                nnz = rng.randint(3, 8)
                idx = np.sort(rng.choice(NCOL, nnz, replace=False))
                vals = rng.rand(nnz)
                score = float((vals * w_true[idx]).sum())
                grade = int(np.digitize(score, [0.6, 1.4]))
                feats = " ".join(f"{j}:{v:.4f}" for j, v in zip(idx, vals))
                f.write(f"{grade} qid:{q} {feats}\n")


def main() -> None:
    with TemporaryDirectory() as tmp:
        data = os.path.join(tmp.path, "train.libsvm")
        make_ranking_libsvm(data)

        mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
        print(f"mesh: {mesh.devices.size} devices on "
              f"{jax.devices()[0].platform}")

        # modest row bucket: the pairwise loss is O(row_bucket^2)
        it = ShardedRowBlockIter(data, mesh, format="libsvm",
                                 row_bucket=64, nnz_bucket=512)
        batches = list(it)
        model = SparseRankingModel(NCOL, learning_rate=1.0)
        model.validate_batch(batches[0])  # qid flowed to the device
        params = jax.device_put(model.init_params())
        step = model.make_sharded_train_step(mesh)

        # accuracy evaluated per device block (a flat concatenation
        # would need offsets rebuilt; the per-device view is exact)
        def accuracy(p):
            accs = []
            for b in batches:
                hb = {k: np.asarray(v) for k, v in b.items()}
                for d in range(hb["label"].shape[0]):
                    flat = {k: hb[k][d] for k in
                            ("offset", "index", "value", "label",
                             "weight", "qid")}
                    a = model.pairwise_accuracy(p, flat)
                    if np.isfinite(a):
                        accs.append(a)
            return float(np.mean(accs))

        acc0 = accuracy(jax.device_get(params))
        for epoch in range(EPOCHS):
            for batch in batches:
                params, loss = step(params, batch)
            loss = float(loss)  # per-epoch sync (see train_fm.py)
            if (epoch + 1) % 10 == 0:
                print(f"epoch {epoch + 1}: pairwise loss {loss:.4f}")
        acc1 = accuracy(jax.device_get(params))
        print(f"pairwise accuracy {acc0:.3f} -> {acc1:.3f} "
              f"(qid groups parsed from text, pairs formed on device)")
        assert acc1 > max(acc0, 0.8), (acc0, acc1)
        print("OK")


if __name__ == "__main__":
    main()
