"""Launcher + rendezvous (reference: tracker/dmlc_tracker — local backend,
env contract, ring/tree topology)."""

import os
import subprocess
import sys

import pytest

from dmlc_tpu.parallel.launch import (
    find_free_port, find_free_ports, get_link_map, get_ring, get_tree,
    launch_local, launch_ssh, worker_envs, main,
)
from dmlc_tpu.utils.logging import DMLCError


class TestTopology:
    @pytest.mark.parametrize("n", [1, 2, 3, 8, 17])
    def test_ring_properties(self, n):
        ring = get_ring(n)
        assert len(ring) == n
        for r, (prev, nxt) in ring.items():
            assert ring[nxt][0] == r  # my next's prev is me
            assert ring[prev][1] == r
        # walking next pointers visits every rank once
        seen, r = [], 0
        for _ in range(n):
            seen.append(r)
            r = ring[r][1]
        assert sorted(seen) == list(range(n)) and r == 0

    @pytest.mark.parametrize("n", [1, 2, 5, 16])
    def test_tree_properties(self, n):
        tree = get_tree(n)
        assert tree[0] == -1
        for r in range(1, n):
            assert 0 <= tree[r] < r  # parents precede children: acyclic
        links = get_link_map(n)
        assert sum(len(v) for v in links.values()) == 2 * (n - 1)
        for r, neigh in links.items():
            for m in neigh:
                assert r in links[m]  # symmetric

    def test_bad_n(self):
        with pytest.raises(DMLCError):
            get_ring(0)


class TestEnvContract:
    def test_worker_envs(self):
        envs = worker_envs("10.0.0.1:9000", 4, 2)
        assert envs["DMLC_TPU_COORDINATOR_URI"] == "10.0.0.1:9000"
        assert envs["DMLC_TPU_NUM_WORKER"] == "4"
        assert envs["DMLC_TPU_TASK_ID"] == "2"
        # reference names present for downstream compatibility
        assert envs["DMLC_TRACKER_URI"] == "10.0.0.1"
        assert envs["DMLC_TRACKER_PORT"] == "9000"
        assert envs["DMLC_NUM_WORKER"] == "4"
        assert envs["DMLC_TASK_ID"] == "2"
        assert envs["DMLC_ROLE"] == "worker"

    def test_find_free_port(self):
        p = find_free_port()
        assert 0 < p < 65536

    def test_find_free_ports_distinct(self):
        # ADVICE r5: probes held open together must never hand out the
        # same port twice (jax coordinator vs PS root collision)
        ports = find_free_ports(8)
        assert len(set(ports)) == 8
        assert all(0 < p < 65536 for p in ports)

    def test_find_free_ports_bad_n(self):
        with pytest.raises(DMLCError):
            find_free_ports(0)


class TestLocalLaunch:
    def test_spawns_workers_with_ranks(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(
            "import os\n"
            "rank = os.environ['DMLC_TPU_TASK_ID']\n"
            "n = os.environ['DMLC_TPU_NUM_WORKER']\n"
            f"open(r'{tmp_path}' + f'/out-{{rank}}', 'w').write(n)\n")
        codes = launch_local(3, [sys.executable, str(script)])
        assert codes == [0, 0, 0]
        for r in range(3):
            assert (tmp_path / f"out-{r}").read_text() == "3"

    def test_ps_roles_spawned_with_contract(self, tmp_path):
        # VERDICT r4 #6: the PS-role half of the reference env contract
        # (tracker.py PSTracker): --num-servers spawns one scheduler +
        # N servers + workers, all sharing DMLC_PS_ROOT_URI/PORT, each
        # branching on DMLC_ROLE. The command is ROLE-GENERIC, as a
        # PS-Lite-style binary would be.
        script = tmp_path / "node.py"
        script.write_text(
            "import os\n"
            "role = os.environ.get('DMLC_ROLE', 'worker')\n"
            "tid = os.environ.get('DMLC_TASK_ID', 'x')\n"
            "line = ','.join([os.environ['DMLC_PS_ROOT_URI'],\n"
            "                 os.environ['DMLC_PS_ROOT_PORT'],\n"
            "                 os.environ['DMLC_NUM_SERVER'],\n"
            "                 os.environ['DMLC_NUM_WORKER']])\n"
            f"open(r'{tmp_path}' + f'/role-{{role}}-{{tid}}', 'w')"
            ".write(line)\n")
        codes = launch_local(2, [sys.executable, str(script)],
                             num_servers=2)
        assert codes == [0] * 5  # 2 workers + scheduler + 2 servers
        names = sorted(p.name for p in tmp_path.glob("role-*"))
        assert names == ["role-scheduler-0", "role-server-0",
                         "role-server-1", "role-worker-0",
                         "role-worker-1"]
        # every role sees the SAME PS root and world sizes
        contents = {(tmp_path / n).read_text() for n in names}
        assert len(contents) == 1
        uri, port, ns, nw = contents.pop().split(",")
        assert uri == "127.0.0.1" and int(port) > 0
        assert (ns, nw) == ("2", "2")

    def test_ps_role_guard_in_init_from_env(self, monkeypatch):
        # scheduler/server processes must not join the jax worker gang
        from dmlc_tpu.parallel.launch import init_from_env
        monkeypatch.setenv("DMLC_ROLE", "server")
        with pytest.raises(DMLCError, match="WORKER gang"):
            init_from_env()

    def test_worker_failure_raises(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(3)\n")
        with pytest.raises(DMLCError, match="exit codes"):
            launch_local(2, [sys.executable, str(script)])

    def test_cli_main(self, tmp_path):
        script = tmp_path / "w.py"
        script.write_text(
            "import os\n"
            f"open(r'{tmp_path}/cli-' + os.environ['DMLC_TPU_TASK_ID'], "
            "'w').close()\n")
        assert main(["-n", "2", "--", sys.executable, str(script)]) == 0
        assert (tmp_path / "cli-0").exists() and (tmp_path / "cli-1").exists()


class TestSSHLaunch:
    def test_dry_run_command_lines(self):
        lines = launch_ssh(["hostA", "hostB"], ["python", "train.py"],
                           "hostA:9000", num_workers=4, dry_run=True)
        assert len(lines) == 4
        assert "hostA" in lines[0] and "hostB" in lines[1]
        assert "DMLC_TPU_TASK_ID=3" in lines[3]
        assert "python train.py" in lines[0]
        # no rendezvous by default: the env contract stays out of the
        # command lines entirely
        assert "DMLC_TPU_RNDV" not in "".join(lines)

    def test_rendezvous_env_contract(self):
        """launch_ssh exports the SAME rendezvous env contract that
        launch_local gives its workers: DMLC_TPU_RNDV_URI/PORT/GANG,
        pinned here so remote elastic gangs keep working."""
        lines = launch_ssh(["hostA", "hostB"], ["python", "train.py"],
                           "hostA:9000", num_workers=2, dry_run=True,
                           rendezvous_addr=("hostA", 9100),
                           rendezvous_gang="g1")
        for line in lines:
            assert "DMLC_TPU_RNDV_URI=hostA" in line
            assert "DMLC_TPU_RNDV_PORT=9100" in line
            assert "DMLC_TPU_RNDV_GANG=g1" in line

    def test_rendezvous_env_fallback(self, monkeypatch):
        # a launcher already inside a rendezvous-enabled environment
        # forwards its own contract when none is given explicitly
        monkeypatch.setenv("DMLC_TPU_RNDV_URI", "10.0.0.5")
        monkeypatch.setenv("DMLC_TPU_RNDV_PORT", "9200")
        monkeypatch.delenv("DMLC_TPU_RNDV_GANG", raising=False)
        lines = launch_ssh(["h0"], ["python", "t.py"], "h0:9000",
                           num_workers=1, dry_run=True)
        assert "DMLC_TPU_RNDV_URI=10.0.0.5" in lines[0]
        assert "DMLC_TPU_RNDV_PORT=9200" in lines[0]
        assert "DMLC_TPU_RNDV_GANG=local" in lines[0]


class TestLaunchRegressions:
    def test_bad_coordinator_raises_clearly(self):
        with pytest.raises(DMLCError, match="host:port"):
            worker_envs("justahost", 2, 0)

    def test_timeout_kills_all_workers(self, tmp_path):
        script = tmp_path / "hang.py"
        script.write_text("import time, os\n"
                          f"open(r'{tmp_path}/pid-' + "
                          "os.environ['DMLC_TPU_TASK_ID'], 'w')"
                          ".write(str(os.getpid()))\n"
                          "time.sleep(60)\n")
        import time
        t0 = time.monotonic()
        with pytest.raises(DMLCError, match="timeout"):
            launch_local(3, [sys.executable, str(script)], timeout=2)
        assert time.monotonic() - t0 < 20  # deadline shared, not 3x
        time.sleep(0.2)
        for r in range(3):
            pid_file = tmp_path / f"pid-{r}"
            if pid_file.exists():
                pid = int(pid_file.read_text())
                with pytest.raises(OSError):
                    os.kill(pid, 0)  # process must be gone

    def test_dead_worker_kills_waiting_gang(self, tmp_path):
        """ADVICE r5: with num_servers > 0 and NO timeout, a worker
        dying at startup used to leave scheduler/server processes
        (blocked waiting for the full world) running forever —
        launch_local hung on the sequential waits. The gang poll must
        kill the survivors and raise promptly with the codes."""
        import time
        script = tmp_path / "node.py"
        script.write_text(
            "import os, sys, time\n"
            "role = os.environ.get('DMLC_ROLE', 'worker')\n"
            "if role == 'worker' and os.environ['DMLC_TASK_ID'] == '0':\n"
            "    sys.exit(7)  # dies at startup\n"
            f"open(r'{tmp_path}' + f'/pid-{{role}}-' +\n"
            "     os.environ.get('DMLC_TASK_ID', 'x'), 'w')"
            ".write(str(os.getpid()))\n"
            "time.sleep(300)  # 'waiting for the world to register'\n")
        t0 = time.monotonic()
        with pytest.raises(DMLCError, match="exit codes"):
            launch_local(2, [sys.executable, str(script)],
                         num_servers=1)  # note: timeout=None
        elapsed = time.monotonic() - t0
        assert elapsed < 60, f"hung {elapsed:.0f}s instead of failing fast"
        time.sleep(0.2)
        for pid_file in tmp_path.glob("pid-*"):
            text = pid_file.read_text()
            if not text:
                # the gang kill raced the node between open() and
                # write(): an empty pid file IS evidence the process
                # was killed — there is no pid left to probe
                continue
            pid = int(text)
            with pytest.raises(OSError):
                os.kill(pid, 0)  # survivors must have been killed
