"""Scheduler-backend generators, validated by EXECUTION (VERDICT r1
weak #9): the generated sbatch/qsub scripts and mpirun line are run
against stub schedulers (a fake `srun`/`mpirun` on PATH, SGE task-id
env), so the rank-injection and env-contract logic actually executes —
not just substring checks. The k8s manifest is validated structurally
against the Indexed-Job schema contract.
"""

import json
import os
import stat
import subprocess
import sys

import pytest

from dmlc_tpu.parallel.backends import (
    kubernetes_manifest, mpi_command, sge_script, slurm_script,
)

COORD = "10.0.0.1:9876"

# worker: append "<rank> <nworker> <coord>" to the shared results file
WORKER = [sys.executable, "-c",
          "import os;"
          "f=open(os.environ['RESULTS'],'a');"
          "f.write(' '.join([os.environ['DMLC_TPU_TASK_ID'],"
          "os.environ['DMLC_TPU_NUM_WORKER'],"
          "os.environ['DMLC_TPU_COORDINATOR_URI'],"
          "os.environ['DMLC_TASK_ID'],os.environ['DMLC_ROLE']])+'\\n');"
          "f.close()"]


def _results(path):
    with open(path) as f:
        return sorted(line.split() for line in f.read().splitlines())


def _expect(n):
    return sorted([str(r), str(n), COORD, str(r), "worker"]
                  for r in range(n))


def _write_stub(dir_path, name, body):
    p = os.path.join(dir_path, name)
    with open(p, "w") as f:
        f.write("#!/bin/bash\n" + body)
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return p


class TestSlurmExecuted:
    def test_sbatch_script_runs_under_stub_srun(self, tmp_path):
        script = slurm_script(3, WORKER, COORD, partition="tpu")
        # bash -n: whole-script syntax validation
        syn = subprocess.run(["bash", "-n"], input=script, text=True,
                             capture_output=True)
        assert syn.returncode == 0, syn.stderr
        assert "#SBATCH --ntasks=3" in script
        assert "#SBATCH --partition=tpu" in script
        # stub srun: run the step once per rank with SLURM_PROCID set
        bindir = tmp_path / "bin"
        bindir.mkdir()
        _write_stub(str(bindir), "srun",
                    'for r in 0 1 2; do SLURM_PROCID=$r "$@" || exit 1; '
                    'done\n')
        results = tmp_path / "out.txt"
        sh = tmp_path / "job.sh"
        sh.write_text(script)
        run = subprocess.run(
            ["bash", str(sh)],
            env={**os.environ, "PATH": f"{bindir}:{os.environ['PATH']}",
                 "RESULTS": str(results)},
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(3)


class TestSGEExecuted:
    def test_qsub_array_script_runs_per_task(self, tmp_path):
        script = sge_script(3, WORKER, COORD, queue="tpu.q")
        syn = subprocess.run(["bash", "-n"], input=script, text=True,
                             capture_output=True)
        assert syn.returncode == 0, syn.stderr
        assert "#$ -t 1-3" in script and "#$ -q tpu.q" in script
        results = tmp_path / "out.txt"
        sh = tmp_path / "job.sh"
        sh.write_text(script)
        # SGE runs the script once per array task with SGE_TASK_ID=1..N
        for task in (1, 2, 3):
            run = subprocess.run(
                ["bash", str(sh)],
                env={**os.environ, "SGE_TASK_ID": str(task),
                     "RESULTS": str(results)},
                capture_output=True, text=True, timeout=120)
            assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(3)


class TestMPIExecuted:
    def test_mpirun_line_runs_under_stub(self, tmp_path):
        line = mpi_command(2, WORKER, COORD)
        bindir = tmp_path / "bin"
        bindir.mkdir()
        # stub mpirun: honor -n N and -x K=V exports, run per rank
        _write_stub(str(bindir), "mpirun", r"""
n=1; declare -a exports
while [ $# -gt 0 ]; do
  case "$1" in
    -n) n="$2"; shift 2;;
    -x) exports+=("$2"); shift 2;;
    --hostfile) shift 2;;
    *) break;;
  esac
done
for ((r=0; r<n; r++)); do
  env "${exports[@]}" OMPI_COMM_WORLD_RANK=$r "$@" || exit 1
done
""")
        results = tmp_path / "out.txt"
        run = subprocess.run(
            line, shell=True,
            env={**os.environ, "PATH": f"{bindir}:{os.environ['PATH']}",
                 "RESULTS": str(results)},
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(2)


class TestKubernetesManifest:
    def test_manifest_schema_contract(self):
        m = kubernetes_manifest(4, ["python", "train.py"], COORD,
                                image="gcr.io/x/worker:1")
        # structural schema contract of a batch/v1 Indexed Job
        assert m["apiVersion"] == "batch/v1" and m["kind"] == "Job"
        spec = m["spec"]
        assert spec["completions"] == spec["parallelism"] == 4
        assert spec["completionMode"] == "Indexed"
        pod = spec["template"]["spec"]
        assert pod["restartPolicy"] == "Never"
        (container,) = pod["containers"]
        assert container["image"] == "gcr.io/x/worker:1"
        assert container["command"] == ["python", "train.py"]
        assert all(isinstance(c, str) for c in container["command"])
        # env contract: unique names; static values are strings; the two
        # task-id vars come from the completion-index downward API
        names = [e["name"] for e in container["env"]]
        assert len(names) == len(set(names)), "duplicate env names"
        by_name = {e["name"]: e for e in container["env"]}
        assert by_name["DMLC_TPU_COORDINATOR_URI"]["value"] == COORD
        assert by_name["DMLC_TPU_NUM_WORKER"]["value"] == "4"
        for var in ("DMLC_TPU_TASK_ID", "DMLC_TASK_ID"):
            ref = by_name[var]["valueFrom"]["fieldRef"]["fieldPath"]
            assert "job-completion-index" in ref
            assert "value" not in by_name[var]
        # the manifest must be pure JSON-serializable data (kubectl-able)
        json.dumps(m)

    def test_manifest_rejects_bad_world(self):
        with pytest.raises(Exception):
            kubernetes_manifest(0, ["x"], COORD, image="img")


# worker: append the rendezvous env contract to the shared results file
RNDV_WORKER = [sys.executable, "-c",
               "import os;"
               "f=open(os.environ['RESULTS'],'a');"
               "f.write(' '.join([os.environ['DMLC_TPU_RNDV_URI'],"
               "os.environ['DMLC_TPU_RNDV_PORT'],"
               "os.environ['DMLC_TPU_RNDV_GANG']])+'\\n');"
               "f.close()"]

RNDV = ("rndv.example", 9901)


class TestRendezvousEnvExport:
    """ROADMAP item 1's named leftover: every scheduler backend must
    export DMLC_TPU_RNDV_URI/PORT/GANG so scheduler-launched gangs
    reach the same elastic membership service that launch_local and
    launch_ssh gangs do — validated by execution per backend."""

    def test_mpi_exports_rendezvous_env(self, tmp_path):
        line = mpi_command(2, RNDV_WORKER, COORD,
                           rendezvous_addr=RNDV, rendezvous_gang="g1")
        bindir = tmp_path / "bin"
        bindir.mkdir()
        _write_stub(str(bindir), "mpirun", r"""
n=1; declare -a exports
while [ $# -gt 0 ]; do
  case "$1" in
    -n) n="$2"; shift 2;;
    -x) exports+=("$2"); shift 2;;
    --hostfile) shift 2;;
    *) break;;
  esac
done
for ((r=0; r<n; r++)); do
  env "${exports[@]}" OMPI_COMM_WORLD_RANK=$r "$@" || exit 1
done
""")
        results = tmp_path / "out.txt"
        run = subprocess.run(
            line, shell=True,
            env={**os.environ, "PATH": f"{bindir}:{os.environ['PATH']}",
                 "RESULTS": str(results)},
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert _results(results) == [["rndv.example", "9901", "g1"]] * 2

    def test_sge_exports_rendezvous_env(self, tmp_path):
        script = sge_script(2, RNDV_WORKER, COORD,
                            rendezvous_addr=RNDV, rendezvous_gang="g1")
        syn = subprocess.run(["bash", "-n"], input=script, text=True,
                             capture_output=True)
        assert syn.returncode == 0, syn.stderr
        results = tmp_path / "out.txt"
        sh = tmp_path / "job.sh"
        sh.write_text(script)
        for task in (1, 2):
            run = subprocess.run(
                ["bash", str(sh)],
                env={**os.environ, "SGE_TASK_ID": str(task),
                     "RESULTS": str(results)},
                capture_output=True, text=True, timeout=120)
            assert run.returncode == 0, run.stderr
        assert _results(results) == [["rndv.example", "9901", "g1"]] * 2

    def test_kubernetes_exports_rendezvous_env(self):
        m = kubernetes_manifest(3, ["python", "train.py"], COORD,
                                image="gcr.io/x/worker:1",
                                rendezvous_addr=RNDV,
                                rendezvous_gang="g1")
        (container,) = m["spec"]["template"]["spec"]["containers"]
        by_name = {e["name"]: e for e in container["env"]}
        assert by_name["DMLC_TPU_RNDV_URI"]["value"] == "rndv.example"
        assert by_name["DMLC_TPU_RNDV_PORT"]["value"] == "9901"
        assert by_name["DMLC_TPU_RNDV_GANG"]["value"] == "g1"
        json.dumps(m)

    def test_backends_default_to_submit_host_env(self, monkeypatch):
        # no explicit addr: the submit host's own rendezvous env is
        # forwarded (gang defaults "local"); without either, nothing
        # is exported
        monkeypatch.setenv("DMLC_TPU_RNDV_URI", "fwd.example")
        monkeypatch.setenv("DMLC_TPU_RNDV_PORT", "9333")
        monkeypatch.delenv("DMLC_TPU_RNDV_GANG", raising=False)
        line = mpi_command(2, ["w"], COORD)
        assert "-x DMLC_TPU_RNDV_URI=fwd.example" in line
        assert "-x DMLC_TPU_RNDV_PORT=9333" in line
        assert "-x DMLC_TPU_RNDV_GANG=local" in line
        script = sge_script(2, ["w"], COORD)
        assert "export DMLC_TPU_RNDV_URI=fwd.example" in script
        m = kubernetes_manifest(2, ["w"], COORD, image="img")
        names = [e["name"] for e in
                 m["spec"]["template"]["spec"]["containers"][0]["env"]]
        assert "DMLC_TPU_RNDV_URI" in names
        monkeypatch.delenv("DMLC_TPU_RNDV_URI")
        monkeypatch.delenv("DMLC_TPU_RNDV_PORT")
        line = mpi_command(2, ["w"], COORD)
        assert "RNDV" not in line
