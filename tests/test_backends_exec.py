"""Scheduler-backend generators, validated by EXECUTION (VERDICT r1
weak #9): the generated sbatch/qsub scripts and mpirun line are run
against stub schedulers (a fake `srun`/`mpirun` on PATH, SGE task-id
env), so the rank-injection and env-contract logic actually executes —
not just substring checks. The k8s manifest is validated structurally
against the Indexed-Job schema contract.
"""

import json
import os
import stat
import subprocess
import sys

import pytest

from dmlc_tpu.parallel.backends import (
    kubernetes_manifest, mpi_command, sge_script, slurm_script,
)

COORD = "10.0.0.1:9876"

# worker: append "<rank> <nworker> <coord>" to the shared results file
WORKER = [sys.executable, "-c",
          "import os;"
          "f=open(os.environ['RESULTS'],'a');"
          "f.write(' '.join([os.environ['DMLC_TPU_TASK_ID'],"
          "os.environ['DMLC_TPU_NUM_WORKER'],"
          "os.environ['DMLC_TPU_COORDINATOR_URI'],"
          "os.environ['DMLC_TASK_ID'],os.environ['DMLC_ROLE']])+'\\n');"
          "f.close()"]


def _results(path):
    with open(path) as f:
        return sorted(line.split() for line in f.read().splitlines())


def _expect(n):
    return sorted([str(r), str(n), COORD, str(r), "worker"]
                  for r in range(n))


def _write_stub(dir_path, name, body):
    p = os.path.join(dir_path, name)
    with open(p, "w") as f:
        f.write("#!/bin/bash\n" + body)
    os.chmod(p, os.stat(p).st_mode | stat.S_IEXEC)
    return p


class TestSlurmExecuted:
    def test_sbatch_script_runs_under_stub_srun(self, tmp_path):
        script = slurm_script(3, WORKER, COORD, partition="tpu")
        # bash -n: whole-script syntax validation
        syn = subprocess.run(["bash", "-n"], input=script, text=True,
                             capture_output=True)
        assert syn.returncode == 0, syn.stderr
        assert "#SBATCH --ntasks=3" in script
        assert "#SBATCH --partition=tpu" in script
        # stub srun: run the step once per rank with SLURM_PROCID set
        bindir = tmp_path / "bin"
        bindir.mkdir()
        _write_stub(str(bindir), "srun",
                    'for r in 0 1 2; do SLURM_PROCID=$r "$@" || exit 1; '
                    'done\n')
        results = tmp_path / "out.txt"
        sh = tmp_path / "job.sh"
        sh.write_text(script)
        run = subprocess.run(
            ["bash", str(sh)],
            env={**os.environ, "PATH": f"{bindir}:{os.environ['PATH']}",
                 "RESULTS": str(results)},
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(3)


class TestSGEExecuted:
    def test_qsub_array_script_runs_per_task(self, tmp_path):
        script = sge_script(3, WORKER, COORD, queue="tpu.q")
        syn = subprocess.run(["bash", "-n"], input=script, text=True,
                             capture_output=True)
        assert syn.returncode == 0, syn.stderr
        assert "#$ -t 1-3" in script and "#$ -q tpu.q" in script
        results = tmp_path / "out.txt"
        sh = tmp_path / "job.sh"
        sh.write_text(script)
        # SGE runs the script once per array task with SGE_TASK_ID=1..N
        for task in (1, 2, 3):
            run = subprocess.run(
                ["bash", str(sh)],
                env={**os.environ, "SGE_TASK_ID": str(task),
                     "RESULTS": str(results)},
                capture_output=True, text=True, timeout=120)
            assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(3)


class TestMPIExecuted:
    def test_mpirun_line_runs_under_stub(self, tmp_path):
        line = mpi_command(2, WORKER, COORD)
        bindir = tmp_path / "bin"
        bindir.mkdir()
        # stub mpirun: honor -n N and -x K=V exports, run per rank
        _write_stub(str(bindir), "mpirun", r"""
n=1; declare -a exports
while [ $# -gt 0 ]; do
  case "$1" in
    -n) n="$2"; shift 2;;
    -x) exports+=("$2"); shift 2;;
    --hostfile) shift 2;;
    *) break;;
  esac
done
for ((r=0; r<n; r++)); do
  env "${exports[@]}" OMPI_COMM_WORLD_RANK=$r "$@" || exit 1
done
""")
        results = tmp_path / "out.txt"
        run = subprocess.run(
            line, shell=True,
            env={**os.environ, "PATH": f"{bindir}:{os.environ['PATH']}",
                 "RESULTS": str(results)},
            capture_output=True, text=True, timeout=120)
        assert run.returncode == 0, run.stderr
        assert _results(results) == _expect(2)


class TestKubernetesManifest:
    def test_manifest_schema_contract(self):
        m = kubernetes_manifest(4, ["python", "train.py"], COORD,
                                image="gcr.io/x/worker:1")
        # structural schema contract of a batch/v1 Indexed Job
        assert m["apiVersion"] == "batch/v1" and m["kind"] == "Job"
        spec = m["spec"]
        assert spec["completions"] == spec["parallelism"] == 4
        assert spec["completionMode"] == "Indexed"
        pod = spec["template"]["spec"]
        assert pod["restartPolicy"] == "Never"
        (container,) = pod["containers"]
        assert container["image"] == "gcr.io/x/worker:1"
        assert container["command"] == ["python", "train.py"]
        assert all(isinstance(c, str) for c in container["command"])
        # env contract: unique names; static values are strings; the two
        # task-id vars come from the completion-index downward API
        names = [e["name"] for e in container["env"]]
        assert len(names) == len(set(names)), "duplicate env names"
        by_name = {e["name"]: e for e in container["env"]}
        assert by_name["DMLC_TPU_COORDINATOR_URI"]["value"] == COORD
        assert by_name["DMLC_TPU_NUM_WORKER"]["value"] == "4"
        for var in ("DMLC_TPU_TASK_ID", "DMLC_TASK_ID"):
            ref = by_name[var]["valueFrom"]["fieldRef"]["fieldPath"]
            assert "job-completion-index" in ref
            assert "value" not in by_name[var]
        # the manifest must be pure JSON-serializable data (kubectl-able)
        json.dumps(m)

    def test_manifest_rejects_bad_world(self):
        with pytest.raises(Exception):
            kubernetes_manifest(0, ["x"], COORD, image="img")
