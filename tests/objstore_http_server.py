"""Test-side HTTP object endpoint for the real ranged-GET client.

A tiny ``ThreadingHTTPServer`` speaking the dialect
``dmlc_tpu.io.objstore.http_client.HttpObjectStoreClient`` expects —
ranged GET (206 + Content-Range, clamped like real object stores),
HEAD (Content-Length / ETag / X-Dmlc-Mtime-Ns), PUT, DELETE, the
``?dmlc-list=`` JSON listing convention, the multipart upload
convention (``PUT ?dmlc-upload=&dmlc-part=``, ``POST
?dmlc-complete=`` / ``?dmlc-abort=``, ``GET ?dmlc-uploads=1``), the
optional ``dtpc`` transfer coding, and an optional required auth
header — DELEGATING
storage and ground-truth request counters to an inner
:class:`~dmlc_tpu.io.objstore.emulator.EmulatedObjectStore`. That
delegation is the point: the whole emulator-backed objstore suite
(FS surface, hydration acceptance, chaos at ``io.objstore.get``)
reruns over the REAL wire client by swapping the configured client,
while the emulator's counters keep proving what actually moved.

Helper module, not a test module (no ``test_`` prefix); lives in
tests/ so the ``http.server`` lint confinement (dmlc_tpu/ only) does
not apply.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, unquote, urlparse

from dmlc_tpu.obs import rpc as _rpc

_RANGE_RE = re.compile(r"bytes=(\d+)-(\d*)$")


class _Handler(BaseHTTPRequestHandler):
    server_version = "dmlc-test-objstore/1"

    def log_message(self, fmt, *args):  # noqa: A002 — base signature
        pass

    # -- plumbing

    def handle_one_request(self):
        # per-request arrival stamp for the trace-context echo below
        self._rpc_t0 = time.perf_counter()
        super().handle_one_request()

    def end_headers(self):
        # speak the server half of the trace-context contract: echo
        # the inbound context + our handle time, like obs/serve.py and
        # real traced endpoints do, so client spans against this test
        # server get server_us attribution too
        headers = getattr(self, "headers", None)
        trace = headers.get(_rpc.TRACE_HEADER) if headers else None
        if trace is not None:
            t0 = getattr(self, "_rpc_t0", time.perf_counter())
            handle_us = (time.perf_counter() - t0) * 1e6
            self.send_header(_rpc.TRACE_HEADER, trace)
            self.send_header(_rpc.HANDLE_HEADER,
                             str(round(handle_us, 1)))
        super().end_headers()

    def _em(self):
        return self.server.emulator

    def _auth_ok(self) -> bool:
        required: Optional[Dict[str, str]] = self.server.require_headers
        if not required:
            return True
        for name, value in required.items():
            if self.headers.get(name) != value:
                return False
        return True

    def _deny(self) -> None:
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _not_found(self) -> None:
        self.send_response(404)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _bucket_key(self):
        parts = unquote(urlparse(self.path).path).lstrip("/").split(
            "/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        return bucket, key

    def _send_bytes(self, code: int, data: bytes,
                    extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        length = len(data)
        if self.server.truncate_bodies_to is not None:
            # torn-transfer mode: declare the full length, send less —
            # the client's Content-Length check must catch it
            data = data[:self.server.truncate_bodies_to]
        self.send_header("Content-Length", str(length))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # -- verbs

    def do_GET(self):  # noqa: N802 — contract
        if not self._auth_ok():
            return self._deny()
        url = urlparse(self.path)
        bucket, key = self._bucket_key()
        q = parse_qs(url.query)
        if "dmlc-uploads" in q:
            body = json.dumps(self._em().list_uploads(bucket)).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if "dmlc-list" in q:
            if not self.server.support_list:
                return self._not_found()
            try:
                rows = [{"key": o.key, "size": o.size,
                         "mtime_ns": o.mtime_ns, "etag": o.etag}
                        for o in self._em().list(
                            bucket, q["dmlc-list"][0])]
            except FileNotFoundError:
                rows = []
            body = json.dumps(rows).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            info = self._em().head(bucket, key, count=False)
        except (FileNotFoundError, Exception) as e:  # noqa: B014
            if isinstance(e, FileNotFoundError):
                return self._not_found()
            raise
        rng = self.headers.get("Range")
        m = _RANGE_RE.match((rng or "").strip())
        start, end = 0, info.size
        code = 206 if m else 200
        if m:
            start = int(m.group(1))
            end = int(m.group(2)) + 1 if m.group(2) else info.size
            end = min(end, info.size)  # clamp like a real object store
            if start >= info.size and info.size:
                self.send_response(416)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
        if self.server.ignore_range:
            start, end, code = 0, info.size, 200
        level = 0
        accept_codec = self.headers.get("X-Dmlc-Accept-Codec")
        if accept_codec == "dtpc" and self.server.support_encoded:
            raw = self.headers.get("X-Dmlc-Codec-Level", "0")
            level = int(raw) if raw.isdigit() else 0
        extra = {}
        if code == 206:
            extra["Content-Range"] = (f"bytes {start}-{end - 1}"
                                      f"/{info.size}")
        if level > 0:
            # the emulator's transfer-coding path counts ENCODED bytes
            data = self._em().get_encoded(bucket, key, start, end,
                                          level)
            extra["X-Dmlc-Codec"] = "dtpc"
        else:
            data = self._em().get(bucket, key, start, end)
        self._send_bytes(code, data, extra)

    def do_HEAD(self):  # noqa: N802 — contract
        if not self._auth_ok():
            return self._deny()
        bucket, key = self._bucket_key()
        try:
            info = self._em().head(bucket, key)
        except FileNotFoundError:
            return self._not_found()
        self.send_response(200)
        self.send_header("Content-Length", str(info.size))
        if not self.server.no_change_token:
            self.send_header("ETag", f'"{info.etag}"')
            self.send_header("X-Dmlc-Mtime-Ns", str(info.mtime_ns))
        self.end_headers()

    def do_PUT(self):  # noqa: N802 — contract
        if not self._auth_ok():
            return self._deny()
        bucket, key = self._bucket_key()
        q = parse_qs(urlparse(self.path).query)
        length = int(self.headers.get("Content-Length", "0") or "0")
        body = self.rfile.read(length)
        if "dmlc-upload" in q and "dmlc-part" in q:
            self._em().put_part(bucket, key, q["dmlc-upload"][0],
                                int(q["dmlc-part"][0]), body)
        else:
            self._em().put(bucket, key, body)
        self.send_response(201)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_POST(self):  # noqa: N802 — contract
        """Multipart control plane: complete / abort an upload."""
        if not self._auth_ok():
            return self._deny()
        bucket, key = self._bucket_key()
        q = parse_qs(urlparse(self.path).query)
        upload = (q.get("dmlc-upload") or [""])[0]
        if "dmlc-complete" in q:
            try:
                self._em().complete_multipart(
                    bucket, key, upload, int(q["dmlc-complete"][0]))
            except FileNotFoundError:
                return self._not_found()
            self.send_response(201)
        elif "dmlc-abort" in q:
            self._em().abort_multipart(bucket, key, upload)
            self.send_response(204)
        else:
            return self._not_found()
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):  # noqa: N802 — contract
        if not self._auth_ok():
            return self._deny()
        bucket, key = self._bucket_key()
        existed = self._em().delete(bucket, key)
        self.send_response(204 if existed else 404)
        self.send_header("Content-Length", "0")
        self.end_headers()


class ObjstoreHttpServer:
    """The test endpoint: ``.endpoint`` for the client, ``.emulator``
    for ground truth. Knobs (set between requests):

    - ``require_headers`` — auth headers every request must carry;
    - ``ignore_range`` — act like a Range-ignoring server (200 + full
      body);
    - ``truncate_bodies_to`` — declare full Content-Length but send
      only N bytes (torn transfer);
    - ``support_list`` / ``support_encoded`` — advertise the listing
      convention / dtpc transfer coding;
    - ``no_change_token`` — omit ETag/X-Dmlc-Mtime-Ns on HEAD (a
      plain endpoint with no change tokens).
    """

    def __init__(self, emulator, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, 0), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.emulator = emulator
        self._httpd.require_headers = None
        self._httpd.ignore_range = False
        self._httpd.truncate_bodies_to = None
        self._httpd.support_list = True
        self._httpd.support_encoded = True
        self._httpd.no_change_token = False
        self.host = host
        self.port = self._httpd.server_address[1]
        self.endpoint = f"http://{host}:{self.port}"
        self.emulator = emulator
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tests.objstore_http_server")
        self._thread.start()

    def __getattr__(self, name):
        if name in ("require_headers", "ignore_range",
                    "truncate_bodies_to", "support_list",
                    "support_encoded", "no_change_token"):
            return getattr(self._httpd, name)
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in ("require_headers", "ignore_range",
                    "truncate_bodies_to", "support_list",
                    "support_encoded", "no_change_token"):
            setattr(self._httpd, name, value)
        else:
            object.__setattr__(self, name, value)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
