"""dmlc_tpu.pipeline: graph construction, lowering parity with the
hand-wired stacks, stats-snapshot schema, and autotuner behavior."""

import os
import time

import numpy as np
import pytest

from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.rowblock import RowBlockContainer
from dmlc_tpu.pipeline import (
    PIPELINE_STATS_SCHEMA, Autotuner, Knob, Pipeline,
)
from dmlc_tpu.utils.logging import DMLCError


def _write_libsvm(tmp_path, name="data.libsvm", rows=3000, seed=0,
                  qid_from=None):
    rng = np.random.RandomState(seed)
    lines = []
    for i in range(rows):
        nnz = rng.randint(3, 9)
        idx = np.sort(rng.choice(500, nnz, replace=False))
        feats = " ".join(f"{j}:{v:.4f}" for j, v in zip(idx, rng.rand(nnz)))
        qid = (f"qid:{i // 50} " if qid_from is not None and i >= qid_from
               else "")
        lines.append(f"{i % 2} {qid}{feats}")
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _write_csv(tmp_path, rows=2000, seed=1):
    rng = np.random.RandomState(seed)
    lines = [f"{i % 2}," + ",".join(f"{v:.4f}" for v in rng.rand(6))
             for i in range(rows)]
    p = tmp_path / "data.csv"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _drain_hash(built):
    c = RowBlockContainer(np.uint32)
    for b in built:
        c.push_block(b)
    return c.get_block().content_hash()


def _parser_hash(uri, fmt, **kw):
    c = RowBlockContainer(np.uint32)
    p = Parser.create(uri, 0, 1, format=fmt, **kw)
    for b in p:
        c.push_block(b)
    if hasattr(p, "destroy"):
        p.destroy()
    return c.get_block().content_hash()


class TestGraphConstruction:
    def test_chaining_is_immutable(self, tmp_path):
        base = Pipeline.from_uri(_write_libsvm(tmp_path))
        a = base.parse(format="libsvm")
        b = base.parse(format="csv")
        assert len(base.stages) == 1
        assert len(a.stages) == 2 and len(b.stages) == 2
        assert a.stages[1].params["format"] == "libsvm"
        assert b.stages[1].params["format"] == "csv"

    def test_repr_names_stages(self, tmp_path):
        pipe = (Pipeline.from_uri(_write_libsvm(tmp_path))
                .parse(format="libsvm").batch(64).prefetch())
        r = repr(pipe)
        for kind in ("source", "parse", "batch", "prefetch"):
            assert kind in r

    def test_illegal_chains_raise(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        src = Pipeline.from_uri(uri)
        with pytest.raises(DMLCError, match="cannot follow"):
            src.batch(64).build()
        with pytest.raises(DMLCError, match="cannot follow"):
            src.parse().parse().build()
        with pytest.raises(DMLCError, match="cannot follow"):
            src.cache(str(tmp_path / "c")).build()
        with pytest.raises(DMLCError, match="cannot follow"):
            src.parse().to_device().map(lambda x: x).build()

    def test_build_without_parse_or_shard_raises(self, tmp_path):
        with pytest.raises(DMLCError, match="nothing to run"):
            Pipeline.from_uri(_write_libsvm(tmp_path)).build()

    def test_bad_part_index(self):
        with pytest.raises(DMLCError):
            Pipeline.from_uri("x", part_index=3, num_parts=2)

    def test_shuffle_native_engine_rejected(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        pipe = Pipeline.from_uri(uri).shuffle().parse(engine="native")
        with pytest.raises(DMLCError, match="python parse engine"):
            pipe.build()


class TestFusionEquivalence:
    """The compiled pipeline must be byte-identical to the hand-wired
    parser stack it lowers onto (content_hash over the drained CSR)."""

    def test_libsvm_parse_only(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        built = Pipeline.from_uri(uri).parse(format="libsvm").build()
        assert _drain_hash(built) == _parser_hash(uri, "libsvm")
        built.close()

    def test_libsvm_with_batch_and_prefetch(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(700).prefetch(depth=2).build())
        assert _drain_hash(built) == _parser_hash(uri, "libsvm")
        built.close()

    def test_csv_parse(self, tmp_path):
        uri = _write_csv(tmp_path)
        built = (Pipeline.from_uri(uri)
                 .parse(format="csv", label_column=0).build())
        assert _drain_hash(built) == _parser_hash(uri, "csv",
                                                  label_column=0)
        built.close()

    def test_cache_stage_replays_pages(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        cache = str(tmp_path / "rows.pages")
        # an explicit path forces the page tier (pre-r6 contract)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .cache(cache).build())
        h1 = _drain_hash(built)
        assert h1 == _parser_hash(uri, "libsvm")
        assert os.path.exists(cache)
        assert built.stats()["stages"][0]["extra"]["replay_tier"] \
            == "pages"
        # epoch 2 replays the same pages
        assert _drain_hash(built) == h1
        built.close()

    def test_cache_stage_memory_tier_by_budget(self, tmp_path):
        # path=None + a fitting budget → blocks retained raw in RAM,
        # same content as a direct parse, no page file involved
        uri = _write_libsvm(tmp_path)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .cache().build())
        h1 = _drain_hash(built)
        assert h1 == _parser_hash(uri, "libsvm")
        assert built.stats()["stages"][0]["extra"]["replay_tier"] \
            == "memory"
        assert _drain_hash(built) == h1  # epoch 2 from memory
        built.close()

    def test_cache_stage_spills_over_budget(self, tmp_path):
        # path=None + a tiny budget → the lowering falls through to the
        # page tier at a derived fingerprint-keyed path, content intact
        uri = _write_libsvm(tmp_path)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .cache(memory_budget_bytes=1024).build())
        h1 = _drain_hash(built)
        assert h1 == _parser_hash(uri, "libsvm")
        assert built.stats()["stages"][0]["extra"]["replay_tier"] \
            == "pages"
        assert _drain_hash(built) == h1
        built.close()

    def test_batch_rechunks_to_fixed_rows(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=1000)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(256).build())
        sizes = [b.size for b in built]
        assert sizes == [256, 256, 256, 232]
        built.close()
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(256, drop_remainder=True).build())
        assert [b.size for b in built] == [256, 256, 256]
        built.close()

    def test_map_stage(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=500)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .map(lambda b: b.size).build())
        assert sum(built) == 500
        built.close()

    def test_shuffle_deterministic_and_complete(self, tmp_path):
        uri = _write_libsvm(tmp_path)

        def run():
            built = (Pipeline.from_uri(uri)
                     .shuffle(num_shuffle_parts=4, seed=11)
                     .parse(format="libsvm").build())
            h = _drain_hash(built)
            rows = built.stats()["stages"][0]["rows"]
            built.close()
            return h, rows

        (h1, r1), (h2, r2) = run(), run()
        assert h1 == h2  # same seed ⇒ same order
        # complete coverage: same row count as the unshuffled parse
        direct = Parser.create(uri, 0, 1, format="libsvm")
        assert r1 == r2 == sum(b.size for b in direct)

    def test_multi_epoch_stable(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=800)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .prefetch(depth=2).build())
        h = [_drain_hash(built) for _ in range(3)]
        assert h[0] == h[1] == h[2]
        assert built.epochs == 3
        built.close()


class TestRecordFraming:
    def test_split_type_reaches_the_parser(self, tmp_path):
        # from_uri(split_type=...) must not be silently dropped: libsvm
        # lines framed as RecordIO records parse identically to the
        # plain text file
        from dmlc_tpu.io.recordio import RecordIOWriter
        from dmlc_tpu.io.stream import create_stream
        text_uri = _write_libsvm(tmp_path, rows=400)
        rec_uri = str(tmp_path / "data.rec")
        with create_stream(rec_uri, "w") as s:
            w = RecordIOWriter(s)
            with open(text_uri, "rb") as f:
                for line in f:
                    w.write_record(line.strip())
        built = (Pipeline.from_uri(rec_uri, split_type="recordio")
                 .parse(format="libsvm", engine="python").build())
        assert _drain_hash(built) == _parser_hash(text_uri, "libsvm")
        built.close()

    def test_shuffle_unsupported_format_refused(self, tmp_path):
        pytest.importorskip("pyarrow")
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = str(tmp_path / "t.parquet")
        pq.write_table(pa.table({"label": pa.array([0.0, 1.0]),
                                 "f0": pa.array([0.5, 0.25])}), path)
        pipe = (Pipeline.from_uri(path).shuffle(num_shuffle_parts=2)
                .parse(format="parquet", label_column="label"))
        # silently yielding UNshuffled data would be worse than an error
        with pytest.raises(DMLCError, match="shuffle is not supported"):
            pipe.build()


class TestNativeLeaseDiscipline:
    def test_prefetch_then_device_keeps_arenas_alive(self, tmp_path):
        # prefetch marks items owned, but they still carry native arena
        # leases: to_device must take the lease over for the duration
        # of the async transfer — corruption here scrambles values
        pytest.importorskip("dmlc_tpu.native.bindings")
        from dmlc_tpu.native import native_available
        if not native_available():
            pytest.skip("native engine not built")
        uri = _write_libsvm(tmp_path, rows=2000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="native",
                        chunk_size=64 << 10)   # several blocks in flight
                 .prefetch(depth=4)
                 .to_device(window=4).build())
        got_label = []
        got_value = []
        for batch in built:
            got_label.append(np.asarray(batch["label"]))
            got_value.append(np.asarray(batch["value"]))
        built.close()
        ref = Parser.create(uri, 0, 1, format="libsvm", engine="python")
        ref_label = []
        ref_value = []
        for b in ref:
            ref_label.append(b.label.copy())
            ref_value.append(b.value.copy())
        np.testing.assert_array_equal(np.concatenate(got_label),
                                      np.concatenate(ref_label))
        np.testing.assert_array_equal(np.concatenate(got_value),
                                      np.concatenate(ref_value))


class TestDeviceStage:
    def test_to_device_delivers_all_blocks(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=600)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(100).to_device(window=2).build())
        batches = list(built)
        assert len(batches) == 6
        total = sum(int(b["offset"].shape[0]) - 1 for b in batches)
        assert total == 600
        snap = built.stats()
        dev_st = snap["stages"][-1]
        assert dev_st["name"] == "to_device"
        assert "xfer_wait_s" in dev_st["extra"]
        built.close()


class TestShardStage:
    def test_shard_lowering_smoke(self, tmp_path):
        import jax
        from jax.sharding import Mesh
        uri = _write_libsvm(tmp_path, rows=640)
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .shard(mesh, row_bucket=128, nnz_bucket=1 << 12)
                 .build())
        rows = 0
        for batch in built:
            assert batch["offset"].shape[0] == 8
            rows += int(np.sum(np.asarray(batch["num_rows"])))
        assert rows == 640
        snap = built.stats()
        assert snap["stages"][0]["kind"] == "shard"
        built.close()

    def test_shard_probe_reports_replay_tier(self, tmp_path):
        # the probe must say which tier served each epoch — that is
        # what the autotuner's tier gate and BENCH JSON read — and the
        # serve queue's occupancy must be sampled so shard.prefetch is
        # actually tunable
        import jax
        from jax.sharding import Mesh
        uri = _write_libsvm(tmp_path, rows=640)
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .shard(mesh, row_bucket=128, nnz_bucket=1 << 12,
                        first_epoch_cache="always")
                 .build())
        s1 = built.run_epoch()
        ex1 = s1["stages"][0]["extra"]
        assert ex1["replay_tier"] == "parse"
        assert ex1["replay_epochs"] == 0
        s2 = built.run_epoch()
        ex2 = s2["stages"][0]["extra"]
        assert ex2["replay_tier"] == "memory"
        assert ex2["replay_epochs"] == 1
        assert ex2["page_replay_epochs"] == 0
        assert "produced" in ex2["serve"]
        # the serve queue was sampled: occupancy telemetry exists
        assert s2["stages"][0]["queue_cap"] is not None
        built.close()


class TestStatsSchema:
    STAGE_KEYS = {"name", "kind", "items", "rows", "nnz", "bytes",
                  "wait_s", "wait_frac", "throughput_gbps", "rows_per_s",
                  "queue_depth_mean", "queue_cap", "queue_occupancy"}

    def test_snapshot_schema(self, tmp_path):
        uri = _write_libsvm(tmp_path)
        # engine pinned: the parse.chunk_prefetch knob (and its queue
        # telemetry) exists only on the python engine's chunk queue
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python")
                 .batch(500).prefetch().build())
        assert built.stats() is None  # nothing before the first epoch
        snap = built.run_epoch()
        assert snap["schema"] == PIPELINE_STATS_SCHEMA
        assert snap["epoch"] == 1
        assert snap["wall_s"] > 0
        assert [s["name"] for s in snap["stages"]] == \
            ["parse", "batch", "prefetch"]
        for st in snap["stages"]:
            assert self.STAGE_KEYS <= set(st)
        parse_st, batch_st, pf_st = snap["stages"]
        assert parse_st["extra"]["bytes_read"] > 0
        assert parse_st["rows"] == batch_st["rows"] == pf_st["rows"]
        assert pf_st["queue_cap"] == 4
        assert 0.0 <= pf_st["queue_occupancy"] <= 1.0
        # knob registry mirrors the "auto" depths
        assert set(snap["knobs"]) == {"parse.chunk_prefetch",
                                      "prefetch.depth"}
        built.close()

    def test_json_serializable(self, tmp_path):
        import json
        uri = _write_libsvm(tmp_path, rows=200)
        built = Pipeline.from_uri(uri).parse(format="libsvm").build()
        snap = built.run_epoch()
        json.dumps(snap)  # must not raise
        built.close()


class TestAutotuner:
    def _snap(self, occupancy, wall=1.0, bytes_=10 ** 9, wait_frac=0.5,
              cap=4):
        return {
            "schema": PIPELINE_STATS_SCHEMA, "epoch": 1, "wall_s": wall,
            "stages": [{"name": "prefetch", "kind": "prefetch",
                        "items": 10, "rows": 100, "nnz": 0,
                        "bytes": bytes_, "wait_s": wait_frac * wall,
                        "wait_frac": wait_frac, "throughput_gbps": None,
                        "rows_per_s": None, "queue_depth_mean": None,
                        "queue_cap": cap,
                        "queue_occupancy": occupancy}],
            "knobs": {},
        }

    def _knob(self, store):
        return Knob("prefetch.depth", "prefetch",
                    lambda: store["v"],
                    lambda n: store.__setitem__("v", n), lo=1, hi=64)

    def test_grows_on_full_queue(self):
        store = {"v": 4}
        t = Autotuner([self._knob(store)])
        t.after_epoch(self._snap(occupancy=0.9))
        assert store["v"] == 8  # trial armed
        t.after_epoch(self._snap(occupancy=0.9, bytes_=2 * 10 ** 9))
        assert store["v"] == 16  # accepted, next trial armed
        assert t.tuned() == {"prefetch.depth": 16}

    def test_reverts_on_regression_and_freezes(self):
        store = {"v": 4}
        t = Autotuner([self._knob(store)], cooldown=5)
        t.after_epoch(self._snap(occupancy=0.9, bytes_=10 ** 9))
        assert store["v"] == 8
        # trial epoch collapses throughput → revert + freeze
        t.after_epoch(self._snap(occupancy=0.9, bytes_=10 ** 8))
        assert store["v"] == 4
        assert t.report()["decisions"][-1]["outcome"] == "reverted"
        # frozen: the same full-queue signal proposes nothing
        t.after_epoch(self._snap(occupancy=0.9))
        assert store["v"] == 4

    def test_shrinks_idle_queue(self):
        store = {"v": 16}
        t = Autotuner([self._knob(store)])
        t.after_epoch(self._snap(occupancy=0.05, wait_frac=0.0))
        assert store["v"] == 8

    def _tier_snap(self, occupancy, tier, bytes_=10 ** 9):
        snap = self._snap(occupancy, bytes_=bytes_)
        snap["stages"][0]["extra"] = {"replay_tier": tier}
        return snap

    def test_tier_flip_discards_pending_trial(self):
        # a knob trial must never be judged across a replay-tier flip:
        # page replay vs parse differ ~5x, so the trial epoch's
        # throughput says nothing about the knob. The trial is
        # discarded (knob restored, NO freeze) and the best-throughput
        # reference resets.
        store = {"v": 4}
        t = Autotuner([self._knob(store)])
        t.after_epoch(self._tier_snap(0.9, "parse"))
        assert store["v"] == 8  # trial armed during the parse epoch
        # the next epoch serves from pages with 5x the bytes/s — without
        # the gate this would be 'accepted' on tier speedup alone
        t.after_epoch(self._tier_snap(0.9, "pages", bytes_=5 * 10 ** 9))
        assert store["v"] == 8  # re-armed fresh on the pages epoch...
        d0 = t.report()["decisions"][0]
        assert d0["outcome"] == "discarded (replay tier changed)"
        assert d0["old"] == 4 and d0["new"] == 8
        # ...and judged within the pages regime from then on
        t.after_epoch(self._tier_snap(0.9, "pages", bytes_=5 * 10 ** 9))
        assert t.report()["decisions"][1]["outcome"] == "accepted"

    def test_same_tier_epochs_judge_normally(self):
        store = {"v": 4}
        t = Autotuner([self._knob(store)])
        t.after_epoch(self._tier_snap(0.9, "memory"))
        t.after_epoch(self._tier_snap(0.9, "memory", bytes_=2 * 10 ** 9))
        assert t.report()["decisions"][0]["outcome"] == "accepted"

    def test_converges_on_synthetic_slow_stage(self, tmp_path):
        """Fast producer, slow consumer: the prefetch queue sits full,
        the tuner must raise its depth from the initial 4 and reach a
        fixed point (the cap) within a few epochs."""
        uri = _write_libsvm(tmp_path, rows=640)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(16)                       # ~40 small items
                 .prefetch(depth="auto")
                 .map(lambda b: (time.sleep(0.008), b)[1], name="slow")
                 .build(autotune=True))
        initial = built.knob_values()["prefetch.depth"]
        values = []
        for _ in range(12):
            built.run_epoch()
            values.append(built.knob_values()["prefetch.depth"])
            # fixed point reached early — but only ABOVE initial: a
            # climate-noise revert freezes the knob at initial for
            # cooldown=3 epochs, and 3 equal frozen values are a
            # cooldown, not convergence (the tuner re-trials after;
            # breaking here misread exactly that and flaked under
            # load)
            if (len(values) >= 3 and values[-1] > initial
                    and values[-1] == values[-2] == values[-3]):
                break
        report = built.autotune_report()
        built.close()
        assert values[-1] > initial, (values, report)
        # fixed point: the depth stopped moving (cap or steady accept)
        assert values[-1] == values[-2], (values, report)

    def test_no_knobs_no_tuner(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=100)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", prefetch_depth=2)
                 .build(autotune=True))
        # all depths fixed ⇒ autotune=True binds nothing
        assert built.autotuner is None
        built.close()


class TestShardedSchemaWarning:
    def test_mid_file_qid_discovery_warns_once(self, tmp_path):
        """ADVICE r5: qid first appearing mid-file flips the batch key
        set after the first assembled round — log_warning fires once,
        naming the structure change."""
        import jax
        from jax.sharding import Mesh
        from dmlc_tpu.parallel.sharded import ShardedRowBlockIter
        from dmlc_tpu.utils.logging import set_log_sink
        # qid must first appear in a LATER parser chunk (column
        # presence is chunk-granular): small chunks, late qid
        uri = _write_libsvm(tmp_path, rows=3000, qid_from=2000)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("data",))
        it = ShardedRowBlockIter(uri, mesh, format="libsvm",
                                 row_bucket=256, nnz_bucket=1 << 12,
                                 first_epoch_cache="never",
                                 steady_replay=False,
                                 chunk_size=64 << 10)
        hits = []
        set_log_sink(lambda level, msg: hits.append((level, msg)))
        try:
            for _ in it:
                pass
            warnings = [m for lv, m in hits
                        if lv == "WARNING" and "qid" in m]
            assert len(warnings) == 1, hits
            assert "key set changes" in warnings[0]
            # once only — a second epoch must not re-warn
            for _ in it:
                pass
            assert len([m for lv, m in hits
                        if lv == "WARNING" and "qid" in m]) == 1
        finally:
            set_log_sink(None)

    def test_uniform_qid_does_not_warn(self, tmp_path):
        import jax
        from jax.sharding import Mesh
        from dmlc_tpu.parallel.sharded import ShardedRowBlockIter
        from dmlc_tpu.utils.logging import set_log_sink
        uri = _write_libsvm(tmp_path, rows=3000, qid_from=0)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("data",))
        it = ShardedRowBlockIter(uri, mesh, format="libsvm",
                                 row_bucket=256, nnz_bucket=1 << 12,
                                 first_epoch_cache="never",
                                 steady_replay=False,
                                 chunk_size=64 << 10)
        hits = []
        set_log_sink(lambda level, msg: hits.append((level, msg)))
        try:
            for _ in it:
                pass
            assert not [m for lv, m in hits if lv == "WARNING"], hits
        finally:
            set_log_sink(None)
