"""Lint gate wired into tier-1 (SURVEY §4's scripts/lint.py analogue):
the suite fails on a lint regression, with or without the optional
external tools installed."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
import lint  # noqa: E402


class TestLintGate:
    def test_builtin_python_lint_clean(self):
        findings = lint.builtin_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_ruff_clean_when_available(self):
        findings = lint.run_ruff()
        if findings is None:
            pytest.skip("ruff not installed on this host")
        assert findings == [], "\n".join(findings)

    def test_clang_format_clean_when_available(self):
        findings = lint.run_clang_format()
        if findings is None:
            pytest.skip("clang-format not installed on this host")
        assert findings == [], "\n".join(findings)

    def test_builtin_catches_planted_violations(self, tmp_path):
        # the gate must actually bite: a tab-indented, trailing-space,
        # newline-less file yields one finding per violation class
        bad = tmp_path / "bad.py"
        bad.write_bytes(b"def f():\n\treturn 1 \nx = f()")
        findings = lint.builtin_lint([str(bad)])
        kinds = "\n".join(findings)
        assert "tab in indentation" in kinds
        assert "trailing whitespace" in kinds
        assert "missing trailing newline" in kinds

    def test_builtin_catches_syntax_error(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n    pass\n")
        findings = lint.builtin_lint([str(bad)])
        assert any("syntax error" in f for f in findings)

    def test_obs_gate_clean(self):
        # no bare print()/ad-hoc stats() surfaces crept into dmlc_tpu/
        # outside obs/ and the pinned pre-obs allowlists
        findings = lint.obs_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_obs_gate_catches_planted_violations(self):
        # the gate must bite on package files outside the allowlists —
        # plant one in-memory via a real temp file under dmlc_tpu/
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe.py")
        with open(bad, "w") as f:
            f.write("def stats():\n    return {}\n\n\n"
                    "def run():\n    print('x')\n")
        try:
            findings = lint.obs_lint([bad])
        finally:
            os.remove(bad)
        kinds = "\n".join(findings)
        assert "bare print()" in kinds
        assert "new stats() surface" in kinds

    def test_metric_gate_clean(self):
        # every literal instrument name in dmlc_tpu/ is exposition-safe
        # and no module outside obs/serve.py stands up an http.server
        findings = lint.metric_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_metric_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe.py")
        with open(bad, "w") as f:
            f.write("from http.server import HTTPServer\n"
                    "from dmlc_tpu.obs.metrics import REGISTRY\n"
                    "REGISTRY.counter('Bad Name!').inc()\n"
                    "REGISTRY.gauge('ok.name').set(1)\n")
        try:
            findings = lint.metric_lint([bad])
        finally:
            os.remove(bad)
        kinds = "\n".join(findings)
        assert "metric name 'Bad Name!'" in kinds
        assert "http.server outside" in kinds
        assert "ok.name" not in kinds  # the clean name passes

    def test_metric_gate_allows_serve_module(self):
        serve = os.path.join(lint.REPO, "dmlc_tpu", "obs", "serve.py")
        assert lint.metric_lint([serve]) == []

    def test_resilience_gate_clean(self):
        # no hand-rolled sleep/retry loops or naked except-OSError-
        # continue outside dmlc_tpu/resilience/ and the pinned allowlist
        findings = lint.resilience_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_resilience_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe.py")
        with open(bad, "w") as f:
            f.write("import time\n"
                    "def pull(paths):\n"
                    "    for p in paths:\n"
                    "        try:\n"
                    "            return open(p)\n"
                    "        except OSError:\n"
                    "            continue\n"
                    "def fetch(fn):\n"
                    "    while True:\n"
                    "        try:\n"
                    "            return fn()\n"
                    "        except (IOError, ValueError):\n"
                    "            time.sleep(0.1)\n")
        try:
            findings = lint.resilience_lint([bad])
        finally:
            os.remove(bad)
        kinds = "\n".join(findings)
        assert "naked 'except OSError: continue'" in kinds
        assert "hand-rolled sleep/retry loop" in kinds

    def test_resilience_gate_exempts_resilience_package(self):
        # the policy engine itself sleeps between attempts, by design
        pol = os.path.join(lint.REPO, "dmlc_tpu", "resilience",
                           "policy.py")
        assert lint.resilience_lint([pol]) == []

    def test_io_seam_gate_clean(self):
        # no direct open()/os.stat on data paths in dmlc_tpu/ outside
        # dmlc_tpu/io/ and the pinned allowlist — byte access goes
        # through the FileSystem/stream seams so retry policies and
        # fault plans always apply
        findings = lint.io_seam_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_io_seam_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe2.py")
        with open(bad, "w") as f:
            f.write("import os\n"
                    "def load(p):\n"
                    "    with open(p, 'rb') as fh:\n"
                    "        return fh.read(), os.stat(p).st_size\n")
        try:
            findings = lint.io_seam_lint([bad])
        finally:
            os.remove(bad)
        kinds = "\n".join(findings)
        assert "direct open() outside dmlc_tpu/io/" in kinds
        assert "direct os.stat() outside dmlc_tpu/io/" in kinds

    def test_row_loop_gate_clean(self):
        # no per-row Python loops over block payloads crept into
        # dmlc_tpu/data/ or dmlc_tpu/pipeline/ outside the pinned
        # golden-path allowlist — per-row work is engine (ABI-5 padded
        # emission) or vectorized numpy (data.padding)
        findings = lint.row_loop_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_row_loop_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "data",
                           "_lintprobe3.py")
        with open(bad, "w") as f:
            f.write("def tally(block):\n"
                    "    s = 0.0\n"
                    "    for row in block:\n"
                    "        s += float(row.label)\n"
                    "    n = [block.label[i] "
                    "for i in range(block.size)]\n"
                    "    return s, n\n")
        try:
            findings = lint.row_loop_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 2, "\n".join(findings)
        assert all("per-row Python loop" in f for f in findings)

    def test_row_loop_gate_scope(self):
        # block-level loops are fine; rowblock.py's Row protocol and
        # files outside data//pipeline/ are exempt
        probe = os.path.join(lint.REPO, "dmlc_tpu", "data",
                             "_lintprobe3.py")
        with open(probe, "w") as f:
            f.write("def drain(parser):\n"
                    "    return [b.nnz for b in parser]\n")
        try:
            assert lint.row_loop_lint([probe]) == []
        finally:
            os.remove(probe)
        rb = os.path.join(lint.REPO, "dmlc_tpu", "data", "rowblock.py")
        assert lint.row_loop_lint([rb]) == []  # pinned golden path
        outside = os.path.join(lint.REPO, "dmlc_tpu", "parallel",
                               "sharded.py")
        assert lint.row_loop_lint([outside]) == []  # out of scope

    def test_verdict_gate_clean(self):
        # the analysis-verdict key set matches the pin everywhere a
        # literal verdict dict appears (dmlc_tpu/ + scripts/) and
        # obs/analyze.py's VERDICT_KEYS tuple equals it
        findings = lint.verdict_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_verdict_gate_pin_matches_analyze(self):
        # the two sources of truth agree (change both consciously)
        from dmlc_tpu.obs.analyze import VERDICT_KEYS
        assert tuple(VERDICT_KEYS) == tuple(lint.VERDICT_KEYS)

    def test_verdict_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe4.py")
        with open(bad, "w") as f:
            f.write("def fake():\n"
                    "    return {'bound': 'parse', 'evidence': [],\n"
                    "            'extra_key': 1}\n")
        try:
            findings = lint.verdict_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 1, "\n".join(findings)
        assert "verdict-shaped dict" in findings[0]

    def test_verdict_gate_scans_scripts_too(self):
        bad = os.path.join(lint.REPO, "scripts", "_lintprobe5.py")
        with open(bad, "w") as f:
            f.write("V = {'bound': 'xfer', 'evidence': []}\n")
        try:
            findings = lint.verdict_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 1 and "obsctl" not in findings[0]

    def test_verdict_gate_requires_pin_in_analyze(self, tmp_path):
        # a drifted VERDICT_KEYS tuple in analyze.py is a finding —
        # simulate by linting a fake tree rooted at the analyze path
        import ast as _ast
        fake = ("VERDICT_KEYS = ('schema', 'bound')\n")
        tree = _ast.parse(fake)
        findings = []
        probe = os.path.join(lint.REPO, "dmlc_tpu", "obs", "analyze.py")
        findings = lint.verdict_lint(
            [probe], trees={probe: (lint._ANALYZE_REL, tree)})
        assert any("drifted from the lint pin" in f for f in findings)

    def test_io_seam_gate_exempts_io_package_and_allowlist(self):
        fsys = os.path.join(lint.REPO, "dmlc_tpu", "io", "filesys.py")
        assert lint.io_seam_lint([fsys]) == []
        flight = os.path.join(lint.REPO, "dmlc_tpu", "obs", "flight.py")
        assert lint.io_seam_lint([flight]) == []

    def test_knob_gate_clean(self):
        # steady-state knob mutation (set_capacity, depth/window
        # assignment, configure(coalesce/parallel/codec_level))
        # confined to the exploration rails + the pinned allowlist
        findings = lint.knob_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_knob_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe10.py")
        with open(bad, "w") as f:
            f.write("def tune(ti, it, dev, objstore, a, b):\n"
                    "    ti.set_capacity(8)\n"
                    "    it.prefetch_depth = 4\n"
                    "    dev.window = 16\n"
                    "    dev.window += 8\n"          # augmented form
                    "    dev.window: int = 4\n"      # annotated form
                    "    a.prefetch_depth, b = 2, 0\n"  # tuple unpack
                    "    objstore.configure(coalesce=8, parallel=2)\n"
                    "    objstore.configure(hydrate=False)\n"  # fine
                    "    b[dev.window] = 1\n"        # READ: fine
                    "    dev.window.inner = 2\n")    # assigns .inner
        try:
            findings = lint.knob_lint([bad])
        finally:
            os.remove(bad)
        kinds = "\n".join(findings)
        assert len(findings) == 7, kinds
        assert "direct set_capacity()" in kinds
        assert kinds.count(".prefetch_depth assignment") == 2
        assert kinds.count(".window assignment") == 3
        assert "configure(coalesce/parallel=...)" in kinds

    def test_knob_gate_exempts_the_rails(self):
        for rel in ("pipeline/autotune.py", "obs/control.py",
                    "pipeline/graph.py"):
            path = os.path.join(lint.REPO, "dmlc_tpu",
                                *rel.split("/"))
            assert lint.knob_lint([path]) == [], rel

    def test_verdict_gate_exempts_decision_records(self):
        # a control-plane ledger record carries bound+evidence but
        # CITES a verdict (by id) rather than being one — "outcome"
        # marks it; its shape is pinned by obs/control.py RECORD_KEYS
        probe = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe11.py")
        with open(probe, "w") as f:
            f.write("R = {'bound': 'parse', 'evidence': [],\n"
                    "     'outcome': 'trial'}\n")
        try:
            assert lint.verdict_lint([probe]) == []
        finally:
            os.remove(probe)

    def test_codec_gate_clean(self):
        # no direct zlib/gzip/bz2/lzma imports in dmlc_tpu/ outside
        # io/codec.py and the pinned crc32 allowlist
        findings = lint.codec_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_codec_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe6.py")
        with open(bad, "w") as f:
            f.write("import zlib\nfrom gzip import compress\n")
        try:
            findings = lint.codec_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 2, "\n".join(findings)
        assert all("io/codec.py" in f for f in findings)

    def test_codec_gate_exempts_codec_and_crc_allowlist(self):
        codec = os.path.join(lint.REPO, "dmlc_tpu", "io", "codec.py")
        assert lint.codec_lint([codec]) == []
        # resilience/policy.py's zlib.crc32 use is pinned — but a gzip
        # import there would NOT be covered by the crc pin
        policy = os.path.join(lint.REPO, "dmlc_tpu", "resilience",
                              "policy.py")
        assert lint.codec_lint([policy]) == []

    def test_arrow_gate_clean(self):
        # pyarrow imports in dmlc_tpu/ confined to the parquet golden
        # and the bench corpus makers (the native lane must never
        # silently lean on pyarrow)
        findings = lint.arrow_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_arrow_gate_catches_planted_violation(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe12.py")
        with open(bad, "w") as f:
            f.write("import pyarrow\nfrom pyarrow import parquet\n")
        try:
            findings = lint.arrow_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 2, "\n".join(findings)
        assert all("parquet_parser.py" in f for f in findings)

    def test_arrow_gate_exempts_golden_and_bench(self):
        golden = os.path.join(lint.REPO, "dmlc_tpu", "data",
                              "parquet_parser.py")
        bench = os.path.join(lint.REPO, "dmlc_tpu", "bench_suite.py")
        assert lint.arrow_lint([golden]) == []
        assert lint.arrow_lint([bench]) == []

    def test_profile_gate_clean(self):
        # sys._current_frames walks and cProfile/profile/pstats
        # imports confined to obs/profile.py
        findings = lint.profile_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_profile_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe7.py")
        with open(bad, "w") as f:
            f.write("import sys\nimport cProfile\n"
                    "from sys import _current_frames\n"
                    "frames = sys._current_frames()\n")
        try:
            findings = lint.profile_lint([bad])
        finally:
            os.remove(bad)
        # cProfile import + BOTH _current_frames forms (attribute
        # access and the from-import bypass)
        assert len(findings) == 3, "\n".join(findings)
        assert all("obs/profile.py" in f for f in findings)

    def test_profile_gate_exempts_profile_module_and_pkg_import(self):
        mod = os.path.join(lint.REPO, "dmlc_tpu", "obs", "profile.py")
        assert lint.profile_lint([mod]) == []
        # `from dmlc_tpu.obs import profile` must NOT trip the
        # stdlib-`profile` import check — only top-level module
        # imports count
        probe = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe8.py")
        with open(probe, "w") as f:
            f.write("from dmlc_tpu.obs import profile as _prof\n"
                    "from dmlc_tpu.obs.profile import hot_frames\n")
        try:
            findings = lint.profile_lint([probe])
        finally:
            os.remove(probe)
        assert findings == [], "\n".join(findings)

    def test_http_client_gate_clean(self):
        # http.client/urllib.request imports in dmlc_tpu/ confined to
        # the objstore client modules + obs/serve.py's scrape
        findings = lint.http_client_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_http_client_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe9.py")
        with open(bad, "w") as f:
            f.write("import urllib.request\n"
                    "import http.client\n"
                    "from urllib.request import urlopen\n"
                    "from http import client\n"
                    "from urllib.parse import urlparse\n")  # fine
        try:
            findings = lint.http_client_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 4, "\n".join(findings)
        assert all("objstore client modules" in f for f in findings)

    def test_socket_gate_clean(self):
        # raw socket/socketserver imports in dmlc_tpu/ confined to
        # rendezvous/service.py + obs/serve.py (the rendezvous wire
        # protocol and the HTTP status plane)
        findings = lint.socket_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_socket_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe12.py")
        with open(bad, "w") as f:
            f.write("import socket\n"
                    "import socketserver\n"
                    "from socket import create_connection\n"
                    "from urllib.parse import urlparse\n")  # fine
        try:
            findings = lint.socket_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 3, "\n".join(findings)
        assert all("rendezvous/service.py" in f for f in findings)

    def test_socket_gate_allows_service_and_serve(self):
        for rel in ("rendezvous/service.py", "obs/serve.py"):
            path = os.path.join(lint.REPO, "dmlc_tpu",
                                *rel.split("/"))
            assert lint.socket_lint([path]) == [], rel

    def test_thread_gate_clean(self):
        # threading.Thread / executor pools in dmlc_tpu/pipeline/
        # confined to scheduler.py (the budget owner)
        findings = lint.thread_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_thread_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "pipeline",
                           "_lintprobe10.py")
        with open(bad, "w") as f:
            f.write("import threading\n"
                    "from threading import Thread\n"
                    "from concurrent.futures import "
                    "ThreadPoolExecutor\n"
                    "t = threading.Thread(target=print)\n"
                    "u = Thread(target=print)\n"
                    "p = ThreadPoolExecutor(2)\n"
                    "ok = threading.Lock()\n")  # locks are fine
        try:
            findings = lint.thread_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 3, "\n".join(findings)
        assert all("scheduler-owned budget" in f for f in findings)

    def test_thread_gate_scope_and_allowlist(self):
        # the scheduler module itself and code OUTSIDE pipeline/ are
        # exempt (ThreadedIter et al. are the audited seams)
        sched = os.path.join(lint.REPO, "dmlc_tpu", "pipeline",
                             "scheduler.py")
        assert lint.thread_lint([sched]) == []
        outside = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe11.py")
        with open(outside, "w") as f:
            f.write("import threading\n"
                    "t = threading.Thread(target=print)\n")
        try:
            assert lint.thread_lint([outside]) == []
        finally:
            os.remove(outside)

    def test_http_client_gate_allows_client_modules(self):
        for rel in ("io/objstore/http_client.py", "io/objstore/peer.py",
                    "obs/serve.py"):
            path = os.path.join(lint.REPO, "dmlc_tpu",
                                *rel.split("/"))
            assert lint.http_client_lint([path]) == [], rel

    def test_trace_header_gate_clean(self):
        # the X-Dmlc-Trace/X-Dmlc-Handle-Us wire literals live only in
        # obs/rpc.py; everything else imports the helpers
        findings = lint.trace_header_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_trace_header_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe13.py")
        with open(bad, "w") as f:
            f.write("H = 'X-Dmlc-Trace'\n"
                    "def f(resp):\n"
                    "    return resp.headers.get('X-Dmlc-Handle-Us')\n"
                    "OK = 'X-Dmlc-Codec'\n")  # other headers are fine
        try:
            findings = lint.trace_header_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 2, "\n".join(findings)
        assert all("obs/rpc.py" in f for f in findings)

    def test_trace_header_gate_allows_rpc_module(self):
        path = os.path.join(lint.REPO, "dmlc_tpu", "obs", "rpc.py")
        assert lint.trace_header_lint([path]) == []

    def test_slo_gate_clean(self):
        # slo.* instrument names and the 14.4/6.0 burn-rate floats
        # live only in obs/slo.py; everyone else reads the engine
        findings = lint.slo_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_slo_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe14.py")
        with open(bad, "w") as f:
            f.write("def f(reg, name):\n"
                    "    reg.gauge('slo.x.attainment').set(1)\n"
                    "    reg.counter(f'slo.{name}.hits').inc()\n"
                    "    fast = 14.4\n"
                    "    slow = 6.0\n"
                    "    ok = reg.gauge('slow.x')\n"  # not slo.*
                    "    s = 'slo.free.string'\n"     # not an
                    "    return fast, slow, ok, s\n")  # instrument
        try:
            findings = lint.slo_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 4, "\n".join(findings)
        assert all("obs/slo.py" in f for f in findings)

    def test_slo_gate_allowlist_and_burn_exemption(self):
        slo = os.path.join(lint.REPO, "dmlc_tpu", "obs", "slo.py")
        assert lint.slo_lint([slo]) == []
        # supervise.py's 6.0 is a drain margin, not a burn threshold
        sup = os.path.join(lint.REPO, "dmlc_tpu", "resilience",
                           "supervise.py")
        assert lint.slo_lint([sup]) == []

    def test_random_gate_clean(self):
        # random/numpy.random construction in dmlc_tpu/io/ +
        # dmlc_tpu/data/ confined to dmlc_tpu/shuffle/ (epoch_rng)
        findings = lint.random_lint(lint.python_files())
        assert findings == [], "\n".join(findings)

    def test_random_gate_catches_planted_violations(self):
        bad = os.path.join(lint.REPO, "dmlc_tpu", "io",
                           "_lintprobe15.py")
        with open(bad, "w") as f:
            f.write("import random\n"
                    "import numpy.random\n"
                    "from random import shuffle\n"
                    "from numpy.random import RandomState\n"
                    "from numpy import random\n"
                    "import numpy as np\n"
                    "r = np.random.RandomState(0)\n"
                    "from dmlc_tpu.shuffle.permutation "
                    "import epoch_rng\n")  # fine: the one home
        try:
            findings = lint.random_lint([bad])
        finally:
            os.remove(bad)
        assert len(findings) == 6, "\n".join(findings)
        assert all("epoch_rng" in f for f in findings)

    def test_random_gate_scope(self):
        # outside io/ + data/ the gate does not apply (shuffle/ owns
        # the permutation; bench/test helpers keep their own rngs)
        out = os.path.join(lint.REPO, "dmlc_tpu", "_lintprobe16.py")
        with open(out, "w") as f:
            f.write("import random\nimport numpy.random\n")
        try:
            assert lint.random_lint([out]) == []
        finally:
            os.remove(out)
        # the permutation module itself draws numpy randomness freely
        perm = os.path.join(lint.REPO, "dmlc_tpu", "shuffle",
                            "permutation.py")
        assert lint.random_lint([perm]) == []
