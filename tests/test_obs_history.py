"""Analysis half of the obs plane (PR 8): time-series history, gang
aggregation, and bottleneck attribution.

Covers: the bounded downsampling ring (coarsening keeps the byte
budget AND the run's span; sampling costs <2% of a pipeline epoch —
the tightened overhead smoke gate), the /history endpoint, histogram
p50/p99 estimates, watchdog reports carrying the decay INTO a stall,
crash bundles gaining history.json (a REAL subprocess crash leaves >=2
samples spanning the run), the gang aggregator (in-process rollups +
explicit gaps, and the acceptance gang: a REAL 2-process launch_local
gang serving /history and /gang live where one rank dies mid-poll and
the aggregator keeps serving with an explicit gap), the attribution
engine's verdicts against synthetic and real snapshots, band-aware
BENCH comparison over the repo's own BENCH_r0*.json archive, and the
scripts/obsctl.py CLI.
"""

import glob
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from dmlc_tpu.obs import aggregate as obs_agg
from dmlc_tpu.obs import analyze as obs_analyze
from dmlc_tpu.obs import flight as obs_flight
from dmlc_tpu.obs import log as obs_log
from dmlc_tpu.obs import timeseries as obs_ts
from dmlc_tpu.obs import trace as obs_trace
from dmlc_tpu.obs import watchdog as obs_watchdog
from dmlc_tpu.obs.metrics import REGISTRY, MetricsRegistry
from dmlc_tpu.obs.serve import StatusServer
from dmlc_tpu.obs.watchdog import Watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import obsctl  # noqa: E402


@pytest.fixture(autouse=True)
def _obs_clean():
    """No flight recorder, history ring, aggregator, or trace state
    leaks across tests."""
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_agg.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()
    yield
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_agg.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()


def _get(url: str, timeout_s: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.status, resp.read()


def _write_libsvm(tmp_path, rows=600, name="hist.libsvm"):
    lines = [f"{i % 2} 1:0.5 7:1.25 9:{i}.0" for i in range(rows)]
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestTimeSeriesRing:
    def test_sampler_thread_collects(self):
        reg = MetricsRegistry()
        reg.counter("ticks").inc()
        ring = obs_ts.TimeSeriesRing(period_s=0.02, registry=reg)
        ring.start()
        try:
            deadline = time.time() + 5.0
            while len(ring.samples()) < 3 and time.time() < deadline:
                reg.counter("ticks").inc()
                time.sleep(0.01)
        finally:
            ring.stop()
        samples = ring.samples()
        assert len(samples) >= 3
        # monotonic time, numeric-only leaves, counters present
        ts = [s["t"] for s in samples]
        assert ts == sorted(ts)
        assert all(isinstance(v, (int, float))
                   for s in samples for v in s["v"].values())
        assert samples[-1]["v"]["counters.ticks"] >= \
            samples[0]["v"]["counters.ticks"]

    def test_numeric_leaves_sections(self):
        reg = MetricsRegistry()
        reg.counter("rows").inc(7)
        reg.gauge("depth").set(3)
        reg.gauge("tier").set("pages")  # string: no timeline
        reg.histogram("wait_s").observe(0.25)

        class Surface:
            def stats(self):
                return {"qsize": 2, "note": "text", "nested": {"n": 5}}

        s = Surface()
        reg.register("queue/demo", s, Surface.stats)
        leaves = obs_ts.numeric_leaves(reg.snapshot())
        assert leaves["counters.rows"] == 7
        assert leaves["gauges.depth"] == 3
        assert "gauges.tier" not in leaves
        assert leaves["histograms.wait_s.count"] == 1
        assert "histograms.wait_s.p50" in leaves
        assert leaves["collectors.queue/demo.qsize"] == 2
        assert leaves["collectors.queue/demo.nested.n"] == 5
        assert "collectors.queue/demo.note" not in leaves

    def test_coarsening_holds_budget_and_span(self):
        """The byte-budget soak: thousands of appends never exceed the
        budget, the oldest sample (the span anchor) survives every
        coarsening pass, and resolution degrades instead of history
        disappearing."""
        ring = obs_ts.TimeSeriesRing(period_s=1.0, budget_bytes=4 << 10)
        for i in range(20000):
            ring.append(float(i), {"counters.rows": i,
                                   "gauges.queue.depth": i % 7,
                                   "histograms.wait_s.sum": i * 0.1})
            assert ring.approx_bytes() <= ring.budget_bytes
        d = ring.to_dict()
        assert d["samples"][0]["t"] == 0.0          # span anchor
        assert d["samples"][-1]["t"] > 19000.0      # newest kept
        assert d["stride"] > 1 and d["coarsenings"] >= 1
        assert d["kept"] == len(d["samples"])
        assert d["kept"] < 20000                    # actually bounded
        # samples stay evenly ordered after repeated halving
        ts = [s["t"] for s in d["samples"]]
        assert ts == sorted(ts)

    def test_forced_sample_bypasses_stride(self):
        """Crash/stall dumps force a final sample: once the ring has
        coarsened (stride >= 2), a plain append may be skipped but a
        forced one must always be stored — the black box carries the
        actual end state, not one up to stride*period_s stale."""
        ring = obs_ts.TimeSeriesRing(period_s=1.0, budget_bytes=4 << 10)
        for i in range(20000):
            ring.append(float(i), {"counters.rows": i,
                                   "gauges.queue.depth": i % 7,
                                   "histograms.wait_s.sum": i * 0.1})
        assert ring.to_dict()["stride"] >= 2
        # consecutive ticks cannot both be keep-ticks at stride >= 2:
        # without force at least one of these would be dropped
        assert ring.append(99998.0, {"counters.rows": 1}, force=True)
        assert ring.append(99999.0, {"counters.rows": 2}, force=True)
        assert ring.to_dict()["samples"][-1]["t"] == 99999.0

    def test_install_if_env(self, monkeypatch):
        monkeypatch.delenv(obs_ts.ENV_HISTORY_S, raising=False)
        assert obs_ts.install_if_env() is None
        monkeypatch.setenv(obs_ts.ENV_HISTORY_S, "0.05")
        monkeypatch.setenv(obs_ts.ENV_HISTORY_BYTES, str(32 << 10))
        ring = obs_ts.install_if_env()
        assert ring is not None and obs_ts.active() is ring
        assert ring.period_s == 0.05
        assert ring.budget_bytes == 32 << 10
        # idempotent: a second hook call returns the SAME ring
        assert obs_ts.install_if_env() is ring
        obs_ts.uninstall()
        assert obs_ts.active() is None

    def test_history_endpoint(self):
        # installed but NOT started: samples driven manually so the
        # endpoint's counts are deterministic
        ring = obs_ts.TimeSeriesRing(period_s=60)
        obs_ts._ring = ring
        REGISTRY.counter("hist.demo").inc(5)
        ring.sample_now(t=time.time() - 100.0)
        ring.sample_now()
        with StatusServer() as srv:
            status, body = _get(srv.url("/history"))
            doc = json.loads(body)
            assert doc["schema"] == obs_ts.TIMESERIES_SCHEMA
            assert doc["kept"] == 2
            assert doc["samples"][-1]["v"]["counters.hist.demo"] == 5
            # ?seconds=N trims to the trailing window
            doc = json.loads(_get(srv.url("/history?seconds=30"))[1])
            assert len(doc["samples"]) == 1
        obs_ts.uninstall()

    def test_history_endpoint_404_without_ring(self):
        with StatusServer() as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/history"))
            assert e.value.code == 404

    def test_overhead_smoke_under_2pct(self, tmp_path):
        """Tier-1 gate (the ISSUE-8 acceptance number): sampling
        enabled costs <2% of a pipeline epoch. Interleaved rounds,
        judged on the QUIETEST adjacent (off, on) pair — climate is
        shared inside a pair on this burstable box, where min-vs-min
        across all rounds flaked on 2x wall swings (the PR-10
        profiler gate's statistic, applied here for the same
        reason)."""
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=4000, name="overhead.libsvm")
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=4096)
                 .batch(256)
                 .build())

        def epoch_wall():
            t0 = time.perf_counter()
            for _ in built:
                pass
            return time.perf_counter() - t0

        epoch_wall()  # warm caches/imports outside the measurement
        off, on = [], []
        sampled = 0
        for _ in range(5):
            off.append(epoch_wall())
            ring = obs_ts.install(period_s=0.05)
            try:
                on.append(epoch_wall())
            finally:
                sampled += len(ring.samples())
                obs_ts.uninstall()
        built.close()
        assert sampled > 0  # sampling was actually on
        grace = 0.010 / min(off)  # flat 10 ms, scaled to the wall
        ratios = [a / b for a, b in zip(on, off)]
        assert min(ratios) <= 1.02 + grace, (on, off, ratios)


class TestHistogramQuantiles:
    def test_estimates_ordered_and_clamped(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for v in (0.001, 0.002, 0.004, 0.008, 0.5):
            h.observe(v)
        s = h.summary()
        assert s["min"] <= s["p50"] <= s["p99"] <= s["max"]
        # the single outlier pulls p99 toward max, not p50
        assert s["p50"] < 0.1 and s["p99"] > 0.1

    def test_empty_histogram_has_no_estimates(self):
        s = MetricsRegistry().histogram("empty").summary()
        assert s["p50"] is None and s["p99"] is None

    def test_single_observation_pins_both(self):
        reg = MetricsRegistry()
        reg.histogram("one").observe(0.125)
        s = reg.histogram("one").summary()
        assert s["p50"] == s["p99"] == 0.125  # clamped to min==max


class TestWatchdogHistory:
    def test_stall_report_attaches_decay(self):
        """A stall report carries the trailing time-series samples —
        the decay INTO the stall, not just the frozen end state."""
        ring = obs_ts.TimeSeriesRing(period_s=60)
        obs_ts._ring = ring  # installed, manually driven
        REGISTRY.counter("decay.rows").inc(100)
        ring.sample_now(t=time.time() - 10.0)
        REGISTRY.counter("decay.rows").inc(5)  # the rate died
        wd = Watchdog(threshold_s=0.02, interval_s=999,
                      history_s=60.0).start()
        try:
            token = obs_watchdog.begin_wait("pull/dying.demo")
            time.sleep(0.03)
            report = wd.check()
            obs_watchdog.end_wait(token)
        finally:
            wd.stop()
        assert report is not None
        assert report["history_s"] == 60.0
        hist = report["history"]
        assert len(hist) >= 2  # the old sample + the forced fresh one
        assert hist[0]["v"]["counters.decay.rows"] == 100
        assert hist[-1]["v"]["counters.decay.rows"] == 105

    def test_report_without_ring_has_empty_history(self):
        assert obs_ts.active() is None
        wd = Watchdog(threshold_s=0.02, interval_s=999).start()
        try:
            token = obs_watchdog.begin_wait("pull/lonely.demo")
            time.sleep(0.03)
            report = wd.check()
            obs_watchdog.end_wait(token)
        finally:
            wd.stop()
        assert report is not None and report["history"] == []


class TestFlightHistory:
    def test_flight_owns_ring_when_none_installed(self, tmp_path):
        assert obs_ts.active() is None
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "fl"),
            metrics_interval_s=0.05).install()
        try:
            ring = obs_ts.active()
            assert ring is not None
            assert ring.period_s == 0.05
        finally:
            fl.uninstall()
        assert obs_ts.active() is None  # owned ring removed with it

    def test_flight_shares_preinstalled_ring(self, tmp_path):
        ring = obs_ts.install(period_s=30)
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "fl")).install()
        try:
            assert obs_ts.active() is ring  # joined, not displaced
        finally:
            fl.uninstall()
        assert obs_ts.active() is ring      # not owned: survives
        obs_ts.uninstall()

    def test_bundle_gains_history_json(self, tmp_path):
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "fl"),
            metrics_interval_s=0.05).install()
        try:
            REGISTRY.counter("flight.hist").inc(9)
            time.sleep(0.12)
            d = fl.dump("unit_test")
            hist = json.load(open(os.path.join(d, "history.json")))
            assert hist["schema"] == obs_ts.TIMESERIES_SCHEMA
            assert len(hist["samples"]) >= 2
            assert hist["samples"][-1]["v"]["counters.flight.hist"] == 9
            # metrics.json's history mirrors the SAME ring's samples
            metrics = json.load(open(os.path.join(d, "metrics.json")))
            assert len(metrics["history"]) == len(hist["samples"])
        finally:
            fl.uninstall()

    def test_subprocess_crash_bundle_history_spans_run(self, tmp_path):
        """Satellite regression pin: a REAL worker crash leaves a
        bundle whose history.json holds >=2 samples SPANNING the run —
        the shared ring replaced flight's private sampler end to end
        (env wiring included)."""
        from dmlc_tpu.parallel.launch import launch_local
        from dmlc_tpu.utils.logging import DMLCError
        out = str(tmp_path / "flight")
        script = tmp_path / "crash.py"
        script.write_text(
            "import time\n"
            "from dmlc_tpu.obs.timeseries import install_if_env\n"
            "ring = install_if_env()\n"
            "assert ring is not None, 'history env missing'\n"
            "from dmlc_tpu.obs.flight import install_if_env as fl_env\n"
            "assert fl_env() is not None\n"
            "from dmlc_tpu.obs.metrics import REGISTRY\n"
            "for i in range(6):\n"
            "    REGISTRY.counter('doomed.ticks').inc()\n"
            "    time.sleep(0.05)\n"
            "raise RuntimeError('deliberate history crash')\n"
        )
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
        with pytest.raises(DMLCError):
            launch_local(1, [sys.executable, str(script)], env=env,
                         flight_dir=out, history_s=0.05, timeout=120)
        bundles = glob.glob(os.path.join(out, "flight-*"))
        assert len(bundles) == 1, bundles
        hist = json.load(open(os.path.join(bundles[0], "history.json")))
        samples = hist["samples"]
        assert len(samples) >= 2, hist
        assert samples[-1]["t"] - samples[0]["t"] >= 0.05
        # the run's counters are on the timeline, rising
        assert samples[-1]["v"]["counters.doomed.ticks"] == 6


class TestGangAggregator:
    def _server(self, name, count):
        reg = MetricsRegistry()
        reg.counter("agg.rows").inc(count)
        reg.gauge("agg.depth").set(count // 100)
        return StatusServer(registry=reg)

    def test_rollups_and_labels(self):
        a = self._server("a", 100)
        b = self._server("b", 200)
        try:
            agg = obs_agg.GangAggregator(ports=[a.port, b.port],
                                         period_s=60)
            status = agg.poll_once()
            assert status == {f"port{a.port}": True,
                              f"port{b.port}": True}
            agg.poll_once()
            view = agg.view()
            assert view["schema"] == obs_agg.GANG_SCHEMA
            assert set(view["ranks"]) == {f"port{a.port}",
                                          f"port{b.port}"}
            ra = view["ranks"][f"port{a.port}"]
            assert ra["unreachable"] is False and ra["polls_ok"] == 2
            assert ra["series"]["samples"][-1]["v"][
                "counters.agg.rows"] == 100
            roll = view["rollup"]["samples"][-1]["v"]
            assert roll["gang.expected"] == 2.0
            assert roll["gang.reachable"] == 2.0
            assert roll["sum.counters.agg.rows"] == 300
            assert roll["min.counters.agg.rows"] == 100
            assert roll["max.counters.agg.rows"] == 200
        finally:
            a.close()
            b.close()

    def test_unreachable_rank_gets_explicit_gap(self):
        """The dead member's series STOPS (no interpolation) and the
        poll logs an explicit gap while the aggregator keeps serving
        the survivor."""
        a = self._server("a", 100)
        b = self._server("b", 200)
        agg = obs_agg.GangAggregator(ports=[a.port, b.port],
                                     period_s=60, timeout_s=0.5)
        agg.poll_once()
        b_label = f"port{b.port}"
        b.close()  # the rank "dies mid-poll"
        try:
            status = agg.poll_once()
            assert status[f"port{a.port}"] is True
            assert status[b_label] is False
            view = agg.view()
            dead = view["ranks"][b_label]
            assert dead["unreachable"] is True
            assert dead["last_error"]
            assert len(dead["gaps"]) == 1
            assert dead["gaps"][0]["first"] is True
            # series: exactly the one pre-death sample, nothing invented
            assert len(dead["series"]["samples"]) == 1
            roll = view["rollup"]["samples"][-1]["v"]
            assert roll["gang.reachable"] == 1.0
            assert roll["sum.counters.agg.rows"] == 100
        finally:
            a.close()
            agg.stop()

    def test_install_if_env(self, monkeypatch):
        monkeypatch.delenv(obs_agg.ENV_GANG_POLL_S, raising=False)
        assert obs_agg.install_if_env() is None
        srv = self._server("a", 7)
        try:
            monkeypatch.setenv(obs_agg.ENV_GANG_POLL_S, "0.05")
            monkeypatch.setenv("DMLC_TPU_SERVE_PORTS", str(srv.port))
            agg = obs_agg.install_if_env()
            assert agg is not None and agg.ports == [srv.port]
            deadline = time.time() + 5.0
            while agg.view()["polls"] < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert agg.view()["polls"] >= 2
        finally:
            obs_agg.uninstall()
            srv.close()

    def test_gang_endpoint_404_without_aggregator(self):
        with StatusServer() as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/gang"))
            assert e.value.code == 404


class TestGangServeLive:
    """ISSUE-8 acceptance: a REAL 2-process launch_local gang serves
    /history and /gang live DURING the run; one rank dying mid-poll
    leaves the rank-0 aggregator serving, with the dead rank's series
    showing an explicit gap and /gang marking it unreachable (extends
    the PR-4 scrape-under-load pattern)."""

    def test_two_process_gang_history_and_gap(self, tmp_path):
        from dmlc_tpu.parallel.launch import find_free_ports, launch_local
        script = tmp_path / "gang_worker.py"
        stop_file = tmp_path / "stop"
        die_file = tmp_path / "die"
        script.write_text(
            "import os, sys, time\n"
            "from dmlc_tpu.obs.serve import serve_if_env\n"
            "from dmlc_tpu.obs.timeseries import install_if_env as h\n"
            "from dmlc_tpu.obs.aggregate import install_if_env as g\n"
            "from dmlc_tpu.obs.metrics import REGISTRY\n"
            "srv = serve_if_env()\n"
            "assert srv is not None, 'serve port env missing'\n"
            "assert h() is not None, 'history env missing'\n"
            "rank = int(os.environ['DMLC_TPU_TASK_ID'])\n"
            "agg = g()\n"
            "assert (agg is not None) == (rank == 0), (rank, agg)\n"
            "REGISTRY.counter('gang.rows').inc(100 * (rank + 1))\n"
            "deadline = time.time() + 60\n"
            "while time.time() < deadline:\n"
            "    REGISTRY.counter('gang.ticks').inc()\n"
            "    if rank == 1 and os.path.exists(sys.argv[2]):\n"
            "        os._exit(0)\n"  # vanish mid-poll
            "    if rank == 0 and os.path.exists(sys.argv[1]):\n"
            "        break\n"
            "    time.sleep(0.05)\n"
        )
        ports = find_free_ports(2)
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
        result = {}

        def gang():
            try:
                result["codes"] = launch_local(
                    2, [sys.executable, str(script), str(stop_file),
                        str(die_file)],
                    env=env, serve_ports=ports, history_s=0.1,
                    gang_poll_s=0.1, timeout=90)
            except Exception as e:  # noqa: BLE001
                result["error"] = e

        t = threading.Thread(target=gang, daemon=True)
        t.start()
        try:
            # phase 1: both ranks aggregated live — rank 0's /gang
            # shows two reachable members with samples
            deadline = time.time() + 45.0
            view = None
            while time.time() < deadline:
                try:
                    view = json.loads(_get(
                        f"http://127.0.0.1:{ports[0]}/gang",
                        timeout_s=2.0)[1])
                except (OSError, urllib.error.URLError, ValueError):
                    time.sleep(0.05)
                    continue
                ranks = view.get("ranks") or {}
                if (set(ranks) == {"rank0", "rank1"}
                        and all(r["series"]["samples"]
                                for r in ranks.values())):
                    break
                time.sleep(0.05)
            assert view is not None and set(view["ranks"]) == \
                {"rank0", "rank1"}, f"gang never aggregated: {result}"
            r1 = view["ranks"]["rank1"]
            assert r1["unreachable"] is False
            assert r1["series"]["samples"][-1]["v"][
                "counters.gang.rows"] == 200
            # /history is live on BOTH ranks during the run
            for port in ports:
                h = json.loads(_get(
                    f"http://127.0.0.1:{port}/history")[1])
                assert h["samples"], f"no history on :{port}"
            # phase 2: rank 1 dies mid-poll; the aggregator keeps
            # serving with an explicit gap and marks it unreachable
            die_file.write_text("die")
            deadline = time.time() + 45.0
            dead = None
            while time.time() < deadline:
                try:
                    view = json.loads(_get(
                        f"http://127.0.0.1:{ports[0]}/gang",
                        timeout_s=2.0)[1])
                except (OSError, urllib.error.URLError, ValueError):
                    time.sleep(0.05)
                    continue
                dead = view["ranks"]["rank1"]
                if dead["unreachable"] and dead["gaps"]:
                    break
                time.sleep(0.05)
            assert dead is not None and dead["unreachable"] is True, \
                f"rank1 never marked unreachable: {result}"
            assert dead["gaps"][0]["error"]
            assert dead["polls_failed"] >= 1
            # the survivor's series keeps growing past the death
            alive = view["ranks"]["rank0"]
            assert alive["unreachable"] is False
            roll = view["rollup"]["samples"][-1]["v"]
            assert roll["gang.reachable"] == 1.0
            assert roll["gang.expected"] == 2.0
        finally:
            stop_file.write_text("stop")
            t.join(timeout=45.0)
        assert result.get("codes") == [0, 0], result


def _snap(stages, wall_s=2.0):
    return {"schema": 1, "epoch": 1, "wall_s": wall_s,
            "stages": stages, "knobs": {}}


class TestAnalyze:
    def test_parse_bound(self):
        v = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 1.4,
             "bytes": 1 << 30},
            {"name": "to_device", "kind": "to_device", "wait_s": 0.2,
             "extra": {"xfer_wait_s": 0.2}},
        ]), epoch_gauges=[2.0, 2.2])
        assert v["bound"] == "parse" and v["confidence"] == "high"
        assert v["band"] == "elevated"
        assert sorted(v) == sorted(obs_analyze.VERDICT_KEYS)
        assert any("parse wait 1.4" in e for e in v["evidence"])
        json.dumps(v)  # plain JSON end to end

    def test_xfer_bound(self):
        v = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 0.3,
             "bytes": 1 << 30},
            {"name": "to_device", "kind": "to_device", "wait_s": 1.5,
             "extra": {"xfer_wait_s": 1.5}},
        ]))
        assert v["bound"] == "xfer"

    def test_assemble_bound_fused_first_stage(self):
        # the ABI-5 fused rung: ONE assemble-kind stage carrying the
        # engine's measured assemble seconds — parse is its delivery
        # wait minus those
        v = obs_analyze.attribute(_snap([
            {"name": "assemble", "kind": "assemble", "wait_s": 1.0,
             "bytes": 1 << 30,
             "extra": {"assembly_path": "native-padded",
                       "assemble_s": 0.8, "engine": {}}},
        ]))
        assert v["stage_waits"]["parse_s"] == pytest.approx(0.2)
        assert v["stage_waits"]["assemble_s"] == pytest.approx(0.8)
        assert v["bound"] == "assemble"
        assert any("assembly_path=native-padded" in e
                   for e in v["evidence"])

    def test_fused_carveout_uses_stage0_assemble_only(self):
        """The fused-parse carve-out subtracts only stage 0's OWN
        measured assemble seconds — downstream staging assembly
        belongs to other stages and must not eat the parse credit."""
        v = obs_analyze.attribute(_snap([
            {"name": "assemble", "kind": "assemble", "wait_s": 2.0,
             "bytes": 1 << 30,
             "extra": {"assembly_path": "native-padded",
                       "assemble_s": 0.3}},
            {"name": "to_device", "kind": "to_device", "wait_s": 0.1,
             "extra": {"staging_assemble_s": 1.0,
                       "xfer_wait_s": 0.1}},
        ]))
        assert v["stage_waits"]["parse_s"] == pytest.approx(1.7)
        assert v["stage_waits"]["assemble_s"] == pytest.approx(1.3)
        assert v["bound"] == "parse"

    def test_cache_first_stage_not_credited_to_parse(self):
        """Only the fused ASSEMBLE-kind first stage earns the parse
        credit: a cache- or shard-first pipeline's stage-0 wait is
        replay/shard I/O — a 'parse'-bound verdict for an epoch that
        never parsed would be fabricated evidence."""
        v = obs_analyze.attribute(_snap([
            {"name": "cache", "kind": "cache", "wait_s": 1.4,
             "bytes": 1 << 30},
            {"name": "to_device", "kind": "to_device", "wait_s": 0.1,
             "extra": {"xfer_wait_s": 0.1}},
        ]))
        assert v["stage_waits"]["parse_s"] == 0.0
        assert v["bound"] != "parse"
        assert not any("parse wait" in e for e in v["evidence"])

    def test_credit_limited_overrides_waits(self):
        v = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 1.4,
             "bytes": 1 << 30},
        ]), epoch_gauges=[0.2, 0.4, 0.3])
        assert v["bound"] == "credit-limited"
        assert v["band"] == "drained"

    def test_consumer_bound_when_waits_tiny(self):
        v = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 0.02,
             "bytes": 1 << 30},
        ], wall_s=5.0))
        assert v["bound"] == "consumer"

    def test_wire_bound(self):
        metrics = {"counters": {"objstore.get": 50,
                                "objstore.bytes": 1 << 30,
                                "pagestore.hit": 1,
                                "pagestore.miss": 40}}
        v = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 1.4,
             "bytes": 1 << 30},
        ]), metrics=metrics)
        assert v["bound"] == "wire"
        assert any("objstore" in e for e in v["evidence"])

    def test_sharded_vs_unsharded_legs_differ_in_evidence(self):
        """Acceptance: two config-12-style legs may share a bound but
        must NOT share evidence — the verdict names the measured
        waits, which differ."""
        fused = obs_analyze.attribute(_snap([
            {"name": "assemble", "kind": "assemble", "wait_s": 1.2,
             "bytes": 1 << 30,
             "extra": {"assembly_path": "native-padded",
                       "assemble_s": 0.3}},
        ]))
        sharded = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 0.7,
             "bytes": 1 << 30},
            {"name": "assemble", "kind": "assemble", "wait_s": 0.9,
             "bytes": 1 << 30,
             "extra": {"assembly_path": "python-fused",
                       "assemble_s": 0.2}},
        ]))
        assert fused["evidence"] != sharded["evidence"]
        assert fused["stage_waits"] != sharded["stage_waits"]
        assert any("native-padded" in e for e in fused["evidence"])
        assert any("python-fused" in e for e in sharded["evidence"])

    def test_compare_in_band_variance_not_flagged(self):
        a = {"metric": "m", "value": 1.0, "epochs": 10,
             "run_band": "plateau", "parse_cpu_gbps_core": 1.0,
             "gauge_bands": {"plateau": {"epochs": 10,
                                         "sustained": 1.0}}}
        b = json.loads(json.dumps(a))
        b["gauge_bands"]["plateau"]["sustained"] = 0.9  # -10%: in-band
        r = obs_analyze.compare(a, b)
        assert r["bands"]["plateau"]["status"] == "in-band"
        assert r["regressions"] == []
        b["gauge_bands"]["plateau"]["sustained"] = 0.5  # -50%: real
        r = obs_analyze.compare(a, b)
        assert r["bands"]["plateau"]["status"] == "regression"
        assert len(r["regressions"]) == 1

    def test_compare_cross_band_is_incomparable(self):
        a = {"metric": "m", "value": 1.0,
             "gauge_bands": {"drained": {"epochs": 8,
                                         "sustained": 0.2}}}
        b = {"metric": "m", "value": 1.1,
             "gauge_bands": {"full": {"epochs": 8, "sustained": 1.1}}}
        r = obs_analyze.compare(a, b)
        assert all(row["status"] == "incomparable"
                   for row in r["bands"].values())
        assert r["regressions"] == []

    def test_compare_archive_files(self):
        """The repo's own BENCH_r0*.json archive (campaign wrappers):
        compare loads them, reports band-aware rows, and flags no
        regression across differing credit climates."""
        a = os.path.join(REPO, "BENCH_r04.json")
        b = os.path.join(REPO, "BENCH_r05.json")
        r = obs_analyze.compare_files(a, b)
        assert r["bands"], r
        assert r["regressions"] == []
        # credit-immune CPU rate compared despite the band mismatch
        assert r["parse_cpu"]["status"] == "in-band"
        # identical runs never regress
        r2 = obs_analyze.compare_files(b, b)
        assert r2["regressions"] == [] and r2["improvements"] == []

    def test_diagnose_bench_prefers_embedded_analysis(self, tmp_path):
        verdict = obs_analyze.attribute(_snap([
            {"name": "parse", "kind": "parse", "wait_s": 1.0,
             "bytes": 1 << 20}]))
        doc = {"metric": "m", "value": 1.0, "analysis": verdict}
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(doc))
        assert obs_analyze.diagnose_bench(str(p)) == verdict

    def test_analyze_endpoint_serves_pipeline_verdict(self, tmp_path):
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=2000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=2048)
                 .batch(128)
                 .build())
        built.run_epoch()
        with StatusServer() as srv:
            v = json.loads(_get(srv.url("/analyze"))[1])
        built.close()
        assert v["bound"] in obs_analyze.BOUNDS
        assert sorted(v) == sorted(obs_analyze.VERDICT_KEYS)
        assert v["stage_waits"]["stages"]

    def test_analyze_endpoint_scopes_wire_counters_to_epoch(self):
        """/analyze deltas the wire counters against the previous
        epoch's close: cold-hydration traffic from EARLIER work must
        not flip a purely local later epoch to wire-bound (the same
        scoping config 13 applies)."""
        reg = MetricsRegistry()
        state = {"epoch": 1}

        class Holder:
            def stats(self):
                return _snap([{"name": "parse", "kind": "parse",
                               "wait_s": 1.4, "bytes": 1 << 30}])\
                    | {"epoch": state["epoch"]}

        h = Holder()
        reg.register("pipeline", h, Holder.stats)
        reg.counter("objstore.get").inc(50)
        reg.counter("objstore.bytes").inc(1 << 30)
        reg.counter("pagestore.miss").inc(40)
        reg.counter("pagestore.hit").inc(1)
        with StatusServer(registry=reg) as srv:
            # first call: no baseline yet — cumulative counters still
            # look like wire traffic
            v1 = json.loads(_get(srv.url("/analyze"))[1])
            assert v1["bound"] == "wire"
            state["epoch"] = 2   # a LOCAL epoch, no new wire traffic
            v2 = json.loads(_get(srv.url("/analyze"))[1])
            assert v2["bound"] != "wire"
            assert not any("objstore" in e for e in v2["evidence"])

    def test_bench_suite_config13_block(self):
        """The config-13 acceptance body: a short epoch emits a
        non-empty, schema-valid "analysis" block whose bound is
        consistent with the measured waits (asserted inside)."""
        from dmlc_tpu.bench_suite import bench_analyze
        out = bench_analyze(2)
        assert out["config"] == "analyze"
        v = out["analysis"]
        assert sorted(v) == sorted(obs_analyze.VERDICT_KEYS)
        assert v["bound"] in obs_analyze.BOUNDS and v["evidence"]

    def test_bench_suite_config13_counters_are_epoch_deltas(self):
        """Wire counters feeding config 13's verdict are deltas across
        THE MEASURED EPOCH: remote traffic left in the process-global
        registry by an earlier config (config 11 in a full-suite run)
        must not flip a purely local epoch to wire-bound."""
        from dmlc_tpu.bench_suite import bench_analyze
        try:
            REGISTRY.counter("objstore.get").inc(5000)
            REGISTRY.counter("objstore.bytes").inc(50 << 30)
            REGISTRY.counter("pagestore.miss").inc(10000)
            out = bench_analyze(2)
            assert out["analysis"]["bound"] != "wire"
            assert not any("objstore" in e
                           for e in out["analysis"]["evidence"])
        finally:
            REGISTRY.reset()


class TestObsctl:
    def test_compare_cli_in_band(self, tmp_path, capsys):
        a = {"metric": "m", "value": 1.0, "run_band": "plateau",
             "gauge_bands": {"plateau": {"epochs": 6,
                                         "sustained": 1.0}}}
        b = json.loads(json.dumps(a))
        b["gauge_bands"]["plateau"]["sustained"] = 0.93
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        rc = obsctl.main(["compare", str(pa), str(pb)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "in-band" in out and "no regressions" in out

    def test_compare_cli_regression_exit_code(self, tmp_path, capsys):
        a = {"metric": "m", "value": 1.0,
             "gauge_bands": {"plateau": {"epochs": 6,
                                         "sustained": 1.0}}}
        b = {"metric": "m", "value": 0.5,
             "gauge_bands": {"plateau": {"epochs": 6,
                                         "sustained": 0.5}}}
        pa, pb = tmp_path / "a.json", tmp_path / "b.json"
        pa.write_text(json.dumps(a))
        pb.write_text(json.dumps(b))
        rc = obsctl.main(["compare", str(pa), str(pb)])
        assert rc == 3
        assert "REGRESSION" in capsys.readouterr().out

    def test_top_once(self, capsys):
        reg = MetricsRegistry()

        class Holder:
            def stats(self):
                return _snap([
                    {"name": "parse", "kind": "parse", "items": 12,
                     "rows": 3000, "nnz": 9000, "bytes": 1 << 20,
                     "wait_s": 0.5, "wait_frac": 0.25,
                     "throughput_gbps": 0.8, "rows_per_s": 1500.0,
                     "queue_depth_mean": 2.0, "queue_cap": 4,
                     "queue_occupancy": 0.5},
                ])

        h = Holder()
        reg.register("pipeline", h, Holder.stats)
        with StatusServer(registry=reg) as srv:
            rc = obsctl.main(["top", "--once", "--port",
                              str(srv.port)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "parse" in out and "wait_s" in out and "2.0/4" in out

    def test_diagnose_live_endpoint(self, tmp_path, capsys):
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=2000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=2048)
                 .batch(128)
                 .build())
        built.run_epoch()
        with StatusServer() as srv:
            rc = obsctl.main(["diagnose", "--port", str(srv.port)])
        built.close()
        out = capsys.readouterr().out
        assert rc == 0 and "bound:" in out and "evidence:" in out

    def test_history_cli_surfaces_404_payload(self, capsys):
        """The server's 404s carry a JSON {error, hint} body; the CLI
        must surface it (exit 2) instead of dying on the bare
        urllib HTTPError before ever reading the payload."""
        with StatusServer() as srv:
            rc = obsctl.main(["history", "--port", str(srv.port)])
        out = capsys.readouterr().out
        assert rc == 2
        assert "no timeseries ring installed" in out
        assert "DMLC_TPU_HISTORY_S" in out   # the hint survives

    def test_history_and_gang_cli(self, capsys):
        ring = obs_ts.install(period_s=60)
        REGISTRY.counter("cli.demo").inc(3)
        ring.sample_now()
        with StatusServer() as srv:
            obs_agg.install(ports=[srv.port], period_s=60)
            obs_agg.active().poll_once()
            rc_h = obsctl.main(["history", "--port", str(srv.port)])
            rc_g = obsctl.main(["gang", "--port", str(srv.port)])
        out = capsys.readouterr().out
        assert rc_h == 0 and "samples spanning" in out
        assert rc_g == 0 and "gang of 1" in out and "up" in out
