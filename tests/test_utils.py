"""Tests for the utils layer (reference: unittest_param/json/config/env/
logging/serializer)."""

import os

import numpy as np
import pytest

from dmlc_tpu.utils.logging import (
    DMLCError, check, check_eq, check_lt, check_notnone, log_fatal,
    set_log_sink,
)
from dmlc_tpu.utils.registry import Registry
from dmlc_tpu.utils.parameter import Parameter, ParamError, field, get_env
from dmlc_tpu.utils.config import Config
from dmlc_tpu.utils import serializer as ser
from dmlc_tpu.io.stream import MemoryStream


class TestLogging:
    def test_check_pass(self):
        check(True)
        check_eq(1, 1)
        check_lt(1, 2)
        assert check_notnone("x") == "x"

    def test_check_fail_messages(self):
        with pytest.raises(DMLCError, match="=="):
            check_eq(1, 2, "context")
        with pytest.raises(DMLCError, match="context"):
            check_eq(1, 2, "context")
        with pytest.raises(DMLCError):
            check_notnone(None)

    def test_fatal_raises(self):
        with pytest.raises(DMLCError, match="boom"):
            log_fatal("boom")

    def test_custom_sink(self):
        got = []
        set_log_sink(lambda lvl, msg: got.append((lvl, msg)))
        try:
            with pytest.raises(DMLCError):
                log_fatal("sunk")
        finally:
            set_log_sink(None)
        assert got == [("FATAL", "sunk")]


class TestRegistry:
    def test_register_find(self):
        reg = Registry.get("TestReg1")

        @reg.register("alpha", description="first")
        def make_alpha():
            return "A"

        assert reg.find("alpha").body() == "A"
        assert reg.find("missing") is None
        assert "alpha" in reg.list_all_names()

    def test_duplicate_raises(self):
        reg = Registry.get("TestReg2")
        reg.register("x", body=lambda: 1)
        with pytest.raises(DMLCError, match="already registered"):
            reg.register("x", body=lambda: 2)

    def test_lookup_error_lists_names(self):
        reg = Registry.get("TestReg3")
        reg.register("only", body=lambda: 1)
        with pytest.raises(DMLCError, match="only"):
            reg.lookup("nope")

    def test_singleton(self):
        assert Registry.get("TestReg4") is Registry.get("TestReg4")


class MyParam(Parameter):
    num_hidden = field(100, lower=1, upper=10000, desc="hidden units")
    learning_rate = field(0.01, lower=0.0)
    act = field("relu", enum=["relu", "tanh", "sigmoid"])
    use_bias = field(True)
    name = field(dtype=str)  # required
    seed = field(None, dtype=int, optional=True)


class TestParameter:
    def test_defaults_and_kwargs_strings(self):
        p = MyParam(name="m", num_hidden="200", learning_rate="0.1",
                    use_bias="false")
        assert p.num_hidden == 200 and isinstance(p.num_hidden, int)
        assert p.learning_rate == 0.1
        assert p.use_bias is False
        assert p.act == "relu"

    def test_required_missing(self):
        with pytest.raises(ParamError, match="name"):
            MyParam(num_hidden=5)

    def test_range_enum_violations(self):
        with pytest.raises(ParamError, match="lower bound"):
            MyParam(name="m", num_hidden=0)
        with pytest.raises(ParamError, match="upper bound"):
            MyParam(name="m", num_hidden=20000)
        with pytest.raises(ParamError, match="allowed set"):
            MyParam(name="m", act="gelu")

    def test_unknown_key(self):
        with pytest.raises(ParamError, match="unknown"):
            MyParam(name="m", bogus=1)
        p = MyParam()
        rest = p.init_allow_unknown({"name": "m", "bogus": 1})
        assert rest == {"bogus": 1}

    def test_optional_none_spelling(self):
        p = MyParam(name="m", seed="None")
        assert p.seed is None
        p2 = MyParam(name="m", seed="7")
        assert p2.seed == 7
        assert p2.get_dict()["seed"] == "7"
        assert p.get_dict()["seed"] == "None"

    def test_doc_generation(self):
        doc = MyParam.__DOC__
        assert "num_hidden" in doc and "hidden units" in doc
        assert "required" in doc  # name has no default

    def test_setattr_validates(self):
        p = MyParam(name="m")
        with pytest.raises(ParamError):
            p.num_hidden = -1

    def test_update_dict_consumes(self):
        p = MyParam(name="m")
        kw = {"num_hidden": "5", "other": "x"}
        p.update_dict(kw)
        assert kw == {"other": "x"}
        assert p.num_hidden == 5


class TestGetEnv:
    def test_get_env(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_TEST_X", "42")
        assert get_env("DMLC_TPU_TEST_X", int) == 42
        assert get_env("DMLC_TPU_TEST_MISSING", int, 7) == 7
        with pytest.raises(ParamError):
            get_env("DMLC_TPU_TEST_MISSING2", int)


class TestConfig:
    def test_parse_basic(self):
        cfg = Config("a = 1\nb = hello # comment\n# full comment\nc=3")
        assert cfg.get_param("a") == "1"
        assert cfg.get_param("b") == "hello"
        assert cfg.get_param("c") == "3"

    def test_multi_value(self):
        cfg = Config("k = 1\nk = 2")
        assert cfg.get_all("k") == ["1", "2"]
        assert cfg.get_param("k") == "2"
        assert list(cfg) == [("k", "1"), ("k", "2")]

    def test_quoted_values(self):
        cfg = Config('msg = "hello # world \\"quoted\\""')
        assert cfg.get_param("msg") == 'hello # world "quoted"'

    def test_proto_roundtrip(self):
        cfg = Config('a = 1\nmsg = "x y"')
        cfg2 = Config(cfg.proto_string())
        assert list(cfg) == list(cfg2)

    def test_bad_line(self):
        with pytest.raises(DMLCError):
            Config("nonsense line")


class TestSerializer:
    def test_scalars_roundtrip(self):
        s = MemoryStream()
        ser.write_u32(s, 7)
        ser.write_i64(s, -5)
        ser.write_f32(s, 1.5)
        ser.write_str(s, "héllo")
        s.seek(0)
        assert ser.read_u32(s) == 7
        assert ser.read_i64(s) == -5
        assert ser.read_f32(s) == 1.5
        assert ser.read_str(s) == "héllo"

    def test_ndarray_roundtrip(self, rng):
        a = rng.randn(3, 4).astype(np.float32)
        s = MemoryStream()
        ser.write_ndarray(s, a)
        s.seek(0)
        b = ser.read_ndarray(s)
        np.testing.assert_array_equal(a, b)
        assert b.dtype == np.float32

    def test_tagged_tree_roundtrip(self, rng):
        obj = {"a": [1, 2.5, "x", None, True], "b": (b"bytes",),
               "arr": rng.randint(0, 100, 10).astype(np.uint32)}
        s = MemoryStream()
        ser.serialize(obj, s)
        s.seek(0)
        out = ser.deserialize(s)
        assert out["a"] == obj["a"]
        assert out["b"] == obj["b"]
        np.testing.assert_array_equal(out["arr"], obj["arr"])

    def test_eof_raises(self):
        s = MemoryStream(b"\x01\x02")
        with pytest.raises(DMLCError, match="EOF"):
            s.read_exact(5)


class TestEndianGolden:
    """On-disk byte-order goldens (reference: test/unittest/unittest_endian.cc
    — the serialized format must be identical regardless of host endianness;
    ours is frozen little-endian)."""

    def test_scalar_goldens(self):
        s = MemoryStream()
        ser.write_u32(s, 0x11223344)
        ser.write_i64(s, -2)
        ser.write_f32(s, 1.0)
        assert s.getvalue() == (
            b"\x44\x33\x22\x11"                      # u32 LE
            + b"\xfe\xff\xff\xff\xff\xff\xff\xff"    # i64 two's complement LE
            + b"\x00\x00\x80\x3f")                   # f32 IEEE-754 LE

    def test_ndarray_payload_is_le(self):
        s = MemoryStream()
        ser.write_ndarray(s, np.array([0x01020304], dtype=">u4"))
        raw = s.getvalue()
        # payload bytes (last 4) must be little-endian regardless of the
        # source array's byte order
        assert raw[-4:] == b"\x04\x03\x02\x01"

    def test_rowblock_page_magic_bytes(self):
        from dmlc_tpu.data.rowblock import RowBlock, RowBlockContainer
        c = RowBlockContainer(np.uint32)
        c.push_block(RowBlock(offset=np.array([0, 1], np.int64),
                              label=np.array([1.0], np.float32),
                              index=np.array([7], np.uint32),
                              value=np.array([0.5], np.float32)))
        s = MemoryStream()
        c.save(s)
        # page magic 0x42524F57 ("WORB" little-endian on disk)
        assert s.getvalue()[:4] == b"\x57\x4f\x52\x42"
