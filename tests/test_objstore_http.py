"""The real HTTP ranged-GET client (io/objstore/http_client.py) —
what the parametrized FS-surface suite in test_objstore.py does NOT
pin: the auth-header hook, Range dialect corner cases, torn-transfer
detection, the dtpc transfer coding, the endpoint env contract, and
import-optionality."""

import subprocess
import sys
import tempfile

import pytest

import dmlc_tpu.io.objstore as objstore
from dmlc_tpu.io.codec import decode_page, is_encoded
from dmlc_tpu.io.objstore.emulator import EmulatedObjectStore
from dmlc_tpu.io.objstore.http_client import (
    HttpObjectStoreClient, RemoteObjectInfo,
)
from dmlc_tpu.utils.logging import DMLCError
from objstore_http_server import ObjstoreHttpServer


@pytest.fixture
def srv():
    inner = EmulatedObjectStore(tempfile.mkdtemp())
    server = ObjstoreHttpServer(inner)
    yield server
    server.close()


class TestAuthHook:
    def test_static_headers_and_callable(self, srv):
        c0 = HttpObjectStoreClient(srv.endpoint)
        c0.put("b", "a.bin", b"payload")
        srv.require_headers = {"Authorization": "Bearer tok"}
        with pytest.raises(IOError, match="HTTP 403"):
            c0.get("b", "a.bin", 0, 3)
        static = HttpObjectStoreClient(
            srv.endpoint, auth={"Authorization": "Bearer tok"})
        assert static.get("b", "a.bin", 0, 3) == b"pay"
        calls = []

        def rotating():
            calls.append(1)
            return {"Authorization": "Bearer tok"}

        hook = HttpObjectStoreClient(srv.endpoint, auth=rotating)
        assert hook.get("b", "a.bin", 3, 7) == b"load"
        assert hook.head("b", "a.bin").size == 7
        assert len(calls) == 2, "the auth hook must run PER request"

    def test_denied_put_and_head(self, srv):
        srv.require_headers = {"X-Key": "k"}
        c = HttpObjectStoreClient(srv.endpoint)
        with pytest.raises(IOError):
            c.put("b", "x", b"z")
        with pytest.raises(IOError):
            c.head("b", "x")


class TestRangeDialect:
    def test_open_ended_and_clamped_ranges(self, srv):
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "r.bin", b"0123456789")
        assert c.get("b", "r.bin") == b"0123456789"
        assert c.get("b", "r.bin", 4) == b"456789"
        assert c.get("b", "r.bin", 8, 99) == b"89"  # clamped tail
        assert c.get("b", "r.bin", 5, 5) == b""

    def test_range_ignoring_server_still_exact(self, srv):
        """A server that answers 200 + full body to a Range request:
        the client slices locally — byte-exact, never shifted."""
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "f.bin", bytes(range(100)))
        srv.ignore_range = True
        assert c.get("b", "f.bin", 10, 20) == bytes(range(10, 20))
        assert c.get("b", "f.bin") == bytes(range(100))

    def test_range_ignoring_server_warns_about_wire_cost(self, srv):
        """Each ranged GET against a Range-ignoring server transfers
        the whole object — correct but N× the wire; the operator must
        hear about it (rate-limited warning)."""
        from dmlc_tpu.obs import log as obs_log
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "warn.bin", b"W" * 500)
        srv.ignore_range = True
        obs_log.reset()
        assert c.get("b", "warn.bin", 10, 20) == b"W" * 10
        assert "objstore-http-range-ignored" in obs_log._last_emit
        obs_log.reset()
        # a full-object read is NOT a misuse of such a server: silent
        assert c.get("b", "warn.bin") == b"W" * 500
        assert "objstore-http-range-ignored" not in obs_log._last_emit

    def test_no_change_token_degrades_with_warning(self, srv):
        """An endpoint sending neither ETag nor Last-Modified: change
        detection degrades to size-only — the client must say so."""
        from dmlc_tpu.obs import log as obs_log
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "tok.bin", b"T" * 64)
        srv.no_change_token = True
        obs_log.reset()
        info = c.head("b", "tok.bin")
        assert info.etag == "64-0"  # the degenerate token
        assert "objstore-http-no-change-token" in obs_log._last_emit
        srv.no_change_token = False
        obs_log.reset()
        assert c.head("b", "tok.bin").etag not in ("", "64-0")
        assert "objstore-http-no-change-token" not in obs_log._last_emit

    def test_torn_body_raises_ioerror(self, srv):
        """Content-Length says N, the socket delivers fewer: the
        client must raise a RETRYABLE IOError inside the call — the
        io.objstore.get seam's ladder depends on it."""
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "t.bin", b"Z" * 1000)
        srv.truncate_bodies_to = 100
        with pytest.raises(IOError, match="mid-transfer|torn"):
            c.get("b", "t.bin", 0, 1000)
        srv.truncate_bodies_to = None
        assert c.get("b", "t.bin", 0, 1000) == b"Z" * 1000


class TestEncodedTransfer:
    def test_get_encoded_round_trips_dtpc_frame(self, srv):
        c = HttpObjectStoreClient(srv.endpoint, encoded=True)
        payload = b"compress me " * 500
        c.put("b", "e.bin", payload)
        wire = c.get_encoded("b", "e.bin", 0, len(payload), 6)
        assert is_encoded(wire), "no dtpc frame came back"
        assert len(wire) < len(payload)
        assert decode_page(wire) == payload

    def test_plain_server_reply_stays_unambiguous(self, srv):
        """An endpoint without the coding answers raw bytes; the
        client wraps only what decode_page could misread, so the
        fs.py decode-inside-the-seam path is always correct."""
        c = HttpObjectStoreClient(srv.endpoint, encoded=True)
        payload = b"plain bytes " * 100
        c.put("b", "p.bin", payload)
        srv.support_encoded = False
        wire = c.get_encoded("b", "p.bin", 0, len(payload), 6)
        assert decode_page(wire) == payload
        # a raw payload that STARTS with the frame magic is the
        # ambiguous case: the wrap must keep decode exact
        tricky = b"DTPC" + b"\x00" * 200
        c.put("b", "m.bin", tricky)
        wire = c.get_encoded("b", "m.bin", 0, len(tricky), 6)
        assert decode_page(wire) == tricky

    def test_range_ignoring_dtpc_server_sliced_exactly(self, srv):
        """A server that speaks the coding but ignores Range encodes
        the WHOLE object: the client decodes + slices locally, like
        the plain path — never a permanently-short read."""
        c = HttpObjectStoreClient(srv.endpoint, encoded=True)
        payload = b"whole object " * 300
        c.put("b", "w.bin", payload)
        srv.ignore_range = True
        wire = c.get_encoded("b", "w.bin", 13, 26, 6)
        assert decode_page(wire) == payload[13:26]

    def test_capability_is_per_instance(self, srv):
        plain = HttpObjectStoreClient(srv.endpoint)
        assert not hasattr(plain, "get_encoded"), \
            "fs.py probes hasattr — a plain endpoint must not " \
            "advertise the coding"
        assert hasattr(HttpObjectStoreClient(srv.endpoint,
                                             encoded=True),
                       "get_encoded")


class TestListingConvention:
    def test_listing_unsupported_raises_dmlc_error(self, srv):
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "one.bin", b"x")
        srv.support_list = False
        with pytest.raises(DMLCError, match="dmlc-list"):
            c.list("b")
        assert c.is_prefix("b") is False  # degrades, never raises
        # single-object reads never needed the listing
        assert c.get("b", "one.bin") == b"x"

    def test_info_shape_matches_emulator(self, srv):
        c = HttpObjectStoreClient(srv.endpoint)
        c.put("b", "k.bin", b"abc")
        info = c.head("b", "k.bin")
        assert isinstance(info, RemoteObjectInfo)
        assert (info.size, info.key) == (3, "k.bin")
        assert info.mtime_ns > 0 and info.etag
        listed = c.list("b", "k.bin")
        assert [o.key for o in listed] == ["k.bin"]
        assert listed[0].etag  # the server's etag rides the listing


class TestEndpointContract:
    def test_configure_endpoint_and_env(self, srv, monkeypatch):
        import dmlc_tpu.io.objstore.fs as ofs
        monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
        srv.require_headers = {"Authorization": "Bearer envtok"}
        try:
            c = objstore.configure(
                endpoint=srv.endpoint,
                auth={"Authorization": "Bearer envtok"})
            assert isinstance(c, HttpObjectStoreClient)
            c.put("b", "cfg.bin", b"hi")
            assert c.get("b", "cfg.bin") == b"hi"
            objstore.configure(None)
            # the env contract: endpoint + one static auth header
            monkeypatch.setenv(ofs.ENV_ENDPOINT, srv.endpoint)
            monkeypatch.setenv(ofs.ENV_AUTH,
                               "Authorization: Bearer envtok")
            c2 = objstore.client()
            assert isinstance(c2, HttpObjectStoreClient)
            assert c2.get("b", "cfg.bin") == b"hi"
        finally:
            objstore.configure(None)

    def test_malformed_auth_env_fails_fast(self, srv, monkeypatch):
        """A DMLC_TPU_OBJSTORE_AUTH without the 'Header:' prefix must
        raise at configure time — silently dropping it would send
        unauthenticated requests that die as baffling 403s."""
        import dmlc_tpu.io.objstore.fs as ofs
        monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
        monkeypatch.setenv(ofs.ENV_ENDPOINT, srv.endpoint)
        monkeypatch.setenv(ofs.ENV_AUTH, "Bearer abc123")  # no colon
        try:
            with pytest.raises(DMLCError, match="Header-Name"):
                objstore.client()
        finally:
            objstore.configure(None)

    def test_root_env_outranks_endpoint_env(self, srv, monkeypatch,
                                            tmp_path):
        import dmlc_tpu.io.objstore.fs as ofs
        try:
            monkeypatch.setenv(ofs.ENV_ROOT, str(tmp_path / "root"))
            monkeypatch.setenv(ofs.ENV_ENDPOINT, srv.endpoint)
            c = objstore.client()
            assert isinstance(c, EmulatedObjectStore)
        finally:
            objstore.configure(None)

    def test_bad_endpoint_rejected(self):
        with pytest.raises(DMLCError):
            HttpObjectStoreClient("ftp://host/x")
        with pytest.raises(DMLCError):
            HttpObjectStoreClient("http://")

    def test_traversal_rejected_client_side(self, srv):
        c = HttpObjectStoreClient(srv.endpoint)
        with pytest.raises(DMLCError):
            c.head("..", "x")
        with pytest.raises(DMLCError):
            c.get("b", "../escape")


class TestImportOptional:
    def test_package_import_does_not_load_the_wire_client(self):
        """The emulator remains the test backend: importing the
        objstore package must not pull http_client (or http.client)
        in — only configure(endpoint=...) does."""
        code = ("import sys; import dmlc_tpu.io.objstore; "
                "assert 'dmlc_tpu.io.objstore.http_client' "
                "not in sys.modules, 'wire client imported eagerly'; "
                "print('ok')")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=120)
        assert out.returncode == 0, out.stderr
        assert "ok" in out.stdout
