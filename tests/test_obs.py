"""dmlc_tpu.obs: trace recorder + Chrome export golden keys, probe-vs-
span consistency, metrics registry schema, stall watchdog diagnosis,
rate-limited log channel, gang merging, and the tracing-overhead smoke
gate (tier-1: a tiny traced pipeline must stay within 5% of untraced).
"""

import json
import os
import threading
import time

import pytest

from dmlc_tpu.obs import log as obs_log
from dmlc_tpu.obs import metrics as obs_metrics
from dmlc_tpu.obs import trace as obs_trace
from dmlc_tpu.obs.export import (
    chrome_events, merge_chrome_files, write_chrome,
)
from dmlc_tpu.obs.metrics import REGISTRY, merge_snapshots
from dmlc_tpu.obs.watchdog import Watchdog
from dmlc_tpu.data.threaded_iter import ThreadedIter

CHROME_REQUIRED_KEYS = ("ph", "ts", "pid", "tid", "name")


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts with tracing off and fresh log dedup state."""
    obs_trace.stop()
    obs_log.reset()
    yield
    obs_trace.stop()
    obs_log.reset()


def _write_libsvm(tmp_path, rows=600, name="obs.libsvm"):
    lines = [f"{i % 2} 1:0.5 7:1.25 9:{i}.0" for i in range(rows)]
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestTraceRecorder:
    def test_span_instant_counter_recorded(self):
        rec = obs_trace.start(capacity=100)
        with obs_trace.span("work", "test", {"k": 1}):
            pass
        obs_trace.instant("marker", "test")
        obs_trace.counter("queue", {"depth": 3, "skip": "notnum"})
        assert obs_trace.stop() is rec
        phs = [e[0] for e in rec.events()]
        assert phs == ["X", "i", "C"]
        # counter keeps numeric series only
        assert rec.events()[2][6] == {"depth": 3}

    def test_off_is_noop(self):
        assert obs_trace.active() is None
        with obs_trace.span("ghost"):
            pass
        obs_trace.instant("ghost")
        obs_trace.counter("ghost", {"x": 1})  # nothing raises, no state

    def test_ring_buffer_drops_oldest(self):
        rec = obs_trace.start(capacity=10)
        for i in range(25):
            obs_trace.instant(f"e{i}")
        obs_trace.stop()
        assert rec.recorded == 25
        assert rec.dropped == 15
        names = [e[1] for e in rec.events()]
        assert names == [f"e{i}" for i in range(15, 25)]

    def test_start_over_live_recorder_warns(self):
        from dmlc_tpu.utils.logging import set_log_sink
        hits = []
        set_log_sink(lambda lvl, msg: hits.append((lvl, msg)))
        try:
            obs_trace.start()
            obs_trace.instant("doomed")
            obs_trace.start()  # replaces: the buffered event is gone
            obs_trace.stop()
        finally:
            set_log_sink(None)
        assert any("replacing an active recorder" in m
                   for _, m in hits), hits

    def test_thread_names_tracked(self):
        rec = obs_trace.start()

        def work():
            obs_trace.instant("from-thread")

        t = threading.Thread(target=work, name="obs-test-thread")
        t.start()
        t.join()
        obs_trace.stop()
        assert "obs-test-thread" in rec.thread_names().values()


class TestChromeExport:
    def test_golden_required_keys(self, tmp_path):
        """Golden: every exported event carries the Chrome trace-event
        required keys; X events carry dur; the envelope is loadable."""
        rec = obs_trace.start()
        with obs_trace.span("stage", "pipeline"):
            time.sleep(0.001)
        obs_trace.instant("tick")
        obs_trace.counter("engine", {"busy_ns": 10})
        obs_trace.stop()
        path = str(tmp_path / "trace.json")
        write_chrome(rec, path)
        doc = json.load(open(path))
        assert "traceEvents" in doc and doc["traceEvents"]
        for ev in doc["traceEvents"]:
            for key in CHROME_REQUIRED_KEYS:
                assert key in ev, (key, ev)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert xs and all("dur" in e for e in xs)
        assert any(e["ph"] == "C" for e in doc["traceEvents"])
        # metadata names the process and the recording threads
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        assert any(e["name"] == "thread_name" for e in metas)

    def test_merge_tags_processes(self, tmp_path):
        a = obs_trace.TraceRecorder()
        a.complete("wa", time.perf_counter(), 0.001)
        b = obs_trace.TraceRecorder()
        b.complete("wb", time.perf_counter(), 0.001)
        pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        write_chrome(a, pa, pid=1001, process_name="rank 0")
        write_chrome(b, pb, pid=1002, process_name="rank 1")
        merged = merge_chrome_files([pa, pb],
                                    str(tmp_path / "gang.json"))
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {1001, 1002}
        assert os.path.exists(tmp_path / "gang.json")

    def test_rank_tag_from_env(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_TASK_ID", "3")
        rec = obs_trace.TraceRecorder()
        rec.instant("x")
        evs = chrome_events(rec)
        proc = [e for e in evs if e["ph"] == "M"
                and e["name"] == "process_name"][0]
        assert "rank 3" in proc["args"]["name"]


class TestPipelineTracing:
    def test_span_count_matches_probe_items(self, tmp_path):
        """Probe-vs-span consistency: with tracing on, every stage
        emits exactly probe.items ``pull/<stage>`` spans, and span
        totals agree with the probe's wait_s (same perf_counter pair,
        so agreement is construction, checked to 10%)."""
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=2048)
                 .batch(64)
                 .prefetch(depth=2)
                 .build())
        path = str(tmp_path / "pipe.json")
        with built.trace(path):
            for _ in built:
                pass
        snap = built.stats()
        built.close()
        evs = json.load(open(path))["traceEvents"]
        for st in snap["stages"]:
            spans = [e for e in evs
                     if e["ph"] == "X" and e["name"] == f"pull/{st['name']}"]
            assert len(spans) == st["items"], st["name"]
            total_s = sum(e["dur"] for e in spans) / 1e6
            # the terminal end-of-stream wait is a separate span
            ends = [e for e in evs if e["ph"] == "X"
                    and e["name"] == f"pull/{st['name']}.end"]
            total_s += sum(e["dur"] for e in ends) / 1e6
            assert total_s == pytest.approx(st["wait_s"],
                                            rel=0.10, abs=0.002)

    def test_queue_wait_spans_present(self, tmp_path):
        """ThreadedIter waits appear as queue-category spans under the
        names the docs promise."""
        rec = obs_trace.start()
        ti = ThreadedIter(max_capacity=1, name="obs.demo")

        src = iter(range(8))
        ti.init(lambda: next(src, None))
        time.sleep(0.05)  # producer fills the 1-slot queue and blocks
        while ti.next() is not None:
            time.sleep(0.005)  # slow consumer: producer re-blocks
        ti.destroy()
        obs_trace.stop()
        names = {e[1] for e in rec.events()}
        assert "obs.demo.producer_wait" in names

    def test_overhead_smoke_under_5pct(self, tmp_path):
        """Tier-1 gate: tracing a small pipeline costs <5% wall time
        vs tracing off. One shared pipeline, traced and untraced
        epochs INTERLEAVED (off,on × 5), judged on the QUIETEST
        adjacent pair — climate is shared inside a pair on this
        burstable host, where min-vs-min across rounds flaked on 2x
        wall swings (the PR-10 profiler gate's statistic, applied to
        this gate for the same reason); plus a small absolute slack
        for scheduler noise on sub-100ms epochs."""
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=4000, name="overhead.libsvm")
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=4096)
                 .batch(256)
                 .build())

        def epoch_wall():
            t0 = time.perf_counter()
            for _ in built:
                pass
            return time.perf_counter() - t0

        epoch_wall()  # warm caches/imports outside the measurement
        off, on = [], []
        recorded = 0
        for _ in range(5):
            off.append(epoch_wall())
            obs_trace.start()
            try:
                on.append(epoch_wall())
            finally:
                recorded += obs_trace.stop().recorded
        built.close()
        assert recorded > 0  # tracing was actually on
        grace = 0.010 / min(off)  # flat 10 ms, scaled to the wall
        ratios = [a / b for a, b in zip(on, off)]
        assert min(ratios) <= 1.05 + grace, (on, off, ratios)


class TestMetricsRegistry:
    def test_snapshot_schema(self):
        """The versioned snapshot shape (schema 1) is pinned: bump
        METRICS_SCHEMA when changing it."""
        reg = obs_metrics.MetricsRegistry()
        reg.counter("events").inc(3)
        reg.gauge("tier").set("pages")
        reg.histogram("wait_s").observe(0.25)
        snap = reg.snapshot()
        assert snap["schema"] == obs_metrics.METRICS_SCHEMA == 1
        for key in ("schema", "pid", "rank", "counters", "gauges",
                    "histograms", "collectors"):
            assert key in snap, key
        assert snap["counters"]["events"] == 3
        assert snap["gauges"]["tier"] == "pages"
        h = snap["histograms"]["wait_s"]
        assert h["count"] == 1 and h["min"] == h["max"] == 0.25
        assert sum(h["buckets"].values()) == 1
        json.dumps(snap)  # plain JSON end to end

    def test_collector_registration_and_weak_drop(self):
        reg = obs_metrics.MetricsRegistry()

        class Surface:
            def stats(self):
                return {"produced": 7}

        s = Surface()
        name = reg.register("queue/x", s, Surface.stats)
        assert reg.snapshot()["collectors"][name] == {"produced": 7}
        del s  # weakly held: the surface drops out on its own
        import gc
        gc.collect()
        assert name not in reg.snapshot()["collectors"]

    def test_collector_name_collision_suffixed(self):
        reg = obs_metrics.MetricsRegistry()

        class Surface:
            def stats(self):
                return {}

        a, b = Surface(), Surface()
        na = reg.register("queue/q", a, Surface.stats)
        nb = reg.register("queue/q", b, Surface.stats)
        assert na != nb and na == "queue/q"

    def test_collector_exception_reports_none(self):
        reg = obs_metrics.MetricsRegistry()

        class Broken:
            def stats(self):
                raise RuntimeError("torn down")

        b = Broken()
        name = reg.register("broken", b, Broken.stats)
        assert reg.snapshot()["collectors"][name] is None

    def test_existing_surfaces_register(self):
        """The five pre-obs stats() surfaces land in one snapshot: a
        named ThreadedIter and the global profiler here (native engine
        + pipeline covered by their own suites)."""
        ti = ThreadedIter(max_capacity=2, name="reg.demo")
        src = iter([1, 2])
        ti.init(lambda: next(src, None))
        while ti.next() is not None:
            pass
        snap = REGISTRY.snapshot()
        keys = [k for k in snap["collectors"] if k.startswith("queue/reg.demo")]
        assert keys, snap["collectors"].keys()
        got = snap["collectors"][keys[0]]
        assert got["produced"] == 2 and "capacity" in got
        assert "profiler" in snap["collectors"]
        ti.destroy()
        assert not [k for k in REGISTRY.snapshot()["collectors"]
                    if k.startswith("queue/reg.demo")]

    def test_merge_snapshots_keys_by_rank(self):
        a = {"schema": 1, "pid": 10, "rank": 0, "counters": {}}
        b = {"schema": 1, "pid": 11, "rank": 1, "counters": {}}
        c = {"schema": 1, "pid": 12, "rank": None, "counters": {}}
        merged = merge_snapshots([a, b, c])
        assert set(merged["workers"]) == {"rank0", "rank1", "pid12"}


class TestWatchdog:
    def test_stall_produces_diagnosis_report(self, tmp_path):
        """Acceptance: a deliberate stall yields ONE report naming the
        blocked stage and its queue state, with metrics + stacks."""
        release = threading.Event()

        def blocked_next():
            release.wait(30.0)  # deliberate wedge
            return None

        ti = ThreadedIter(max_capacity=2, name="stalled.stage")
        ti.init(blocked_next)
        report_path = str(tmp_path / "stall.json")
        wd = Watchdog(threshold_s=0.15, interval_s=0.05,
                      report_path=report_path)
        consumer = threading.Thread(target=ti.next, daemon=True)
        with wd:
            consumer.start()
            deadline = time.time() + 5.0
            while not wd.reports and time.time() < deadline:
                time.sleep(0.02)
        release.set()
        consumer.join(timeout=5.0)
        ti.destroy()
        assert wd.reports, "watchdog never fired"
        report = wd.reports[0]
        blocked = report["blocked"]
        names = [b["name"] for b in blocked]
        assert "stalled.stage.consumer_wait" in names, names
        entry = [b for b in blocked
                 if b["name"] == "stalled.stage.consumer_wait"][0]
        assert entry["blocked_s"] >= 0.15
        # queue state rides in the report
        assert entry["detail"]["qsize"] == 0
        assert entry["detail"]["capacity"] == 2
        # metrics snapshot + all-thread stacks
        assert report["metrics"]["schema"] == obs_metrics.METRICS_SCHEMA
        assert "Thread" in report["stacks"]
        # and the JSON report file landed
        on_disk = json.load(open(report_path))
        assert on_disk["kind"] == "dmlc_tpu_stall_report"
        assert on_disk["blocked"][0]["name"] == entry["name"]

    def test_stage_exception_leaves_no_phantom_wait(self, tmp_path):
        """A raising stage must unregister its watchdog wait: the
        token leak would later fire a stall report for a pull that
        ended (in an exception) long ago."""
        from dmlc_tpu.pipeline import Pipeline
        from dmlc_tpu.utils.logging import DMLCError
        uri = _write_libsvm(tmp_path, rows=300, name="boom.libsvm")

        def boom(item):
            raise DMLCError("deliberate stage failure")

        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python")
                 .map(boom)
                 .build())
        with Watchdog(threshold_s=0.1, interval_s=0.03) as wd:
            with pytest.raises(DMLCError):
                for _ in built:
                    pass
            time.sleep(0.3)  # several polls past the threshold
            assert wd.reports == [], wd.reports
        built.close()

    def test_no_report_below_threshold(self):
        ti = ThreadedIter(max_capacity=2, name="quick.stage")
        src = iter(range(5))
        ti.init(lambda: next(src, None))
        with Watchdog(threshold_s=5.0, interval_s=0.05) as wd:
            while ti.next() is not None:
                pass
            time.sleep(0.2)
        ti.destroy()
        assert wd.reports == []

    def test_replacing_watchdog_inherits_blocked_waits(self):
        """A successor watchdog must see a pull that was ALREADY
        blocked when it took over (blocked waits never re-register, so
        neither start()'s predecessor-stop nor a late stop() may clear
        the shared registry); the predecessor's poll thread is stopped
        so stalls are not double-reported."""
        release = threading.Event()
        ti = ThreadedIter(max_capacity=2, name="handover")
        ti.init(lambda: (release.wait(30.0), None)[1])
        a = Watchdog(threshold_s=0.15, interval_s=0.04).start()
        consumer = threading.Thread(target=ti.next, daemon=True)
        consumer.start()
        time.sleep(0.05)          # the wait registers under A
        b = Watchdog(threshold_s=0.15, interval_s=0.04).start()
        a.stop()                  # late stop must not blind B
        deadline = time.time() + 5.0
        while not b.reports and time.time() < deadline:
            time.sleep(0.02)
        b.stop()
        release.set()
        consumer.join(timeout=5.0)
        ti.destroy()
        assert a.reports == []    # predecessor was stopped, not racing
        assert b.reports, "successor never saw the inherited stall"
        assert [x["name"] for x in b.reports[0]["blocked"]] \
            == ["handover.consumer_wait"]

    def test_one_report_per_stall(self, tmp_path):
        release = threading.Event()

        def blocked_next():
            release.wait(30.0)
            return None

        ti = ThreadedIter(max_capacity=2)
        ti.init(blocked_next)
        wd = Watchdog(threshold_s=0.1, interval_s=0.03)
        consumer = threading.Thread(target=ti.next, daemon=True)
        with wd:
            consumer.start()
            deadline = time.time() + 5.0
            while not wd.reports and time.time() < deadline:
                time.sleep(0.02)
            time.sleep(0.3)  # several more polls over the SAME stall
            n = len(wd.reports)
        release.set()
        consumer.join(timeout=5.0)
        ti.destroy()
        assert n == 1


class TestObsLog:
    def _capture(self):
        from dmlc_tpu.utils.logging import set_log_sink
        hits = []
        set_log_sink(lambda lvl, msg: hits.append((lvl, msg)))
        return hits

    def _restore(self):
        from dmlc_tpu.utils.logging import set_log_sink
        set_log_sink(None)

    def test_warn_once_dedups(self):
        hits = self._capture()
        try:
            assert obs_log.warn_once("k1", "first") is True
            assert obs_log.warn_once("k1", "second") is False
            assert obs_log.warn_once("k2", "other") is True
            assert [m for _, m in hits] == ["first", "other"]
        finally:
            self._restore()

    def test_warn_limited_rate(self):
        hits = self._capture()
        try:
            assert obs_log.warn_limited("r", "a", min_interval_s=60)
            assert not obs_log.warn_limited("r", "b", min_interval_s=60)
            assert obs_log.warn_limited("r", "c", min_interval_s=0.0)
            assert len(hits) == 2
        finally:
            self._restore()

    def test_nonzero_rank_suppressed(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_TASK_ID", "2")
        hits = self._capture()
        try:
            before = REGISTRY.counter("log.suppressed.rank").value
            assert obs_log.warn_once("gang-key", "dup") is False
            assert hits == []
            assert REGISTRY.counter("log.suppressed.rank").value \
                == before + 1
            # rank-local facts opt out of the gang dedup
            assert obs_log.warn_once("local-key", "mine",
                                     all_ranks=True) is True
            assert [m for _, m in hits] == ["mine"]
        finally:
            self._restore()


class TestGangTracing:
    def test_trace_if_env_and_merge(self, tmp_path, monkeypatch):
        d = str(tmp_path / "gang")
        monkeypatch.setenv("DMLC_TPU_TRACE_DIR", d)
        monkeypatch.setenv("DMLC_TPU_TASK_ID", "0")
        with obs_trace.trace_if_env():
            with obs_trace.span("worker-work"):
                pass
        assert os.path.exists(os.path.join(d, "trace-rank0.json"))
        from dmlc_tpu.parallel.launch import merge_gang_traces
        out = merge_gang_traces(d)
        assert out is not None
        merged = json.load(open(out))
        assert any(e.get("name") == "worker-work"
                   for e in merged["traceEvents"])

    def test_trace_if_env_noop_without_env(self, monkeypatch):
        monkeypatch.delenv("DMLC_TPU_TRACE_DIR", raising=False)
        with obs_trace.trace_if_env():
            assert obs_trace.active() is None

    def test_merge_gang_traces_empty_dir(self, tmp_path):
        from dmlc_tpu.parallel.launch import merge_gang_traces
        assert merge_gang_traces(str(tmp_path)) is None


class TestProfilerShim:
    def test_deprecated_import_warns_and_aliases(self):
        import dmlc_tpu.utils.profiler as shim
        with pytest.warns(DeprecationWarning):
            cls = shim.Profiler
        assert cls is obs_trace.Profiler
        with pytest.warns(DeprecationWarning):
            assert shim.trace is obs_trace.jax_trace

    def test_profiler_stage_feeds_recorder(self):
        rec = obs_trace.start()
        p = obs_trace.Profiler()
        with p.stage("fold", nbytes=100, items=2):
            pass
        obs_trace.stop()
        st = p.stats()["fold"]
        assert st.calls == 1 and st.bytes == 100
        spans = [e for e in rec.events() if e[0] == "X"
                 and e[1] == "fold"]
        assert len(spans) == 1  # one span API: stage() == span
