"""Native C++ engine: byte-parity with the Python golden
(the BASELINE "CSR byte-identical" criterion), shard parity, error
propagation, float-parse contract."""

import os
import subprocess
import sys

import numpy as np
import pytest

from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.rowblock import RowBlockContainer
from dmlc_tpu.utils.logging import DMLCError


def _ensure_native() -> bool:
    from dmlc_tpu import native
    if native.native_available():
        return True
    try:
        subprocess.run([sys.executable, "-m", "dmlc_tpu.native.build"],
                       check=True, capture_output=True, timeout=300)
    except Exception:
        return False
    native._tried = False  # re-probe after build
    return native.native_available()


pytestmark = pytest.mark.skipif(not _ensure_native(),
                                reason="native engine not buildable")


def parse_all(uri, engine, k=0, n=1, fmt="libsvm", **kw):
    c = RowBlockContainer(np.uint32)
    p = Parser.create(uri, k, n, format=fmt, engine=engine, **kw)
    for b in p:
        c.push_block(b)
    if hasattr(p, "destroy"):
        p.destroy()
    return c.get_block()


@pytest.fixture
def libsvm_file(tmp_path, rng):
    lines = []
    for i in range(800):
        nnz = rng.randint(0, 15)
        idx = np.sort(rng.choice(2000, nnz, replace=False))
        feats = " ".join(f"{j}:{rng.rand():.9g}" for j in idx)
        qid = f"qid:{i // 10} " if i % 3 == 0 else ""
        lines.append(f"{(-1) ** i} {qid}{feats}".rstrip())
    p = tmp_path / "t.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


class TestEngineParity:
    def test_libsvm_whole(self, libsvm_file):
        g = parse_all(libsvm_file, "python")
        n = parse_all(libsvm_file, "native")
        assert g.content_hash() == n.content_hash()

    @pytest.mark.parametrize("nparts", [2, 3, 5])
    def test_libsvm_sharded(self, libsvm_file, nparts):
        g = parse_all(libsvm_file, "python")
        c = RowBlockContainer(np.uint32)
        for k in range(nparts):
            c.push_block(parse_all(libsvm_file, "native", k, nparts))
        assert c.get_block().content_hash() == g.content_hash()

    def test_libsvm_short_token_shape_parity(self, tmp_path, rng):
        # r4: the fused short-token fast path ("d:d"/"dd:d"/"ddd:d",
        # branchless colon-find) — parity over its boundary and
        # FALLTHROUGH shapes: mixed 1-4 digit indices (4-digit falls to
        # the general path), leading zeros, multi-digit/float/signed
        # values, '+' prefixes, qid tokens, tokens abutting the slice
        # end, CRLF, and blank lines
        tok = ["7:1", "42:3", "122:9", "0:0", "00:1", "007:5",  # fused
               "1234:1", "9:12", "3:1.5", "8:-1", "+55:2", "6:1e0"]
        lines = []
        for i in range(600):
            n = rng.randint(1, 8)
            toks = [tok[rng.randint(len(tok))] for _ in range(n)]
            if i % 7 == 0:
                toks.insert(0, f"qid:{i}")
            lines.append(f"{(-1) ** i} " + " ".join(toks))
        lines.append("1 55:7")    # token abuts EOF (no trailing sep)
        body = "\n".join(lines) + "\n1 3:1\r\n\n1 2:2"
        p = tmp_path / "short.libsvm"
        p.write_bytes(body.encode())
        g = parse_all(str(p), "python")
        n = parse_all(str(p), "native")
        assert g.content_hash() == n.content_hash()
        # and sharded reads stitch to the same bytes
        c = RowBlockContainer(np.uint32)
        for k in range(3):
            c.push_block(parse_all(str(p), "native", k, 3))
        assert c.get_block().content_hash() == g.content_hash()

    def test_libsvm_fixed6_value_shape_parity(self, tmp_path, rng):
        # r4: the fused "d.dddddd" value path (%.6f export shape)
        # computes the float as one exact-operand IEEE division; this
        # pins byte parity with the python golden over the edge shapes
        # AND over rows that mix matching and non-matching values (the
        # per-token fallback inside the fixed6 kernel variant)
        edge = ["0.000000", "9.999999", "1.000000", "0.000001",
                "5.500000", "0.123456"]
        other = ["10.123456", "0.12345", "0.1234567", "2", "3e-1",
                 "0.123456e1", "-0.500000"]
        lines = []
        for i in range(400):
            vals = [edge[rng.randint(len(edge))] for _ in range(5)]
            if i % 3 == 0:  # mixed rows exercise the in-variant fallback
                vals[rng.randint(5)] = other[rng.randint(len(other))]
            feats = " ".join(f"{j * 7 + 3}:{v}" for j, v in enumerate(vals))
            lines.append(f"{i % 2} {feats}")
        # first line decides the probe: make it match fixed6
        lines.insert(0, "1 3:0.654321 10:0.111111")
        p = tmp_path / "f6.libsvm"
        p.write_bytes(("\n".join(lines) + "\n").encode())
        g = parse_all(str(p), "python")
        n = parse_all(str(p), "native")
        assert g.content_hash() == n.content_hash()

    def test_csv_parity(self, tmp_path, rng):
        rows = [",".join(f"{rng.randn():.7g}" for _ in range(8))
                for _ in range(500)]
        p = tmp_path / "d.csv"
        p.write_bytes(("\n".join(rows) + "\n").encode())
        g = parse_all(str(p), "python", fmt="csv", label_column=0)
        n = parse_all(str(p), "native", fmt="csv", label_column=0)
        assert g.content_hash() == n.content_hash()

    def test_libfm_fused_shape_parity(self, tmp_path, rng):
        # r4: the libfm raw-cursor rewrite — parity over the fused
        # branches AND their fallthroughs: sign labels, single-digit /
        # fixed6 / general values, 8+-digit fields and indices (general
        # path), a mid-slice >u32 index (widen + cursor resync), and a
        # missing trailing newline
        tok = ["3:17:1", "0:0:0", "30:99999:0.123456", "7:123:0.5",
               "12345678:5:1",          # 8-digit field -> general path
               "2:123456789:2",         # 9-digit index -> general path
               "1:5000000000:1",        # >u32 index -> widen + resync
               "+4:8:1", "-2:9:0.25",   # signed fields -> general path
               "5:6:1e-2", "8:9:-3.5"]
        lines = []
        for i in range(500):
            n = rng.randint(1, 7)
            toks = [tok[rng.randint(len(tok))] for _ in range(n)]
            lab = ["1", "-1", "+1", "0", "0.5"][rng.randint(5)]
            lines.append(f"{lab} " + " ".join(toks))
        body = "\n".join(lines) + "\n1 3:4:7"  # no trailing newline
        p = tmp_path / "fm.libfm"
        p.write_bytes(body.encode())
        g = parse_all(str(p), "python", fmt="libfm")
        n = parse_all(str(p), "native", fmt="libfm")
        assert g.content_hash() == n.content_hash()
        assert n.field is not None
        # and with a u64 container the widened index survives intact
        gc = RowBlockContainer(np.uint64)
        pg = Parser.create(str(p), 0, 1, format="libfm", engine="native",
                           index_dtype=np.uint64)
        for blk in pg:
            gc.push_block(blk)
        if hasattr(pg, "destroy"):
            pg.destroy()
        assert int(gc.get_block().index.max()) == 5000000000

    def test_csv_fixed6_cell_shape_parity(self, tmp_path, rng):
        # r4: the fused "d.dddddd" CELL path (csv flavor) — parity over
        # edge shapes and rows mixing matching and non-matching cells
        # (the per-cell fallback inside the fixed6 variant), including
        # whitespace-padded cells and row-final cells before newline
        edge = ["0.000000", "9.999999", "1.000000", "0.000001",
                "0.123456"]
        other = ["10.123456", "0.12345", "0.1234567", "2", "3e-1",
                 "-0.500000", " 0.123456", "0.123456 "]
        lines = ["1,0.654321,0.111111,0.222222"]  # probe: fixed6 selected
        for i in range(400):
            cells = [edge[rng.randint(len(edge))] for _ in range(3)]
            if i % 3 == 0:
                cells[rng.randint(3)] = other[rng.randint(len(other))]
            lines.append(f"{i % 2}," + ",".join(cells))
        p = tmp_path / "f6.csv"
        p.write_bytes(("\n".join(lines) + "\n").encode())
        g = parse_all(str(p), "python", fmt="csv", label_column=0)
        n = parse_all(str(p), "native", fmt="csv", label_column=0)
        assert g.content_hash() == n.content_hash()

    def test_csv_sparse_mode_parity_and_semantics(self, tmp_path, rng):
        # r4 (BASELINE config 2 "dense + sparse"): sparse=True drops
        # zero cells in BOTH engines identically, indices keep the
        # column ordinal, and -0.0 counts as zero. Mixed zero shapes
        # ("0", "0.0", "0.000000", "-0.0") land on both the fused
        # fixed6 and the general cell paths.
        zero = ["0", "0.0", "0.000000", "-0.0", "0e0"]
        val = ["1.5", "0.123456", "2", "9.999999"]
        lines = ["1,0.654321,0.000000,0.111111"]  # fixed6 probe line
        for i in range(400):
            cells = [(zero if rng.rand() < 0.5 else val)[
                rng.randint(4)] for _ in range(3)]
            lines.append(f"{i % 2}," + ",".join(cells))
        p = tmp_path / "sp.csv"
        p.write_bytes(("\n".join(lines) + "\n").encode())
        g = parse_all(str(p), "python", fmt="csv", label_column=0,
                      sparse=True)
        n = parse_all(str(p), "native", fmt="csv", label_column=0,
                      sparse=True)
        assert g.content_hash() == n.content_hash()
        assert (g.value != 0).all()          # zeros really dropped
        dense = parse_all(str(p), "python", fmt="csv", label_column=0)
        assert g.nnz < dense.nnz             # and the mode differs
        assert dense.size == g.size          # same rows either way

    def test_csv_weight_column(self, tmp_path):
        p = tmp_path / "w.csv"
        p.write_bytes(b"1,0.5,9\n0,2.0,8\n")
        g = parse_all(str(p), "python", fmt="csv", label_column=0,
                      weight_column=1)
        n = parse_all(str(p), "native", fmt="csv", label_column=0,
                      weight_column=1)
        assert g.content_hash() == n.content_hash()

    @pytest.mark.parametrize("delim", ["\t", ";", "|", " "])
    def test_csv_delimiter_parity(self, tmp_path, rng, delim):
        rows = [delim.join(f"{rng.randn():.5g}" for _ in range(6))
                for _ in range(300)]
        p = tmp_path / "d.csv"
        p.write_bytes(("\n".join(rows) + "\n").encode())
        g = parse_all(str(p), "python", fmt="csv", label_column=0,
                      delimiter=delim)
        n = parse_all(str(p), "native", fmt="csv", label_column=0,
                      delimiter=delim)
        assert g.content_hash() == n.content_hash(), repr(delim)

    @pytest.mark.parametrize("delim", ["1", "e", "E", ".", "+", "-"])
    def test_csv_exotic_delimiter_parity(self, tmp_path, delim):
        """Delimiters that can appear INSIDE a decimal must disable the
        fused fast path (`fast_ok` guard, engine.cc) — these cells are
        crafted so a naive fused parse would mis-split them (VERDICT r2
        weak #5: the guard itself was never exercised in CI)."""
        # cells avoid the delimiter char itself; values are chosen so the
        # delimiter char would CONTINUE a decimal if wrongly fused
        # (digit delim between digits, e/./+/- inside number spellings)
        safe = {"1": ["0", "23", "4.5", "67"],
                "e": ["1", "2.5", "30", "4"],
                "E": ["1", "2.5", "30", "4"],
                ".": ["1", "25", "3", "40"],
                "+": ["1", "2.5", "3", "40"],
                "-": ["1", "2.5", "3", "40"]}[delim]
        rows = [delim.join(safe), delim.join(reversed(safe)),
                delim.join(safe)]
        p = tmp_path / "x.csv"
        p.write_bytes(("\n".join(rows) + "\n").encode())
        g = parse_all(str(p), "python", fmt="csv", label_column=0,
                      delimiter=delim)
        n = parse_all(str(p), "native", fmt="csv", label_column=0,
                      delimiter=delim)
        assert g.content_hash() == n.content_hash(), repr(delim)

    @pytest.mark.parametrize("cell", ["1.2.3", "1e", "+", "nan.0", "1e+"])
    def test_csv_malformed_decimal_cells_rejected_by_both(self, tmp_path,
                                                          cell):
        """Cells that BEGIN like decimals but are malformed must error in
        both engines (the fused parse may consume a prefix; the boundary
        check must reroute to the exact path, which rejects)."""
        from dmlc_tpu.utils.logging import DMLCError
        p = tmp_path / "bad.csv"
        p.write_bytes(f"1,{cell},3\n".encode())
        for engine in ("python", "native"):
            with pytest.raises((DMLCError, ValueError)):
                parse_all(str(p), engine, fmt="csv", label_column=0)

    @pytest.mark.parametrize("cell,want", [
        ("1.5e3", 1500.0), (".5", 0.5), ("2.", 2.0), ("+3.25", 3.25),
        ("-0", -0.0), ("1e-2", 0.01), ("INF", float("inf")),
    ])
    def test_csv_decimal_edge_cells_parity(self, tmp_path, cell, want):
        """Cells with exponents / bare dots / signs parse identically in
        both engines and to the expected float32 value."""
        import numpy as np
        p = tmp_path / "edge.csv"
        p.write_bytes(f"1,{cell},3\n".encode())
        vals = []
        for engine in ("python", "native"):
            blk = parse_all(str(p), engine, fmt="csv", label_column=0)
            v = np.asarray(blk.value)
            vals.append(v.tobytes())
            got = float(v[0])
            assert got == np.float32(want) or (
                np.isinf(got) and np.isinf(want)), (engine, cell, got)
        assert vals[0] == vals[1]

    def test_libfm_parity(self, tmp_path, rng):
        lines = []
        for i in range(300):
            nnz = rng.randint(1, 8)
            toks = " ".join(
                f"{rng.randint(0, 5)}:{rng.randint(0, 100)}:{rng.rand():.6g}"
                for _ in range(nnz))
            lines.append(f"{i % 2} {toks}")
        p = tmp_path / "x.libfm"
        p.write_bytes(("\n".join(lines) + "\n").encode())
        g = parse_all(str(p), "python", fmt="libfm")
        n = parse_all(str(p), "native", fmt="libfm")
        assert g.content_hash() == n.content_hash()

    def test_crlf_parity(self, tmp_path):
        p = tmp_path / "c.libsvm"
        p.write_bytes(b"1 1:2.5\r\n0 2:1.5\r\n\r\n1 3:0.25\r\n")
        g = parse_all(str(p), "python")
        n = parse_all(str(p), "native")
        assert g.content_hash() == n.content_hash()

    def test_multi_file_parity(self, tmp_path, rng):
        paths = []
        for f in range(3):
            lines = [f"{i % 2} {rng.randint(1, 99)}:{rng.rand():.5g}"
                     for i in range(rng.randint(5, 50))]
            p = tmp_path / f"f{f}.libsvm"
            p.write_bytes(("\n".join(lines) + "\n").encode())
            paths.append(str(p))
        uri = ";".join(paths)
        g = parse_all(uri, "python")
        n = parse_all(uri, "native")
        assert g.content_hash() == n.content_hash()
        c = RowBlockContainer(np.uint32)
        for k in range(4):
            c.push_block(parse_all(uri, "native", k, 4))
        assert c.get_block().content_hash() == g.content_hash()

    def test_indexing_mode_parity(self, tmp_path):
        p = tmp_path / "i.libsvm"
        p.write_bytes(b"1 1:2.0 5:3.0\n0 2:1.0\n")
        for mode in (0, 1, -1):
            g = parse_all(str(p), "python", indexing_mode=mode)
            n = parse_all(str(p), "native", indexing_mode=mode)
            assert g.content_hash() == n.content_hash(), f"mode={mode}"


class TestEngineAutoFallback:
    def test_cache_uri_falls_back_to_python(self, tmp_path):
        """engine='auto' must serve '#cache' URIs via the Python golden
        (the native engine declines them) — and the cached replay still
        matches the direct parse."""
        data = b"".join(f"{i % 2} {i}:1.5\n".encode() for i in range(500))
        p = tmp_path / "c.libsvm"
        p.write_bytes(data)
        cache = tmp_path / "cachefile"
        direct = parse_all(str(p), "auto")
        cached1 = parse_all(f"{p}#{cache}", "auto")   # builds the cache
        cached2 = parse_all(f"{p}#{cache}", "auto")   # replays it
        assert direct.content_hash() == cached1.content_hash()
        assert direct.content_hash() == cached2.content_hash()
        assert cache.exists() or any(
            f.name.startswith(cache.name) for f in tmp_path.iterdir())

    def test_native_refuses_cache_uri_explicitly(self, tmp_path):
        p = tmp_path / "c2.libsvm"
        p.write_bytes(b"1 1:1\n")
        with pytest.raises(DMLCError, match="cache"):
            parse_all(f"{p}#{p}.cache", "native")


class TestNativeErrors:
    def test_bad_token_raises(self, tmp_path):
        p = tmp_path / "bad.libsvm"
        p.write_bytes(b"1 1:2.0\n1 nonsense\n")
        with pytest.raises(DMLCError, match="nonsense"):
            parse_all(str(p), "native")

    def test_bad_label_raises(self, tmp_path):
        p = tmp_path / "bad2.libsvm"
        p.write_bytes(b"abc 1:2.0\n")
        with pytest.raises(DMLCError, match="label"):
            parse_all(str(p), "native")

    def test_ragged_csv_raises(self, tmp_path):
        p = tmp_path / "r.csv"
        p.write_bytes(b"1,2,3\n4,5\n")
        with pytest.raises(DMLCError, match="column"):
            parse_all(str(p), "native", fmt="csv")

    def test_zero_index_mode1_raises(self, tmp_path):
        p = tmp_path / "z.libsvm"
        p.write_bytes(b"1 0:1.0\n")
        with pytest.raises(DMLCError, match="indexing_mode"):
            parse_all(str(p), "native", indexing_mode=1)

    def test_recovers_after_before_first(self, tmp_path):
        p = tmp_path / "ok.libsvm"
        p.write_bytes(b"1 1:2.0\n0 2:3.0\n")
        parser = Parser.create(str(p), 0, 1, format="libsvm",
                               engine="native")
        b1 = [b.content_hash() for b in parser]
        b2 = [b.content_hash() for b in parser]  # before_first replay
        assert b1 == b2
        parser.destroy()


class TestFloatParseContract:
    def test_adversarial_decimals(self, rng):
        from dmlc_tpu.native.bindings import native_parse_float32
        from dmlc_tpu.data.strtonum import parse_float32
        tokens = [b"1.5", b"-0.0", b"0.1", b"1e-45", b"3.4028235e38",
                  b"1.17549435e-38", b"2.2250738585072014e-308",
                  b"9007199254740993", b"0.30000000000000004",
                  b"1.0000000000000002", b".5", b"5.", b"1e-400", b"123456789.123456789",
                  b"4.9406564584124654e-324", b"1.7976931348623157e308"]
        for _ in range(500):
            mantissa = rng.randint(0, 10 ** rng.randint(1, 18))
            exp = rng.randint(-40, 40)
            tokens.append(f"{mantissa}e{exp}".encode())
            tokens.append(f"{mantissa / 10**rng.randint(0, 17):.17g}".encode())
        for t in tokens:
            try:
                golden = parse_float32(t)
            except (ValueError, OverflowError):
                # Python float() raises on overflow for e.g. 1e400? (no,
                # returns inf); keep symmetric anyway
                with pytest.raises(ValueError):
                    native_parse_float32(t)
                continue
            got = native_parse_float32(t)
            assert np.float32(golden).tobytes() == np.float32(got).tobytes(), t

    def test_exhaustive_short_tokens(self):
        """EVERY token of length <= 3 over the decimal charset parses
        (or rejects) identically across engines — exhaustive closure of
        the short-token space where tokenizer edge cases live."""
        from dmlc_tpu.native.bindings import native_parse_float32
        from dmlc_tpu.data.strtonum import parse_float32
        chars = b"0123456789.eE+-"
        tokens = [bytes([a]) for a in chars]
        tokens += [bytes([a, b]) for a in chars for b in chars]
        tokens += [bytes([a, b, c]) for a in chars for b in chars
                   for c in chars]
        diverged = []
        for t in tokens:
            try:
                golden = parse_float32(t)
                gold_ok = True
            except (ValueError, OverflowError):
                gold_ok = False
            try:
                got = native_parse_float32(t)
                nat_ok = True
            except ValueError:
                nat_ok = False
            if gold_ok != nat_ok:
                diverged.append((t, gold_ok, nat_ok))
            elif gold_ok and np.float32(golden).tobytes() != \
                    np.float32(got).tobytes():
                diverged.append((t, float(golden), float(got)))
        assert not diverged, f"{len(diverged)} divergent: {diverged[:10]}"

    def test_underscore_rejected_both(self):
        from dmlc_tpu.native.bindings import native_parse_float32
        from dmlc_tpu.data.strtonum import parse_float32
        with pytest.raises(ValueError):
            parse_float32(b"1_0")
        with pytest.raises(ValueError):
            native_parse_float32(b"1_0")


class TestIndexContract:
    """Frozen index semantics: optional '+', ASCII digits only — identical
    across engines (regression: the engines used to diverge on '+3:v' and
    Python's int() accepted '-'/'_' forms the native engine rejects)."""

    def test_plus_prefixed_index_parity(self, tmp_path):
        p = tmp_path / "plus.libsvm"
        p.write_bytes(b"1 +3:0.5 7:1.25\n0 +0:0.75\n")
        g = parse_all(str(p), "python")
        n = parse_all(str(p), "native")
        assert g.content_hash() == n.content_hash()
        assert g.index.tolist() == [3, 7, 0]

    @pytest.mark.parametrize("tok", [b"-3:1.0", b"1_0:1.0", b"+:1.0"])
    def test_bad_index_rejected_by_both(self, tmp_path, tok):
        p = tmp_path / "badidx.libsvm"
        p.write_bytes(b"1 " + tok + b"\n")
        with pytest.raises(Exception):
            parse_all(str(p), "python")
        with pytest.raises(DMLCError):
            parse_all(str(p), "native")

    def test_strict_uint64_contract(self):
        from dmlc_tpu.data.strtonum import parse_index, parse_uint64
        assert parse_uint64(b"+3") == 3
        assert parse_uint64(b"0") == 0
        assert parse_uint64(str(2 ** 64 - 1).encode()) == 2 ** 64 - 1
        for bad in (b"", b"+", b"-1", b"1_0", b" 1", b"1 ", str(2 ** 64).encode()):
            with pytest.raises(ValueError):
                parse_uint64(bad)
        assert parse_index(b"-5") == -5
        with pytest.raises(ValueError):
            parse_index(b"1_0")


class TestTruncatedFile:
    def test_short_read_raises_not_hangs(self, tmp_path):
        """File shrinking between size listing and read must error, not
        spin the reader thread forever (regression)."""
        import ctypes as C

        from dmlc_tpu.native import get_lib
        lib = get_lib()
        p = tmp_path / "trunc.libsvm"
        p.write_bytes(b"1 1:2.0\n")
        paths = (C.c_char_p * 1)(str(p).encode())
        sizes = (C.c_int64 * 1)(10_000)  # lie: promise more bytes
        h = lib.dtp_parser_create(paths, sizes, 1, 0, 1, b"libsvm", 1,
                                  1 << 20, 0, -1, -1, b",", 0, None,
                                  None)
        assert h
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        parser = NativeLibSVMParser.__new__(NativeLibSVMParser)
        parser._lib = lib
        parser._handle = h
        parser._block = None
        parser._lease = None
        parser._init_outparams()
        parser.index_dtype = np.dtype(np.uint32)
        with pytest.raises(DMLCError, match="short read|truncated"):
            while parser.next():
                pass
        parser.destroy()


class TestDoubleSignRejection:
    """'+-1.5' must be rejected by BOTH engines (regression: the native
    slow path stripped '+' then let from_chars accept the second sign)."""

    @pytest.mark.parametrize("line", [b"1 2:+-1.5\n", b"1 qid:+-7 2:1.0\n",
                                      b"+-1 2:1.0\n"])
    def test_rejected_by_both(self, tmp_path, line):
        p = tmp_path / "ds.libsvm"
        p.write_bytes(line)
        with pytest.raises(Exception):
            parse_all(str(p), "python")
        with pytest.raises(DMLCError):
            parse_all(str(p), "native")

    def test_huge_index_uint64_parity(self, tmp_path):
        """Indices in [2^63, 2^64) flow through both engines (regression:
        the golden stored them in int64 and crashed with OverflowError)."""
        big = 2 ** 63 + 5
        p = tmp_path / "big.libsvm"
        p.write_bytes(f"1 {big}:1.5\n".encode())

        def parse64(engine):
            c = RowBlockContainer(np.uint64)
            pr = Parser.create(str(p), 0, 1, format="libsvm", engine=engine,
                               index_dtype=np.uint64)
            for b in pr:
                c.push_block(b)
            if hasattr(pr, "destroy"):
                pr.destroy()
            return c.get_block()

        g, n = parse64("python"), parse64("native")
        assert g.content_hash() == n.content_hash()
        assert int(g.index[0]) == big
        assert int(n.index[0]) == big


class TestPipelineScaling:
    """The pipeline must impose no serialization beyond the parse work
    itself (VERDICT r1 #1). Real multi-core scaling can't be measured on
    a 1-core CI host, so the proof is structural: a test hook makes each
    chunk's parse take >= T, and with N pool workers M chunks must
    complete in ~ceil(M/N)*T — sleeps overlap only if chunks genuinely
    run concurrently through independent workers. Stage timings also
    prove the reader thread runs concurrently with parse workers."""

    @pytest.fixture
    def chunky_file(self, tmp_path):
        # EXACTLY 16 chunks of 64KB (the engine's minimum chunk size):
        # sized to 15.9 nominal chunks because record-boundary cutting
        # rounds up — a full 16.0 yields a 17th chunk, which caps the
        # achievable 4-worker scaling at 17/ceil(17/4) = 3.4x and turns
        # the 3.2x criterion below into a 94%-efficiency bar that flakes
        # under suite load; at 16 chunks the ideal is 4.0x and 3.2x is
        # the intended 80% (VERDICT r1 #1)
        line = b"1 1:0.5 2:0.25 3:0.125\n"
        p = tmp_path / "chunky.libsvm"
        p.write_bytes(line * (int(15.9 * 65536) // len(line)))
        return str(p)

    def _timed_epoch(self, path, nthreads, delay_ms, touch_rounds=0):
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        import time
        parser = NativeLibSVMParser(path, 0, 1, nthreads=nthreads,
                                    chunk_size=65536)
        parser.set_test_delay_ms(delay_ms)
        if touch_rounds:
            parser.set_test_touch_rounds(touch_rounds)
        t0 = time.perf_counter()
        blocks = 0
        while parser.next():
            blocks += 1
        wall = time.perf_counter() - t0
        stats = parser.stats()
        parser.destroy()
        return wall, blocks, stats

    def test_n_workers_overlap_chunks(self, chunky_file):
        delay = 30
        # best-of-2 per arm: the 4-worker wall's ideal is ~0.15 s, so a
        # few ms of scheduler noise under a loaded suite run can tip the
        # 3.2x criterion without any structural regression — the proof
        # is about overlap, and the best wall is the overlap evidence
        wall1, blocks1, stats1 = self._timed_epoch(chunky_file, 1, delay)
        wall4, blocks4, stats4 = self._timed_epoch(chunky_file, 4, delay)
        wall1 = min(wall1, self._timed_epoch(chunky_file, 1, delay)[0])
        wall4 = min(wall4, self._timed_epoch(chunky_file, 4, delay)[0])
        assert blocks1 == blocks4
        chunks = stats1["chunks"]
        assert chunks >= 8, "fixture should split into many chunks"
        # serial: every chunk pays the delay back-to-back
        assert wall1 >= chunks * delay / 1000 * 0.9
        # 4 workers: delays must overlap 4-wide. Perfect scaling would be
        # ceil(chunks/4) delay-batches; require >= 0.8 * 4 = 3.2x speedup
        # over the serial run (the VERDICT's >=0.8*N criterion).
        scaling = wall1 / wall4
        assert scaling >= 3.2, \
            f"pipeline scaling {scaling:.2f}x < 3.2x with 4 workers " \
            f"({chunks} chunks, wall1={wall1:.2f}s wall4={wall4:.2f}s)"

    def test_n_workers_overlap_with_byte_touching_work(self, chunky_file):
        """VERDICT r3 #5: the sleep proxy doesn't contend for memory
        bandwidth, allocator locks, or the reorder window — this variant
        adds REAL byte-touching work (FNV checksum over every chunk
        byte) on top of the delay. On the 1-core host the checksums
        serialize on the core but overlap other workers' delay windows,
        so with touch ≈ delay/10 near-perfect scaling is still the
        prediction: wall4 ≈ max(M·t, ceil(M/4)·(t+d)) vs wall1 =
        M·(t+d). A hidden serialization around the byte work (a lock
        held across parse, reorder-window blocking) would break the
        overlap and crater the ratio."""
        import pathlib
        # 32 chunks so ceil(M/4) leaves headroom: ideal sleep-only
        # scaling is 32/8 = 4.0x and the 3.0x bar is 75% of ideal
        # (the 17-chunk fixture caps the ideal at 3.4x)
        line = b"1 1:0.5 2:0.25 3:0.125\n"
        path = str(pathlib.Path(chunky_file).with_name("chunky32.libsvm"))
        with open(path, "wb") as f:
            f.write(line * (32 * 65536 // len(line)))
        delay = 30
        # calibrate: how long does one checksum round over the whole
        # file take on this host right now? Target t ~ delay/20 per
        # chunk: within one 4-wide wave the four touches may fully
        # serialize on the single core, so the pessimistic scaling bound
        # is M(d+t) / (ceil(M/4)(d+4t)) — t=d/20 puts that at 3.2x for
        # 33 chunks, above the 3.0x bar (t=d/10 would put it at 2.9x,
        # under it).
        cal_rounds = 16
        w_plain, _, s_plain = self._timed_epoch(path, 1, 0, 0)
        w_touch, _, _ = self._timed_epoch(path, 1, 0, cal_rounds)
        chunks = s_plain["chunks"]
        per_round_per_chunk = max(
            (w_touch - w_plain) / chunks / cal_rounds, 1e-6)
        # cap the rounds: if scheduler noise swallowed the calibration
        # signal (w_touch <= w_plain), the 1e-6 clamp would otherwise
        # explode rounds and the serialized checksums would dominate
        # wall4, failing the test spuriously on a loaded host
        rounds = max(1, min(64,
                            int(delay / 1000 * 0.05 / per_round_per_chunk)))
        wall1, blocks1, _ = self._timed_epoch(path, 1, delay, rounds)
        wall4, blocks4, _ = self._timed_epoch(path, 4, delay, rounds)
        assert blocks1 == blocks4
        scaling = wall1 / wall4
        # bar: 2.8x = ~87% of the 3.21x pessimistic bound above —
        # measured 3.2-3.3x solo, but a loaded CI host (another test
        # stealing the core mid-cell) can shave a few percent and this
        # must not flake the suite; no-overlap serialization would
        # measure ~1x, far below either number
        assert scaling >= 2.8, \
            f"byte-touching pipeline scaling {scaling:.2f}x < 2.8x " \
            f"({chunks} chunks, rounds={rounds}, wall1={wall1:.2f}s " \
            f"wall4={wall4:.2f}s)"

    def test_parse_busy_exceeds_wall_with_pool(self, chunky_file):
        # parse_busy summed over workers must exceed wall when delays
        # overlap — direct evidence N chunks were in flight at once
        wall4, _, stats = self._timed_epoch(chunky_file, 4, 20)
        assert stats["parse_busy_ns"] > 1.5 * stats["wall_ns"]

    def test_reader_runs_ahead(self, chunky_file):
        # with slow parsing, the reader thread must fill the chunk queue
        # while workers are busy (IO/parse overlap)
        _, _, stats = self._timed_epoch(chunky_file, 2, 20)
        assert stats["max_chunk_queue_depth"] >= 2

    def test_stats_sane_without_delay(self, chunky_file):
        wall, blocks, stats = self._timed_epoch(chunky_file, 2, 0)
        assert stats["chunks"] >= blocks
        assert stats["reader_busy_ns"] > 0
        assert stats["parse_busy_ns"] > 0
        assert stats["wall_ns"] > 0


class TestZeroCopyLease:
    """Blocks are zero-copy views into engine arenas; the lease keeps an
    arena alive until released (VERDICT r1 #2)."""

    def test_views_stable_while_held(self, tmp_path):
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        p = tmp_path / "lease.libsvm"
        lines = [f"{i % 2} {i}:{i}.5".encode() for i in range(20000)]
        p.write_bytes(b"\n".join(lines) + b"\n")
        parser = NativeLibSVMParser(str(p), 0, 1, chunk_size=65536)
        held = []
        while parser.next():
            block = parser.value()
            assert block.lease is not None
            lease = parser.detach()
            held.append((block.label.copy(), block.index.copy(),
                         block, lease))
        assert len(held) >= 2, "fixture should produce multiple blocks"
        # every detached block's views must still match the snapshot
        # taken at yield time (no arena was recycled under us)
        for label_snap, index_snap, block, lease in held:
            assert np.array_equal(block.label, label_snap)
            assert np.array_equal(block.index, index_snap)
        for _, _, _, lease in held:
            lease.release()
        parser.destroy()

    def test_container_copies_ephemeral(self, tmp_path):
        # push_block on a leased block must deep-copy: after the arena is
        # recycled and overwritten, the container's content is unchanged
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        p = tmp_path / "eph.libsvm"
        p.write_bytes(b"".join(f"1 {i}:2.5\n".encode() for i in range(500)))
        parser = NativeLibSVMParser(str(p), 0, 1, chunk_size=1024)
        c = RowBlockContainer(np.uint32)
        while parser.next():
            c.push_block(parser.value())  # auto-released on next next()
        first_pass = c.get_block().content_hash()
        parser.before_first()
        while parser.next():
            pass  # recycle arenas through more parsing
        parser.destroy()
        assert c.get_block().content_hash() == first_pass


class TestNativeRecordIO:
    """Native sharded RecordIO reader: record-stream parity with the
    Python split (reference: src/io/recordio_split.cc + src/recordio.cc),
    including multi-frame (escaped magic) records and multi-part shards."""

    @pytest.fixture
    def rec_files(self, tmp_path, rng):
        from dmlc_tpu.io.recordio import RecordIOWriter, RECORDIO_MAGIC
        import struct
        magic = struct.pack("<I", RECORDIO_MAGIC)
        paths = []
        for f in range(3):
            p = tmp_path / f"part{f}.rec"
            with open(p, "wb") as fh:
                w = RecordIOWriter(fh)
                for i in range(120):
                    if i % 7 == 0:
                        # adversarial: aligned magic inside the payload
                        # forces multi-frame escaping
                        rec = (b"A" * (4 * rng.randint(0, 8)) + magic +
                               rng.bytes(rng.randint(0, 64)))
                    else:
                        rec = rng.bytes(rng.randint(1, 3000))
                    w.write_record(rec)
            paths.append(str(p))
        return ";".join(paths)

    def _python_records(self, uri, k, n):
        from dmlc_tpu.io.input_split import InputSplit
        return list(InputSplit.create(uri, k, n, "recordio"))

    def _native_records(self, uri, k, n, chunk=1 << 20):
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        r = NativeRecordIOReader(uri, k, n, chunk_size=chunk)
        out = list(r.records())
        r.destroy()
        return out

    @pytest.mark.parametrize("nparts", [1, 2, 5])
    def test_record_parity(self, rec_files, nparts):
        for k in range(nparts):
            g = self._python_records(rec_files, k, nparts)
            n = self._native_records(rec_files, k, nparts)
            assert len(g) == len(n)
            assert g == n, f"part {k}/{nparts} diverges"

    def test_small_chunks_force_carry(self, rec_files):
        # 64KB chunks (engine minimum) make records straddle chunk cuts
        g = self._python_records(rec_files, 0, 1)
        n = self._native_records(rec_files, 0, 1, chunk=1)
        assert g == n

    def test_zero_copy_batches(self, rec_files):
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        r = NativeRecordIOReader(rec_files, 0, 1)
        total = 0
        while True:
            batch = r.next_batch()
            if batch is None:
                break
            data, starts, ends = batch
            assert np.all(starts <= ends) and int(ends[-1]) == len(data)
            assert np.all(ends[:-1] <= starts[1:])  # in-order, no overlap
            total += len(starts)
        stats = r.stats()
        assert stats["chunks"] >= 1 and stats["reader_busy_ns"] > 0
        r.destroy()
        assert total == len(self._python_records(rec_files, 0, 1))

    def test_corrupt_stream_raises(self, tmp_path):
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        p = tmp_path / "bad.rec"
        p.write_bytes(b"\x00" * 64)  # no magic anywhere
        # offset 0 is a record start by contract (no realignment scan), so
        # garbage at 0 errors in BOTH engines (python parity checked above)
        with pytest.raises(DMLCError, match="magic"):
            self._python_records(str(p), 0, 1)
        r = NativeRecordIOReader(str(p), 0, 1)
        with pytest.raises(DMLCError, match="magic"):
            r.next_batch()
        r.destroy()
        from dmlc_tpu.io.recordio import RECORDIO_MAGIC
        import struct
        # valid magic + truncated payload must error, not hang
        p2 = tmp_path / "trunc.rec"
        p2.write_bytes(struct.pack("<II", RECORDIO_MAGIC, 5000))
        r2 = NativeRecordIOReader(str(p2), 0, 1)
        with pytest.raises(DMLCError):
            r2.next_batch()
        r2.destroy()


def _gcc_flags():
    """-march=native is opt-in (DMLC_TPU_MARCH_NATIVE=1): it can emit
    illegal instructions on heterogeneous CI fleets (ADVICE r1).
    -DDTP_DEBUG arms the engine's hot-path invariant DCHECKs."""
    flags = ["-O2", "-std=c++17", "-pthread", "-DDTP_DEBUG"]
    if os.environ.get("DMLC_TPU_MARCH_NATIVE") == "1":
        flags.insert(1, "-march=native")
    return flags


def _link_flags():
    """Trailing link/feature flags every engine-including binary needs:
    the zlib decision (ABI 8 parquet GZIP pages) is build.zlib_flags(),
    shared with the .so build so test binaries and the library always
    agree."""
    from dmlc_tpu.native.build import zlib_flags
    return zlib_flags()


_have_gxx = __import__("shutil").which("g++") is not None


class TestNativeIndexedRecordIO:
    """Native shuffled indexed-RecordIO reader: order/content parity
    with the Python golden (reference: src/io/indexed_recordio_split.cc).
    """

    def test_indexed_shuffled_parity(self, tmp_path, rng):
        """Native indexed-RecordIO shuffled reads must replay the Python
        golden's record order byte-for-byte across epochs, parts, and
        the pread fallback (reference: src/io/indexed_recordio_split.cc).
        """
        import struct
        from dmlc_tpu.io.recordio import (IndexedRecordIOWriter,
                                          RECORDIO_MAGIC)
        from dmlc_tpu.io.stream import create_stream
        from dmlc_tpu.io.indexed_recordio_split import IndexedRecordIOSplit
        from dmlc_tpu.native.bindings import NativeIndexedRecordIOReader
        magic = struct.pack("<I", RECORDIO_MAGIC)
        path = str(tmp_path / "idx.rec")
        with create_stream(path, "w") as s, \
                create_stream(path + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(s, ix)
            for i in range(300):
                if i % 13 == 0:  # escaped-magic multi-frame record
                    rec = magic + rng.bytes(40) + magic
                else:
                    rec = rng.bytes(rng.randint(30, 2000))
                w.write_record(rec)

        def py_epochs(part, nparts, epochs):
            sp = IndexedRecordIOSplit(path, part, nparts, shuffle=True,
                                      seed=5, batch_size=17)
            out = []
            for ep in range(epochs):
                if ep:
                    sp.before_first()
                recs = []
                while True:
                    r = sp.next_record()
                    if r is None:
                        break
                    recs.append(r)
                out.append(recs)
            return out

        for part, nparts in ((0, 1), (2, 4)):
            golden = py_epochs(part, nparts, 2)
            nat = NativeIndexedRecordIOReader(path, part, nparts,
                                              shuffle=True, seed=5,
                                              batch_size=17)
            for ep in range(2):
                if ep:
                    nat.before_first()
                assert list(nat.records()) == golden[ep]
            nat.destroy()
        # epoch orders must actually differ (reshuffle happened)
        two = py_epochs(0, 1, 2)
        assert two[0] != two[1]

    @pytest.mark.parametrize("no_mmap", [False, True])
    def test_sparse_index_one_record_per_window(self, tmp_path, rng,
                                                monkeypatch, no_mmap):
        """An index that skips records makes windows span 2+ framed
        records; the golden's next_record returns only the FIRST record
        of each window, and BOTH native modes (views and copy/pread)
        must match that — not emit the extra records."""
        import struct
        from dmlc_tpu.io.recordio import (RecordIOWriter, RECORDIO_MAGIC)
        from dmlc_tpu.io.stream import create_stream
        from dmlc_tpu.io.indexed_recordio_split import IndexedRecordIOSplit
        from dmlc_tpu.native.bindings import NativeIndexedRecordIOReader
        magic = struct.pack("<I", RECORDIO_MAGIC)
        path = str(tmp_path / "sparse.rec")
        offsets = []
        with open(path, "wb") as fh:
            class _Counting:
                def __init__(self, inner):
                    self.inner, self.written = inner, 0
                def write(self, d):
                    self.written += len(d)
                    return self.inner.write(d)
            cs = _Counting(fh)
            w = RecordIOWriter(cs)
            for i in range(60):
                offsets.append(cs.written)
                if i % 10 == 0:  # some multi-frame records too
                    w.write_record(magic + rng.bytes(24))
                else:
                    w.write_record(rng.bytes(rng.randint(10, 200)))
        # sparse index: every SECOND record only
        with create_stream(path + ".idx", "w") as ix:
            for k, off in enumerate(offsets[::2]):
                ix.write(f"{k}\t{off}\n".encode())
        if no_mmap:
            monkeypatch.setenv("DMLC_TPU_NO_MMAP", "1")
        sp = IndexedRecordIOSplit(path, 0, 1, shuffle=True, seed=2,
                                  batch_size=7)
        golden = []
        while True:
            r = sp.next_record()
            if r is None:
                break
            golden.append(r)
        nat = NativeIndexedRecordIOReader(path, 0, 1, shuffle=True,
                                          seed=2, batch_size=7)
        got = list(nat.records())
        nat.destroy()
        assert len(got) == len(golden) == 30
        assert got == golden

    def test_indexed_shuffled_no_mmap(self, tmp_path, rng, monkeypatch):
        from dmlc_tpu.io.recordio import IndexedRecordIOWriter
        from dmlc_tpu.io.stream import create_stream
        from dmlc_tpu.io.indexed_recordio_split import IndexedRecordIOSplit
        from dmlc_tpu.native.bindings import NativeIndexedRecordIOReader
        path = str(tmp_path / "idx2.rec")
        with create_stream(path, "w") as s, \
                create_stream(path + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(s, ix)
            for _ in range(100):
                w.write_record(rng.bytes(rng.randint(10, 500)))
        monkeypatch.setenv("DMLC_TPU_NO_MMAP", "1")
        nat = NativeIndexedRecordIOReader(path, 0, 1, shuffle=True, seed=3,
                                          batch_size=9)
        sp = IndexedRecordIOSplit(path, 0, 1, shuffle=True, seed=3,
                                  batch_size=9)
        golden = []
        while True:
            r = sp.next_record()
            if r is None:
                break
            golden.append(r)
        assert list(nat.records()) == golden
        assert nat.bytes_read() > 0
        nat.destroy()


@pytest.mark.skipif(not _have_gxx, reason="g++ not available")
class TestCppUnittests:
    """Build and run the native C++ unit-test program (reference:
    test/unittest gtest suite; see engine_unittest.cc)."""

    @staticmethod
    def _build_and_run(tmp_path, source_name, argv=()):
        """Build a native test/tool program against engine.cc and run it
        (shared by the unittest and microbench smoke)."""
        from dmlc_tpu import native as native_pkg
        src = os.path.join(os.path.dirname(native_pkg.__file__),
                           "src", source_name)
        exe = str(tmp_path / source_name.replace(".cc", ""))
        build = subprocess.run(
            ["g++"] + _gcc_flags() + [src, "-o", exe] + _link_flags(),
            capture_output=True, text=True, timeout=300)
        assert build.returncode == 0, build.stderr[-2000:]
        run = subprocess.run([exe, *argv], capture_output=True, text=True,
                             timeout=300)
        assert run.returncode == 0, (run.stdout + run.stderr)[-2000:]
        return run

    def test_cpp_unittests(self, tmp_path):
        run = self._build_and_run(tmp_path, "engine_unittest.cc")
        assert "all native unit tests passed" in run.stdout

    def test_microbench_smoke(self, tmp_path):
        """The kernel A/B harness (engine_microbench.cc) must keep
        compiling and producing sane numbers+digests — it is the tool
        perf work leans on, so CI smoke-builds it at 1 iter / 2 MB."""
        run = self._build_and_run(tmp_path, "engine_microbench.cc",
                                  argv=("1", "2"))
        for name in ("libsvm/a1a", "libsvm/criteo", "csv/higgs"):
            assert name in run.stdout, run.stdout
        assert "GB/s" in run.stdout and "digest=" in run.stdout


@pytest.mark.skipif(not _have_gxx, reason="g++ not available")
class TestASANFuzz:
    """Corruption fuzz of the parse/decode paths under ASAN+UBSAN
    (SURVEY §5.2): bit flips, truncations, and splices over valid
    libsvm/csv/libfm/recordio inputs must either parse or throw
    EngineError — never touch memory out of bounds (the raw-cursor
    reserves and the in-place RecordIO stitch are the invariants at
    risk)."""

    def test_asan_fuzz(self, tmp_path):
        from dmlc_tpu import native as native_pkg
        src = os.path.join(os.path.dirname(native_pkg.__file__),
                           "src", "engine_fuzz.cc")
        exe = str(tmp_path / "engine_fuzz_asan")
        build = subprocess.run(
            ["g++", "-fsanitize=address,undefined",
             "-fno-sanitize-recover=all", "-O1", "-g", "-std=c++17",
             "-pthread", src, "-o", exe] + _link_flags(),
            capture_output=True, text=True, timeout=300)
        if build.returncode != 0 and "asan" in build.stderr.lower():
            pytest.skip("libasan not available on this toolchain")
        assert build.returncode == 0, build.stderr[-2000:]
        run = subprocess.run([exe, "600"], capture_output=True, text=True,
                             timeout=540)
        report = run.stdout + run.stderr
        assert "ERROR: AddressSanitizer" not in report, report[-4000:]
        assert "runtime error" not in report, report[-4000:]
        assert run.returncode == 0, report[-4000:]
        assert "fuzz complete" in run.stdout


@pytest.mark.skipif(not _have_gxx, reason="g++ not available")
class TestTSAN:
    """ThreadSanitizer stress of the concurrent C++ core (VERDICT r1 #8;
    SURVEY §5.2): reader thread + parser pool + ordered queue + lease
    recycling + mid-stream kill, under -fsanitize=thread. Clean = exit 0
    and no 'WARNING: ThreadSanitizer' in the output."""

    def test_tsan_stress(self, tmp_path):
        from dmlc_tpu import native as native_pkg
        src = os.path.join(os.path.dirname(native_pkg.__file__),
                           "src", "engine_stress.cc")
        exe = str(tmp_path / "engine_stress_tsan")
        build = subprocess.run(
            ["g++", "-fsanitize=thread", "-O1", "-g", "-std=c++17",
             "-pthread", src, "-o", exe] + _link_flags(),
            capture_output=True, text=True, timeout=300)
        if build.returncode != 0 and "tsan" in build.stderr.lower():
            pytest.skip("libtsan not available on this toolchain")
        assert build.returncode == 0, build.stderr[-2000:]
        run = subprocess.run(
            [exe], capture_output=True, text=True, timeout=540,
            env={**os.environ, "TSAN_OPTIONS": "halt_on_error=0"})
        report = run.stdout + run.stderr
        assert "WARNING: ThreadSanitizer" not in report, report[-4000:]
        assert run.returncode == 0, report[-4000:]
        assert "scenarios completed" in run.stdout
