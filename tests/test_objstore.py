"""The remote object-store I/O plane (io/objstore/): emulator model,
``obj://`` FileSystem surface, ranged-GET coalescing, page-store
hydration — and THE acceptance: byte-identical epochs vs local reads,
a wire-free second epoch proven by GET counters, and chaos runs that
still complete byte-identical through the retry seams.

The whole suite is PARAMETRIZED over the wire client: the on-disk
emulator directly, and the REAL stdlib HTTP ranged-GET client
(``io/objstore/http_client.py``) speaking to a test HTTP endpoint
that delegates storage + ground-truth counters to an inner emulator —
the same FS-surface and retry-seam behavior, byte for byte, over a
real socket."""

import os

import numpy as np
import pytest

import dmlc_tpu.io.objstore as objstore
from dmlc_tpu.io.filesys import FileSystem, URI
from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.pagestore import PageStore
from dmlc_tpu.io.stream import create_seek_stream_for_read, create_stream
from dmlc_tpu.resilience import (
    RetryPolicy, inject, reset_policies, retry_counts, set_policy,
)
from dmlc_tpu.utils.logging import DMLCError


def _counter(name):
    from dmlc_tpu.obs.metrics import REGISTRY
    return REGISTRY.counter(name).value


class _HttpBackendHandle:
    """The parametrized suite's handle for the HTTP backend: object
    VERBS go through the real wire client (that is the parity under
    test), ground truth — request counters, the on-disk root — stays
    with the inner emulator behind the test endpoint."""

    def __init__(self, client, inner):
        self._client = client
        self._inner = inner
        self.root = inner.root

    def counters(self):
        return self._inner.counters()

    def reset_counters(self):
        return self._inner.reset_counters()

    def __getattr__(self, name):
        return getattr(self._client, name)


@pytest.fixture(params=["emulator", "http"])
def em(request, tmp_path, monkeypatch):
    """A fresh backend client + an isolated page store root, with the
    process-global client/options restored afterwards. Runs twice:
    once on the emulator, once on the real HTTP ranged-GET client in
    front of an emulator-backed test endpoint."""
    import dmlc_tpu.io.objstore.fs as ofs
    import dmlc_tpu.io.pagestore as ps
    monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
    monkeypatch.setattr(ps, "default_store_dir",
                        lambda: str(tmp_path / "pagestore"))
    saved = ofs.options()
    server = None
    from dmlc_tpu.io.objstore.emulator import EmulatedObjectStore
    inner = EmulatedObjectStore(str(tmp_path / "objroot"))
    if request.param == "emulator":
        handle = objstore.configure(inner, block_bytes=1 << 15,
                                    coalesce=4, parallel=2)
    else:
        from objstore_http_server import ObjstoreHttpServer

        from dmlc_tpu.io.objstore.http_client import (
            HttpObjectStoreClient,
        )
        server = ObjstoreHttpServer(inner)
        client = HttpObjectStoreClient(server.endpoint, encoded=True,
                                       multipart=True)
        objstore.configure(client, block_bytes=1 << 15, coalesce=4,
                           parallel=2)
        handle = _HttpBackendHandle(client, inner)
    yield handle
    objstore.configure(None, block_bytes=saved["block_bytes"],
                       coalesce=saved["coalesce"],
                       parallel=saved["parallel"],
                       hydrate=saved["hydrate"],
                       put_part_bytes=saved["put_part_bytes"],
                       put_parallel=saved["put_parallel"])
    if server is not None:
        server.close()
    inject.uninstall()
    reset_policies()


def _text_payload(rows=20000, seed=0):
    rng = np.random.RandomState(seed)
    return b"".join(b"%d %d:%.4f %d:%.4f\n"
                    % (i % 2, rng.randint(0, 40), rng.rand(),
                       40 + rng.randint(0, 40), rng.rand())
                    for i in range(rows))


def _noop_sleep(_s):
    pass


# ------------------------------------------------------------ emulator

class TestEmulator:
    def test_put_head_get_round_trip(self, em):
        info = em.put("b", "k/nested/x.bin", b"0123456789")
        assert info.size == 10 and info.etag
        assert em.head("b", "k/nested/x.bin").size == 10
        assert em.get("b", "k/nested/x.bin") == b"0123456789"
        assert em.get("b", "k/nested/x.bin", 2, 5) == b"234"
        assert em.get("b", "k/nested/x.bin", 8, 99) == b"89"

    def test_missing_object_raises(self, em):
        with pytest.raises(FileNotFoundError):
            em.head("b", "ghost")
        with pytest.raises(FileNotFoundError):
            em.get("b", "ghost")

    def test_list_is_prefix_recursive_sorted(self, em):
        for k in ("d/2.bin", "d/sub/3.bin", "d/1.bin", "other.bin"):
            em.put("b", k, b"x")
        got = [o.key for o in em.list("b", "d")]
        assert got == ["d/1.bin", "d/2.bin", "d/sub/3.bin"]
        assert em.is_prefix("b", "d") and not em.is_prefix("b", "zz")

    def test_counters_ground_truth(self, em):
        em.put("b", "k", b"abcdef")
        em.reset_counters()
        em.get("b", "k", 0, 3)
        em.get("b", "k", 3, 6)
        em.head("b", "k")
        c = em.counters()
        assert c["gets"] == 2 and c["get_bytes"] == 6
        assert c["heads"] == 1

    def test_traversal_rejected(self, em):
        with pytest.raises(DMLCError):
            em.head("..", "x")
        with pytest.raises(DMLCError):
            em.head("b", "../escape")


# --------------------------------------------------- FileSystem surface

class TestObjectStoreFileSystem:
    def test_stat_list_through_registry(self, em):
        em.put("bucket", "d/a.bin", b"aaa")
        em.put("bucket", "d/b.bin", b"bb")
        u = URI("obj://bucket/d/a.bin")
        fs = FileSystem.get_instance(u)
        info = fs.get_path_info(u)
        assert (info.size, info.type) == (3, "file")
        assert info.mtime_ns > 0
        d = URI("obj://bucket/d")
        assert fs.get_path_info(d).type == "directory"
        listing = fs.list_directory(d)
        assert [fi.path for fi in listing] == \
            ["obj://bucket/d/a.bin", "obj://bucket/d/b.bin"]
        assert [fi.size for fi in listing] == [3, 2]

    def test_write_stream_puts_object(self, em):
        with create_stream("obj://bucket/out/w.bin", "w") as s:
            s.write(b"part1-")
            s.write(b"part2")
        assert em.get("bucket", "out/w.bin") == b"part1-part2"

    def test_append_mode_rejected(self, em):
        with pytest.raises(DMLCError, match="no append"):
            create_stream("obj://bucket/x", "a")

    def test_missing_object_propagates(self, em):
        with pytest.raises(FileNotFoundError):
            create_seek_stream_for_read("obj://bucket/ghost.bin")

    def test_unconfigured_plane_error_is_actionable(self, tmp_path,
                                                    monkeypatch):
        import dmlc_tpu.io.objstore.fs as ofs
        monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
        objstore.configure(None)
        try:
            with pytest.raises(DMLCError, match="DMLC_TPU_OBJSTORE_ROOT"):
                create_seek_stream_for_read("obj://bucket/x")
        finally:
            objstore.configure(None)

    def test_env_contract_builds_emulator(self, tmp_path, monkeypatch):
        import dmlc_tpu.io.objstore.fs as ofs
        objstore.configure(None)
        monkeypatch.setenv(ofs.ENV_ROOT, str(tmp_path / "envroot"))
        try:
            c = objstore.client()
            assert c is not None and c.root == str(tmp_path / "envroot")
        finally:
            objstore.configure(None)


# ----------------------------------------------------- the seek stream

class TestObjectSeekStream:
    def test_read_is_byte_identical_across_blocks(self, em):
        payload = bytes(range(256)) * 700  # 175 KiB over 32 KiB blocks
        em.put("b", "x.bin", payload)
        s = create_seek_stream_for_read("obj://b/x.bin")
        assert s.size == len(payload)
        assert s.read_all() == payload
        s.seek(70000)
        assert s.tell() == 70000
        assert s.read(10) == payload[70000:70010]
        s.seek(len(payload))
        assert s.read(10) == b""
        with pytest.raises(DMLCError):
            s.seek(len(payload) + 1)
        with pytest.raises(DMLCError):
            s.write(b"nope")
        s.close()

    def test_coalescing_bounds_request_count(self, em):
        payload = b"z" * (14 * (1 << 15))  # 14 blocks
        em.put("b", "big.bin", payload)
        em.reset_counters()
        s = create_seek_stream_for_read("obj://b/big.bin")
        assert s.read_all() == payload
        s.close()
        # coalesce=4, parallel=2: 4 spans of <=4 blocks, each split
        # into <=2 ranged GETs — far fewer wire calls than 14 blocks
        assert 0 < em.counters()["gets"] <= 8
        assert em.counters()["get_bytes"] == len(payload)

    def test_objstore_metrics_counted(self, em):
        em.put("b", "m.bin", b"q" * 1000)
        g0, b0 = _counter("objstore.get"), _counter("objstore.bytes")
        s = create_seek_stream_for_read("obj://b/m.bin")
        s.read_all()
        s.close()
        assert _counter("objstore.get") > g0
        assert _counter("objstore.bytes") >= b0 + 1000

    def test_changed_object_serves_new_generation(self, em, tmp_path):
        em.put("b", "gen.bin", b"A" * 50000)
        s = create_seek_stream_for_read("obj://b/gen.bin")
        assert s.read_all() == b"A" * 50000
        s.close()
        em.put("b", "gen.bin", b"B" * 60000)  # new size → new etag
        s2 = create_seek_stream_for_read("obj://b/gen.bin")
        assert s2.read_all() == b"B" * 60000
        s2.close()


# ------------------------------------------------- hydration acceptance

class TestHydration:
    def test_second_epoch_is_wire_free(self, em):
        """THE acceptance: epoch 2 over the same obj:// URI performs
        ZERO emulator GETs — hydrated pages serve every block — proven
        by the emulator's own request counters AND the
        dmlc_objstore_* / dmlc_pagestore_* registry counters."""
        payload = _text_payload()
        em.put("bucket", "train/d.libsvm", payload)
        uri = "obj://bucket/train/d.libsvm"
        em.reset_counters()
        g0 = _counter("objstore.get")
        h0 = _counter("pagestore.hit")
        cold = list(InputSplit.create(uri, 0, 1))
        cold_gets = em.counters()["gets"]
        assert cold_gets > 0
        assert _counter("objstore.get") == g0 + cold_gets
        em.reset_counters()
        warm = list(InputSplit.create(uri, 0, 1))
        assert warm == cold
        assert em.counters()["gets"] == 0, \
            "second epoch must not touch the wire"
        assert _counter("objstore.get") == g0 + cold_gets
        assert _counter("pagestore.hit") > h0

    def test_hydrate_off_hits_wire_every_epoch(self, em, tmp_path):
        objstore.configure(hydrate=False)
        payload = b"x" * 100000
        em.put("b", "nh.bin", payload)
        for _ in range(2):
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/nh.bin")
            assert s.read_all() == payload
            s.close()
            assert em.counters()["gets"] > 0

    def test_hydrated_pages_are_stamped_and_sweepable(self, em,
                                                      tmp_path):
        em.put("b", "sw.bin", b"h" * 40000)
        s = create_seek_stream_for_read("obj://b/sw.bin")
        s.read_all()
        s.close()
        store = PageStore.default()
        entries = [n for n in os.listdir(store.root)
                   if n.startswith("obj-") and n.endswith(".pages")]
        assert entries
        stamp = store.stamp(entries[0])
        assert stamp["fingerprint"][0][0] == "obj://b/sw.bin"
        # the object changes → the one sweep reclaims the generation
        em.put("b", "sw.bin", b"h" * 41000)
        assert store.sweep() >= len(entries)


# ------------------------------------------------ epoch parity pinning

class TestEpochParity:
    def test_text_epoch_byte_identical_to_local(self, em, tmp_path):
        payload = _text_payload()
        em.put("bucket", "d.libsvm", payload)
        local = tmp_path / "d.libsvm"
        local.write_bytes(payload)
        for parts in (1, 3):
            remote_recs, local_recs = [], []
            for k in range(parts):
                remote_recs += list(InputSplit.create(
                    "obj://bucket/d.libsvm", k, parts))
                local_recs += list(InputSplit.create(str(local), k,
                                                     parts))
            assert remote_recs == local_recs

    def test_recordio_epoch_byte_identical_to_local(self, em, tmp_path):
        from dmlc_tpu.io.recordio import RecordIOWriter
        rng = np.random.RandomState(3)
        local = str(tmp_path / "d.rec")
        with create_stream(local, "w") as s:
            w = RecordIOWriter(s)
            for i in range(4000):
                w.write_record(bytes(rng.randint(0, 256,
                                                 rng.randint(1, 200),
                                                 dtype=np.uint8)))
        em.put_file("bucket", "d.rec", local)
        for parts in (1, 2):
            for k in range(parts):
                remote = list(InputSplit.create("obj://bucket/d.rec",
                                                k, parts,
                                                split_type="recordio"))
                loc = list(InputSplit.create(local, k, parts,
                                             split_type="recordio"))
                assert remote == loc

    def test_parsed_batches_identical_via_pipeline(self, em, tmp_path):
        from dmlc_tpu.data.rowblock import RowBlockContainer
        from dmlc_tpu.pipeline import Pipeline

        def drain_hash(uri):
            built = (Pipeline.from_uri(uri).parse(format="libsvm")
                     .batch(512).build())
            c = RowBlockContainer(np.uint32)
            for b in built:
                c.push_block(b)
            built.close()
            return c.get_block().content_hash()

        payload = _text_payload(rows=8000)
        em.put("bucket", "p.libsvm", payload)
        local = tmp_path / "p.libsvm"
        local.write_bytes(payload)
        assert drain_hash("obj://bucket/p.libsvm") == \
            drain_hash(str(local))


# ------------------------------------------------------------- chaos

class TestChaos:
    def test_ioerror_at_get_retries_byte_identical(self, em):
        payload = _text_payload(rows=5000)
        em.put("bucket", "c.libsvm", payload)
        want = list(InputSplit.create("obj://bucket/c.libsvm", 0, 1))
        # fresh store root would be cleaner, but simply dropping the
        # hydrated pages forces the wire again
        PageStore.default().sweep(max_tmp_age_s=0)
        for n in os.listdir(PageStore.default().root):
            PageStore.default().delete(n)
        set_policy("io.objstore.get",
                   RetryPolicy(max_attempts=4, sleep=_noop_sleep))
        inject.install("site=io.objstore.get,fault=ioerror,times=2")
        got = list(InputSplit.create("obj://bucket/c.libsvm", 0, 1))
        assert got == want
        assert retry_counts().get("io.objstore.get", 0) >= 2

    def test_truncate_at_get_detected_and_refetched(self, em):
        """An injected truncation (or a really-torn transfer) must be
        DETECTED against the requested range and retried — never handed
        downstream as silently shifted bytes."""
        payload = _text_payload(rows=5000)
        em.put("bucket", "t.libsvm", payload)
        want = list(InputSplit.create("obj://bucket/t.libsvm", 0, 1))
        for n in os.listdir(PageStore.default().root):
            PageStore.default().delete(n)
        set_policy("io.objstore.get",
                   RetryPolicy(max_attempts=4, sleep=_noop_sleep))
        inject.install("site=io.objstore.get,fault=truncate,times=3")
        got = list(InputSplit.create("obj://bucket/t.libsvm", 0, 1))
        assert got == want
        assert retry_counts().get("io.objstore.get", 0) >= 3

    def test_really_shrunk_object_surfaces_as_error(self, em):
        em.put("bucket", "shrink.bin", b"L" * 100000)
        split = InputSplit.create("obj://bucket/shrink.bin", 0, 1)
        first = split.next_chunk()
        assert first
        # the SOURCE object shrinks under the live split (its recorded
        # byte range still says 100000): the replay must surface an
        # unexpected-EOF error, never silently shifted/short bytes
        em.put("bucket", "shrink.bin", b"L" * 10)
        set_policy("io.objstore.get",
                   RetryPolicy(max_attempts=2, sleep=_noop_sleep))
        split.before_first()
        with pytest.raises((DMLCError, IOError)):
            while split.next_chunk() is not None:
                pass


# --------------------------------------------------- the write plane

class TestMultipart:
    def _payload(self, n=1 << 18, seed=7):
        return np.random.RandomState(seed).bytes(n)

    def test_multipart_round_trip_with_part_counters(self, em):
        from dmlc_tpu.io.objstore.multipart import MultipartWriter
        data = self._payload(100_000)
        em.reset_counters()
        w = MultipartWriter(em, "b", "mp.bin", "obj://b/mp.bin",
                            part_bytes=1 << 14, parallel=2)
        for i in range(0, len(data), 7777):
            w.write(data[i:i + 7777])
        w.close()
        assert em.get("b", "mp.bin") == data
        c = em.counters()
        # ground truth: every byte moved exactly once, as parts
        assert c["put_parts"] == -(-len(data) // (1 << 14))
        assert c["put_bytes"] == len(data)
        assert c["puts"] == 1  # the complete, not a re-upload
        assert em.list_uploads("b") == []  # staging area drained

    def test_abort_leaves_no_object_and_no_parts(self, em):
        from dmlc_tpu.io.objstore.multipart import MultipartWriter
        w = MultipartWriter(em, "b", "gone.bin", "obj://b/gone.bin",
                            part_bytes=1 << 12, parallel=2)
        w.write(self._payload(1 << 14))
        w.abort()
        with pytest.raises(FileNotFoundError):
            em.head("b", "gone.bin")
        assert em.list_uploads("b") == []

    def test_write_stream_spills_into_multipart(self, em):
        objstore.configure(put_part_bytes=1 << 14, put_parallel=2)
        data = self._payload(90_000)
        em.reset_counters()
        with create_stream("obj://b/auto.bin", "w") as s:
            for i in range(0, len(data), 5000):
                s.write(data[i:i + 5000])
        assert em.get("b", "auto.bin") == data
        c = em.counters()
        assert c["put_parts"] > 0 and c["put_bytes"] == len(data)

    def test_small_write_stream_stays_single_shot(self, em):
        em.reset_counters()
        with create_stream("obj://b/small.bin", "w") as s:
            s.write(b"tiny")
        assert em.get("b", "small.bin") == b"tiny"
        c = em.counters()
        assert c["puts"] == 1 and c["put_parts"] == 0

    def test_complete_with_missing_part_raises(self, em):
        up = em.create_multipart("b", "torn.bin")
        em.put_part("b", "torn.bin", up, 0, b"aa")
        # part 1 never uploaded: complete must refuse, not concatenate
        with pytest.raises(FileNotFoundError):
            em.complete_multipart("b", "torn.bin", up, 2)
        with pytest.raises(FileNotFoundError):
            em.head("b", "torn.bin")
        em.abort_multipart("b", "torn.bin", up)

    def test_delete_verb(self, em):
        em.put("b", "d.bin", b"x")
        assert em.delete("b", "d.bin") is True
        assert em.delete("b", "d.bin") is False
        with pytest.raises(FileNotFoundError):
            em.head("b", "d.bin")

    def test_put_wire_model_charges_latency(self, em):
        """Satellite: the emulator wire model charges PUTs too —
        latency_s applies to put and put_part, so write benchmarks
        measure a believable wire."""
        import time as _time

        from dmlc_tpu.io.objstore.emulator import EmulatedObjectStore
        shaped = EmulatedObjectStore(em.root, latency_s=0.03)
        t0 = _time.monotonic()
        shaped.put("b", "lat.bin", b"x" * 100)
        single = _time.monotonic() - t0
        assert single >= 0.025
        up = shaped.create_multipart("b", "lat2.bin")
        t0 = _time.monotonic()
        shaped.put_part("b", "lat2.bin", up, 0, b"y" * 100)
        assert _time.monotonic() - t0 >= 0.025
        shaped.abort_multipart("b", "lat2.bin", up)


class TestPutChaos:
    """Chaos at the ``io.objstore.put`` seam (satellite): a faulted
    part retries JUST that part byte-identically; faults past the
    ladder abort with no partial object visible and parts swept."""

    def _upload(self, data, part_bytes=1 << 14):
        with create_stream("obj://b/chaos.bin", "w") as s:
            s.write(data)

    def test_nth_part_ioerror_retries_that_part_byte_identical(self, em):
        data = np.random.RandomState(3).bytes(80_000)
        objstore.configure(put_part_bytes=1 << 14, put_parallel=1)
        set_policy("io.objstore.put",
                   RetryPolicy(max_attempts=4, sleep=_noop_sleep))
        inject.install("site=io.objstore.put,fault=ioerror,nth=3")
        self._upload(data)
        # the retry re-sent the faulted part verbatim: the assembled
        # object is byte-identical, no part doubled or dropped
        assert em.get("b", "chaos.bin") == data
        assert retry_counts().get("io.objstore.put", 0) >= 1

    def test_truncated_part_detected_and_resent(self, em):
        data = np.random.RandomState(4).bytes(60_000)
        objstore.configure(put_part_bytes=1 << 14, put_parallel=1)
        set_policy("io.objstore.put",
                   RetryPolicy(max_attempts=4, sleep=_noop_sleep))
        before = _counter("objstore.put.retries")
        inject.install("site=io.objstore.put,fault=truncate,times=2")
        self._upload(data)
        assert em.get("b", "chaos.bin") == data
        assert _counter("objstore.put.retries") > before

    def test_exhausted_ladder_aborts_no_partial_object(self, em):
        data = np.random.RandomState(5).bytes(80_000)
        objstore.configure(put_part_bytes=1 << 14, put_parallel=2)
        set_policy("io.objstore.put",
                   RetryPolicy(max_attempts=2, sleep=_noop_sleep))
        before = _counter("objstore.put.aborts")
        s = create_stream("obj://b/chaos.bin", "w")
        s.write(data[: 1 << 14])  # spill: the multipart upload is live
        inject.install("site=io.objstore.put,fault=ioerror,times=50")
        with pytest.raises((IOError, OSError, DMLCError)):
            try:
                s.write(data[1 << 14:])
            finally:
                s.close()
        # no torn object became visible, and the writer's own abort
        # already swept its staged parts
        with pytest.raises(FileNotFoundError):
            em.head("b", "chaos.bin")
        assert em.list_uploads("b") == []
        assert _counter("objstore.put.aborts") > before

    def test_single_shot_truncation_never_lands_short(self, em):
        set_policy("io.objstore.put",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        inject.install("site=io.objstore.put,fault=truncate,times=2")
        with create_stream("obj://b/ss.bin", "w") as s:
            s.write(b"Z" * 5000)
        assert em.get("b", "ss.bin") == b"Z" * 5000

    def test_sweep_reaps_dead_writer_uploads_only(self, em):
        from dmlc_tpu.io.objstore.multipart import sweep_uploads
        live = em.create_multipart("b", "live.bin")
        em.put_part("b", "live.bin", live, 0, b"l")
        dead = em.create_multipart("b", "dead.bin")
        em.put_part("b", "dead.bin", dead, 0, b"d")
        # re-stage the second upload under a pid that cannot be alive
        import dmlc_tpu.io.objstore.emulator as _emu
        inner = em if isinstance(em, _emu.EmulatedObjectStore) \
            else em._inner
        mpu = os.path.join(inner.root, "b", ".mpu")
        dead_id = "p999999999-feedbeef"
        os.rename(os.path.join(mpu, dead), os.path.join(mpu, dead_id))
        assert sweep_uploads(em, "b") == 1
        ids = [u for u, _ in em.list_uploads("b")]
        assert ids == [live]
        em.abort_multipart("b", "live.bin", live)
