"""tpu:// URI scheme: create/read/seek contract + device staging +
RecordIO-to-device (the BASELINE north-star sentence; SURVEY §7 step 2).

Runs on CPU JAX (conftest forces the 8-device virtual platform); on TPU
hardware device_put lands in HBM — same code path.
"""

import numpy as np
import pytest

import jax

from dmlc_tpu.io import create_stream, create_seek_stream_for_read
from dmlc_tpu.io.filesys import FileSystem, URI
from dmlc_tpu.io.recordio import RecordIOWriter
from dmlc_tpu.io.tpu_fs import recordio_device_batches


@pytest.fixture
def payload_file(tmp_path):
    p = tmp_path / "blob.bin"
    data = bytes(range(256)) * 512  # 128KB
    p.write_bytes(data)
    return str(p), data


class TestTPUStreamContract:
    def test_create_read_seek(self, payload_file):
        path, data = payload_file
        s = create_stream(f"tpu://{path}", "r")
        assert s is not None
        assert s.read(16) == data[:16]
        s.seek(1000)
        assert s.tell() == 1000
        assert s.read(8) == data[1000:1008]
        s.close()

    def test_seek_stream_for_read(self, payload_file):
        path, data = payload_file
        s = create_seek_stream_for_read(f"tpu://{path}")
        s.seek(len(data) - 4)
        assert s.read(100) == data[-4:]
        s.close()

    def test_write_roundtrip(self, tmp_path):
        p = tmp_path / "out.bin"
        with create_stream(f"tpu://{p}", "w") as s:
            s.write(b"host-bytes;")
            s.write(np.arange(4, dtype=np.uint8))         # numpy array
            s.write(jax.numpy.arange(4, dtype=jax.numpy.uint8))  # device
        raw = p.read_bytes()
        assert raw == b"host-bytes;" + bytes([0, 1, 2, 3]) * 2

    def test_path_info_and_listing(self, payload_file, tmp_path):
        path, data = payload_file
        fs = FileSystem.get_instance(URI(f"tpu://{path}"))
        info = fs.get_path_info(URI(f"tpu://{path}"))
        assert info.size == len(data)
        assert info.path.startswith("tpu://")
        listing = fs.list_directory(URI(f"tpu://{tmp_path}"))
        assert any(fi.path.endswith("blob.bin") for fi in listing)

    def test_scheme_registered(self):
        # the north-star sentence: create_stream("tpu://...") works
        assert "tpu://" in FileSystem._schemes


class TestDeviceStaging:
    def test_read_to_device(self, payload_file):
        path, data = payload_file
        s = create_seek_stream_for_read(f"tpu://{path}")
        chunk = s.read_to_device(4096)
        chunk = jax.block_until_ready(chunk)
        assert isinstance(chunk, jax.Array)
        assert chunk.dtype == jax.numpy.uint8
        assert bytes(np.asarray(chunk)) == data[:4096]
        assert s.tell() == 4096  # device read advances the stream
        s.close()

    def test_device_chunks_cover_stream(self, payload_file):
        path, data = payload_file
        s = create_seek_stream_for_read(f"tpu://{path}")
        got = b"".join(bytes(np.asarray(c))
                       for c in s.device_chunks(chunk_bytes=30_000))
        assert got == data
        s.close()

    def test_explicit_device_placement(self, payload_file):
        path, _ = payload_file
        dev = jax.devices()[-1]
        s = create_seek_stream_for_read(f"tpu://{path}")
        chunk = s.read_to_device(1024, device=dev)
        assert chunk.devices() == {dev}
        s.close()


class TestRecordIOToDevice:
    @pytest.fixture
    def rec_file(self, tmp_path, rng):
        p = tmp_path / "x.rec"
        recs = [rng.bytes(rng.randint(1, 5000)) for _ in range(200)]
        with open(p, "wb") as fh:
            w = RecordIOWriter(fh)
            for r in recs:
                w.write_record(r)
        return str(p), recs

    def test_records_land_on_device_intact(self, rec_file):
        path, recs = rec_file
        got = []
        for batch in recordio_device_batches(f"tpu://{path}"):
            payload = np.asarray(jax.block_until_ready(batch["payload"]))
            starts = np.asarray(batch["starts"])
            ends = np.asarray(batch["ends"])
            for i in range(len(starts)):
                got.append(bytes(payload[starts[i]:ends[i]]))
        assert got == recs

    def test_sharded_coverage(self, rec_file):
        path, recs = rec_file
        got = []
        for k in range(3):
            for batch in recordio_device_batches(path, k, 3,
                                                 chunk_size=1 << 16):
                payload = np.asarray(batch["payload"])
                starts = np.asarray(batch["starts"])
                ends = np.asarray(batch["ends"])
                got += [bytes(payload[s:e]) for s, e in zip(starts, ends)]
        assert got == recs  # parts tile the record stream exactly

    def test_early_close_drains_in_flight(self, rec_file):
        # break after the first batch: the generator's cleanup must drain
        # pending transfers before destroying the reader (their device_put
        # sources are leased native buffers) — regression for a
        # use-after-free on early close
        path, recs = rec_file
        it = recordio_device_batches(path, chunk_size=1 << 16, lookahead=2)
        first = next(it)
        payload = np.asarray(jax.block_until_ready(first["payload"]))
        starts = np.asarray(first["starts"])
        ends = np.asarray(first["ends"])
        it.close()  # GeneratorExit -> finally
        got = [bytes(payload[s:e]) for s, e in zip(starts, ends)]
        assert got == recs[:len(got)]

    def test_python_fallback_matches(self, rec_file, monkeypatch):
        path, recs = rec_file
        import dmlc_tpu.io.tpu_fs as tpu_fs
        monkeypatch.setattr("dmlc_tpu.native.native_available",
                            lambda: False)
        got = []
        for batch in recordio_device_batches(path):
            payload = np.asarray(batch["payload"])
            starts = np.asarray(batch["starts"])
            ends = np.asarray(batch["ends"])
            got += [bytes(payload[s:e]) for s, e in zip(starts, ends)]
        assert got == recs


def _native_built() -> bool:
    from dmlc_tpu import native
    return native.native_available()


@pytest.mark.skipif(not _native_built(),
                    reason="native engine not built")
class TestParsersOverTPUScheme:
    def test_native_and_python_parse_tpu_uri(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        from dmlc_tpu.data.rowblock import RowBlockContainer
        p = tmp_path / "t.libsvm"
        p.write_bytes(b"".join(f"{i%2} {i}:1.5\n".encode()
                               for i in range(2000)))

        def hsh(uri, engine):
            c = RowBlockContainer(np.uint32)
            pr = Parser.create(uri, 0, 1, format="libsvm", engine=engine)
            for b in pr:
                c.push_block(b)
            if hasattr(pr, "destroy"):
                pr.destroy()
            return c.get_block().content_hash()

        plain = hsh(str(p), "python")
        assert hsh(f"tpu://{p}", "python") == plain
        assert hsh(f"tpu://{p}", "native") == plain

    def test_native_recordio_tpu_uri(self, tmp_path, rng):
        from dmlc_tpu.io.recordio import RecordIOWriter
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        path = tmp_path / "x.rec"
        recs = [rng.bytes(rng.randint(1, 500)) for _ in range(50)]
        with open(path, "wb") as fh:
            w = RecordIOWriter(fh)
            for r in recs:
                w.write_record(r)
        r = NativeRecordIOReader(f"tpu://{path}", 0, 1)
        assert list(r.records()) == recs
        r.destroy()
