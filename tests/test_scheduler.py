"""Multi-tenant pipeline scheduler (dmlc_tpu.pipeline.scheduler):
DRR pull credits, admission control, backpressure/queue budgets,
per-tenant accounting + verdicts, the /tenants surface, and the
watchdog naming the starved tenant."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dmlc_tpu.obs import watchdog as obs_watchdog
from dmlc_tpu.obs.metrics import MetricsRegistry
from dmlc_tpu.pipeline import AdmissionError, Pipeline
from dmlc_tpu.pipeline import scheduler as sched_mod
from dmlc_tpu.pipeline.scheduler import (
    MANAGED_KNOBS, ENV_SCHED, PipelineScheduler,
)
from dmlc_tpu.utils.logging import DMLCError


@pytest.fixture(autouse=True)
def _clean_scheduler():
    yield
    sched_mod.uninstall()


def _mk(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return PipelineScheduler(**kw)


def _libsvm_file(tmp_path, name="t.libsvm", rows=600):
    lines = [f"{i % 2} {i % 40 + 1}:1.5 {i % 70 + 3}:2.25\n"
             for i in range(rows)]
    p = tmp_path / name
    p.write_text("".join(lines))
    return str(p)


class TestDRR:
    def test_lone_tenant_unthrottled(self):
        s = _mk(quantum=2.0)
        s.register_tenant("a")
        for _ in range(50):
            s.acquire("a")
        row = s.to_dict()["tenants"]["a"]
        # a lone demander advances rounds itself: no credit waits
        assert row["credit_waits"] == 0
        assert s.rounds >= 25
        s.close()

    def test_weighted_interleave(self):
        """Two saturating tenants split pulls in weight proportion."""
        s = _mk(quantum=2.0, active_horizon_s=5.0, round_period_s=5.0)
        s.register_tenant("small", weight=1.0)
        s.register_tenant("big", weight=3.0)
        counts = {"small": 0, "big": 0}
        stop = time.monotonic() + 1.0

        def burn(name):
            while time.monotonic() < stop:
                s.acquire(name)
                counts[name] += 1

        ts = [threading.Thread(target=burn, args=(n,)) for n in counts]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        ratio = counts["big"] / max(counts["small"], 1)
        assert 2.0 <= ratio <= 4.5, (counts, ratio)
        s.close()

    def test_idle_tenant_keeps_burst_allowance(self):
        """An idle tenant's hoard caps at burst x quantum x weight —
        its next sparse burst clears instantly."""
        s = _mk(quantum=2.0, burst=2.0)
        s.register_tenant("idle", weight=3.0)
        for _ in range(40):
            s.acquire("idle")  # rounds advance, deficit replenishes
        with s._cond:
            assert s._tenants["idle"].deficit <= 2.0 * 2.0 * 3.0 + 1e-9
        s.close()

    def test_round_period_floor(self):
        """A peer holding unspent credits but not pulling cannot stall
        a broke tenant past round_period_s: the clocked round
        replenishes the demander."""
        s = _mk(quantum=1.0, burst=1.0, active_horizon_s=10.0,
                round_period_s=0.05)
        s.register_tenant("slow")
        s.register_tenant("fast")
        s.acquire("slow")   # slow now holds credit, stays "active"
        with s._cond:
            s._tenants["slow"].deficit = 5.0  # unspent hoard
        t0 = time.perf_counter()
        for _ in range(3):
            s.acquire("fast")
        # three clocked rounds at most: ~3 x round_period, never the
        # 10 s activity horizon
        assert time.perf_counter() - t0 < 1.0
        s.close()

    def test_cost_clamped_to_burst(self):
        s = _mk(quantum=1.0, burst=2.0)
        s.register_tenant("a")
        s.acquire("a", cost=1e9)  # clamped: must not deadlock
        s.close()

    def test_unknown_tenant_raises(self):
        s = _mk()
        with pytest.raises(DMLCError, match="unknown tenant"):
            s.acquire("ghost")
        s.close()

    def test_pause_blocks_resume_releases(self):
        s = _mk()
        s.register_tenant("a")
        s.pause("a")
        got = threading.Event()

        def puller():
            s.acquire("a")
            got.set()

        t = threading.Thread(target=puller, daemon=True)
        t.start()
        assert not got.wait(0.3)
        s.resume("a")
        assert got.wait(2.0)
        t.join()
        s.close()

    def test_close_releases_blocked_acquire(self):
        s = _mk()
        s.register_tenant("a")
        s.pause("a")
        done = threading.Event()

        def puller():
            s.acquire("a")
            done.set()

        t = threading.Thread(target=puller, daemon=True)
        t.start()
        time.sleep(0.1)
        s.close()
        assert done.wait(2.0)
        t.join()


class TestAdmission:
    def test_reject_past_budget(self):
        s = _mk()
        s.register_tenant("a", max_pipelines=1)
        mk = type("P", (), {"knobs": lambda self: []})
        p1 = mk()  # keep alive: admission slots are weakly held
        s.admit("a", p1)
        with pytest.raises(AdmissionError, match="pipeline budget"):
            s.admit("a", mk())
        row = s.to_dict()["tenants"]["a"]
        assert row["rejected"] == 1 and row["admitted"] == 1
        s.close()

    def test_queue_mode_waits_for_slot(self):
        s = _mk()
        s.register_tenant("a", max_pipelines=1, admission="queue")
        mk = type("P", (), {"knobs": lambda self: []})
        p1, p2 = mk(), mk()
        s.admit("a", p1)
        admitted = threading.Event()

        def second():
            s.admit("a", p2, timeout_s=5.0)
            admitted.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert not admitted.wait(0.3)   # queued, not rejected
        s.release(p1)
        assert admitted.wait(3.0)
        t.join()
        assert s.to_dict()["tenants"]["a"]["queued"] == 1
        s.close()

    def test_queue_mode_times_out(self):
        s = _mk()
        s.register_tenant("a", max_pipelines=1, admission="queue")
        mk = type("P", (), {"knobs": lambda self: []})
        p1 = mk()
        s.admit("a", p1)
        with pytest.raises(AdmissionError, match="timed out"):
            s.admit("a", mk(), timeout_s=0.2)
        s.close()

    def test_gced_pipeline_frees_slot(self):
        s = _mk()
        s.register_tenant("a", max_pipelines=1)
        mk = type("P", (), {"knobs": lambda self: []})
        s.admit("a", mk())  # dropped immediately: weakref dies
        s.admit("a", mk())  # must not raise
        s.close()


class TestPipelineIntegration:
    def test_build_tenant_needs_scheduler(self, tmp_path):
        path = _libsvm_file(tmp_path)
        with pytest.raises(DMLCError, match="installed scheduler"):
            (Pipeline.from_uri(path).parse(format="libsvm")
             .batch(128).build(tenant="a"))

    def test_epoch_bills_the_tenant(self, tmp_path):
        path = _libsvm_file(tmp_path)
        s = sched_mod.install(quantum=8.0)
        s.register_tenant("job")
        built = (Pipeline.from_uri(path).parse(format="libsvm")
                 .batch(128).build(tenant="job"))
        n = sum(1 for _ in built)
        row = s.to_dict()["tenants"]["job"]
        assert row["pulls"] == n > 0
        assert row["bytes"] > 0 and row["rows"] == 600
        assert row["batches"] == n and row["batch_p99_s"] is not None
        # the snapshot carries the tenant label; the stored verdict
        # cites it (per-tenant bound verdicts, ANALYSIS_SCHEMA 4)
        assert built.stats()["tenant"] == "job"
        assert row["last_verdict"]["bound"] is not None
        v = s._tenants["job"].last_verdict
        assert v["tenant"] == "job"
        from dmlc_tpu.obs.analyze import VERDICT_KEYS
        assert sorted(v) == sorted(VERDICT_KEYS)
        built.close()
        assert s.to_dict()["tenants"]["job"]["pipelines"] == 0

    def test_queue_budget_rebalances_on_admission(self, tmp_path):
        """The scheduler owns the queue-capacity knobs: a second
        tenant's admission SHRINKS the first tenant's share."""
        path = _libsvm_file(tmp_path)
        s = sched_mod.install(queue_budget=32)
        s.register_tenant("a")
        s.register_tenant("b")
        pa = (Pipeline.from_uri(path)
              .parse(format="libsvm", engine="python")
              .batch(64).prefetch(depth="auto").build(tenant="a"))
        knob = next(k for k in pa.knobs()
                    if k.name == "prefetch.depth")
        assert knob.get() == 32  # whole budget: a is alone
        pb = (Pipeline.from_uri(path)
              .parse(format="libsvm", engine="python")
              .batch(64).prefetch(depth="auto").build(tenant="b"))
        assert knob.get() == 16  # b's admission halved a's share
        pb.close()
        assert knob.get() == 32  # and release restores it
        pa.close()

    def test_autotuner_excludes_scheduler_owned_knobs(self, tmp_path):
        path = _libsvm_file(tmp_path)
        s = sched_mod.install()
        s.register_tenant("a")
        built = (Pipeline.from_uri(path)
                 .parse(format="libsvm", engine="python")
                 .batch(64).prefetch(depth="auto")
                 .build(autotune=True, tenant="a"))
        assert built.scheduler_owned == MANAGED_KNOBS
        if built.autotuner is not None:
            names = {k.name for k in built.autotuner.knobs}
            assert not (names & set(MANAGED_KNOBS))
        built.close()

    def test_untenanted_build_untouched(self, tmp_path):
        """No tenant, no scheduler interplay — the pre-scheduler
        contract is unchanged even with one installed."""
        path = _libsvm_file(tmp_path)
        sched_mod.install().register_tenant("x")
        built = (Pipeline.from_uri(path).parse(format="libsvm")
                 .batch(128).build(autotune=True))
        assert built.tenant is None
        assert sum(1 for _ in built) > 0
        assert "tenant" not in built.stats()
        built.close()


class TestWatchdogNaming:
    def test_stall_report_names_the_tenant(self):
        """The acceptance detail: a wedged tenant is NAMED in the
        stall report (tenant/<name>.* wait), not inferred."""
        s = _mk()
        s.register_tenant("victim")
        s.pause("victim")
        wd = obs_watchdog.Watchdog(threshold_s=0.1, interval_s=0.05)
        wd.start()
        try:
            t = threading.Thread(target=s.acquire, args=("victim",),
                                 daemon=True)
            t.start()
            deadline = time.monotonic() + 5.0
            while not wd.reports and time.monotonic() < deadline:
                time.sleep(0.05)
            assert wd.reports, "watchdog never fired"
            names = [b["name"] for r in wd.reports
                     for b in r["blocked"]]
            assert any(n == "tenant/victim.paused" for n in names), \
                names
            detail = next(b["detail"] for r in wd.reports
                          for b in r["blocked"]
                          if b["name"].startswith("tenant/victim"))
            assert detail["tenant"] == "victim"
        finally:
            wd.stop()
            s.resume("victim")
            t.join(timeout=2)
            s.close()


class TestTenantsSurface:
    def test_endpoint_404_hint_without_scheduler(self):
        from dmlc_tpu.obs.serve import StatusServer
        srv = StatusServer(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url("/tenants"), timeout=5)
            payload = json.load(ei.value)
            assert "DMLC_TPU_SCHED" in payload["hint"]
        finally:
            srv.close()

    def test_endpoint_serves_rows(self, tmp_path):
        from dmlc_tpu.obs.serve import StatusServer
        path = _libsvm_file(tmp_path)
        s = sched_mod.install()
        s.register_tenant("svc", weight=2.0)
        built = (Pipeline.from_uri(path).parse(format="libsvm")
                 .batch(128).build(tenant="svc"))
        for _ in built:
            pass
        srv = StatusServer(port=0)
        try:
            with urllib.request.urlopen(srv.url("/tenants"),
                                        timeout=5) as r:
                doc = json.load(r)
            assert doc["schema"] == sched_mod.TENANTS_SCHEMA
            row = doc["tenants"]["svc"]
            assert row["pulls"] > 0 and row["weight"] == 2.0
            assert row["last_verdict"]["bound"]
        finally:
            srv.close()
            built.close()

    def test_obsctl_renders_fabricated_view(self):
        """Pin the obsctl tenants rendering against a fabricated
        /tenants payload (the gang/control fabricated-view pattern)."""
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "obsctl", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "obsctl.py"))
        obsctl = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(obsctl)
        doc = {
            "schema": 1, "quantum": 4.0, "burst": 2.0,
            "queue_budget": 48, "rounds": 17,
            "tenants": {
                "svc": {"weight": 2.0, "deficit": 3.5, "paused": False,
                        "pipelines": 1, "max_pipelines": 4,
                        "queue_share": 32, "pulls": 120,
                        "batch_p50_s": 0.002, "batch_p99_s": 0.011,
                        "queue_occupancy": 0.4,
                        "admitted": 1, "rejected": 0, "queued": 0,
                        "last_verdict": {"verdict_id": "v3-abc",
                                         "bound": "parse",
                                         "band": "plateau",
                                         "confidence": "high"},
                        "watermark": {"uri": "feed.log", "windows": 9,
                                      "watermark_records": 900,
                                      "watermark_bytes": 12345,
                                      "last_advance_s_ago": 0.2,
                                      "retries": 1}},
                "batch": {"weight": 1.0, "deficit": 0.0, "paused": True,
                          "pipelines": 0, "max_pipelines": 2,
                          "queue_share": None, "pulls": 8,
                          "batch_p50_s": None, "batch_p99_s": None,
                          "queue_occupancy": None,
                          "admitted": 2, "rejected": 1, "queued": 1},
            },
        }
        out = obsctl.render_tenants(doc)
        assert "svc" in out and "parse/high" in out
        assert "11.0" in out            # p99 ms
        assert "watermark 900 records" in out
        assert "PAUSED" in out
        assert "1 rejected" in out

    def test_install_if_env(self, monkeypatch):
        monkeypatch.setenv(ENV_SCHED, "quantum=3,queue=9,burst=4")
        s = sched_mod.install_if_env()
        assert s is not None
        assert s.quantum == 3.0 and s.queue_budget == 9 \
            and s.burst == 4.0
        sched_mod.uninstall()
        monkeypatch.setenv(ENV_SCHED, "0")
        assert sched_mod.install_if_env() is None

    def test_scheduler_metrics_collector(self):
        reg = MetricsRegistry()
        s = _mk(registry=reg)
        s.register_tenant("a")
        s.acquire("a")
        snap = reg.snapshot()
        sched = snap["collectors"]["scheduler"]
        assert sched["tenants"]["a"]["pulls"] == 0  # acquire != pull
        assert sched["rounds"] >= 1
        s.close()
        assert "scheduler" not in reg.snapshot()["collectors"]


class TestAnalyzeTenant:
    def test_attribute_passes_tenant_through(self):
        from dmlc_tpu.obs import analyze
        snap = {"schema": 1, "epoch": 2, "wall_s": 1.0, "tenant": "t9",
                "stages": [{"name": "parse", "kind": "parse",
                            "items": 10, "rows": 100, "nnz": 0,
                            "bytes": 10 ** 9, "wait_s": 0.9}]}
        v = analyze.attribute(snap)
        assert v["tenant"] == "t9" and v["bound"] == "parse"
        v2 = analyze.attribute({**snap, "tenant": None})
        assert v2["tenant"] is None
        # the tenant participates in the verdict identity
        assert v["verdict_id"] != v2["verdict_id"]


class TestBenchConfig:
    def test_config_19_registered(self):
        from dmlc_tpu import bench_suite
        assert bench_suite.CONFIGS[19][0] == "multi_tenant"

    @pytest.mark.slow
    def test_multi_tenant_acceptance(self):
        """THE acceptance probe: three adversarial tenants, pinned
        isolation bound (full run — slow)."""
        from dmlc_tpu.bench_suite import bench_multi_tenant
        out = bench_multi_tenant(16)
        assert out["isolation_ratio"] <= out["isolation_bound"]
        assert out["noisy_credit_waits"] > 0
        assert set(out["tenants"]) == {"idle", "parse_heavy",
                                       "wire_heavy"}
