"""Tests for streams, VFS, tempdir, RecordIO (reference: unittest_serializer,
recordio_test, filesys_test, iostream_test)."""

import os
import struct

import numpy as np
import pytest

from dmlc_tpu.io.stream import (
    MemoryStream, create_stream, create_seek_stream_for_read,
)
from dmlc_tpu.io.filesys import FileSystem, URI, FileInfo
from dmlc_tpu.io.tempdir import TemporaryDirectory
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC, RecordIOChunkReader, RecordIOReader, RecordIOWriter,
)
from dmlc_tpu.utils.logging import DMLCError

MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)


class TestURI:
    def test_plain_path(self):
        u = URI("/tmp/x.txt")
        assert u.protocol == "file://" and u.name == "/tmp/x.txt"

    def test_file_scheme(self):
        u = URI("file:///tmp/x")
        assert u.name == "/tmp/x"

    def test_s3(self):
        u = URI("s3://bucket/key/a.txt")
        assert u.protocol == "s3://" and u.host == "bucket"
        assert u.name == "/key/a.txt"
        assert u.str_uri() == "s3://bucket/key/a.txt"

    def test_unknown_scheme_stub_raises_on_use(self):
        # s3:// now routes to the objstore plane; hdfs:// remains a
        # stub seam (no libhdfs in this build)
        u = URI("hdfs://nn/key")
        fs = FileSystem.get_instance(u)
        with pytest.raises(DMLCError, match="no backend"):
            fs.open_for_read(u)

    def test_s3_aliases_objstore_plane(self, monkeypatch):
        import dmlc_tpu.io.objstore as objstore
        monkeypatch.delenv(objstore.ENV_ROOT, raising=False)
        u = URI("s3://bucket/key")
        fs = FileSystem.get_instance(u)
        assert isinstance(fs, objstore.ObjectStoreFileSystem)
        with pytest.raises(DMLCError, match="no object-store endpoint"):
            fs.open_for_read(u)

    def test_unregistered_scheme(self):
        with pytest.raises(DMLCError, match="unknown filesystem"):
            FileSystem.get_instance(URI("zzz://x/y"))
        assert FileSystem.get_instance(URI("zzz://x/y"), allow_null=True) is None


class TestURISpec:
    def test_full(self):
        s = URISpec("data/train.csv?format=csv&label_column=0#cachefile")
        assert s.uri == "data/train.csv"
        assert s.args == {"format": "csv", "label_column": "0"}
        assert s.cache_file == "cachefile"

    def test_multipath(self):
        s = URISpec("a.txt;b.txt")
        assert s.paths() == ["a.txt", "b.txt"]


class TestMemoryStream:
    def test_rw_seek(self):
        s = MemoryStream()
        s.write(b"hello")
        s.seek(0)
        assert s.read(2) == b"he"
        assert s.tell() == 2
        s.seek(5)
        s.write(b" world")
        assert s.getvalue() == b"hello world"

    def test_overwrite_middle(self):
        s = MemoryStream(b"abcdef")
        s.seek(2)
        s.write(b"XY")
        assert s.getvalue() == b"abXYef"

    def test_read_at_eof(self):
        s = MemoryStream(b"ab")
        assert s.read(10) == b"ab"
        assert s.read(1) == b""


class TestLocalFS:
    def test_stream_roundtrip(self, tmp_path):
        p = str(tmp_path / "f.bin")
        with create_stream(p, "w") as s:
            s.write(b"data123")
        with create_stream(p, "r") as s:
            assert s.read_all() == b"data123"
        with create_stream(p, "a") as s:
            s.write(b"more")
        with create_seek_stream_for_read(p) as s:
            s.seek(7)
            assert s.read(4) == b"more"

    def test_allow_null_missing(self, tmp_path):
        assert create_stream(str(tmp_path / "nope"), "r",
                             allow_null=True) is None
        with pytest.raises(FileNotFoundError):
            create_stream(str(tmp_path / "nope"), "r")

    def test_list_directory(self, tmp_path):
        (tmp_path / "a.txt").write_bytes(b"xx")
        (tmp_path / "b.txt").write_bytes(b"yyy")
        (tmp_path / "sub").mkdir()
        u = URI(str(tmp_path))
        fs = FileSystem.get_instance(u)
        infos = fs.list_directory(u)
        names = [os.path.basename(i.path) for i in infos]
        assert names == ["a.txt", "b.txt", "sub"]
        assert [i.type for i in infos] == ["file", "file", "directory"]
        assert fs.get_path_info(u).type == "directory"

    def test_as_file_adapter(self, tmp_path):
        p = str(tmp_path / "t.txt")
        with create_stream(p, "w") as s:
            s.as_file().write(b"line1\nline2\n")
        with create_stream(p, "r") as s:
            import io
            assert io.BufferedReader(s.as_file()).readline() == b"line1\n"

    def test_as_file_does_not_own_stream_by_default(self, tmp_path):
        # ADVICE r5: a temporary adapter (GC'd or closed) must not close
        # the stream out from under its owner mid-`with`
        p = str(tmp_path / "own.txt")
        with create_stream(p, "w") as s:
            f = s.as_file()
            f.write(b"a\n")
            f.close()          # adapter gone...
            s.write(b"b\n")    # ...stream still usable by its owner
        with create_stream(p, "r") as s:
            assert s.read_all() == b"a\nb\n"

    def test_as_file_own_stream_transfers_ownership(self, tmp_path):
        p = str(tmp_path / "own2.txt")
        with open(p, "w") as f:
            f.write("x")
        s = create_stream(p, "r")
        s.as_file(own_stream=True).close()
        # FileStream drops its file object on close — ownership moved
        assert s._f is None


class TestTemporaryDirectory:
    def test_create_delete(self):
        td = TemporaryDirectory()
        path = td.path
        assert os.path.isdir(path)
        with open(os.path.join(path, "x"), "w") as f:
            f.write("1")
        os.makedirs(os.path.join(path, "nested", "deep"))
        td.close()
        assert not os.path.exists(path)

    def test_context_manager(self):
        with TemporaryDirectory() as td:
            path = td.path
            assert os.path.isdir(path)
        assert not os.path.exists(path)


class TestRecordIO:
    def roundtrip(self, records):
        s = MemoryStream()
        w = RecordIOWriter(s)
        for r in records:
            w.write_record(r)
        s.seek(0)
        r = RecordIOReader(s)
        out = []
        while True:
            rec = r.next_record()
            if rec is None:
                break
            out.append(rec)
        assert out == list(records)
        # chunk reader over the whole buffer must agree
        chunk_out = list(RecordIOChunkReader(s.getvalue()))
        assert chunk_out == list(records)
        return w

    def test_simple(self):
        self.roundtrip([b"hello", b"world", b""])

    def test_payload_with_magic_aligned(self):
        # aligned magic in payload must be escaped (frame split)
        payload = b"abcd" + MAGIC_BYTES + b"efgh"
        w = self.roundtrip([payload])
        assert w.escaped_magic_count == 1

    def test_payload_magic_at_start(self):
        w = self.roundtrip([MAGIC_BYTES + b"tail"])
        assert w.escaped_magic_count == 1

    def test_payload_magic_unaligned_not_escaped(self):
        payload = b"ab" + MAGIC_BYTES + b"cd"  # magic at offset 2: unaligned
        w = self.roundtrip([payload])
        assert w.escaped_magic_count == 0

    def test_payload_many_magics(self):
        payload = MAGIC_BYTES * 5
        w = self.roundtrip([payload])
        assert w.escaped_magic_count == 5

    def test_adversarial_random(self, rng):
        records = []
        for _ in range(50):
            n = rng.randint(0, 64)
            raw = rng.bytes(n)
            # splice magic bytes at random positions
            if n > 4 and rng.rand() < 0.5:
                pos = rng.randint(0, n - 4)
                raw = raw[:pos] + MAGIC_BYTES + raw[pos + 4:]
            records.append(raw)
        self.roundtrip(records)

    def test_padding_alignment(self):
        s = MemoryStream()
        w = RecordIOWriter(s)
        w.write_record(b"abc")  # 3 bytes -> padded to 4
        assert len(s.getvalue()) % 4 == 0
        w.write_record(b"defgh")
        assert len(s.getvalue()) % 4 == 0

    def test_bad_magic_raises(self):
        s = MemoryStream(b"\x00" * 16)
        with pytest.raises(DMLCError, match="magic"):
            RecordIOReader(s).next_record()
