"""Checkpoint subsystem + JSON utilities (reference: Serializable/Stream
checkpoint primitives + json.h; TPU-native sharded checkpoint)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.io.checkpoint import ShardedCheckpoint, load_pytree, save_pytree
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.utils.json_util import (
    JSONObjectReadHelper, json_dump, json_load, to_jsonable,
)
from dmlc_tpu.utils.logging import DMLCError


class TestJsonUtil:
    def test_roundtrip_with_numpy(self, rng):
        obj = {"a": 1, "b": [1.5, "x"], "arr": rng.rand(3, 2).astype(np.float32),
               "blob": b"\x00\x01", "n": np.int64(7)}
        s = MemoryStream()
        json_dump(obj, s)
        s.seek(0)
        out = json_load(s)
        assert out["a"] == 1 and out["b"] == [1.5, "x"] and out["n"] == 7
        np.testing.assert_array_equal(out["arr"], obj["arr"])
        assert out["blob"] == b"\x00\x01"

    def test_invalid_json(self):
        with pytest.raises(DMLCError, match="invalid JSON"):
            json_load(MemoryStream(b"{nope"))

    def test_object_helper(self):
        h = (JSONObjectReadHelper()
             .declare_field("name", str)
             .declare_field("size", int)
             .declare_field("opt", int, optional=True, default=3))
        out = h.read_all_fields({"name": "x", "size": 2})
        assert out == {"name": "x", "size": 2, "opt": 3}
        with pytest.raises(DMLCError, match="required"):
            h.read_all_fields({"name": "x"})
        with pytest.raises(DMLCError, match="unknown"):
            h.read_all_fields({"name": "x", "size": 1, "zz": 0})
        with pytest.raises(DMLCError, match="expected"):
            h.read_all_fields({"name": "x", "size": "two"})


class TestPytreeCheckpoint:
    def test_roundtrip_dict(self, tmp_path, rng):
        tree = {"w": rng.rand(8, 4).astype(np.float32),
                "opt": {"m": rng.rand(8).astype(np.float32)},
                "step": np.int64(17)}
        path = str(tmp_path / "ck.bin")
        save_pytree(tree, path)
        flat = load_pytree(path)
        np.testing.assert_array_equal(flat["w"], tree["w"])
        restored = load_pytree(path, like=tree)
        np.testing.assert_array_equal(restored["opt"]["m"], tree["opt"]["m"])
        assert restored["step"] == 17

    def test_jax_arrays(self, tmp_path):
        tree = {"w": jnp.arange(12.0).reshape(3, 4)}
        path = str(tmp_path / "j.bin")
        save_pytree(tree, path)
        out = load_pytree(path, like=tree)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(tree["w"]))

    def test_missing_key_raises(self, tmp_path):
        save_pytree({"a": np.zeros(2)}, str(tmp_path / "c.bin"))
        with pytest.raises(DMLCError, match="missing"):
            load_pytree(str(tmp_path / "c.bin"), like={"b": np.zeros(2)})


class TestShardedCheckpoint:
    def make_sharded_tree(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        sharding = NamedSharding(mesh, P("data"))
        x = jnp.arange(64.0, dtype=jnp.float32)
        xs = jax.device_put(x, sharding)
        w = jax.device_put(jnp.ones((5,), jnp.float32),
                           NamedSharding(mesh, P()))
        return {"x": xs, "w": w}, mesh

    def test_save_restore_sharded(self, tmp_path):
        tree, mesh = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "root"))
        d = ck.save(3, tree, metadata={"epoch": 1})
        assert os.path.exists(os.path.join(d, "COMMIT"))
        assert ck.latest_step() == 3
        restored, user = ck.restore(like=tree)
        assert user == {"epoch": 1}
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert restored["x"].sharding.is_equivalent_to(
            tree["x"].sharding, ndim=1)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))

    def test_multiple_steps_and_latest(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        ck.save(5, tree)
        assert ck.all_steps() == [1, 5]
        assert ck.latest_step() == 5

    def test_uncommitted_not_restored(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(2, tree)
        os.remove(os.path.join(d, "COMMIT"))  # simulate torn save
        assert ck.latest_step() is None
        with pytest.raises(DMLCError, match="no committed"):
            ck.restore(like=tree)

    def test_restore_without_like(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        flat, _ = ck.restore()
        np.testing.assert_array_equal(flat["x"], np.arange(64.0))

    def test_restore_sweeps_stale_spill_cache(self, tmp_path, rng):
        # restore marks a resume boundary: a replay spill file whose
        # source fingerprint no longer stats clean must be gone after
        # restore (the steady-replay mutation contract), while a cache
        # of an unchanged source survives
        from dmlc_tpu.data.row_iter import (
            RoundSpillWriter, default_spill_dir,
        )
        from dmlc_tpu.data.rowblock import RowBlockContainer
        src = tmp_path / "src.libsvm"
        src.write_bytes(b"1 1:1.0\n")
        st = os.stat(src)
        d = default_spill_dir()
        c = RowBlockContainer(np.uint32)
        c.push(1.0, [1], [1.0])
        blk = c.get_block()
        uniq = os.path.basename(str(tmp_path)).replace("_", "")
        stale = os.path.join(d, f"test-{uniq}-stale.pages")
        fresh = os.path.join(d, f"test-{uniq}-fresh.pages")
        for path, fp in (
                (stale, [[str(src), st.st_size + 1, st.st_mtime_ns]]),
                (fresh, [[str(src), st.st_size, st.st_mtime_ns]])):
            w = RoundSpillWriter(path, nparts=1,
                                 meta={"fingerprint": fp})
            w.add_row([blk])
            w.commit()
        try:
            tree, _ = self.make_sharded_tree()
            ck = ShardedCheckpoint(str(tmp_path / "r"))
            ck.save(1, tree)
            ck.restore(like=tree)
            assert not os.path.exists(stale), \
                "restore must sweep fingerprint-stale spill caches"
            assert os.path.exists(fresh), \
                "restore must keep caches of unchanged sources"
        finally:
            for p in (stale, fresh):
                if os.path.exists(p):
                    os.remove(p)


class TestCheckpointRegressions:
    def test_restore_without_like_replicated_and_scalar(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        repl = NamedSharding(mesh, P())
        tree = {
            "x": jax.device_put(jnp.arange(64.0), NamedSharding(mesh, P("data"))),
            "w": jax.device_put(jnp.ones((5,), jnp.float32), repl),
            "b": jax.device_put(jnp.float32(2.5), repl),
        }
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        flat, _ = ck.restore()
        np.testing.assert_array_equal(flat["x"], np.arange(64.0))
        np.testing.assert_array_equal(flat["w"], np.ones(5))  # not 8x dup
        assert flat["b"].shape == () and float(flat["b"]) == 2.5

    def test_replicated_leaf_written_once(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        big = jax.device_put(jnp.zeros((1 << 16,), jnp.float32),
                             NamedSharding(mesh, P()))
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, {"big": big})
        shard_file = os.path.join(d, "shard-0.bin")
        size = os.path.getsize(shard_file)
        assert size < big.nbytes * 1.5  # one copy + framing, not 8 copies


class TestShardLocalRestore:
    """Restore must read only the placements intersecting the target
    sharding's addressable slices (VERDICT r1 #5): peak host memory ~
    local shard bytes, built via make_array_from_single_device_arrays."""

    def _tree(self, n=1 << 12):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        sh = NamedSharding(mesh, P("data"))
        x = jax.device_put(jnp.arange(float(n), dtype=jnp.float32), sh)
        return {"x": x}, mesh, sh

    def test_restore_reads_only_needed_placements(self, tmp_path):
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        # single-process: all 8 devices are addressable, so the whole row
        # space is needed — the probe is that each placement is read
        # EXACTLY once (no full-file rescans, no per-device re-reads)
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        # every byte read was a needed placement: total == stored bytes
        # of x exactly once (8 placements, no re-reads, no full-file scan)
        assert ck.last_restore_bytes_read <= tree["x"].nbytes + 8 * 64

    def test_accounting_scales_with_slice(self, tmp_path):
        # restore only x (sharded); a second huge leaf must NOT be read
        tree, mesh, sh = self._tree()
        big = jax.device_put(jnp.zeros((1 << 15,), jnp.float32),
                             NamedSharding(mesh, P()))
        full = {"x": tree["x"], "big": big}
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, full)
        restored, _ = ck.restore(like={"x": tree["x"]})
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert ck.last_restore_bytes_read < big.nbytes // 2, \
            "restore read leaves outside the requested tree"

    def test_reshard_on_restore(self, tmp_path):
        # stored on 8 devices, restored onto a 4-device mesh (placement-
        # driven assembly, mesh-topology independent)
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(4), ("data",))
        sh4 = NamedSharding(mesh4, P("data"))
        like = jax.device_put(jnp.zeros_like(np.asarray(tree["x"])), sh4)
        restored, _ = ck.restore(like={"x": like})
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert restored["x"].sharding.is_equivalent_to(sh4, ndim=1)

    def test_scalar_leaf_does_not_pull_full_model(self, tmp_path):
        # regression: an unsharded leaf (step counter) in `like` must not
        # trigger a full-model host assembly of the sharded leaves
        tree, mesh, sh = self._tree()
        full = {"x": tree["x"], "step": np.int64(7)}
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, full)
        restored, _ = ck.restore(like=full)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert int(restored["step"]) == 7
        # x read exactly once + the scalar, not twice
        assert ck.last_restore_bytes_read <= tree["x"].nbytes + 1024

    def test_replicated_saved_to_sharded_target_reads_once(self, tmp_path):
        # regression: a replicated-SAVED leaf restored onto a sharded
        # target must read the single stored record once, not once per
        # device span (was 8x I/O)
        tree, mesh, _ = self._tree()
        repl = NamedSharding(mesh, P())
        w = jax.device_put(jnp.arange(4096.0, dtype=jnp.float32), repl)
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, {"w": w})
        sh = NamedSharding(mesh, P("data"))
        like = jax.device_put(jnp.zeros(4096, jnp.float32), sh)
        restored, _ = ck.restore(like={"w": like})
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(4096.0))
        assert ck.last_restore_bytes_read <= w.nbytes + 1024

    def test_missing_index_file_still_restores(self, tmp_path):
        # regression: mixed indexed/unindexed shard files (version skew,
        # lost idx) must restore via the structural scan, and a stale
        # index whose bin_size mismatches is rejected in favor of a scan
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, tree)
        idx = os.path.join(d, "shard-0.idx.json")
        os.remove(idx)  # simulate a pre-index writer / lost idx
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        # stale index: wrong bin_size must be ignored, not trusted
        with open(idx, "w") as f:
            json.dump({"entries": [], "bin_size": 1}, f)
        ck2 = ShardedCheckpoint(str(tmp_path / "r"))
        restored2, _ = ck2.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored2["x"]),
                                      np.asarray(tree["x"]))

    def test_resave_removes_stale_world_shards(self, tmp_path):
        # regression: re-saving a step must drop shard files from pids
        # outside the current world (elastic restart with fewer procs)
        # and invalidate COMMIT while rewriting
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, tree)
        # plant shards from a departed pid 5 of a previous larger world
        with open(os.path.join(d, "shard-5.bin"), "wb") as f:
            f.write(b"stale")
        with open(os.path.join(d, "shard-5.idx.json"), "w") as f:
            json.dump({"entries": [], "bin_size": 5}, f)
        ck.save(1, tree)  # re-save same step, world=1
        assert not os.path.exists(os.path.join(d, "shard-5.bin"))
        assert not os.path.exists(os.path.join(d, "shard-5.idx.json"))
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))

    def test_resave_replaces_data_and_cleans_up(self, tmp_path):
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        n = np.asarray(tree["x"]).shape[0]
        tree2 = {"x": jax.device_put(
            jnp.arange(float(n), dtype=jnp.float32) * 3, sh)}
        d = ck.save(1, tree2)
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree2["x"]))
        assert not os.path.isdir(d + ".new")
        assert not os.path.isdir(d + ".trash")

    def test_resave_crash_never_loses_committed(self, tmp_path):
        # ADVICE r2: a torn re-save (crash while writing the replacement)
        # must leave the previously committed step fully restorable —
        # the replacement builds in step-N.new and only swaps in once
        # committed
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, tree)
        new = d + ".new"
        os.makedirs(new)
        with open(os.path.join(new, "shard-0.bin"), "wb") as f:
            f.write(b"torn re-save garbage")  # no COMMIT: crashed mid-write
        assert ck.latest_step() == 1
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        # and a subsequent re-save recovers cleanly over the torn .new
        ck.save(1, tree)
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))

    def test_interrupted_swap_serves_committed_new(self, tmp_path):
        # crash BETWEEN the swap's two renames: step dir missing, .new
        # fully committed — discovery and restore must serve the .new
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, tree)
        os.rename(d, d + ".new")  # exactly the mid-swap on-disk state
        assert ck.latest_step() == 1
        assert ck.all_steps() == [1]
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))

    def test_both_committed_serves_newer_new(self, tmp_path, monkeypatch):
        # ADVICE r4: crash BETWEEN .new's COMMIT and the swap renames
        # leaves BOTH step-1 and step-1.new committed. The .new is
        # provably the newer save (save() strips COMMIT from .new before
        # reuse) — restore must serve it, and keep serving the same data
        # after a later save promotes it (no flip-flop over time).
        tree, mesh, sh = self._tree()
        newer = {"x": np.asarray(tree["x"]) + 100.0}
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        monkeypatch.setattr(ShardedCheckpoint, "_swap_in",
                            staticmethod(lambda final: None))
        ck.save(1, newer)  # commits .new, "crashes" before the swap
        monkeypatch.undo()
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(newer["x"]))
        # later activity elsewhere must not flip which copy step 1 means
        ck.save(2, tree)
        restored, _ = ck.restore(step=1, like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(newer["x"]))

    def test_save_over_interrupted_swap_crash_keeps_committed(
            self, tmp_path, monkeypatch):
        # r4 regression (code review): start from the mid-swap state
        # (step dir missing, .new fully committed — the step's ONLY
        # committed copy). A save of that step must NOT invalidate the
        # committed .new before a replacement exists: crash the save
        # during shard writing and the old data must still restore.
        tree, mesh, sh = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, tree)
        os.rename(d, d + ".new")  # exactly the mid-swap on-disk state
        bomb = RuntimeError("simulated crash mid shard write")

        def boom(leaf):
            raise bomb

        monkeypatch.setattr(ShardedCheckpoint, "_addressable_shards",
                            staticmethod(boom))
        with pytest.raises(RuntimeError):
            ck.save(1, {"x": np.zeros_like(np.asarray(tree["x"]))})
        monkeypatch.undo()
        assert ck.latest_step() == 1  # the old committed copy survived
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))

    def test_replicated_target_restores(self, tmp_path):
        tree, mesh, _ = self._tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        repl = NamedSharding(mesh, P())
        like = jax.device_put(jnp.zeros_like(np.asarray(tree["x"])), repl)
        restored, _ = ck.restore(like={"x": like})
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        # 8 replicated devices share one assembled slice (cache), so the
        # stored data is read once, not 8 times
        assert ck.last_restore_bytes_read <= tree["x"].nbytes + 8 * 64


class TestRemoteCheckpoint:
    """Device-direct sharded checkpoint on an ``obj://`` root: pages
    stream through the objstore write plane, saves are incremental by
    content digest, COMMIT gates restorability, and restore verifies
    every page against its digest."""

    @pytest.fixture
    def remote(self, tmp_path, monkeypatch):
        import dmlc_tpu.io.objstore as objstore
        import dmlc_tpu.io.objstore.fs as ofs
        import dmlc_tpu.io.pagestore as ps
        from dmlc_tpu.io.objstore.emulator import EmulatedObjectStore
        monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
        monkeypatch.setattr(ps, "default_store_dir",
                            lambda: str(tmp_path / "pagestore"))
        saved = ofs.options()
        em = EmulatedObjectStore(str(tmp_path / "objroot"))
        objstore.configure(em)
        yield em
        objstore.configure(
            None, block_bytes=saved["block_bytes"],
            coalesce=saved["coalesce"], parallel=saved["parallel"],
            hydrate=saved["hydrate"],
            put_part_bytes=saved["put_part_bytes"],
            put_parallel=saved["put_parallel"])

    def _tree(self, rng, scale=1.0):
        return {"w": (rng.rand(256, 16) * scale).astype(np.float32),
                "b": rng.rand(64).astype(np.float32),
                "step": np.int64(7)}

    def test_save_restore_roundtrip(self, remote, rng):
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        d = ck.save(3, tree, metadata={"epoch": 2})
        assert d == "obj://b/ck/step-00000003"
        assert ck.last_save_bytes_written > 0
        assert ck.latest_step() == 3 and ck.all_steps() == [3]
        restored, user = ck.restore(like=tree)
        assert user == {"epoch": 2}
        np.testing.assert_array_equal(restored["w"], tree["w"])
        np.testing.assert_array_equal(restored["b"], tree["b"])
        assert restored["step"] == 7
        assert ck.last_restore_bytes_read > 0

    def test_incremental_save_reuses_unchanged_pages(self, remote, rng):
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(1, tree)
        first = ck.last_save_bytes_written
        assert first > 0 and ck.last_save_bytes_reused == 0
        tree2 = dict(tree, b=(tree["b"] + 1.0))  # one small leaf moves
        ck.save(2, tree2)
        # only the changed leaf uploads; the big unchanged pages dedup
        assert ck.last_save_bytes_reused > 0
        assert 0 < ck.last_save_bytes_written < first // 4
        for step, want in ((1, tree), (2, tree2)):
            got, _ = ck.restore(step=step, like=tree)
            np.testing.assert_array_equal(got["w"], want["w"])
            np.testing.assert_array_equal(got["b"], want["b"])

    def test_same_tree_resave_uploads_nothing(self, remote, rng):
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(1, tree)
        ck.save(2, tree)
        assert ck.last_save_bytes_written == 0
        assert ck.last_save_bytes_reused > 0

    def test_uncommitted_step_not_restorable(self, remote, rng):
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(4, tree)
        remote.delete("b", "ck/step-00000004/COMMIT")  # torn save
        assert ck.latest_step() is None
        with pytest.raises(DMLCError, match="no committed"):
            ck.restore(like=tree)
        with pytest.raises(DMLCError, match="not committed"):
            ck.restore(step=4, like=tree)

    def test_multi_writer_gang_save(self, remote, rng):
        """Two writers with DISJOINT leaves converge on one committed
        step: writer 1 publishes its shard index first, writer 0
        commits only after seeing every index."""
        t0 = {"w0": rng.rand(32, 8).astype(np.float32)}
        t1 = {"w1": rng.rand(16, 4).astype(np.float32)}
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(9, t1, writer=1, num_writers=2)   # no COMMIT yet
        assert ck.latest_step() is None
        ck.save(9, t0, writer=0, num_writers=2)   # commits
        assert ck.latest_step() == 9
        like = {"w0": t0["w0"], "w1": t1["w1"]}
        restored, _ = ck.restore(like=like)
        np.testing.assert_array_equal(restored["w0"], t0["w0"])
        np.testing.assert_array_equal(restored["w1"], t1["w1"])

    def test_writer_args_rejected_on_local_root(self, tmp_path, rng):
        ck = ShardedCheckpoint(str(tmp_path / "local"))
        with pytest.raises(DMLCError, match="remote"):
            ck.save(1, self._tree(rng), writer=0, num_writers=2)

    def test_corrupt_page_detected(self, remote, rng, tmp_path,
                                   monkeypatch):
        import dmlc_tpu.io.pagestore as ps
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(1, tree)
        # corrupt ONE page object in place (valid serialized ndarray,
        # wrong content), and point at a fresh page store so restore
        # must take the wire and verify the digest
        pages = os.path.join(remote.root, "b", "ck", "pages")
        name = sorted(os.listdir(pages))[0]
        with open(os.path.join(pages, name), "r+b") as f:
            raw = bytearray(f.read())
            raw[-4] ^= 0xFF  # flip payload bytes near the tail
            f.seek(0)
            f.write(raw)
        monkeypatch.setattr(ps, "default_store_dir",
                            lambda: str(tmp_path / "pagestore2"))
        with pytest.raises(DMLCError, match="content mismatch"):
            ck.restore(like=tree)

    def test_restore_split_accounting(self, remote, rng, tmp_path,
                                      monkeypatch):
        import dmlc_tpu.io.pagestore as ps
        tree = self._tree(rng)
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(1, tree)
        # same process: the saver's page store answers everything
        ck.restore(like=tree)
        assert ck.last_restore_local_bytes == ck.last_restore_bytes_read
        assert ck.last_restore_wire_bytes == 0
        # a cold process (fresh page store) pays the wire — no gang,
        # so the whole checkpoint is wire bytes
        monkeypatch.setattr(ps, "default_store_dir",
                            lambda: str(tmp_path / "pagestore2"))
        ck2 = ShardedCheckpoint("obj://b/ck")
        ck2.restore(like=tree)
        assert ck2.last_restore_wire_bytes == ck2.last_restore_bytes_read
        assert ck2.last_restore_bytes_read > 0

    def test_sharded_jax_tree_remote(self, remote):
        tree, _ = TestShardedCheckpoint().make_sharded_tree()
        ck = ShardedCheckpoint("obj://b/ck")
        ck.save(2, tree)
        restored, _ = ck.restore(like=tree)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert restored["x"].sharding.is_equivalent_to(
            tree["x"].sharding, ndim=1)


class TestAnalyzeRestoreEvidence:
    def test_evidence_names_fanout_split_rates(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 1_000_000_000}]}
        metrics = {"counters": {
            "checkpoint.restore_bytes": 900_000_000,
            "checkpoint.restore.local_bytes": 100_000_000,
            "checkpoint.restore.peer_bytes": 500_000_000,
            "checkpoint.restore.wire_bytes": 300_000_000}}
        v = attribute(snap, metrics=metrics)
        lines = [e for e in v["evidence"]
                 if e.startswith("checkpoint restore:")]
        assert len(lines) == 1
        assert "900000000 bytes" in lines[0]
        assert "100000000 local" in lines[0]
        assert "500000000 peer-served" in lines[0]
        assert "300000000 wire" in lines[0]
        assert "GB/s peer-served" in lines[0]
        assert "GB/s wire-served" in lines[0]

    def test_no_restore_no_evidence_line(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 1_000_000_000}]}
        v = attribute(snap, metrics={"counters": {}})
        assert not [e for e in v["evidence"]
                    if e.startswith("checkpoint restore:")]
