"""Checkpoint subsystem + JSON utilities (reference: Serializable/Stream
checkpoint primitives + json.h; TPU-native sharded checkpoint)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.io.checkpoint import ShardedCheckpoint, load_pytree, save_pytree
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.utils.json_util import (
    JSONObjectReadHelper, json_dump, json_load, to_jsonable,
)
from dmlc_tpu.utils.logging import DMLCError


class TestJsonUtil:
    def test_roundtrip_with_numpy(self, rng):
        obj = {"a": 1, "b": [1.5, "x"], "arr": rng.rand(3, 2).astype(np.float32),
               "blob": b"\x00\x01", "n": np.int64(7)}
        s = MemoryStream()
        json_dump(obj, s)
        s.seek(0)
        out = json_load(s)
        assert out["a"] == 1 and out["b"] == [1.5, "x"] and out["n"] == 7
        np.testing.assert_array_equal(out["arr"], obj["arr"])
        assert out["blob"] == b"\x00\x01"

    def test_invalid_json(self):
        with pytest.raises(DMLCError, match="invalid JSON"):
            json_load(MemoryStream(b"{nope"))

    def test_object_helper(self):
        h = (JSONObjectReadHelper()
             .declare_field("name", str)
             .declare_field("size", int)
             .declare_field("opt", int, optional=True, default=3))
        out = h.read_all_fields({"name": "x", "size": 2})
        assert out == {"name": "x", "size": 2, "opt": 3}
        with pytest.raises(DMLCError, match="required"):
            h.read_all_fields({"name": "x"})
        with pytest.raises(DMLCError, match="unknown"):
            h.read_all_fields({"name": "x", "size": 1, "zz": 0})
        with pytest.raises(DMLCError, match="expected"):
            h.read_all_fields({"name": "x", "size": "two"})


class TestPytreeCheckpoint:
    def test_roundtrip_dict(self, tmp_path, rng):
        tree = {"w": rng.rand(8, 4).astype(np.float32),
                "opt": {"m": rng.rand(8).astype(np.float32)},
                "step": np.int64(17)}
        path = str(tmp_path / "ck.bin")
        save_pytree(tree, path)
        flat = load_pytree(path)
        np.testing.assert_array_equal(flat["w"], tree["w"])
        restored = load_pytree(path, like=tree)
        np.testing.assert_array_equal(restored["opt"]["m"], tree["opt"]["m"])
        assert restored["step"] == 17

    def test_jax_arrays(self, tmp_path):
        tree = {"w": jnp.arange(12.0).reshape(3, 4)}
        path = str(tmp_path / "j.bin")
        save_pytree(tree, path)
        out = load_pytree(path, like=tree)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(tree["w"]))

    def test_missing_key_raises(self, tmp_path):
        save_pytree({"a": np.zeros(2)}, str(tmp_path / "c.bin"))
        with pytest.raises(DMLCError, match="missing"):
            load_pytree(str(tmp_path / "c.bin"), like={"b": np.zeros(2)})


class TestShardedCheckpoint:
    def make_sharded_tree(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        sharding = NamedSharding(mesh, P("data"))
        x = jnp.arange(64.0, dtype=jnp.float32)
        xs = jax.device_put(x, sharding)
        w = jax.device_put(jnp.ones((5,), jnp.float32),
                           NamedSharding(mesh, P()))
        return {"x": xs, "w": w}, mesh

    def test_save_restore_sharded(self, tmp_path):
        tree, mesh = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "root"))
        d = ck.save(3, tree, metadata={"epoch": 1})
        assert os.path.exists(os.path.join(d, "COMMIT"))
        assert ck.latest_step() == 3
        restored, user = ck.restore(like=tree)
        assert user == {"epoch": 1}
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(tree["x"]))
        assert restored["x"].sharding.is_equivalent_to(
            tree["x"].sharding, ndim=1)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(tree["w"]))

    def test_multiple_steps_and_latest(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        ck.save(5, tree)
        assert ck.all_steps() == [1, 5]
        assert ck.latest_step() == 5

    def test_uncommitted_not_restored(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(2, tree)
        os.remove(os.path.join(d, "COMMIT"))  # simulate torn save
        assert ck.latest_step() is None
        with pytest.raises(DMLCError, match="no committed"):
            ck.restore(like=tree)

    def test_restore_without_like(self, tmp_path):
        tree, _ = self.make_sharded_tree()
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        flat, _ = ck.restore()
        np.testing.assert_array_equal(flat["x"], np.arange(64.0))


class TestCheckpointRegressions:
    def test_restore_without_like_replicated_and_scalar(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        repl = NamedSharding(mesh, P())
        tree = {
            "x": jax.device_put(jnp.arange(64.0), NamedSharding(mesh, P("data"))),
            "w": jax.device_put(jnp.ones((5,), jnp.float32), repl),
            "b": jax.device_put(jnp.float32(2.5), repl),
        }
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        ck.save(1, tree)
        flat, _ = ck.restore()
        np.testing.assert_array_equal(flat["x"], np.arange(64.0))
        np.testing.assert_array_equal(flat["w"], np.ones(5))  # not 8x dup
        assert flat["b"].shape == () and float(flat["b"]) == 2.5

    def test_replicated_leaf_written_once(self, tmp_path):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
        big = jax.device_put(jnp.zeros((1 << 16,), jnp.float32),
                             NamedSharding(mesh, P()))
        ck = ShardedCheckpoint(str(tmp_path / "r"))
        d = ck.save(1, {"big": big})
        shard_file = os.path.join(d, "shard-0.bin")
        size = os.path.getsize(shard_file)
        assert size < big.nbytes * 1.5  # one copy + framing, not 8 copies
