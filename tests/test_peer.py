"""The gang-scale data plane (ROADMAP item 5): the ``/pages`` peer
endpoint, the objstore peer hydration tier, singleflight dedup, chaos
degradation — and THE acceptance: a REAL 2-process gang whose cold
``obj://`` epoch moves ~1/N of the single-rank wire bytes, goes
wire-free warm on every rank, and streams byte-identical to local."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import dmlc_tpu.io.objstore as objstore
from dmlc_tpu.io.objstore import peer as peer_mod
from dmlc_tpu.io.pagestore import ENV_STORE_DIR, PageStore
from dmlc_tpu.io.stream import create_seek_stream_for_read
from dmlc_tpu.obs.metrics import REGISTRY
from dmlc_tpu.obs.serve import StatusServer
from dmlc_tpu.resilience import (
    RetryPolicy, inject, reset_policies, set_policy,
)


def _counter(name):
    return REGISTRY.counter(name).value


def _noop_sleep(_s):
    pass


def _get(url, headers=None, timeout=10.0):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers.items())


def _payload(rows=6000, seed=0):
    rng = np.random.RandomState(seed)
    return b"".join(b"%d %d:%.4f %d:%.4f\n"
                    % (i % 2, rng.randint(0, 40), rng.rand(),
                       40 + rng.randint(0, 40), rng.rand())
                    for i in range(rows))


@pytest.fixture
def plane(tmp_path, monkeypatch):
    """An isolated objstore plane: fresh emulator, per-test LOCAL page
    store root (via the DMLC_TPU_PAGESTORE_DIR satellite env), small
    blocks, peer tier reset on both sides."""
    import dmlc_tpu.io.objstore.fs as ofs
    monkeypatch.delenv(ofs.ENV_ROOT, raising=False)
    monkeypatch.delenv("DMLC_TPU_SERVE_PORTS", raising=False)
    monkeypatch.delenv("DMLC_TPU_SERVE_PORT", raising=False)
    monkeypatch.setenv(ENV_STORE_DIR, str(tmp_path / "local-store"))
    saved = ofs.options()
    client = objstore.configure(root=str(tmp_path / "objroot"),
                                block_bytes=1 << 15, coalesce=2,
                                parallel=2)
    peer_mod.reset()
    yield client, tmp_path
    objstore.configure(None, block_bytes=saved["block_bytes"],
                       coalesce=saved["coalesce"],
                       parallel=saved["parallel"],
                       hydrate=saved["hydrate"],
                       peer=saved.get("peer", True))
    peer_mod.reset()
    inject.uninstall()
    reset_policies()


def _hydrate_into(root, uri, payload_len):
    """Fill the page store at ``root`` by streaming the object with
    that store (the 'peer rank already read this' state)."""
    store = PageStore.at(str(root))
    s = create_seek_stream_for_read(uri)
    s._store = store  # this stream hydrates the PEER's store
    s._peer = None
    out = s.read_all()
    s.close()
    assert len(out) == payload_len
    return store


# ------------------------------------------------------ /pages endpoint

class TestPagesEndpoint:
    def test_serves_committed_entry_with_headers(self, plane):
        em, tmp = plane
        em.put("b", "x.bin", b"E" * 50000)
        store = _hydrate_into(tmp / "peer-store", "obj://b/x.bin",
                              50000)
        entries = sorted(n for n in os.listdir(store.root)
                         if n.endswith(".pages"))
        assert entries
        served0 = _counter("objstore.peer.served")
        with StatusServer(pages_root=store.root) as srv:
            status, body, headers = _get(
                srv.url(f"/pages/{entries[0]}"))
            assert status == 200
            stamp = store.stamp(entries[0])
            assert json.loads(headers["X-Dmlc-Fingerprint"]) == \
                stamp["fingerprint"]
            assert headers["X-Dmlc-Codec"] == stamp.get("codec", "raw")
            # the stored bytes verbatim (here: raw codec level 0)
            assert body == (b"E" * 50000)[:1 << 15]
            # ranged read of the STORED entry bytes
            status, part, headers = _get(
                srv.url(f"/pages/{entries[0]}"),
                headers={"Range": "bytes=10-19"})
            assert status == 206 and part == body[10:20]
            assert headers["Content-Range"] == \
                f"bytes 10-19/{len(body)}"
        assert _counter("objstore.peer.served") >= served0 + 2

    def test_unknown_and_unsafe_names_404(self, plane):
        em, tmp = plane
        (tmp / "peer-store").mkdir()
        with StatusServer(pages_root=str(tmp / "peer-store")) as srv:
            for name in ("ghost.pages", "..%2Fescape", ".hidden",
                         "a%5Cb.pages"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _get(srv.url(f"/pages/{name}"))
                assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/pages/"))
            assert e.value.code == 404

    def test_uncommitted_bare_file_not_served(self, plane):
        """A file without a committed sidecar stamp (a tmp, an alien
        file) is never handed to a peer."""
        em, tmp = plane
        root = tmp / "peer-store"
        root.mkdir()
        (root / "bare.pages").write_bytes(b"x" * 100)
        with StatusServer(pages_root=str(root)) as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/pages/bare.pages"))
            assert e.value.code == 404

    def test_stale_fingerprint_rejected_serverside(self, plane):
        """The object changed under the hydrated page: the server
        re-stats the stamped fingerprint and answers 404 — a peer can
        degrade to the wire, it must never serve a stale page."""
        em, tmp = plane
        em.put("b", "st.bin", b"A" * 40000)
        store = _hydrate_into(tmp / "peer-store", "obj://b/st.bin",
                              40000)
        entries = [n for n in os.listdir(store.root)
                   if n.endswith(".pages")]
        em.put("b", "st.bin", b"A" * 40001)  # size change = stale
        with StatusServer(pages_root=store.root) as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url(f"/pages/{entries[0]}"))
            assert e.value.code == 404
            assert b"stale" in e.value.read()

    def test_freshness_verdict_cached_across_requests(self, plane):
        """Serving the same entry repeatedly within the TTL re-stats
        the origin ONCE — the per-block HEAD must not erode the 1/N
        wire saving the tier delivers (a stale page is still rejected
        at the first judgment, and entry names are etag-keyed)."""
        em, tmp = plane
        em.put("b", "ttl.bin", b"T" * 30000)
        store = _hydrate_into(tmp / "peer-store", "obj://b/ttl.bin",
                              30000)
        entry = [n for n in os.listdir(store.root)
                 if n.endswith(".pages")][0]
        with StatusServer(pages_root=store.root) as srv:
            em.reset_counters()
            for _ in range(4):
                status, _, _ = _get(srv.url(f"/pages/{entry}"))
                assert status == 200
            assert em.counters()["heads"] <= 1, \
                "every /pages serve re-statted the origin"

    def test_bad_range_416(self, plane):
        em, tmp = plane
        em.put("b", "r.bin", b"R" * 1000)
        store = _hydrate_into(tmp / "peer-store", "obj://b/r.bin", 1000)
        entry = [n for n in os.listdir(store.root)
                 if n.endswith(".pages")][0]
        with StatusServer(pages_root=store.root) as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url(f"/pages/{entry}"),
                     headers={"Range": "bytes=5000-"})
            assert e.value.code == 416


class TestConcurrentScrape:
    def test_slow_pages_transfer_does_not_starve_healthz(self, plane):
        """The ThreadingHTTPServer pin (satellite): a /pages body
        transfer stuck behind a non-reading client runs on its own
        handler thread; /healthz and /metrics stay live meanwhile."""
        em, tmp = plane
        root = tmp / "peer-store"
        store = PageStore.at(str(root))
        w = store.writer("big.pages", fingerprint=None,
                         meta={"codec": "raw"})
        w.write(os.urandom(8 << 20))  # larger than any socket buffer
        w.commit()
        with StatusServer(pages_root=str(root)) as srv:
            # a hand-rolled client that requests the big page and then
            # stops reading — the handler blocks in wfile.write
            sock = socket.create_connection(("127.0.0.1", srv.port),
                                            timeout=10)
            try:
                sock.sendall(b"GET /pages/big.pages HTTP/1.1\r\n"
                             b"Host: localhost\r\n\r\n")
                first = sock.recv(1024)  # headers arrived; body huge
                assert b"200" in first.split(b"\r\n", 1)[0]
                t0 = time.perf_counter()
                status, body, _ = _get(srv.url("/healthz"), timeout=5)
                dt = time.perf_counter() - t0
                assert status == 200 and json.loads(body)["ok"]
                assert dt < 5.0
                status, _, _ = _get(srv.url("/metrics"), timeout=5)
                assert status == 200
            finally:
                sock.close()


# --------------------------------------------------------- singleflight

class TestSingleflight:
    def test_concurrent_cold_readers_dedup_onto_one_fetch(self, plane):
        """Two threads cold-read the same object at once: singleflight
        elects one leader per hydration group, the follower reads the
        committed page — the emulator sees roughly ONE stream's worth
        of GET bytes, not two."""
        em, tmp = plane
        em.latency_s = 0.002  # a leader fetch takes real time, so the
        # second thread reliably arrives while it is in flight
        payload = _payload(rows=20000)
        em.put("b", "sf.bin", payload)
        em.reset_counters()
        dedup0 = _counter("pagestore.singleflight.dedup")
        barrier = threading.Barrier(2)
        results = [None, None]

        def read(ix):
            s = create_seek_stream_for_read("obj://b/sf.bin")
            barrier.wait()
            results[ix] = s.read_all()
            s.close()

        threads = [threading.Thread(target=read, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert results[0] == payload and results[1] == payload
        assert _counter("pagestore.singleflight.dedup") > dedup0
        # strictly less than two full fetches — the dedup is real
        assert em.counters()["get_bytes"] < 2 * len(payload)

    def test_follower_whose_block_missed_fetches_itself(self, plane):
        """A follower that waited but finds no committed page (the
        leader's span stopped short) fetches on its own — dedup is an
        optimization, never a correctness dependency."""
        em, tmp = plane
        em.put("b", "solo.bin", b"Q" * 100000)
        s = create_seek_stream_for_read("obj://b/solo.bin")
        import dmlc_tpu.io.objstore.fs as ofs
        key = (s._entry_prefix, s._bb, 0)
        assert ofs._SINGLEFLIGHT.lead(key)  # occupy the leader slot
        try:
            done = threading.Event()
            out = []

            def follower():
                out.append(s.read(10))
                done.set()

            th = threading.Thread(target=follower)
            th.start()
            time.sleep(0.1)
            assert not done.is_set()  # follower parked behind leader
        finally:
            ofs._SINGLEFLIGHT.done(key)
        th.join(timeout=30)
        assert out == [b"Q" * 10]
        s.close()


# ------------------------------------------------------- the peer tier

class TestPeerTier:
    def _peer_server(self, em, tmp, uri, size):
        store = _hydrate_into(tmp / "peer-store", uri, size)
        srv = StatusServer(pages_root=store.root)
        return store, srv

    def test_blocks_served_from_peer_not_wire(self, plane):
        em, tmp = plane
        payload = _payload(rows=12000)
        em.put("b", "p.bin", payload)
        store, srv = self._peer_server(em, tmp, "obj://b/p.bin",
                                       len(payload))
        try:
            peer_mod.configure(ports=[srv.port])
            g0, pg0 = _counter("objstore.get"), \
                _counter("objstore.peer.get")
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/p.bin")
            assert s.read_all() == payload
            s.close()
            assert em.counters()["gets"] == 0, \
                "peer-owned blocks must not touch the wire"
            assert _counter("objstore.peer.get") > pg0
            assert _counter("objstore.get") == g0
            # and the peer-fetched blocks hydrated LOCALLY: a second
            # epoch is free of both the wire AND the peer
            pg1 = _counter("objstore.peer.get")
            s = create_seek_stream_for_read("obj://b/p.bin")
            assert s.read_all() == payload
            s.close()
            assert em.counters()["gets"] == 0
            assert _counter("objstore.peer.get") == pg1
        finally:
            srv.close()

    def test_peer_off_option_skips_tier(self, plane):
        em, tmp = plane
        payload = b"n" * 80000
        em.put("b", "off.bin", payload)
        store, srv = self._peer_server(em, tmp, "obj://b/off.bin",
                                       len(payload))
        try:
            peer_mod.configure(ports=[srv.port])
            objstore.configure(peer=False)
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/off.bin")
            assert s.read_all() == payload
            s.close()
            assert em.counters()["gets"] > 0  # straight to the wire
        finally:
            objstore.configure(peer=True)
            srv.close()

    def test_chaos_ioerror_degrades_to_wire_byte_identical(self, plane):
        em, tmp = plane
        payload = _payload(rows=9000)
        em.put("b", "ch.bin", payload)
        store, srv = self._peer_server(em, tmp, "obj://b/ch.bin",
                                       len(payload))
        try:
            peer_mod.configure(ports=[srv.port])
            set_policy("io.objstore.peer",
                       RetryPolicy(max_attempts=2, sleep=_noop_sleep))
            inject.install("site=io.objstore.peer,fault=ioerror")
            m0 = _counter("objstore.peer.miss")
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/ch.bin")
            assert s.read_all() == payload, \
                "chaos at the peer tier corrupted the stream"
            s.close()
            assert em.counters()["gets"] > 0, "wire fallback missing"
            assert _counter("objstore.peer.miss") > m0
        finally:
            srv.close()

    def test_chaos_truncate_degrades_to_wire_byte_identical(self,
                                                            plane):
        em, tmp = plane
        payload = _payload(rows=9000)
        em.put("b", "tr.bin", payload)
        store, srv = self._peer_server(em, tmp, "obj://b/tr.bin",
                                       len(payload))
        try:
            peer_mod.configure(ports=[srv.port])
            set_policy("io.objstore.peer",
                       RetryPolicy(max_attempts=2, sleep=_noop_sleep))
            inject.install("site=io.objstore.peer,fault=truncate")
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/tr.bin")
            assert s.read_all() == payload, \
                "a torn peer payload leaked downstream"
            s.close()
            assert em.counters()["gets"] > 0
        finally:
            srv.close()

    def test_stale_peer_page_rejected_and_refetched(self, plane):
        """A peer serving a page whose stamp does NOT match this
        reader's fingerprint (here: an unstamped commit the server
        cannot judge) is rejected CLIENT-side and the block refetched
        from the wire — byte-identical, never the stale bytes."""
        em, tmp = plane
        payload = b"G" * 90000
        em.put("b", "stale.bin", payload)
        store, srv = self._peer_server(em, tmp, "obj://b/stale.bin",
                                       len(payload))
        # falsify every peer entry: plausible bytes, no fingerprint —
        # the server serves (freshness unknowable), the client must
        # reject on fingerprint mismatch
        for name in os.listdir(store.root):
            if name.endswith(".pages"):
                store._stamp_entry(name, {"fingerprint": None,
                                          "codec": "raw"})
        try:
            peer_mod.configure(ports=[srv.port])
            set_policy("io.objstore.peer",
                       RetryPolicy(max_attempts=2, sleep=_noop_sleep))
            served0 = _counter("objstore.peer.served")
            g0 = _counter("objstore.peer.get")
            em.reset_counters()
            s = create_seek_stream_for_read("obj://b/stale.bin")
            assert s.read_all() == payload
            s.close()
            assert em.counters()["gets"] > 0, "wire refetch missing"
            assert _counter("objstore.peer.served") > served0, \
                "server never served (test exercised nothing)"
            assert _counter("objstore.peer.get") == g0, \
                "client accepted a stale-stamped peer page"
        finally:
            srv.close()

    def test_dead_peer_breaker_bounds_probes_no_hang(self, plane):
        em, tmp = plane
        payload = b"D" * 200000  # several groups
        em.put("b", "dead.bin", payload)
        # a port with nobody listening: every peer fetch fails fast
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        dead_port = sock.getsockname()[1]
        sock.close()
        peer_mod.configure(ports=[dead_port], breaker_failures=2,
                           breaker_snooze_s=60.0)
        set_policy("io.objstore.peer",
                   RetryPolicy(max_attempts=2, sleep=_noop_sleep))
        t0 = time.perf_counter()
        s = create_seek_stream_for_read("obj://b/dead.bin")
        assert s.read_all() == payload
        s.close()
        assert time.perf_counter() - t0 < 30.0, "dead peer ~= hang"
        tier = peer_mod.tier()
        assert tier is not None and not tier.available(0), \
            "breaker never opened on a dead peer"

    def test_tier_env_contract(self, plane, monkeypatch):
        em, tmp = plane
        monkeypatch.setenv("DMLC_TPU_SERVE_PORTS", "7001,7002,7003")
        monkeypatch.setenv("DMLC_TPU_SERVE_PORT", "7002")
        peer_mod.reset()
        t = peer_mod.tier()
        assert t is not None and t.world == 3 and t.self_index == 1
        assert t.remote_count == 2
        # group ownership round-robins; OUR groups return None
        assert t.owner_index(0) == 0
        assert t.owner_index(1) is None
        assert t.owner_index(2) == 2
        peer_mod.reset()
        monkeypatch.setenv("DMLC_TPU_SERVE_PORTS", "7001")
        assert peer_mod.tier() is None  # a gang of one has no peers
        # a MANGLED gang list must not crash the first obj:// read —
        # warn once, run tierless consistently
        peer_mod.reset()
        monkeypatch.setenv("DMLC_TPU_SERVE_PORTS", "9100,910x")
        assert peer_mod.tier() is None
        assert peer_mod.tier() is None  # and stays consistent


# ------------------------------------------- evidence + CLI satellites

class TestPeerTelemetrySurfaces:
    def test_analyze_names_peer_vs_wire_served(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0, "epoch": 1,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 10 ** 9}]}
        metrics = {"counters": {"objstore.get": 4,
                                "objstore.bytes": 10 ** 9,
                                "objstore.bytes_served": 10 ** 9,
                                "objstore.peer.get": 7,
                                "objstore.peer.bytes": 5 * 10 ** 8,
                                "objstore.peer.miss": 1,
                                "pagestore.hit": 0,
                                "pagestore.miss": 8}}
        v = attribute(snap, metrics=metrics)
        line = next((e for e in v["evidence"]
                     if e.startswith("peer tier:")), None)
        assert line is not None, v["evidence"]
        assert "7 peer GETs" in line
        assert "peer-served vs" in line and "wire-served" in line
        # no peer counters -> no fabricated evidence line
        v2 = attribute(snap, metrics={"counters":
                                      {"objstore.get": 4}})
        assert not any(e.startswith("peer tier:")
                       for e in v2["evidence"])

    def test_obsctl_gang_renders_byte_split(self, monkeypatch, capsys):
        import importlib
        import sys as _sys
        _sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        obsctl = importlib.import_module("obsctl")
        view = {
            "schema": 1, "period_s": 0.5, "host": "127.0.0.1",
            "ports": [9100, 9101], "polls": 4,
            "ranks": {
                "rank0": {"port": 9100, "rank": 0,
                          "unreachable": False, "last_error": None,
                          "last_poll_t": 1.0, "polls_ok": 4,
                          "polls_failed": 0, "gaps": [],
                          "series": {"kept": 4, "samples": [
                              {"t": 1.0, "v": {
                                  "counters.objstore.bytes": 500.0,
                                  "counters.objstore.peer.bytes": 0.0,
                                  "counters.objstore.peer."
                                  "served_bytes": 400.0}}]}},
                "rank1": {"port": 9101, "rank": 1,
                          "unreachable": False, "last_error": None,
                          "last_poll_t": 1.0, "polls_ok": 4,
                          "polls_failed": 0, "gaps": [],
                          "series": {"kept": 4, "samples": [
                              {"t": 1.0, "v": {
                                  "counters.objstore.bytes": 100.0,
                                  "counters.objstore.peer.bytes":
                                      400.0}}]}},
            },
            "rollup": {"samples": [
                {"t": 1.0, "v": {"gang.reachable": 2.0,
                                 "gang.expected": 2.0,
                                 "sum.counters.objstore.bytes": 600.0,
                                 "sum.counters.objstore.peer.bytes":
                                     400.0}}]},
        }
        monkeypatch.setattr(obsctl, "_fetch",
                            lambda *a, **k: view)
        rc = obsctl.main(["gang", "--port", "1"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "bytes: wire 500 · peer-served 0 · " \
               "served-to-peers 400" in out
        assert "bytes: wire 100 · peer-served 400" in out
        assert "rollup bytes: wire 600 · peer-served 400" in out


class TestStoreDirEnv:
    def test_default_store_dir_honors_env(self, monkeypatch, tmp_path):
        from dmlc_tpu.io import pagestore
        monkeypatch.setenv(ENV_STORE_DIR, str(tmp_path / "mine"))
        assert pagestore.default_store_dir() == str(tmp_path / "mine")
        monkeypatch.delenv(ENV_STORE_DIR)
        assert pagestore.default_store_dir().endswith("dmlc_tpu_spill")


# ------------------------------------------------- THE gang acceptance

class TestGangAcceptance:
    def test_two_rank_gang_splits_wire_and_goes_warm(self, tmp_path):
        """A REAL 2-process gang over one obj:// object: cold epoch
        wire bytes ≈ corpus/2 per rank (the 1/N tentpole), both peer
        counters live, warm epoch zero-GET everywhere, every stream
        sha256-identical to the local bytes."""
        import hashlib
        import sys

        from dmlc_tpu.parallel.launch import launch_local

        payload = _payload(rows=30000)  # ~1 MB
        objroot = tmp_path / "objroot"
        em = objstore.configure(root=str(objroot))
        try:
            em.put("bench", "gang.libsvm", payload)
        finally:
            objstore.configure(None)
        local_hash = hashlib.sha256(payload).hexdigest()
        worker = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "dmlc_tpu", "bench_peer_worker.py")
        out_dir = tmp_path / "gang"
        out_dir.mkdir()
        env = {
            "DMLC_TPU_OBJSTORE_ROOT": str(objroot),
            "PYTHONPATH": os.pathsep.join(
                [os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))]
                + [p for p in os.environ.get(
                    "PYTHONPATH", "").split(os.pathsep) if p]),
        }
        codes = launch_local(
            2, [sys.executable, worker, "obj://bench/gang.libsvm",
                str(out_dir), str(1 << 16), "2"],
            env=env, serve_ports=True, timeout=180)
        assert codes[:2] == [0, 0]
        results = []
        for rank in range(2):
            with open(out_dir / f"peer-{rank}.json") as f:
                results.append(json.load(f))
        size = len(payload)
        for r in results:
            assert r["cold"]["sha256"] == local_hash
            assert r["warm"]["sha256"] == local_hash
            assert r["warm"]["counters"]["objstore.get"] == 0, \
                f"rank {r['rank']} warm epoch hit the wire"
            assert r["warm"]["counters"]["objstore.peer.get"] == 0, \
                f"rank {r['rank']} warm epoch hit the peer"
            assert r["cold"]["counters"]["objstore.peer.bytes"] > 0
            wired = r["cold"]["counters"]["objstore.bytes"]
            assert wired <= 0.60 * size, \
                (f"rank {r['rank']} moved {wired}/{size} wire bytes —"
                 " the peer tier did not carry its half")
        total = sum(r["cold"]["counters"]["objstore.bytes"]
                    for r in results)
        assert 0.9 * size <= total <= 1.2 * size, \
            f"gang total wire bytes {total} vs corpus {size}"
