"""The unified page store (io/pagestore.py): commit/abort discipline,
fingerprint stamps, byte-budget LRU eviction, the one sweep — plus the
scheme-aware URISpec and the FileSystem scheme registry it builds on."""

import json
import os

import numpy as np
import pytest

from dmlc_tpu.io.filesys import FileSystem, URI, LocalFileSystem
from dmlc_tpu.io.pagestore import (
    PageStore, fingerprint_fresh, stat_fingerprint, stat_uri,
)
from dmlc_tpu.io.uri_spec import URISpec
from dmlc_tpu.utils.logging import DMLCError


def _counter(name):
    from dmlc_tpu.obs.metrics import REGISTRY
    return REGISTRY.counter(name).value


# ------------------------------------------------------ URISpec schemes

class TestURISpecScheme:
    def test_remote_uri_round_trips_with_protocol(self):
        raw = "obj://bucket/key?format=csv&label_column=0#cachefile"
        s = URISpec(raw)
        assert s.uri == "obj://bucket/key"
        assert s.scheme == "obj://"
        assert s.args == {"format": "csv", "label_column": "0"}
        assert s.cache_file == "cachefile"
        assert s.str_spec() == raw

    def test_bare_path_is_file_scheme(self):
        s = URISpec("data/train.csv?format=csv")
        assert s.scheme == "file://"
        assert s.uri == "data/train.csv"
        assert s.str_spec() == "data/train.csv?format=csv"

    def test_tpu_scheme_round_trip(self):
        s = URISpec("tpu:///tmp/x.rec#cache")
        assert s.scheme == "tpu://"
        assert s.uri == "tpu:///tmp/x.rec"
        assert s.str_spec() == "tpu:///tmp/x.rec#cache"

    def test_multipath_keeps_per_path_schemes(self):
        s = URISpec("obj://b/a.txt;/local/b.txt;s3://c/d.txt")
        assert s.paths() == ["obj://b/a.txt", "/local/b.txt",
                             "s3://c/d.txt"]
        assert s.scheme == "obj://"  # first path's protocol

    def test_query_only_on_remote(self):
        s = URISpec("s3://bucket/data.libsvm?format=libsvm")
        assert s.uri == "s3://bucket/data.libsvm"
        assert s.args == {"format": "libsvm"}
        assert s.cache_file == ""

    def test_fragment_only_on_remote(self):
        s = URISpec("obj://bucket/data.txt#c.bin")
        assert s.uri == "obj://bucket/data.txt"
        assert s.cache_file == "c.bin"


# ------------------------------------------------- FileSystem registry

class TestFileSystemRegistry:
    def test_unknown_scheme_error_names_registered(self):
        with pytest.raises(DMLCError) as ei:
            FileSystem.get_instance(URI("nope://x/y"))
        msg = str(ei.value)
        assert "nope://" in msg and "file://" in msg and "obj://" in msg

    def test_allow_null_returns_none(self):
        assert FileSystem.get_instance(URI("nope://x/y"),
                                       allow_null=True) is None

    def test_singleton_instance_caching(self):
        a = FileSystem.get_instance(URI("/tmp/a"))
        b = FileSystem.get_instance(URI("/tmp/b"))
        assert a is b
        assert isinstance(a, LocalFileSystem)

    def test_reregistration_invalidates_cached_instance(self):
        calls = []

        class _FS(LocalFileSystem):
            def __init__(self, tag):
                calls.append(tag)
                self.tag = tag

        FileSystem.register_scheme("tstreg://", lambda: _FS("one"))
        first = FileSystem.get_instance(URI("tstreg://h/p"))
        assert first.tag == "one"
        assert FileSystem.get_instance(URI("tstreg://h/p")) is first
        FileSystem.register_scheme("tstreg://", lambda: _FS("two"))
        second = FileSystem.get_instance(URI("tstreg://h/p"))
        assert second is not first and second.tag == "two"
        assert calls == ["one", "two"]  # factory once per registration

    def test_register_requires_protocol_suffix(self):
        with pytest.raises(DMLCError, match="://"):
            FileSystem.register_scheme("bad", LocalFileSystem)


# ------------------------------------------------------ stat plumbing

class TestStatFingerprint:
    def test_stat_uri_local(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"abc")
        size, mtime_ns, ctime_ns, ino = stat_uri(str(p))
        st = os.stat(p)
        assert (size, mtime_ns) == (3, st.st_mtime_ns)
        assert ino == st.st_ino

    def test_fingerprint_fresh_and_stale(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"abc")
        fp = stat_fingerprint([str(p)])
        assert fingerprint_fresh(fp) is True
        p.write_bytes(b"abcd")  # size change
        assert fingerprint_fresh(fp) is False
        assert fingerprint_fresh(None) is None
        assert fingerprint_fresh(
            [[str(tmp_path / "gone"), 1, 2]]) is False

    def test_filesystem_stat_carries_mtime(self, tmp_path):
        p = tmp_path / "g.bin"
        p.write_bytes(b"xy")
        u = URI(str(p))
        info = FileSystem.get_instance(u).get_path_info(u)
        assert info.mtime_ns == os.stat(p).st_mtime_ns


# --------------------------------------------------------- the store

class TestPageStore:
    def _store(self, tmp_path, budget=None):
        return PageStore.at(str(tmp_path / "store"), byte_budget=budget)

    def test_commit_publishes_entry_and_stamp(self, tmp_path):
        st = self._store(tmp_path)
        fp = [["src", 10, 20]]
        w = st.writer("e1.pages", fingerprint=fp, meta={"k": "v"})
        w.write(b"payload")
        path = w.commit()
        assert os.path.exists(path)
        stamp = st.stamp("e1.pages")
        assert stamp["fingerprint"] == fp
        assert stamp["k"] == "v"
        assert stamp["bytes"] == len(b"payload")
        # no tmp left behind
        assert [n for n in os.listdir(st.root) if ".tmp" in n] == []

    def test_abort_leaves_nothing(self, tmp_path):
        st = self._store(tmp_path)
        w = st.writer("e2.pages")
        w.write(b"half")
        w.abort()
        assert os.listdir(st.root) == []

    def test_lookup_counts_hit_and_miss(self, tmp_path):
        st = self._store(tmp_path)
        h0, m0 = _counter("pagestore.hit"), _counter("pagestore.miss")
        assert st.lookup("absent.pages") is None
        w = st.writer("e3.pages")
        w.write(b"x")
        w.commit()
        assert st.lookup("e3.pages") is not None
        assert _counter("pagestore.hit") == h0 + 1
        assert _counter("pagestore.miss") == m0 + 1

    def test_stale_fingerprint_lookup_deletes_and_misses(self, tmp_path):
        st = self._store(tmp_path)
        w = st.writer("e4.pages", fingerprint=[["s", 1, 2]])
        w.write(b"x")
        w.commit()
        # matching fingerprint: hit, entry stays
        assert st.lookup("e4.pages", fingerprint=[["s", 1, 2]]) is not None
        # changed source: the entry is deleted and the lookup misses
        assert st.lookup("e4.pages", fingerprint=[["s", 9, 2]]) is None
        assert not st.exists("e4.pages")
        assert st.stamp("e4.pages") is None

    def test_open_read_missing_is_none(self, tmp_path):
        st = self._store(tmp_path)
        assert st.open_read("ghost.pages") is None

    def test_budget_lru_eviction_skips_pinned(self, tmp_path):
        st = self._store(tmp_path)
        for i, age in ((0, 100), (1, 200), (2, 300)):
            w = st.writer(f"e{i}.pages")
            w.write(b"x" * 100)
            w.commit()
            os.utime(st.path(f"e{i}.pages"), (age, age))
        st.pin("e0.pages")  # the oldest is pinned: must survive
        e0 = _counter("pagestore.evict")
        # pinned bytes still count against the budget: to fit 150 the
        # store must shed BOTH unpinned entries (oldest-first), and the
        # pinned one survives even though it is the LRU-coldest
        evicted = st.set_budget(150)
        assert evicted == 2
        assert _counter("pagestore.evict") == e0 + 2
        assert st.exists("e0.pages")       # pinned (oldest)
        assert not st.exists("e1.pages")   # LRU victim
        assert not st.exists("e2.pages")
        st.unpin("e0.pages")
        assert st.set_budget(10) == 1     # unpinned now: evictable
        assert not st.exists("e0.pages")

    def test_used_bytes_counts_recognized_entries_only(self, tmp_path):
        st = self._store(tmp_path)
        w = st.writer("a.pages")
        w.write(b"12345")
        w.commit()
        os.makedirs(st.root, exist_ok=True)
        with open(os.path.join(st.root, "alien.bin"), "wb") as f:
            f.write(b"x" * 1000)  # no .pages suffix, no sidecar
        assert st.used_bytes() == 5

    def test_for_path_roots_at_directory(self, tmp_path):
        st, entry = PageStore.for_path(str(tmp_path / "sub" / "c.bin"))
        assert st.root == str(tmp_path / "sub")
        assert entry == "c.bin"
        # same root → same instance
        st2, _ = PageStore.for_path(str(tmp_path / "sub" / "d.bin"))
        assert st2 is st

    def test_sweep(self, tmp_path):
        src = tmp_path / "src.txt"
        src.write_bytes(b"hello\n")
        fp = stat_fingerprint([str(src)])
        stale_fp = [[str(src), fp[0][1] + 7, fp[0][2]]]
        st = self._store(tmp_path)
        for name, f in (("fresh.pages", fp), ("stale.pages", stale_fp)):
            w = st.writer(name, fingerprint=f)
            w.write(b"x")
            w.commit()
        # orphan sidecar (crashed build), old anonymous tmp, alien file
        with open(st.path("ghost.pages.meta.json"), "w") as f:
            json.dump({}, f)
        open(st.path("dead.pages.tmp"), "wb").close()
        os.utime(st.path("dead.pages.tmp"), (1, 1))
        with open(st.path("alien.dat"), "wb") as f:
            f.write(b"not ours")
        removed = st.sweep()
        assert removed == 3  # stale entry, orphan sidecar, old tmp
        assert st.exists("fresh.pages")
        assert st.stamp("fresh.pages")["fingerprint"] == fp
        assert not st.exists("stale.pages")
        assert not os.path.exists(st.path("ghost.pages.meta.json"))
        assert not os.path.exists(st.path("dead.pages.tmp"))
        assert os.path.exists(st.path("alien.dat"))

    def test_sweep_removes_dead_owner_entries(self, tmp_path):
        st = self._store(tmp_path)
        # a round-spill page named for a pid that cannot be alive
        name = "rounds-deadbeef-p999999999-1.pages"
        os.makedirs(st.root, exist_ok=True)
        with open(st.path(name), "wb") as f:
            f.write(b"x")
        assert st.sweep() == 1
        assert not st.exists(name)

    def test_pin_is_refcounted(self, tmp_path):
        # two iterators sharing one derived cache path each pin it;
        # the first one's teardown must NOT expose the entry to
        # eviction while the second still serves it
        st = self._store(tmp_path)
        w = st.writer("shared.pages")
        w.write(b"x" * 100)
        w.commit()
        st.pin("shared.pages")
        st.pin("shared.pages")
        st.unpin("shared.pages")   # first iterator dies
        assert st.set_budget(10) == 0
        assert st.exists("shared.pages")
        st.unpin("shared.pages")   # second iterator dies
        assert st.set_budget(10) == 1
        st.set_budget(None)

    def test_sweep_skips_pinned_stale_entry(self, tmp_path):
        src = tmp_path / "s.txt"
        src.write_bytes(b"v1")
        st = self._store(tmp_path)
        w = st.writer("live.pages",
                      fingerprint=stat_fingerprint([str(src)]))
        w.write(b"x")
        w.commit()
        st.pin("live.pages")
        src.write_bytes(b"v2-longer")  # source mutated: stamp stale
        assert st.sweep() == 0         # pinned: the iterator owns it
        assert st.exists("live.pages")
        st.unpin("live.pages")
        assert st.sweep() == 1         # unpinned: swept as stale
        assert not st.exists("live.pages")

    def test_used_bytes_cache_tracks_commit_and_delete(self, tmp_path):
        st = self._store(tmp_path)
        assert st.used_bytes() == 0    # primes the running total
        for i in range(3):
            w = st.writer(f"u{i}.pages")
            w.write(b"x" * 10)
            w.commit()
        assert st._used_cache == 30    # O(1) accounting, no rescan
        st.delete("u0.pages")
        assert st._used_cache == 20
        assert st.used_bytes() == 20   # full scan agrees

    def test_sweep_keeps_live_writer_tmp(self, tmp_path):
        st = self._store(tmp_path)
        w = st.writer("live.pages")
        w.write(b"in flight")
        assert st.sweep() == 0  # our own pid: never reaped
        w.abort()


# ------------------------------------------- cached split staleness

class TestCachedSplitStaleness:
    def _lines(self, n, tag):
        return b"\n".join(b"%s-%04d" % (tag, i) for i in range(n)) + b"\n"

    def test_changed_source_reruns_first_pass(self, tmp_path):
        from dmlc_tpu.io.input_split import InputSplit
        data = tmp_path / "d.txt"
        data.write_bytes(self._lines(500, b"old"))
        uri = f"{data}#{tmp_path / 'c.bin'}"
        assert list(InputSplit.create(uri, 0, 1)) == \
            self._lines(500, b"old").splitlines()
        # the committed cache carries the source stamp
        stamp_path = str(tmp_path / "c.bin") + ".p0-1.meta.json"
        with open(stamp_path) as f:
            assert json.load(f)["fingerprint"][0][0] == str(data)
        # mutate the source (different size): the old .done-marker
        # contract would replay stale bytes forever — the stamp must
        # force a re-run of the first pass instead
        data.write_bytes(self._lines(600, b"new"))
        got = list(InputSplit.create(uri, 0, 1))
        assert got == self._lines(600, b"new").splitlines()

    def test_same_size_mtime_change_reruns(self, tmp_path):
        from dmlc_tpu.io.input_split import InputSplit
        data = tmp_path / "d.txt"
        data.write_bytes(self._lines(100, b"aaa"))
        uri = f"{data}#{tmp_path / 'c2.bin'}"
        assert list(InputSplit.create(uri, 0, 1)) == \
            self._lines(100, b"aaa").splitlines()
        data.write_bytes(self._lines(100, b"bbb"))  # same byte count
        os.utime(data, (data.stat().st_atime,
                        data.stat().st_mtime + 10))
        got = list(InputSplit.create(uri, 0, 1))
        assert got == self._lines(100, b"bbb").splitlines()

    def test_unchanged_source_replays_without_rebuild(self, tmp_path):
        from dmlc_tpu.io.input_split import InputSplit
        data = tmp_path / "d.txt"
        data.write_bytes(self._lines(300, b"xyz"))
        uri = f"{data}#{tmp_path / 'c3.bin'}"
        list(InputSplit.create(uri, 0, 1))
        cache = str(tmp_path / "c3.bin") + ".p0-1"
        before = os.stat(cache).st_mtime_ns
        h0 = _counter("pagestore.hit")
        assert list(InputSplit.create(uri, 0, 1)) == \
            self._lines(300, b"xyz").splitlines()
        # served from the cache (hit counted), not rebuilt
        assert _counter("pagestore.hit") > h0
        assert os.path.getsize(cache) > 0
        assert os.stat(cache).st_mtime_ns >= before


# ------------------------------------------- DiskRowIter stamp contract

class TestDiskRowIterStamp:
    def test_stamped_cache_rebuilds_on_source_change(self, tmp_path):
        from dmlc_tpu.data.row_iter import RowBlockIter
        src = tmp_path / "d.libsvm"
        src.write_text("1 1:1.0\n0 2:2.0\n" * 50)
        cache = tmp_path / "cache"
        uri = f"{src}?format=libsvm#{cache}"
        it = RowBlockIter.create(uri, 0, 1)
        it.before_first()
        assert it.next()
        first = it.value().label.sum()
        del it
        # in-place mutation, same cache hint: the stamp must catch it
        src.write_text("1 1:1.0\n1 2:2.0\n" * 50)
        it2 = RowBlockIter.create(uri, 0, 1)
        it2.before_first()
        total = 0.0
        while it2.next():
            total += it2.value().label.sum()
        assert total == 100.0  # all-ones labels: the NEW source
        assert first != total
        del it2
