"""Parquet/Arrow parser (BASELINE config 5; no reference counterpart)."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from dmlc_tpu.data.parser import Parser  # noqa: E402
from dmlc_tpu.data.rowblock import RowBlockContainer  # noqa: E402


@pytest.fixture
def parquet_file(tmp_path, rng):
    n = 1000
    table = pa.table({
        "label": rng.randint(0, 2, n).astype(np.float32),
        "f0": rng.rand(n).astype(np.float32),
        "f1": rng.randn(n).astype(np.float32),
        "f2": rng.rand(n).astype(np.float32),
    })
    path = str(tmp_path / "d.parquet")
    pq.write_table(table, path, row_group_size=100)
    return path, table


def drain(parser):
    c = RowBlockContainer(np.uint32)
    for b in parser:
        c.push_block(b)
    return c.get_block()


class TestParquetParser:
    def test_basic(self, parquet_file):
        path, table = parquet_file
        parser = Parser.create(path, 0, 1, format="parquet",
                               label_column="label")
        block = drain(parser)
        assert block.size == 1000
        np.testing.assert_array_equal(
            block.label, table.column("label").to_numpy())
        # dense rows: 3 feature columns in order
        np.testing.assert_allclose(
            block.value.reshape(1000, 3)[:, 0],
            table.column("f0").to_numpy(), rtol=1e-6)
        assert parser.bytes_read() > 0

    def test_row_group_sharding_coverage(self, parquet_file):
        path, table = parquet_file
        whole = drain(Parser.create(path, 0, 1, format="parquet",
                                    label_column="label"))
        labels = []
        for k in range(3):
            blk = drain(Parser.create(path, k, 3, format="parquet",
                                      label_column="label"))
            labels.append(blk.label)
        got = np.concatenate(labels)
        assert len(got) == 1000
        # row groups are whole units: sorting restores equality
        np.testing.assert_array_equal(np.sort(got), np.sort(whole.label))

    def test_directory_of_part_files(self, tmp_path, rng):
        # r4: a directory URI expands to its part files (the
        # Hadoop-style dataset layout), same rule as InputSplit
        import pyarrow as pa
        import pyarrow.parquet as pq
        d = tmp_path / "dataset"
        d.mkdir()
        tables = []
        for k in range(3):
            t = pa.table({"label": pa.array(
                rng.rand(20).astype(np.float32)),
                "f0": pa.array(rng.rand(20).astype(np.float32))})
            pq.write_table(t, str(d / f"part-{k}.parquet"))
            tables.append(t)
        block = drain(Parser.create(str(d), format="parquet",
                                    label_column="label"))
        assert block.size == 60
        got = np.sort(block.label)
        want = np.sort(np.concatenate(
            [t.column("label").to_numpy() for t in tables]))
        np.testing.assert_array_equal(got, want)

    def test_no_label_column(self, parquet_file):
        path, _ = parquet_file
        block = drain(Parser.create(path, 0, 1, format="parquet"))
        assert block.size == 1000
        np.testing.assert_array_equal(block.label, np.zeros(1000))
        assert block.value.reshape(1000, 4).shape == (1000, 4)

    def test_uri_args(self, parquet_file):
        path, table = parquet_file
        block = drain(Parser.create(
            path + "?format=parquet&label_column=label"))
        np.testing.assert_array_equal(
            block.label, table.column("label").to_numpy())


class TestParquetOverVFS:
    def test_registered_scheme_streams_parquet(self, parquet_file):
        # VERDICT r4 #7: every text parser rides the Stream/VFS seam; so
        # must Parquet. A scheme registered via FileSystem.register_scheme
        # whose open_for_read returns a SeekStream must feed pyarrow
        # through the as_file(size=...) adapter — no local-path escape.
        path, table = parquet_file
        from dmlc_tpu.io.filesys import (FileInfo, FileSystem,
                                         LocalFileSystem, URI)

        opened = []

        class PrefixFS(LocalFileSystem):
            """vfsx://<abs path> → local file, paths keep the scheme so
            every re-dispatch stays inside the VFS."""

            def open_for_read(self, uri):
                opened.append(uri.name)
                return super().open_for_read(URI(uri.name))

            def open(self, uri, mode):
                opened.append(uri.name)
                return super().open(URI(uri.name), mode)

            def get_path_info(self, uri):
                info = super().get_path_info(URI(uri.name))
                return FileInfo(path=f"vfsx://{info.path}",
                                size=info.size, type=info.type)

        FileSystem.register_scheme("vfsx://", PrefixFS)
        try:
            parser = Parser.create(f"vfsx://{path}", 0, 1,
                                   format="parquet", label_column="label",
                                   prefetch=False)
            block = drain(parser)
            assert block.size == 1000
            np.testing.assert_array_equal(
                block.label, table.column("label").to_numpy())
            assert opened, "scheme open() was never exercised"
        finally:
            FileSystem._schemes.pop("vfsx://", None)
            FileSystem._instances.pop("vfsx://", None)

    def test_non_seekable_scheme_fails_with_guidance(self, parquet_file):
        path, _ = parquet_file
        from dmlc_tpu.io.filesys import FileInfo, FileSystem, URI
        from dmlc_tpu.io.stream import FileStream, Stream

        class NoSeekFS(FileSystem):
            def open_for_read(self, uri):
                f = open(URI(uri.name).name, "rb")
                s = Stream()  # base Stream: not a SeekStream
                s.read = lambda n: f.read(n)
                s.close = f.close
                return s

            open = open_for_read

            def get_path_info(self, uri):
                import os
                return FileInfo(path=f"noseek://{uri.name}",
                                size=os.path.getsize(uri.name),
                                type="file")

        FileSystem.register_scheme("noseek://", NoSeekFS)
        try:
            with pytest.raises(Exception, match="seek|Seek"):
                Parser.create(f"noseek://{path}", 0, 1, format="parquet",
                              prefetch=False)
        finally:
            FileSystem._schemes.pop("noseek://", None)
            FileSystem._instances.pop("noseek://", None)


class TestNativeInterleave:
    """The native cache-blocked column interleave must be value-identical
    to the numpy fallback on every dtype/fallback combination."""

    def test_native_matches_fallback(self, tmp_path, rng, monkeypatch):
        from dmlc_tpu.native import native_available
        if not native_available():
            pytest.skip("native engine unavailable")
        n = 777  # not a multiple of the native row block (256)
        cols = {"label": pa.array(rng.randint(0, 2, n).astype(np.float32)),
                "a32": pa.array(rng.rand(n).astype(np.float32)),
                "b64": pa.array(rng.rand(n)),  # float64 column
                "c32": pa.array(rng.randn(n).astype(np.float32))}
        path = str(tmp_path / "mix.parquet")
        pq.write_table(pa.table(cols), path, row_group_size=250)
        pn = Parser.create(path, 0, 1, format="parquet",
                           label_column="label")
        native = drain(pn)
        pn.destroy()
        import dmlc_tpu.native as nat
        monkeypatch.setattr(nat, "native_available", lambda: False)
        pf = Parser.create(path, 0, 1, format="parquet",
                           label_column="label")
        fallback = drain(pf)
        pf.destroy()
        assert native.content_hash() == fallback.content_hash()

    def test_null_column_falls_back(self, tmp_path, rng):
        n = 60
        vals = [None if i % 7 == 0 else float(i) for i in range(n)]
        t = pa.table({"label": pa.array(np.zeros(n, np.float32)),
                      "f": pa.array(vals, pa.float32())})
        path = str(tmp_path / "nulls.parquet")
        pq.write_table(t, path)
        p = Parser.create(path, 0, 1, format="parquet",
                          label_column="label")
        block = drain(p)
        p.destroy()
        got = np.asarray(block.value)
        want = np.array([np.nan if v is None else v for v in vals],
                        np.float32)
        np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
        np.testing.assert_array_equal(got[~np.isnan(want)],
                                      want[~np.isnan(want)])


class TestSparseColumnPath:
    def test_sparse_drops_zeros_dense_parity(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from dmlc_tpu.data.parser import Parser
        rng = np.random.RandomState(0)
        dense = rng.rand(200, 6).astype(np.float32)
        dense[dense < 0.5] = 0.0  # half the cells are zero
        cols = {"label": pa.array((np.arange(200) % 2).astype(np.float32))}
        for c in range(6):
            cols[f"f{c}"] = pa.array(dense[:, c])
        path = str(tmp_path / "s.parquet")
        pq.write_table(pa.table(cols), path, row_group_size=64)

        def blocks(**kw):
            p = Parser.create(path, 0, 1, format="parquet",
                              label_column="label", **kw)
            out = [b for b in p]
            if hasattr(p, "destroy"):
                p.destroy()
            return out

        sp = blocks(sparse=True)
        total_nnz = sum(b.nnz for b in sp)
        assert total_nnz == int((dense != 0).sum())
        # per-row reconstruction matches the dense matrix
        row = 0
        for b in sp:
            for r in b:
                full = np.zeros(6, np.float32)
                full[r.index] = r.value
                np.testing.assert_array_equal(full, dense[row])
                row += 1
        assert row == 200
        dn = blocks(sparse=False)
        assert sum(b.nnz for b in dn) == 200 * 6
