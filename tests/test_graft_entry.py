"""__graft_entry__ driver hooks: the single-chip compile check and the
8-device dryrun (with its 1-device parity golden) must stay green — the
round driver runs them out-of-band, so CI failing first is cheaper."""

import os
import subprocess
import sys

import pytest

ENTRY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "__graft_entry__.py")


@pytest.mark.slow
def test_entry_and_dryrun_multichip():
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    proc = subprocess.run([sys.executable, ENTRY], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "entry() compile+run:" in proc.stdout
    assert "dryrun_multichip(8)" in proc.stdout
    # the mesh-vs-1-device parity golden must have executed
    assert "1-device parity" in proc.stdout, proc.stdout
