"""dmlc_tpu.shuffle — the gang-wide sample-level shuffle plane.

Pins the subsystem's three contracts (ISSUE 20 / ROADMAP item 5):

- **Determinism**: same seed ⇒ same global order at any world size —
  per-rank streams round-robin-merge back into one byte-identical
  sequence at worlds 1/2/3, and a mid-epoch restart from the position
  watermark resumes byte-identically.
- **Coverage**: every record exactly once per epoch (the
  unittest_inputsplit invariant), at every world size, across the
  full format family (text, recordio, dense, image, indexed).
- **Quality**: the permutation's position-displacement distribution
  matches a uniform permutation statistically, not just "looks mixed".

Plus the planes it rides: the index sidecar (page-store committed,
fingerprint-stamped, rebuilt on change), the peer /pages window
exchange with /metrics accounting, the /shuffle row surface + obsctl
renderer, and the Pipeline.shuffle(global_seed=...) lowering.
"""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

from dmlc_tpu.io.objstore import peer as peer_mod
from dmlc_tpu.io.pagestore import ENV_STORE_DIR, PageStore
from dmlc_tpu.io.recordio import (
    DenseRecordWriter, ImageRecordWriter, IndexedRecordIOWriter,
    RecordIOWriter,
)
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.obs.metrics import REGISTRY
from dmlc_tpu.obs.serve import StatusServer
from dmlc_tpu.shuffle import (
    GlobalShuffle, GlobalShuffleSplit, ShuffleReader, attach_rendezvous,
    build_record_index, displacement_stats, epoch_rng, install_view, view,
)
from dmlc_tpu.shuffle import exchange as exchange_mod
from dmlc_tpu.utils.logging import DMLCError

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
import obsctl  # noqa: E402


@pytest.fixture()
def plane(tmp_path, monkeypatch):
    """An isolated shuffle plane: private page store, no ambient peer
    tier, no installed /shuffle view leaking across tests."""
    monkeypatch.setenv(ENV_STORE_DIR, str(tmp_path / "store"))
    monkeypatch.delenv("DMLC_TPU_SERVE_PORTS", raising=False)
    monkeypatch.delenv("DMLC_TPU_SERVE_PORT", raising=False)
    peer_mod.reset()
    monkeypatch.setattr(exchange_mod, "_VIEW_REF", None)
    yield tmp_path
    peer_mod.reset()


# ------------------------------------------------------ corpus builders

def _lines(n):
    return [b"line-%05d " % i + b"x" * (i % 37) for i in range(n)]


def _payloads(n):
    return [b"payload-%05d-" % i + b"z" * (i % 53) for i in range(n)]


def make_text(tmp, n=400, name="data.txt"):
    path = str(tmp / name)
    with open(path, "wb") as f:
        f.write(b"\n".join(_lines(n)) + b"\n")
    return path


def make_recordio(tmp, n=400, name="data.rec"):
    path = str(tmp / name)
    with create_stream(path, "w") as s:
        w = RecordIOWriter(s)
        for p in _payloads(n):
            w.write_record(p)
    return path


def make_indexed(tmp, n=400, name="data2.rec"):
    path = str(tmp / name)
    with create_stream(path, "w") as s, \
            create_stream(path + ".idx", "w") as ixs:
        w = IndexedRecordIOWriter(s, ixs)
        for i, p in enumerate(_payloads(n)):
            w.write_record(p, i)
    return path


# ------------------------------------------------------- the index plane

class TestRecordIndex:
    def test_text_index_matches_lines(self, plane):
        path = make_text(plane, 300)
        idx = build_record_index(path, "text")
        raw = open(path, "rb").read()
        assert idx.n == 300
        got = [raw[o:o + s] for o, s in zip(idx.offsets, idx.sizes)]
        assert got == _lines(300)

    def test_text_skips_empty_lines_and_crlf(self, plane):
        path = str(plane / "gaps.txt")
        with open(path, "wb") as f:
            f.write(b"alpha\r\n\n\nbeta\rgamma")  # no trailing newline
        idx = build_record_index(path, "text")
        raw = open(path, "rb").read()
        got = [raw[o:o + s] for o, s in zip(idx.offsets, idx.sizes)]
        assert got == [b"alpha", b"beta", b"gamma"]

    def test_recordio_index_tiles_the_file(self, plane):
        path = make_recordio(plane, 250)
        idx = build_record_index(path, "recordio")
        assert idx.n == 250
        assert int(idx.offsets[0]) == 0
        assert (idx.offsets[1:] == idx.offsets[:-1] + idx.sizes[:-1]).all()
        assert int(idx.offsets[-1] + idx.sizes[-1]) == \
            os.path.getsize(path)

    def test_dense_and_image_formats(self, plane):
        dense = str(plane / "d.rec")
        with create_stream(dense, "w") as s:
            w = DenseRecordWriter(s)
            for i in range(80):
                w.write(float(i), np.arange(5, dtype=np.float32) + i)
        img = str(plane / "i.rec")
        with create_stream(img, "w") as s:
            w = ImageRecordWriter(s)
            for i in range(40):
                w.write(float(i), np.full((4, 3), i % 251, np.uint8))
        for path, st, n in ((dense, "recordio_dense", 80),
                            (img, "recordio_image", 40)):
            idx = build_record_index(path, st)
            assert idx.n == n
            assert int(idx.offsets[-1] + idx.sizes[-1]) == \
                os.path.getsize(path)

    def test_indexed_recordio_rides_its_idx(self, plane):
        path = make_indexed(plane, 120)
        idx = build_record_index(path, "indexed_recordio")
        assert idx.n == 120
        sp = GlobalShuffleSplit(path, 0, 1, "indexed_recordio", seed=1,
                                window_bytes=4096)
        assert sorted(sp) == sorted(_payloads(120))

    def test_sidecar_committed_once_and_reused(self, plane, monkeypatch):
        path = make_text(plane, 150)
        idx = build_record_index(path, "text")
        # a second build must come from the committed sidecar: scanning
        # again would be a cache miss — make the scanner explode
        from dmlc_tpu.shuffle import index as index_mod

        def boom(*_a, **_k):
            raise AssertionError("sidecar miss: text rescan")

        monkeypatch.setattr(index_mod, "_scan_text", boom)
        idx2 = build_record_index(path, "text")
        assert (idx2.offsets == idx.offsets).all()
        assert (idx2.sizes == idx.sizes).all()
        assert idx2.fingerprint == idx.fingerprint

    def test_sidecar_rebuilt_when_source_changes(self, plane):
        path = make_text(plane, 50)
        idx = build_record_index(path, "text")
        assert idx.n == 50
        with open(path, "ab") as f:
            f.write(b"appended-line\n")
        os.utime(path, (1, 1))  # force a distinct mtime fingerprint
        idx2 = build_record_index(path, "text")
        assert idx2.n == 51

    def test_multifile_global_byte_space(self, plane):
        a = make_text(plane, 60, "a.txt")
        b = make_text(plane, 40, "b.txt")
        uri = a + ";" + b
        idx = build_record_index(uri, "text")
        assert idx.n == 100
        assert idx.total_bytes == (os.path.getsize(a)
                                   + os.path.getsize(b))
        # a span crossing the file boundary maps to two segments
        segs = list(idx.segments(os.path.getsize(a) - 10,
                                 os.path.getsize(a) + 10))
        assert [(os.path.basename(p), o, ln) for p, o, ln in segs] == \
            [("a.txt", os.path.getsize(a) - 10, 10), ("b.txt", 0, 10)]


# ---------------------------------------------------- the permutation

class TestGlobalShuffle:
    def test_pure_deterministic_exact_coverage(self):
        sizes = np.full(1000, 64)
        g1 = GlobalShuffle(sizes, seed=9, window_bytes=1 << 12)
        g2 = GlobalShuffle(sizes, seed=9, window_bytes=1 << 12)
        for epoch in (0, 1, 7):
            o1, o2 = g1.order(epoch), g2.order(epoch)
            assert (o1 == o2).all()  # pure in (seed, epoch)
            assert sorted(o1.tolist()) == list(range(1000))  # exact
        assert not (g1.order(0) == g1.order(1)).all()
        assert not (g1.order(0) == GlobalShuffle(
            sizes, seed=10, window_bytes=1 << 12).order(0)).all()

    def test_window_byte_budget_bounds_working_set(self):
        rng = epoch_rng(3, 0)
        sizes = rng.randint(10, 3000, size=500)
        budget = 8 << 10
        g = GlobalShuffle(sizes, seed=1, window_bytes=budget)
        for s, e in g.windows():
            if e - s > 1:  # single over-budget records ride alone
                assert int(sizes[s:e].sum()) <= budget
        # the order visits whole windows contiguously: one window of
        # bytes resident at a time
        order = g.order(2)
        spans = g.windows()
        wid_of = np.empty(len(sizes), np.int64)
        for w, (s, e) in enumerate(spans):
            wid_of[s:e] = w
        seen = []
        for w in wid_of[order]:
            if not seen or seen[-1] != w:
                seen.append(w)
        assert len(seen) == len(spans), "window revisited mid-epoch"

    def test_displacement_distribution_vs_uniform(self):
        n = 5000
        g = GlobalShuffle(np.full(n, 100), seed=4,
                          window_bytes=100 * 250)
        for epoch in range(3):
            st = displacement_stats(g.order(epoch))
            # uniform permutation ⇒ normalized mean ≈ 1.0; identity ⇒ 0;
            # a within-window-only shuffle would sit near 250/n ≈ 0.05
            assert 0.8 <= st["normalized_mean"] <= 1.2, st
        assert displacement_stats(np.arange(n))["normalized_mean"] == 0.0

    def test_epoch_rng_compat_pin(self):
        # epoch_rng is the frozen RandomState stream the io/ shuffles
        # migrated onto — pin its draws against direct construction
        assert (epoch_rng(11, 3).permutation(32)
                == np.random.RandomState(14).permutation(32)).all()


# -------------------------------------- coverage across world sizes

class TestWorldCoverage:
    def _rank_stream(self, path, rank, world, **kw):
        sp = GlobalShuffleSplit(path, rank, world, "recordio", seed=5,
                                window_bytes=4096, **kw)
        return list(sp)

    def test_exactly_once_at_worlds_1_2_3(self, plane):
        path = make_recordio(plane, 300)
        want = sorted(_payloads(300))
        for world in (1, 2, 3):
            streams = [self._rank_stream(path, r, world)
                       for r in range(world)]
            got = [rec for s in streams for rec in s]
            assert len(got) == 300, f"world {world}: duplicated/lost"
            assert sorted(got) == want, f"world {world}: coverage hole"

    def test_same_seed_byte_identity_across_worlds(self, plane):
        path = make_recordio(plane, 300)

        def merged(world):
            its = [iter(self._rank_stream(path, r, world))
                   for r in range(world)]
            out, p = [], 0
            while True:
                it = its[p % world]
                rec = next(it, None)
                if rec is None:
                    break
                out.append(rec)
                p += 1
            # round-robin by position: rank p%world owns position p
            return b"\x00".join(out)

        assert merged(1) == merged(2) == merged(3)

    def test_mid_epoch_restart_resume_identity(self, plane):
        path = make_recordio(plane, 300)
        a = GlobalShuffleSplit(path, 0, 2, "recordio", seed=5,
                               window_bytes=4096)
        a.before_first()
        head = [a.next_record() for _ in range(40)]
        watermark = a.reader.position
        # a fresh process resumes from the checkpointed watermark
        b = GlobalShuffleSplit(path, 0, 2, "recordio", seed=5,
                               window_bytes=4096,
                               start_position=watermark)
        b.before_first()
        assert list(iter(b.next_record, None)) == \
            list(iter(a.next_record, None))
        assert None not in head

    def test_world_change_2_to_3_keeps_exactness(self, plane):
        path = make_recordio(plane, 300)
        idx = build_record_index(path, "recordio")
        g = GlobalShuffle(idx.sizes, 5, window_bytes=4096)
        order = g.order(0)
        watermark = 101
        got = []
        # a 2-gang delivers positions < watermark...
        for rank in range(2):
            r = ShuffleReader(idx, 5, 4096, rank=rank, world=2)
            while r.position + ((rank - r.position) % 2) < watermark:
                got.append(r.next_record_span())
        # ...then a 3-gang (same seed) resumes from the watermark
        for rank in range(3):
            r = ShuffleReader(idx, 5, 4096, rank=rank, world=3,
                              start_position=watermark)
            got.extend(iter(r.next_record_span, None))
        raw = open(path, "rb").read()
        want = [raw[idx.offsets[k]:idx.offsets[k] + idx.sizes[k]]
                for k in order]
        assert sorted(got) == sorted(want)
        assert len(got) == 300  # exactly once despite the reshard

    def test_reset_partition_and_epoch_advance(self, plane):
        path = make_recordio(plane, 200)
        sp = GlobalShuffleSplit(path, 0, 1, "recordio", seed=2,
                                window_bytes=4096)
        e0 = list(sp)
        sp.before_first()
        e1 = list(iter(sp.next_record, None))
        assert e0 != e1 and sorted(e0) == sorted(e1)
        sp.reset_partition(1, 2)
        assert sp.reader.rank == 1 and sp.reader.world == 2
        assert sp.reader.position == 0


# --------------------------------------------- the peer exchange plane

class TestPeerExchange:
    def test_windows_served_from_peer_with_accounting(self, plane):
        path = make_recordio(plane, 400)
        root0 = plane / "rank0-store"
        root1 = plane / "rank1-store"
        store0, store1 = PageStore.at(str(root0)), PageStore.at(str(root1))
        idx = build_record_index(path, "recordio", store=store0)
        # rank 0 hydrates every window from the source
        r0 = ShuffleReader(idx, 7, 4096, rank=0, world=1, store=store0)
        n0 = sum(1 for _ in iter(r0.next_record_span, None))
        assert n0 == 400 and r0.bytes["wire"] > 0
        assert r0.bytes["peer"] == 0
        with StatusServer(pages_root=store0.root) as srv0, \
                StatusServer(pages_root=store1.root) as srv1:
            # this process plays rank 1: peers = [rank0, self]
            peer_mod.configure(ports=[srv0.port, srv1.port],
                               self_port=srv1.port)
            served0 = REGISTRY.counter("objstore.peer.served").value
            peer_b0 = REGISTRY.counter("shuffle.bytes.peer").value
            idx1 = build_record_index(path, "recordio", store=store1)
            r1 = ShuffleReader(idx1, 7, 4096, rank=0, world=1,
                               store=store1)
            got = list(iter(r1.next_record_span, None))
            assert len(got) == 400
            # even windows are rank0-owned → peer-fetched; odd windows
            # are self-owned → source wire
            assert r1.bytes["peer"] > 0 and r1.bytes["wire"] > 0
            assert r1.records["peer"] > 0
            assert r1.bytes["local"] == 0
            assert REGISTRY.counter("objstore.peer.served").value \
                > served0, "rank0's /pages never served"
            assert REGISTRY.counter("shuffle.bytes.peer").value \
                == peer_b0 + r1.bytes["peer"]
            # the exchange is visible on /metrics
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv1.port}/metrics",
                timeout=5).read().decode()
            assert "shuffle_bytes_peer" in text.replace(".", "_") \
                or "shuffle.bytes.peer" in text
            # a second epoch replays entirely from the local store
            r1.next_epoch()
            wire_before = r1.bytes["wire"]
            peer_before = r1.bytes["peer"]
            assert len(list(iter(r1.next_record_span, None))) == 400
            assert r1.bytes["wire"] == wire_before
            assert r1.bytes["peer"] == peer_before
            assert r1.bytes["local"] > 0

    def test_peer_degrades_to_wire(self, plane):
        path = make_recordio(plane, 100)
        store = PageStore.at(str(plane / "solo-store"))
        idx = build_record_index(path, "recordio", store=store)
        # a tier whose peer is unreachable: fetch_entry returns None
        # and the reader falls back to the source, never raises
        peer_mod.configure(ports=[1, 2], self_port=2,
                           breaker_failures=1, timeout_s=0.1)
        r = ShuffleReader(idx, 1, 4096, rank=0, world=1, store=store)
        got = list(iter(r.next_record_span, None))
        assert len(got) == 100
        assert r.bytes["peer"] == 0 and r.bytes["wire"] > 0


# ------------------------------------------- /shuffle + obsctl surface

class TestShuffleSurface:
    def _get(self, port, path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def test_live_404_hint_then_rows(self, plane):
        path = make_text(plane, 120)
        with StatusServer() as srv:
            code, doc = self._get(srv.port, "/shuffle")
            assert code == 404
            assert "global_seed" in doc["hint"]
            sp = GlobalShuffleSplit(path, 0, 1, "text", seed=3,
                                    window_bytes=2048)
            head = [sp.next_record() for _ in range(10)]
            assert None not in head
            code, doc = self._get(srv.port, "/shuffle")
            assert code == 200
            assert doc["seed"] == 3 and doc["records"] == 120
            assert doc["window_bytes"] == 2048
            assert doc["delivered"] == 10
            assert 0 < doc["coverage"] < 1
            tiers = doc["records_by_tier"]
            assert sum(tiers.values()) == 10
            # /shuffle is advertised to the lost
            code, doc = self._get(srv.port, "/nope")
            assert "/shuffle" in doc["endpoints"]

    def test_render_shuffle_fabricated_view(self):
        doc = {"seed": 11, "epoch": 2, "rank": 1, "world": 3,
               "uri": "/tmp/x.rec", "split_type": "recordio",
               "records": 9000, "windows": 14,
               "window_bytes": 32 << 20, "position": 4000,
               "delivered": 1333, "coverage": 0.4444,
               "records_by_tier": {"local": 100, "peer": 1000,
                                   "wire": 233},
               "bytes_by_tier": {"local": 4096, "peer": 9 << 20,
                                 "wire": 1 << 20}}
        out = obsctl.render_shuffle(doc)
        assert "seed 11" in out and "epoch 2" in out
        assert "rank 1/3" in out
        assert "9000" in out and "14" in out
        assert "coverage 44.44%" in out
        assert "peer" in out and "9.0MiB" in out
        assert "wire" in out

    def test_cmd_shuffle_exit_codes(self, plane, monkeypatch, capsys):
        docs = {"/shuffle": {"error": "no global shuffle active",
                             "hint": "Pipeline..."}}
        monkeypatch.setattr(obsctl, "_fetch",
                            lambda port, path, host="x", **k: docs[path])
        args = type("A", (), {"port": 1, "host": "h", "json": False})
        assert obsctl.cmd_shuffle(args) == 2
        assert "hint" in capsys.readouterr().out
        docs["/shuffle"] = {"seed": 1, "records_by_tier": {},
                            "bytes_by_tier": {}}
        assert obsctl.cmd_shuffle(args) == 0


# ------------------------------------------------ pipeline + elastic

class TestPipelineLowering:
    def test_global_shuffle_lowers_and_covers(self, plane):
        from dmlc_tpu.data.parser import Parser
        from dmlc_tpu.pipeline import Pipeline
        path = str(plane / "train.libsvm")
        rng = epoch_rng(0, 0)
        with open(path, "w") as f:
            for i in range(600):
                f.write(f"{i % 2} 1:{rng.rand():.6f} 7:{i}\n")

        def run():
            built = (Pipeline.from_uri(path)
                     .shuffle(global_seed=21, window_bytes=4096)
                     .parse(format="libsvm").build())
            rows = sum(b.size for b in built)
            # the split installs itself as the /shuffle view for as
            # long as it is alive (weakly referenced)
            assert view() is not None and view()["seed"] == 21
            built.close()
            return rows

        assert run() == run() == sum(
            b.size for b in Parser.create(path, 0, 1, format="libsvm"))

    def test_global_shuffle_native_engine_refused(self, plane):
        from dmlc_tpu.pipeline import Pipeline
        path = make_text(plane, 10)
        with pytest.raises(DMLCError, match="python parse engine"):
            (Pipeline.from_uri(path).shuffle(global_seed=1)
             .parse(format="libsvm", engine="native").build())

    def test_window_bytes_requires_global_seed(self):
        from dmlc_tpu.pipeline import Pipeline
        with pytest.raises(DMLCError, match="global_seed"):
            Pipeline.from_uri("x").shuffle(window_bytes=1 << 20)


class TestElasticReshard:
    def test_attach_rendezvous_reshards_on_epoch(self, plane):
        path = make_recordio(plane, 60)
        idx = build_record_index(path, "recordio")
        r = ShuffleReader(idx, 2, 4096, rank=0, world=2)

        class FakeClient:
            def __init__(self):
                self.cbs = []

            def on_change(self, fn):
                self.cbs.append(fn)

        c = FakeClient()
        attach_rendezvous(r, c)
        assert len(c.cbs) == 1
        c.cbs[0]({"rank": 2, "world": 3, "epoch": 4})
        assert r.rank == 2 and r.world == 3
        # torn views are ignored, never raise
        c.cbs[0]({"rank": None, "world": 0})
        c.cbs[0]({})
        assert r.rank == 2 and r.world == 3
