"""The compressed-page codec (io/codec.py) and its three seams: spill
round pages, hydrated objstore blocks (sidecar-stamped), and the
transfer-encoded wire — plus the analyze/compare plumbing that keeps
the accounting honest (compressed on-wire vs served bytes)."""

import hashlib
import os

import numpy as np
import pytest

from dmlc_tpu.io.codec import (
    ENV_LEVEL, HEADER_BYTES, decode_page, default_level, encode_page,
    is_encoded, tag,
)
from dmlc_tpu.utils.logging import DMLCError


class TestCodecFrame:
    @pytest.mark.parametrize("level", [0, 1, 6, 9])
    def test_roundtrip_property(self, level):
        rng = np.random.default_rng(level)
        cases = [
            b"",                          # empty page
            b"ab" * 50000,                # highly compressible
            rng.bytes(20000),             # incompressible (random)
            b"DTPC" + b"payload" * 64,    # raw input wearing the magic
            encode_page(b"x" * 4096, 6),  # already-encoded input
        ]
        for data in cases:
            enc = encode_page(data, level)
            assert decode_page(enc) == data
            # incompressible input never grows more than the header
            assert len(enc) <= len(data) + HEADER_BYTES

    def test_level0_is_passthrough(self):
        data = b"raw bytes, no frame"
        assert encode_page(data, 0) == data
        assert decode_page(data) == data  # plain bytes pass through

    def test_level0_magic_input_gets_stored_frame(self):
        # raw input that happens to start with the frame magic must be
        # wrapped, or decode would misread it
        data = b"DTPC" + b"\x00" * 64
        enc = encode_page(data, 0)
        assert enc != data and is_encoded(enc)
        assert decode_page(enc) == data

    def test_compression_actually_shrinks(self):
        data = b"0 1:0.5 2:0.25\n" * 10000
        enc = encode_page(data, 6)
        assert len(enc) < len(data) // 4
        assert tag(6) == "zlib:6" and tag(0) == "raw"

    def test_corrupt_frames_raise(self):
        enc = encode_page(b"payload" * 1000, 6)
        flipped = bytearray(enc)
        flipped[HEADER_BYTES + 5] ^= 0xFF
        with pytest.raises(DMLCError):
            decode_page(bytes(flipped))
        with pytest.raises(DMLCError):        # truncated payload
            decode_page(enc[: HEADER_BYTES + 3])
        with pytest.raises(DMLCError):        # truncated header
            decode_page(enc[:10])
        bad_ver = bytearray(enc)
        bad_ver[4] = 99
        with pytest.raises(DMLCError, match="version"):
            decode_page(bytes(bad_ver))
        bad_codec = bytearray(enc)
        bad_codec[5] = 7
        with pytest.raises(DMLCError, match="codec id"):
            decode_page(bytes(bad_codec))

    def test_crc_catches_stored_corruption(self):
        enc = encode_page(b"DTPC" + b"\x11" * 100, 0)  # stored frame
        tampered = bytearray(enc)
        tampered[-1] ^= 0x01
        with pytest.raises(DMLCError):
            decode_page(bytes(tampered))

    def test_env_default_level(self, monkeypatch):
        monkeypatch.delenv(ENV_LEVEL, raising=False)
        assert default_level() == 0
        monkeypatch.setenv(ENV_LEVEL, "6")
        assert default_level() == 6
        monkeypatch.setenv(ENV_LEVEL, "40")
        assert default_level() == 9  # clamped
        monkeypatch.setenv(ENV_LEVEL, "junk")
        assert default_level() == 0


def _mkblock(seed, rows=40):
    from dmlc_tpu.data.rowblock import RowBlockContainer
    rng = np.random.default_rng(seed)
    c = RowBlockContainer(np.uint32)
    for i in range(rows):
        n = int(rng.integers(1, 16))
        c.push(float(i), np.arange(n, dtype=np.uint32),
               rng.standard_normal(n).astype(np.float32))
    return c.get_block()


class TestSpillCodec:
    def _roundtrip(self, tmp_path, level):
        from dmlc_tpu.data.row_iter import RoundSpillWriter, \
            read_spill_meta
        path = str(tmp_path / f"spill{level}.pages")
        rows = [[_mkblock(r * 2 + p) for p in range(2)]
                for r in range(5)]
        w = RoundSpillWriter(path, nparts=2, codec_level=level)
        for row in rows:
            w.add_row(row)
        f = w.commit()
        h = hashlib.sha256()
        n = 0
        for row in f.iter_rows():
            for b in row:
                h.update(b.content_hash().encode())
            n += 1
        assert n == 5
        return h.hexdigest(), os.path.getsize(path), \
            read_spill_meta(path)

    def test_v2_replay_byte_identical_and_smaller(self, tmp_path):
        raw_h, raw_sz, raw_meta = self._roundtrip(tmp_path, 0)
        z_h, z_sz, z_meta = self._roundtrip(tmp_path, 6)
        assert raw_h == z_h, "codec changed replayed content"
        assert z_sz < raw_sz, "no NVMe savings"
        assert raw_meta["_version"] == 1 and raw_meta["codec"] == "raw"
        assert z_meta["_version"] == 2 and z_meta["codec"] == "zlib:6"

    def test_sidecar_stamps_codec(self, tmp_path):
        from dmlc_tpu.data.row_iter import RoundSpillWriter
        from dmlc_tpu.io.pagestore import PageStore
        path = str(tmp_path / "st.pages")
        w = RoundSpillWriter(path, nparts=1, codec_level=6)
        w.add_row([_mkblock(0)])
        w.commit()
        store, entry = PageStore.for_path(path)
        assert store.stamp(entry)["codec"] == "zlib:6"

    def test_env_level_applies(self, tmp_path, monkeypatch):
        from dmlc_tpu.data.row_iter import RoundSpillWriter, \
            read_spill_meta
        monkeypatch.setenv(ENV_LEVEL, "4")
        path = str(tmp_path / "env.pages")
        w = RoundSpillWriter(path, nparts=1)
        w.add_row([_mkblock(1)])
        w.commit()
        assert read_spill_meta(path)["codec"] == "zlib:4"


@pytest.fixture
def emulated_store(tmp_path):
    import dmlc_tpu.io.objstore as objstore
    from dmlc_tpu.io.pagestore import PageStore
    em = objstore.configure(root=str(tmp_path / "objroot"))
    store = PageStore.default()
    yield em, store

    def _scrub():
        if os.path.isdir(store.root):
            for name in os.listdir(store.root):
                if name.startswith("obj-"):
                    store.delete(name)

    _scrub()
    objstore.configure(None)
    from dmlc_tpu.io.objstore import fs as _objfs
    _objfs._options["codec_level"] = None


def _read_uri(uri):
    from dmlc_tpu.io.filesys import URI, FileSystem
    s = FileSystem.get_instance(URI(uri)).open_for_read(URI(uri))
    out = b""
    while True:
        c = s.read(1 << 20)
        if not c:
            break
        out += c
    s.close()
    return out


def _drop_hydrated(store):
    for name in (os.listdir(store.root)
                 if os.path.isdir(store.root) else []):
        if name.startswith("obj-"):
            store.delete(name)


class TestObjstoreCodec:
    CORPUS = b"0 1:0.5 2:0.25 3:0.125\n" * 120000

    def test_compressed_hydrate_wire_and_parity(self, emulated_store):
        import dmlc_tpu.io.objstore as objstore
        from dmlc_tpu.obs.metrics import REGISTRY
        em, store = emulated_store
        em.put("b", "k.txt", self.CORPUS)
        # uncompressed baseline
        _drop_hydrated(store)
        em.reset_counters()
        raw = _read_uri("obj://b/k.txt")
        raw_wire = em.counters()["get_bytes"]
        assert raw == self.CORPUS
        # compressed cold epoch: fewer wire bytes, same served bytes
        objstore.configure(codec_level=6)
        _drop_hydrated(store)
        em.reset_counters()
        b0 = REGISTRY.counter("objstore.bytes").value
        s0 = REGISTRY.counter("objstore.bytes_served").value
        got = _read_uri("obj://b/k.txt")
        cold = em.counters()
        wire = REGISTRY.counter("objstore.bytes").value - b0
        served = REGISTRY.counter("objstore.bytes_served").value - s0
        assert got == self.CORPUS, "compressed epoch changed the bytes"
        assert served == len(self.CORPUS)
        assert wire < raw_wire, "codec moved no fewer wire bytes"
        assert cold["get_bytes"] == wire, \
            "emulator ground truth disagrees with the wire counter"
        # hydrated entries are stored encoded, sidecar stamped
        names = [n for n in os.listdir(store.root)
                 if n.startswith("obj-") and n.endswith(".pages")]
        assert names
        assert store.stamp(names[0])["codec"] == "zlib:6"
        on_disk = sum(os.path.getsize(os.path.join(store.root, n))
                      for n in names)
        assert on_disk < len(self.CORPUS), "hydrated pages not encoded"
        # warm epoch: zero GETs, still byte-identical
        em.reset_counters()
        assert _read_uri("obj://b/k.txt") == self.CORPUS
        assert em.counters()["gets"] == 0

    @pytest.mark.parametrize(
        "plan", ["site=io.objstore.get,fault=truncate,times=2",
                 "site=io.objstore.get,fault=ioerror,times=2"])
    def test_chaos_on_encoded_wire_byte_identical(self, emulated_store,
                                                  plan):
        import dmlc_tpu.io.objstore as objstore
        from dmlc_tpu.resilience import inject
        em, store = emulated_store
        em.put("b", "k.txt", self.CORPUS)
        objstore.configure(codec_level=6)
        _drop_hydrated(store)
        armed = inject.install(plan)
        try:
            got = _read_uri("obj://b/k.txt")
        finally:
            inject.uninstall()
        assert armed.injected >= 2
        assert got == self.CORPUS, \
            f"chaos under {plan} broke byte identity"

    def test_corrupt_hydrated_page_refetches(self, emulated_store):
        import dmlc_tpu.io.objstore as objstore
        em, store = emulated_store
        em.put("b", "k.txt", self.CORPUS)
        objstore.configure(codec_level=6)
        _drop_hydrated(store)
        assert _read_uri("obj://b/k.txt") == self.CORPUS
        # tamper with a hydrated encoded page: the read must detect the
        # torn frame, delete it, and refetch — never serve garbage
        names = sorted(n for n in os.listdir(store.root)
                       if n.startswith("obj-") and n.endswith(".pages"))
        p = os.path.join(store.root, names[0])
        blob = bytearray(open(p, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        em.reset_counters()
        assert _read_uri("obj://b/k.txt") == self.CORPUS
        assert em.counters()["gets"] >= 1  # the tampered block refetched


class TestAnalyzeWireEvidence:
    def _snap(self, wire, served):
        return {"counters": {"pagestore.hit": 0, "pagestore.miss": 8,
                             "objstore.get": 8, "objstore.bytes": wire,
                             "objstore.bytes_served": served}}

    def test_evidence_names_compressed_and_served_rates(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 4_000_000_000}]}
        v = attribute(snap, metrics=self._snap(1_000_000_000,
                                               4_000_000_000))
        assert v["bound"] == "wire"
        wire_lines = [e for e in v["evidence"]
                      if e.startswith("objstore:")]
        assert len(wire_lines) == 1
        assert "served from" in wire_lines[0]
        assert "compressed wire" in wire_lines[0]
        assert "GB/s served" in wire_lines[0]

    def test_wire_heaviness_judged_on_served_bytes(self):
        # compressed wire bytes are small; the SERVED side is what the
        # pipeline consumed — a 4 GB epoch fed by 1 GB of wire is still
        # wire-bound, not consumer-bound
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 4_000_000_000}]}
        v = attribute(snap, metrics=self._snap(100_000_000,
                                               4_000_000_000))
        assert v["bound"] == "wire"

    def test_uncompressed_evidence_unchanged(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 2.0,
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 1_000_000_000}]}
        v = attribute(snap, metrics=self._snap(1_000_000_000,
                                               1_000_000_000))
        line = next(e for e in v["evidence"]
                    if e.startswith("objstore:"))
        assert "served from" not in line


class TestCompareConfig14:
    def _doc(self, gbps, gauges):
        return {"config": "recio_native", "gbps": gbps, "bytes": 1,
                "epoch_gauges": gauges}

    def test_config_docs_compare_band_for_band(self):
        from dmlc_tpu.obs.analyze import compare
        a = self._doc(1.0, [1.2, 1.3, 1.1])   # plateau band
        b = self._doc(0.95, [1.25, 1.2, 1.3])
        out = compare(a, b)
        assert out["bands"]["plateau"]["status"] == "in-band"
        assert not out["regressions"]
        worse = compare(a, self._doc(0.5, [1.2, 1.2, 1.2]))
        assert worse["bands"]["plateau"]["status"] == "regression"

    def test_cross_band_config_docs_incomparable(self):
        from dmlc_tpu.obs.analyze import compare
        out = compare(self._doc(1.0, [1.2]), self._doc(0.4, [0.5]))
        assert all(r["status"] == "incomparable"
                   for r in out["bands"].values())
        assert not out["regressions"]
