"""Live telemetry plane + flight recorder + native span ring (PR 4).

Covers: the Prometheus exposition golden format (names/types/HELP
lines pinned), the status server endpoints (scrape-under-load: the
server answers while a pipeline loop runs), a REAL 2-process
launch_local gang serving per-rank /metrics + /healthz during the run,
a provoked subprocess crash leaving a flight-recorder bundle whose
trace file passes the Perfetto golden-key check, native-engine spans
merging consistently onto the Python timeline, watchdog report
timestamping/retention, and warn-channel instants on the trace.
"""

import glob
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from dmlc_tpu.obs import flight as obs_flight
from dmlc_tpu.obs import log as obs_log
from dmlc_tpu.obs import trace as obs_trace
from dmlc_tpu.obs import watchdog as obs_watchdog
from dmlc_tpu.obs.metrics import REGISTRY, MetricsRegistry
from dmlc_tpu.obs.serve import (
    StatusServer, render_prometheus, scrape, scrape_gang,
)
from dmlc_tpu.obs.watchdog import Watchdog

CHROME_REQUIRED_KEYS = ("ph", "ts", "pid", "tid", "name")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_clean():
    """Tracing off, no flight recorder, no history ring/aggregator,
    fresh log state per test."""
    from dmlc_tpu.obs import aggregate as obs_agg
    from dmlc_tpu.obs import timeseries as obs_ts
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_agg.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()
    yield
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_agg.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()


def _write_libsvm(tmp_path, rows=600, name="live.libsvm"):
    lines = [f"{i % 2} 1:0.5 7:1.25 9:{i}.0" for i in range(rows)]
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _get(url: str, timeout_s: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.status, resp.read()


def _assert_chrome_golden(doc):
    assert "traceEvents" in doc and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        for key in CHROME_REQUIRED_KEYS:
            assert key in ev, (key, ev)
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X":
            assert "dur" in ev, ev


class TestPrometheusExposition:
    def _registry(self):
        reg = MetricsRegistry()
        reg.counter("rows.parsed").inc(5)
        reg.gauge("queue.depth").set(3)
        reg.gauge("replay.tier").set("pages")
        reg.gauge("never.set")  # value None: silently absent
        # a structured gauge value has no single exposition line ->
        # skipped + counted (snapshot() reprs plain objects to str,
        # which renders info-style; dicts/lists pass through)
        reg.gauge("weird.object").set({"structured": 1})
        reg.histogram("wait.s").observe(0.25)
        reg.histogram("wait.s").observe(0.5)
        return reg

    def test_golden_families(self):
        """Golden: family names, TYPE lines, HELP lines, and value
        lines of the exposition are pinned — a renderer change must
        change this test consciously."""
        reg = self._registry()

        class Surface:
            def stats(self):
                return {"qsize": 2, "note": "text", "nested": {"n": 7}}

        s = Surface()
        reg.register("queue/demo", s, Surface.stats)
        text = render_prometheus(reg.snapshot(), reg)
        assert text.endswith("\n")
        # identity series
        assert "# TYPE dmlc_obs_info gauge" in text
        assert 'dmlc_obs_info{rank="None"' in text
        # counter family
        assert "# HELP dmlc_rows_parsed_total Counter rows.parsed" \
            in text
        assert "# TYPE dmlc_rows_parsed_total counter" in text
        assert "\ndmlc_rows_parsed_total 5\n" in text
        # numeric gauge
        assert "# TYPE dmlc_queue_depth gauge" in text
        assert "\ndmlc_queue_depth 3\n" in text
        # string gauge -> info-style labeled series, NOT a bare repr
        assert 'dmlc_replay_tier_info{value="pages"} 1' in text
        assert "dmlc_replay_tier pages" not in text
        # non-renderable gauge -> counted, not emitted
        assert "dmlc_weird_object" not in text
        assert "# TYPE dmlc_obs_export_skipped_total counter" in text
        assert "\ndmlc_obs_export_skipped_total 1\n" in text
        # histogram: cumulative buckets + sum/count
        assert "# TYPE dmlc_wait_s histogram" in text
        assert 'dmlc_wait_s_bucket{le="+Inf"} 2' in text
        assert "\ndmlc_wait_s_count 2\n" in text
        assert "\ndmlc_wait_s_sum 0.75\n" in text
        # bucket-estimated quantiles as sibling gauge families
        assert "# TYPE dmlc_wait_s_p50 gauge" in text
        assert "# TYPE dmlc_wait_s_p99 gauge" in text
        assert "\ndmlc_wait_s_p50 " in text
        assert "\ndmlc_wait_s_p99 0.5\n" in text  # clamped to max
        # collector numeric leaves, flattened + labeled; strings dropped
        assert ('dmlc_collector_value{collector="queue/demo",'
                'key="qsize"} 2') in text
        assert ('dmlc_collector_value{collector="queue/demo",'
                'key="nested.n"} 7') in text
        assert "note" not in text

    def test_every_line_is_valid_exposition(self):
        import re
        reg = self._registry()
        text = render_prometheus(reg.snapshot(), reg)
        line_re = re.compile(
            r"^[a-z_][a-z0-9_]*(\{[^{}]*\})? -?[0-9.eE+-]+$")
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            assert line_re.match(line), line

    def test_skipped_counter_accumulates(self):
        reg = self._registry()
        render_prometheus(reg.snapshot(), reg)
        text = render_prometheus(reg.snapshot(), reg)
        # two renders of one bad gauge -> 2 (monotonic counter), and
        # the family appears exactly ONCE even though the counter now
        # also lives in the snapshot (a duplicate family fails the
        # whole scrape under promtool)
        assert "\ndmlc_obs_export_skipped_total 2\n" in text
        assert text.count("# TYPE dmlc_obs_export_skipped_total") == 1
        assert text.count("dmlc_obs_export_skipped_total 2") == 1


class TestStatusServer:
    def test_endpoints(self):
        reg = MetricsRegistry()
        reg.counter("srv.hits").inc(7)
        reg.gauge("srv.tier").set("memory")
        with StatusServer(registry=reg) as srv:
            status, body = _get(srv.url("/metrics"))
            assert status == 200
            assert b"dmlc_srv_hits_total 7" in body
            assert b'dmlc_srv_tier_info{value="memory"} 1' in body
            status, body = _get(srv.url("/metrics.json"))
            snap = json.loads(body)
            assert snap["schema"] == 1
            assert snap["counters"]["srv.hits"] == 7
            status, body = _get(srv.url("/healthz"))
            health = json.loads(body)
            assert health["ok"] is True
            assert health["pid"] == os.getpid()
            assert health["watchdog"]["installed"] is False
            assert health["waits"] == []
            status, body = _get(srv.url("/stacks"))
            assert status == 200 and b"Thread" in body
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/nope"))
            assert e.value.code == 404

    def test_healthz_reports_blocked_waits(self):
        """The liveness endpoint names the pull that is wedged RIGHT
        NOW — the 'curl the slow rank' story."""
        with StatusServer() as srv:
            wd = Watchdog(threshold_s=60, interval_s=10).start()
            try:
                token = obs_watchdog.begin_wait("pull/wedged.demo")
                time.sleep(0.02)
                health = json.loads(_get(srv.url("/healthz"))[1])
                names = [w["name"] for w in health["waits"]]
                assert "pull/wedged.demo" in names
                assert health["watchdog"]["installed"] is True
                obs_watchdog.end_wait(token)
                health = json.loads(_get(srv.url("/healthz"))[1])
                assert health["waits"] == []
            finally:
                wd.stop()

    def test_scrape_under_load(self, tmp_path):
        """The server answers /metrics while a real pipeline loop runs
        in this process (the bench-loop shape)."""
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=3000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="python",
                        chunk_size=2048)
                 .batch(128)
                 .build())
        stop = threading.Event()
        errors = []

        def pump():
            try:
                while not stop.is_set():
                    for _ in built:
                        pass
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=pump, daemon=True)
        with StatusServer() as srv:
            t.start()
            try:
                ok = 0
                deadline = time.time() + 10.0
                while ok < 20 and time.time() < deadline:
                    status, body = _get(srv.url("/metrics"))
                    assert status == 200
                    assert body.startswith(b"# HELP dmlc_obs_info")
                    snap = json.loads(_get(srv.url("/metrics.json"))[1])
                    assert snap["schema"] == 1
                    ok += 1
                assert ok >= 20
            finally:
                stop.set()
                t.join(timeout=10.0)
        # the pipeline's collector is visible in the scraped registry
        # (collision-suffixed when earlier tests registered one too)
        assert any(k.startswith("pipeline")
                   for k in REGISTRY.snapshot()["collectors"])
        built.close()
        assert errors == []

    def test_trace_capture_of_running_recorder(self):
        rec = obs_trace.start()
        with obs_trace.span("live-work"):
            pass
        try:
            with StatusServer() as srv:
                doc = json.loads(
                    _get(srv.url("/trace?seconds=0"))[1])
                _assert_chrome_golden(doc)
                assert any(e.get("name") == "live-work"
                           for e in doc["traceEvents"])
            # the running trace was NOT disturbed by the capture
            assert obs_trace.active() is rec
        finally:
            obs_trace.stop()

    def test_trace_capture_installs_when_off(self):
        assert obs_trace.active() is None
        with StatusServer() as srv:
            doc = json.loads(_get(srv.url("/trace?seconds=0.05"))[1])
            assert "traceEvents" in doc
            assert doc["otherData"]["capture_s"] == 0.05
        # the on-demand recorder was uninstalled after the window
        assert obs_trace.active() is None


class TestGangServe:
    """Acceptance: a REAL 2-process launch_local gang serves scrapeable
    per-rank /metrics and /healthz DURING the run."""

    def test_two_process_gang_scraped_live(self, tmp_path):
        from dmlc_tpu.parallel.launch import find_free_ports, launch_local
        script = tmp_path / "serve_worker.py"
        stop_file = tmp_path / "stop"
        script.write_text(
            "import os, sys, time\n"
            "from dmlc_tpu.obs.serve import serve_if_env\n"
            "from dmlc_tpu.obs.metrics import REGISTRY\n"
            "srv = serve_if_env()\n"
            "assert srv is not None, 'serve port env missing'\n"
            "rank = int(os.environ['DMLC_TPU_TASK_ID'])\n"
            "REGISTRY.counter('gang.rows').inc(100 * (rank + 1))\n"
            "REGISTRY.gauge('gang.tier').set('pages')\n"
            "deadline = time.time() + 30\n"
            "while not os.path.exists(sys.argv[1]) "
            "and time.time() < deadline:\n"
            "    time.sleep(0.05)\n"
        )
        ports = find_free_ports(2)
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
        result = {}

        def gang():
            try:
                result["codes"] = launch_local(
                    2, [sys.executable, str(script), str(stop_file)],
                    env=env, serve_ports=ports, timeout=60)
            except Exception as e:  # noqa: BLE001
                result["error"] = e

        t = threading.Thread(target=gang, daemon=True)
        t.start()
        try:
            # poll until BOTH ranks answer /healthz — they are alive
            # and serving WHILE the gang runs
            deadline = time.time() + 30.0
            healthy = {}
            while len(healthy) < 2 and time.time() < deadline:
                for rank, port in enumerate(ports):
                    if rank in healthy:
                        continue
                    try:
                        h = scrape(port, path="/healthz",
                                   timeout_s=2.0)
                        assert h["ok"] is True
                        assert h["rank"] == rank
                        healthy[rank] = h
                    except (OSError, urllib.error.URLError):
                        time.sleep(0.05)
            assert len(healthy) == 2, f"gang never served: {result}"
            # per-rank Prometheus exposition is live
            status, body = _get(f"http://127.0.0.1:{ports[0]}/metrics")
            assert status == 200 and b"dmlc_gang_rows_total 100" in body
            status, body = _get(f"http://127.0.0.1:{ports[1]}/metrics")
            assert status == 200 and b"dmlc_gang_rows_total 200" in body
            # rank-0-style merged scrape of the whole gang
            merged = scrape_gang(ports)
            assert set(merged["workers"]) == {"rank0", "rank1"}
            assert merged["workers"]["rank1"]["counters"]["gang.rows"] \
                == 200
            assert "unreachable" not in merged
        finally:
            stop_file.write_text("stop")
            t.join(timeout=30.0)
        assert result.get("codes") == [0, 0], result


class TestFlightRecorder:
    def test_fallback_ring_interplay(self):
        """The flight ring serves as the active recorder when no
        explicit trace runs; start() displaces it, stop() reinstates
        it; clear_fallback() removes it."""
        ring = obs_trace.TraceRecorder(100)
        obs_trace.set_fallback(ring)
        assert obs_trace.active() is ring
        obs_trace.instant("background-event")
        assert ring.recorded == 1
        rec = obs_trace.start()  # no replaced-recorder warning path
        assert obs_trace.active() is rec
        obs_trace.instant("foreground-event")
        assert ring.recorded == 1  # explicit trace owns the window
        assert obs_trace.stop() is rec
        assert obs_trace.active() is ring
        assert obs_trace.stop() is None  # fallback not removable here
        assert obs_trace.active() is ring
        assert obs_trace.clear_fallback() is ring
        assert obs_trace.active() is None

    def test_install_dump_uninstall(self, tmp_path):
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "flight"),
            metrics_interval_s=0.05).install()
        try:
            with obs_trace.span("flight-covered-work"):
                pass
            REGISTRY.counter("flight.test_events").inc(3)
            time.sleep(0.15)  # let the sampler take a history snapshot
            d = fl.dump("unit_test")
            assert os.path.isdir(d)
            manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
            assert manifest["kind"] == "dmlc_tpu_flight_bundle"
            assert manifest["reason"] == "unit_test"
            doc = json.load(open(os.path.join(d, "trace.json")))
            _assert_chrome_golden(doc)
            assert any(e.get("name") == "flight-covered-work"
                       for e in doc["traceEvents"])
            metrics = json.load(open(os.path.join(d, "metrics.json")))
            assert metrics["current"]["counters"][
                "flight.test_events"] == 3
            assert len(metrics["history"]) >= 1
            stacks = open(os.path.join(d, "stacks.txt")).read()
            assert "Thread" in stacks
            env = json.load(open(os.path.join(d, "env.json")))
            assert env["argv"]
        finally:
            fl.uninstall()
        assert obs_trace.active() is None

    def test_clean_uninstall_leaves_no_bundle(self, tmp_path):
        out = str(tmp_path / "flight")
        fl = obs_flight.FlightRecorder(out_dir=out).install()
        fl.uninstall()
        assert glob.glob(os.path.join(out, "flight-*")) == []

    def test_worker_crash_leaves_loadable_bundle(self, tmp_path):
        """Acceptance: a provoked launch_local worker crash leaves a
        flight-recorder bundle whose trace file passes the Perfetto
        golden-key check — the flight_dir env wiring end to end."""
        from dmlc_tpu.parallel.launch import launch_local
        from dmlc_tpu.utils.logging import DMLCError
        out = str(tmp_path / "flight")
        script = tmp_path / "crash.py"
        script.write_text(
            "from dmlc_tpu.obs.flight import install_if_env\n"
            "fl = install_if_env()\n"
            "assert fl is not None\n"
            "from dmlc_tpu.obs.metrics import REGISTRY\n"
            "from dmlc_tpu.obs.trace import span\n"
            "REGISTRY.counter('doomed.rows').inc(42)\n"
            "with span('doomed-work'):\n"
            "    pass\n"
            "raise RuntimeError('deliberate flight-recorder crash')\n"
        )
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
        with pytest.raises(DMLCError):
            launch_local(1, [sys.executable, str(script)], env=env,
                         flight_dir=out, timeout=120)
        bundles = glob.glob(os.path.join(out, "flight-*"))
        assert len(bundles) == 1, bundles
        d = bundles[0]
        manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
        assert manifest["reason"] == "uncaught_exception"
        doc = json.load(open(os.path.join(d, "trace.json")))
        _assert_chrome_golden(doc)  # Perfetto-loadable golden keys
        assert any(e.get("name") == "doomed-work"
                   for e in doc["traceEvents"])
        metrics = json.load(open(os.path.join(d, "metrics.json")))
        assert metrics["current"]["counters"]["doomed.rows"] == 42
        error = open(os.path.join(d, "error.txt")).read()
        assert "deliberate flight-recorder crash" in error
        assert "Thread" in open(os.path.join(d, "stacks.txt")).read()

    def test_watchdog_escalation_dumps_bundle(self, tmp_path):
        """A watchdog-confirmed stall dumps a bundle while the process
        is still alive (kill -9 comes later; the bundle survives)."""
        from dmlc_tpu.data.threaded_iter import ThreadedIter
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "flight")).install()
        release = threading.Event()
        ti = ThreadedIter(max_capacity=2, name="flight.stalled")
        ti.init(lambda: (release.wait(30.0), None)[1])
        consumer = threading.Thread(target=ti.next, daemon=True)
        try:
            with Watchdog(threshold_s=0.15, interval_s=0.05) as wd:
                consumer.start()
                deadline = time.time() + 5.0
                while not wd.reports and time.time() < deadline:
                    time.sleep(0.02)
            assert fl.dumped, "escalation never dumped"
            wdj = json.load(open(os.path.join(
                fl.bundle_dir, "watchdog.json")))
            blocked = wdj["escalating_report"]["blocked"]
            assert any("flight.stalled" in b["name"] for b in blocked)
            manifest = json.load(open(os.path.join(
                fl.bundle_dir, "MANIFEST.json")))
            assert manifest["reason"] == "watchdog_stall"
        finally:
            release.set()
            consumer.join(timeout=5.0)
            ti.destroy()
            fl.uninstall()


class TestWatchdogReportRetention:
    def test_timestamped_history_bounded(self, tmp_path):
        """Satellite: each stall report lands under a timestamped name
        next to report_path (which keeps the latest), and only the
        last keep_reports survive a soak."""
        report_path = str(tmp_path / "stall.json")
        wd = Watchdog(threshold_s=0.02, interval_s=999,
                      report_path=report_path, keep_reports=2).start()
        try:
            for i in range(4):
                token = obs_watchdog.begin_wait(f"soak.{i}")
                time.sleep(0.03)
                report = wd.check()
                assert report is not None, f"stall {i} unreported"
                obs_watchdog.end_wait(token)
                time.sleep(0.002)  # distinct ms timestamps
        finally:
            wd.stop()
        assert os.path.exists(report_path)
        latest = json.load(open(report_path))
        assert latest["blocked"][0]["name"] == "soak.3"
        history = sorted(glob.glob(str(tmp_path / "stall.*.json")))
        assert len(history) == 2, history  # keep_reports=2 pruned 4->2
        names = [json.load(open(p))["blocked"][0]["name"]
                 for p in history]
        assert names == ["soak.2", "soak.3"]  # the LAST two survive


class TestWarnInstants:
    def _capture(self):
        from dmlc_tpu.utils.logging import set_log_sink
        hits = []
        set_log_sink(lambda lvl, msg: hits.append(msg))
        return hits

    def _restore(self):
        from dmlc_tpu.utils.logging import set_log_sink
        set_log_sink(None)

    def test_emitted_warning_lands_on_timeline(self):
        hits = self._capture()
        rec = obs_trace.start()
        try:
            assert obs_log.warn_once("spill-degrade",
                                     "spill failed; replay off")
            # suppressed repeat adds NO second instant
            assert not obs_log.warn_once("spill-degrade", "again")
        finally:
            obs_trace.stop()
            self._restore()
        warns = [e for e in rec.events()
                 if e[0] == "i" and e[1] == "warn/spill-degrade"]
        assert len(warns) == 1
        assert warns[0][6] == {"msg": "spill failed; replay off"}
        assert warns[0][2] == "log"
        assert hits == ["spill failed; replay off"]

    def test_no_recorder_no_cost(self):
        hits = self._capture()
        try:
            assert obs_log.warn_once("quiet-key", "no recorder")
        finally:
            self._restore()
        assert hits == ["no recorder"]


def _native_available():
    from dmlc_tpu import native
    return native.native_available()


class TestNativeSpanRing:
    """The engine's span ring merges onto the Python timeline."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        if not _native_available():
            pytest.skip("native engine unavailable on this host")

    def test_ring_off_by_default(self, tmp_path):
        from dmlc_tpu.native.bindings import NativeLibSVMParser, _get_lib
        assert obs_trace.active() is None
        assert _get_lib().dtp_trace_enabled() == 0
        p = NativeLibSVMParser(_write_libsvm(tmp_path), 0, 1,
                               chunk_size=2048)
        while p.next():
            pass
        rec = obs_trace.TraceRecorder(100)
        assert p.drain_trace(rec) == 0  # nothing recorded while off
        p.destroy()

    def test_spans_merge_consistently(self, tmp_path):
        """Drained native spans agree with the engine's own counters
        (tokenize spans == chunks, assemble spans == delivered blocks)
        and land inside the run's perf_counter window after the
        drain-time clock calibration."""
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        uri = _write_libsvm(tmp_path, rows=5000)
        rec = obs_trace.start()
        try:
            t_begin = time.perf_counter()
            p = NativeLibSVMParser(uri, 0, 1, chunk_size=4096)
            blocks = 0
            while p.next():
                blocks += 1
            n = p.drain_trace(rec)
            t_end = time.perf_counter()
            chunks = p.stats()["chunks"]
            p.destroy()
        finally:
            obs_trace.stop()
        assert n > 0 and blocks > 0
        by_name = {}
        for ph, name, cat, t0, dur, tid, args in rec.events():
            if cat != "native":
                continue
            by_name.setdefault(name, []).append((t0, dur, tid))
            assert t_begin <= t0 <= t_end, (name, t0, t_begin, t_end)
            assert t0 + dur <= t_end + 0.001
        assert len(by_name["native/tokenize"]) == chunks
        assert len(by_name["native/chunk_read"]) == chunks
        assert len(by_name["native/batch_assemble"]) == blocks
        # arena events exist and classify every tokenize
        cache = (len(by_name.get("native/cache.hit", []))
                 + len(by_name.get("native/cache.miss", [])))
        assert cache == chunks
        # native tracks are disjoint from Python thread idents and are
        # named in the chrome export
        from dmlc_tpu.obs.export import chrome_events
        evs = chrome_events(rec)
        names = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert "native/reader" in names
        assert any(n.startswith("native/worker-") for n in names)

    def test_pipeline_trace_includes_native_spans(self, tmp_path):
        """End to end: CompiledPipeline.trace() over the native engine
        puts engine spans and Python pull spans in ONE loadable file."""
        from dmlc_tpu.pipeline import Pipeline
        uri = _write_libsvm(tmp_path, rows=5000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="native",
                        chunk_size=4096)
                 .build())
        path = str(tmp_path / "merged.json")
        with built.trace(path):
            for _ in built:
                pass
        built.close()
        doc = json.load(open(path))
        _assert_chrome_golden(doc)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "pull/parse" in names          # the Python span...
        assert "native/tokenize" in names     # ...and the engine span
        assert "native/chunk_read" in names   # on one timeline
        # the flag mirrors back off with tracing stopped
        from dmlc_tpu.native.bindings import _get_lib
        assert _get_lib().dtp_trace_enabled() == 0
