"""Streaming ingestion (dmlc_tpu.io.streaming_split +
Pipeline.from_stream): EOF-less windowed consumption of a growing
file with advancing watermarks, finite-epoch byte identity once the
writer stops, chain-validation, and chaos degradation (truncate /
ioerror faults -> clean windowed retries, never shifted bytes, never
a hang)."""

import threading
import time

import pytest

from dmlc_tpu.io.streaming_split import StreamingSplit
from dmlc_tpu.pipeline import Pipeline
from dmlc_tpu.resilience import inject
from dmlc_tpu.resilience.policy import reset_policies
from dmlc_tpu.utils.logging import DMLCError


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    inject.uninstall()
    reset_policies()


def _lines(n, start=0):
    return [f"{(i + start) % 2} {(i + start) % 40 + 1}:1.5 "
            f"{(i + start) % 70 + 3}:2.5\n" for i in range(n)]


class _Writer:
    """Append records to a file in timed slices on a thread."""

    def __init__(self, path, total=1200, slice_rows=150,
                 interval_s=0.02):
        self.path = str(path)
        self.rows = _lines(total)
        self.slice_rows = slice_rows
        self.interval_s = interval_s
        self.thread = threading.Thread(target=self._run, daemon=True)
        open(self.path, "w").close()

    def _run(self):
        with open(self.path, "a") as f:
            for i in range(0, len(self.rows), self.slice_rows):
                f.write("".join(self.rows[i:i + self.slice_rows]))
                f.flush()
                time.sleep(self.interval_s)

    def start(self):
        self.thread.start()
        return self

    def join(self):
        self.thread.join()


class TestStreamingSplit:
    def test_consumes_growth_eof_less(self, tmp_path):
        w = _Writer(tmp_path / "feed.libsvm").start()
        split = StreamingSplit(w.path, window_records=200,
                               poll_interval_s=0.01,
                               idle_timeout_s=0.4)
        records = list(split)
        w.join()
        assert len(records) == 1200
        assert records == [ln.strip().encode() for ln in w.rows]

    def test_watermark_advances_monotonically(self, tmp_path):
        w = _Writer(tmp_path / "feed.libsvm").start()
        split = StreamingSplit(w.path, window_records=128,
                               poll_interval_s=0.01,
                               idle_timeout_s=0.4)
        marks = []
        while (chunk := split.next_chunk()) is not None:
            wm = split.watermark()
            marks.append((wm["windows"], wm["watermark_bytes"],
                          wm["watermark_records"]))
            assert chunk
        w.join()
        assert len(marks) >= 4
        for a, b in zip(marks, marks[1:]):
            assert b[0] > a[0] and b[1] > a[1] and b[2] > a[2]
        assert split.watermark()["ended"] is True

    def test_count_windows_are_bounded(self, tmp_path):
        w = _Writer(tmp_path / "feed.libsvm", total=600,
                    slice_rows=600).start()
        w.join()  # all bytes present before the first poll
        split = StreamingSplit(w.path, window_records=100,
                               poll_interval_s=0.01,
                               idle_timeout_s=0.3)
        sizes = []
        while (chunk := split.next_chunk()) is not None:
            sizes.append(sum(1 for ln in chunk.splitlines() if ln))
        # the poll reads up to chunk_size at once; the window closes
        # AT or past the count bound within one poll's whole records
        assert sum(sizes) == 600
        assert all(s >= 100 for s in sizes[:-1])

    def test_time_window_flushes_partial(self, tmp_path):
        w = _Writer(tmp_path / "feed.libsvm", total=90,
                    slice_rows=30, interval_s=0.05).start()
        split = StreamingSplit(w.path, window_records=10 ** 6,
                               window_s=0.06, poll_interval_s=0.01,
                               idle_timeout_s=0.5)
        n_windows = 0
        total = 0
        while (chunk := split.next_chunk()) is not None:
            n_windows += 1
            total += sum(1 for ln in chunk.splitlines() if ln)
        w.join()
        assert total == 90
        assert n_windows >= 2  # time closed windows below the count

    def test_stop_drains_and_ends(self, tmp_path):
        path = tmp_path / "feed.libsvm"
        path.write_text("".join(_lines(50)))
        split = StreamingSplit(str(path), poll_interval_s=0.01)
        split.stop()
        chunk = split.next_chunk()
        assert chunk is not None
        assert sum(1 for ln in chunk.splitlines() if ln) == 50
        assert split.next_chunk() is None
        assert split.watermark()["ended"] is True

    def test_stop_drains_unterminated_tail(self, tmp_path):
        """Once the writer stops, a final record without a trailing
        newline is still part of the finite-file epoch."""
        path = tmp_path / "feed.libsvm"
        path.write_text("1 2:1.5\n0 3:2.5")  # no trailing newline
        split = StreamingSplit(str(path), poll_interval_s=0.01)
        split.stop()
        records = list(split)
        assert records == [b"1 2:1.5", b"0 3:2.5"]

    def test_cannot_rewind_or_shard(self, tmp_path):
        path = tmp_path / "feed.libsvm"
        path.write_text("".join(_lines(10)))
        split = StreamingSplit(str(path), poll_interval_s=0.01,
                               idle_timeout_s=0.1)
        list(split)
        with pytest.raises(DMLCError, match="cannot rewind"):
            split.before_first()
        with pytest.raises(DMLCError, match="one part"):
            split.reset_partition(1, 2)

    def test_shrunk_source_raises(self, tmp_path):
        path = tmp_path / "feed.libsvm"
        path.write_text("".join(_lines(100)))
        split = StreamingSplit(str(path), window_records=50,
                               poll_interval_s=0.01,
                               idle_timeout_s=2.0)
        assert split.next_chunk() is not None
        path.write_text("0 1:1\n")  # REWRITE below the watermark
        with pytest.raises(DMLCError, match="shrank"):
            while split.next_chunk() is not None:
                pass

    def test_short_read_at_stop_never_tears_a_record(self, tmp_path):
        """Post-review pin: the stop-time tail force-commit applies
        ONLY when the read reached the source's true end — an
        injected-truncate SHORT read at stop must re-poll, never
        commit the torn prefix as a record."""
        path = tmp_path / "feed.libsvm"
        path.write_text("1 2:1.5")  # one unterminated record
        inject.install("site=io.stream.read,fault=truncate,times=1")
        split = StreamingSplit(str(path), poll_interval_s=0.01)
        split.stop()
        records = list(split)
        assert inject.active().injected >= 1
        assert records == [b"1 2:1.5"]  # whole, never [prefix, rest]

    def test_record_larger_than_chunk_raises(self, tmp_path):
        """Post-review pin: a record that cannot fit the poll buffer
        fails LOUD instead of re-reading the buffer forever (or being
        silently dropped at idle timeout)."""
        path = tmp_path / "feed.libsvm"
        path.write_text("0 " + "1:1 " * 40000 + "\n")  # ~160 KB line
        split = StreamingSplit(str(path), poll_interval_s=0.01,
                               chunk_size=1)  # clamps to 64 KiB
        with pytest.raises(DMLCError, match="exceeds chunk_size"):
            split.next_chunk()

    def test_idle_drain_commits_unterminated_tail(self, tmp_path):
        """Post-review pin: idle expiry takes one stop-style drain
        pass — a writer that stopped mid-line still yields the tail
        record (the finite-file epoch would parse it)."""
        path = tmp_path / "feed.libsvm"
        path.write_text("1 2:1.5\n0 3:2.5")  # no trailing newline
        split = StreamingSplit(str(path), poll_interval_s=0.01,
                               idle_timeout_s=0.15)
        records = list(split)
        assert records == [b"1 2:1.5", b"0 3:2.5"]

    def test_slow_mid_record_writer_not_idle_dropped(self, tmp_path):
        """Post-review pin: RAW byte growth resets the idle clock — a
        writer trickling one long line slower than records appear is
        alive, not idle, and its half-line is never drained torn."""
        path = tmp_path / "feed.libsvm"
        open(path, "w").close()

        def writer():
            with open(path, "a") as f:
                for piece in ("1 7:1.5", " 9:2.5", " 11:4.5\n"):
                    f.write(piece)
                    f.flush()
                    time.sleep(0.2)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        split = StreamingSplit(str(path), poll_interval_s=0.01,
                               idle_timeout_s=0.35)
        records = list(split)
        t.join()
        assert records == [b"1 7:1.5 9:2.5 11:4.5"]

    def test_registered_metrics_collector(self, tmp_path):
        from dmlc_tpu.obs.metrics import REGISTRY
        path = tmp_path / "feed.libsvm"
        path.write_text("".join(_lines(20)))
        split = StreamingSplit(str(path), poll_interval_s=0.01,
                               idle_timeout_s=0.1)
        list(split)
        snap = REGISTRY.snapshot()
        key = next(k for k in snap["collectors"]
                   if k.startswith(f"stream/{path}"))
        assert snap["collectors"][key]["watermark_records"] == 20


class TestStreamingChaos:
    """FaultPlans on the growing file degrade to clean windowed
    retries: the consumed stream stays byte-identical to the finite
    epoch, the degradation is counted, and nothing hangs."""

    def _consume(self, path, **kw):
        kw.setdefault("window_records", 100)
        kw.setdefault("poll_interval_s", 0.01)
        kw.setdefault("idle_timeout_s", 0.5)
        split = StreamingSplit(str(path), **kw)
        records = list(split)
        return records, split

    def test_ioerror_absorbed_by_the_seam(self, tmp_path):
        """A transient open fault is retried INSIDE the io.stream.open
        resilience seam — the split never even sees a degraded poll."""
        w = _Writer(tmp_path / "feed.libsvm").start()
        inject.install("site=io.stream.open,fault=ioerror,nth=3")
        records, split = self._consume(w.path)
        w.join()
        plan = inject.active()
        assert plan.injected > 0, "the fault never fired"
        assert records == [ln.strip().encode() for ln in w.rows]

    def test_ioerror_past_the_ladder_degrades_to_retry(self, tmp_path):
        """Faults that EXHAUST the retry ladder surface as failed
        polls: the split counts the degradation, re-polls from the
        committed watermark, and the stream stays byte-identical."""
        from dmlc_tpu.resilience import RetryPolicy, set_policy
        set_policy("io.stream.open",
                   RetryPolicy(max_attempts=2, base_delay_s=0.0))
        w = _Writer(tmp_path / "feed.libsvm").start()
        inject.install("site=io.stream.open,fault=ioerror,times=10")
        records, split = self._consume(w.path)
        w.join()
        plan = inject.active()
        assert plan.injected == 10
        assert split.watermark()["retries"] > 0
        assert records == [ln.strip().encode() for ln in w.rows]

    def test_truncate_degrades_to_retry(self, tmp_path):
        """An injected truncate (tail of the read dropped, stream
        pinned at EOF) yields a SHORT poll: the committed watermark
        re-reads from the record boundary — never shifted bytes."""
        w = _Writer(tmp_path / "feed.libsvm").start()
        inject.install("site=io.stream.read,fault=truncate,nth=2")
        records, split = self._consume(w.path)
        w.join()
        plan = inject.active()
        assert plan.injected > 0, "the fault never fired"
        assert records == [ln.strip().encode() for ln in w.rows]

    def test_persistent_ioerror_never_hangs(self, tmp_path):
        path = tmp_path / "feed.libsvm"
        path.write_text("".join(_lines(100)))
        inject.install("site=io.stream.open,fault=ioerror")  # every
        t0 = time.monotonic()
        records, split = self._consume(path, idle_timeout_s=0.4)
        assert records == []  # nothing readable, clean end
        assert time.monotonic() - t0 < 10.0
        assert split.watermark()["retries"] > 0


class TestStreamingPipeline:
    def test_stream_epoch_matches_finite_epoch(self, tmp_path):
        """THE streaming acceptance: consumed EOF-less with advancing
        watermarks; once the writer stops, byte-identical to a finite
        epoch over the final bytes."""
        w = _Writer(tmp_path / "feed.libsvm").start()
        built = (Pipeline.from_stream(w.path, window_records=256,
                                      poll_interval_s=0.01,
                                      idle_timeout_s=0.5)
                 .parse(format="libsvm").batch(512).build())
        stream_hashes = [b.content_hash() for b in built]
        w.join()
        wm = built.stream_stats()
        assert wm["watermark_records"] == 1200 and wm["windows"] >= 2
        snap = built.stats()
        assert snap["stages"][0]["extra"]["stream"][
            "watermark_bytes"] > 0
        built.close()
        finite = (Pipeline.from_uri(w.path)
                  .parse(format="libsvm", engine="python")
                  .batch(512).build())
        finite_hashes = [b.content_hash() for b in finite]
        finite.close()
        assert stream_hashes == finite_hashes

    def test_stream_rejects_cache_shuffle_shard(self, tmp_path):
        p = Pipeline.from_stream(str(tmp_path / "x.libsvm"))
        with pytest.raises(DMLCError, match="cache"):
            p.parse(format="libsvm").cache().batch(8).build()
        import jax
        from jax.sharding import Mesh
        mesh = Mesh(jax.devices("cpu")[:1], ("data",))
        with pytest.raises(DMLCError, match="shard"):
            p.parse(format="libsvm").shard(mesh).build()

    def test_stream_rejects_split_ignoring_format(self, tmp_path):
        pytest.importorskip("pyarrow")
        path = tmp_path / "x.parquet"
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        pq.write_table(pa.table({"label": pa.array(
            np.zeros(4, np.float32))}), str(path))
        with pytest.raises(DMLCError, match="from_stream is not"):
            (Pipeline.from_stream(str(path))
             .parse(format="parquet", label_column="label")
             .batch(2).build())

    def test_streaming_tenant_end_to_end(self, tmp_path):
        """Streaming + multi-tenancy: a tenant-billed streaming
        pipeline surfaces its watermark on the /tenants row."""
        from dmlc_tpu.pipeline import scheduler as sched_mod
        w = _Writer(tmp_path / "feed.libsvm", total=400,
                    slice_rows=100).start()
        s = sched_mod.install()
        try:
            s.register_tenant("feed")
            built = (Pipeline.from_stream(w.path, window_records=128,
                                          poll_interval_s=0.01,
                                          idle_timeout_s=0.5)
                     .parse(format="libsvm").batch(128)
                     .build(tenant="feed"))
            n = sum(1 for _ in built)
            w.join()
            row = s.to_dict()["tenants"]["feed"]
            assert row["pulls"] == n > 0
            assert row["watermark"]["watermark_records"] == 400
            built.close()
        finally:
            sched_mod.uninstall()
