"""Dense-RecordIO decode (ABI 6): the frozen payload contract, the
Python golden parser, native/python byte parity (incl. escaped-magic
multi-frame records and 2/4/8-way sharded parses), the fused padded
pipeline, gang assembly, and the corruption contract (EngineError /
DMLCError, never a crash or a silently short row)."""

import hashlib
import struct

import numpy as np
import pytest

from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC, DenseRecordWriter, decode_dense_record,
    encode_dense_record,
)
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.utils.logging import DMLCError

MAGIC_F32 = np.frombuffer(struct.pack("<I", RECORDIO_MAGIC), "<f4")[0]


def _write_dense(path, rows=600, seed=0, magic_every=17):
    """Dense corpus with ragged rows, zero-value rows, and values whose
    f32 bits equal the frame magic (escaped -> multi-frame records)."""
    rng = np.random.default_rng(seed)
    expect = []
    with create_stream(str(path), "w") as s:
        w = DenseRecordWriter(s)
        for i in range(rows):
            n = int(rng.integers(0, 40))
            vals = rng.standard_normal(n).astype(np.float32)
            if magic_every and i % magic_every == 0 and n >= 3:
                vals[1] = MAGIC_F32
            label = float(i % 5) - 2.0
            w.write(label, vals)
            expect.append((np.float32(label), vals))
        escaped = w.escaped_magic_count
    return expect, escaped


def _stream_content(parser):
    """Stream-invariant content digest + row count: one hash per
    COMPONENT over the concatenated stream (block boundaries differ
    across engines/shard counts, so per-block interleaved hashing
    would diverge on identical content)."""
    hs = {k: hashlib.sha256()
          for k in ("nnz", "label", "index", "value")}
    rows = 0
    parser.before_first()
    while parser.next():
        b = parser.value()
        hs["nnz"].update(
            np.diff(np.asarray(b.offset)).astype("<i8").tobytes())
        hs["label"].update(np.ascontiguousarray(b.label).tobytes())
        hs["index"].update(
            np.ascontiguousarray(b.index).astype("<u4").tobytes())
        hs["value"].update(np.ascontiguousarray(b.value).tobytes())
        rows += b.size
    if hasattr(parser, "destroy"):
        parser.destroy()
    return rows, tuple(h.hexdigest() for h in hs.values())


def _native_built():
    from dmlc_tpu import native
    return native.native_available()


class TestDensePayload:
    def test_roundtrip(self):
        for n in (0, 1, 7, 100):
            vals = np.linspace(-3, 3, n).astype(np.float32)
            label, got = decode_dense_record(
                encode_dense_record(1.25, vals))
            assert label == np.float32(1.25)
            assert np.array_equal(got, vals)

    def test_magic_bit_value_roundtrip(self):
        # a value whose f32 bits ARE the frame magic survives bit-exact
        label, got = decode_dense_record(
            encode_dense_record(0.0, [MAGIC_F32]))
        assert got.tobytes() == struct.pack("<I", RECORDIO_MAGIC)

    def test_length_contract(self):
        payload = encode_dense_record(1.0, [1.0, 2.0])
        with pytest.raises(DMLCError, match="disagrees"):
            decode_dense_record(payload + b"\x00\x00\x00\x00")
        with pytest.raises(DMLCError, match="disagrees"):
            decode_dense_record(payload[:-4])
        with pytest.raises(DMLCError, match="shorter"):
            decode_dense_record(payload[:4])

    def test_writer_escapes_magic(self, tmp_path):
        _, escaped = _write_dense(tmp_path / "a.rec", rows=200,
                                  magic_every=10)
        assert escaped > 0  # the multi-frame decode path is exercised


class TestPythonGolden:
    def test_rows_decode_exactly(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        path = tmp_path / "g.rec"
        expect, _ = _write_dense(path, rows=150)
        p = Parser.create(str(path), format="recordio_dense",
                          engine="python")
        got = []
        p.before_first()
        while p.next():
            b = p.value()
            off = np.asarray(b.offset)
            for i in range(b.size):
                got.append((b.label[i],
                            np.asarray(b.value[off[i]:off[i + 1]])))
        assert len(got) == len(expect)
        for (gl, gv), (el, ev) in zip(got, expect):
            assert gl == el
            assert np.array_equal(gv, ev)
            # indices are the column ordinals by contract
        if hasattr(p, "destroy"):
            p.destroy()

    def test_corrupt_payload_raises(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        from dmlc_tpu.io.recordio import RecordIOWriter
        path = tmp_path / "bad.rec"
        with create_stream(str(path), "w") as s:
            w = RecordIOWriter(s)
            w.write_record(struct.pack("<If", 99, 1.0) + b"\x00" * 8)
        p = Parser.create(str(path), format="recordio_dense",
                          engine="python")
        with pytest.raises(DMLCError, match="disagrees"):
            for _ in p:
                pass

    def test_split_type_guard(self, tmp_path):
        from dmlc_tpu.data.dense_record_parser import DenseRecordParser
        path = tmp_path / "g.rec"
        _write_dense(path, rows=5)
        with pytest.raises(DMLCError, match="split_type"):
            DenseRecordParser(uri=str(path), split_type="text")


@pytest.mark.skipif(not _native_built(), reason="native engine not built")
class TestNativeParity:
    def test_native_vs_python_hash(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        path = tmp_path / "p.rec"
        _write_dense(path, rows=800, seed=3)
        py = _stream_content(Parser.create(
            str(path), format="recordio_dense", engine="python"))
        nat = _stream_content(Parser.create(
            str(path), format="recordio_dense", engine="native"))
        assert py == nat and py[0] == 800

    @pytest.mark.parametrize("shards", [2, 4, 8])
    def test_sharded_parity(self, tmp_path, shards):
        from dmlc_tpu.data.parser import Parser
        path = tmp_path / "s.rec"
        _write_dense(path, rows=700, seed=shards)
        one = _stream_content(Parser.create(
            str(path), format="recordio_dense", engine="native"))
        many = _stream_content(Parser.create(
            str(path), format="recordio_dense", engine="native",
            shards=shards, chunk_size=64 << 10))
        assert one == many

    def test_native_corrupt_payload_raises(self, tmp_path):
        from dmlc_tpu.io.recordio import RecordIOWriter
        from dmlc_tpu.native import bindings
        path = tmp_path / "bad.rec"
        with create_stream(str(path), "w") as s:
            w = RecordIOWriter(s)
            w.write_record(encode_dense_record(1.0, [1.0]))
            w.write_record(struct.pack("<If", 7, 0.0))  # n=7, no values
        p = bindings.NativeDenseRecordParser(str(path))
        with pytest.raises(DMLCError, match="disagrees"):
            while p.next():
                pass
        p.destroy()

    def test_truncated_file_raises(self, tmp_path):
        from dmlc_tpu.native import bindings
        path = tmp_path / "t.rec"
        _write_dense(path, rows=50, magic_every=0)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[:-5])  # cut mid-frame
        p = bindings.NativeDenseRecordParser(str(path))
        with pytest.raises(DMLCError):
            while p.next():
                pass
        p.destroy()


@pytest.mark.skipif(not _native_built(), reason="native engine not built")
class TestPaddedPipeline:
    def _padded(self, path, engine, shards=None):
        from dmlc_tpu.pipeline import Pipeline
        kw = {"shards": shards} if shards else {}
        built = (Pipeline.from_uri(str(path))
                 .parse(format="recordio_dense", engine=engine, **kw)
                 .batch(128, pad=True, nnz_bucket=128 * 40)
                 .build())
        h = hashlib.sha256()
        n = 0
        for b in built:
            for k in sorted(b):
                h.update(k.encode())
                h.update(np.ascontiguousarray(b[k]).tobytes())
            n += 1
        snap = built.stats()
        ap = next((x["assembly_path"] for s in snap["stages"]
                   if (x := s.get("extra") or {}).get("assembly_path")),
                  None)
        built.close()
        return n, h.hexdigest(), ap

    def test_padded_parity_and_fusion(self, tmp_path):
        path = tmp_path / "pp.rec"
        _write_dense(path, rows=900, seed=9)
        py = self._padded(path, "python")
        nat = self._padded(path, "native")
        sh = self._padded(path, "native", shards=2)
        assert py[:2] == nat[:2] == sh[:2]
        assert py[2] == "python-fused"
        # the dense decode AND the sharded gang both lower onto the
        # engine's padded emission — sha-identical streams, pinned
        assert nat[2] == "native-padded"
        assert sh[2] == "native-padded"

    def test_outstanding_leak_probe(self, tmp_path):
        # the padded lease is the ONLY live lease: arenas recycle at
        # cut (single parser AND gang)
        from dmlc_tpu.native import bindings
        path = tmp_path / "lk.rec"
        _write_dense(path, rows=400, seed=4)
        for mk in (lambda: bindings.NativeDenseRecordParser(str(path)),
                   lambda: bindings.NativeShardedTextParser(
                       str(path), shards=3, format="recordio_dense")):
            p = mk()
            batches = 0
            while True:
                b = p.next_padded(64, nnz_bucket=64 * 40)
                if b is None:
                    break
                batches += 1
                assert p.outstanding() == 1, \
                    "source arenas still leased after the cut"
            assert batches > 1
            lease = p.detach()
            if lease is not None:
                lease.release()
            assert p.outstanding() == 0
            p.destroy()

    def test_gang_mode_guard(self, tmp_path):
        from dmlc_tpu.native import bindings
        path = tmp_path / "mg.rec"
        _write_dense(path, rows=100, seed=1)
        p = bindings.NativeShardedTextParser(
            str(path), shards=2, format="recordio_dense")
        assert p.next()
        with pytest.raises(DMLCError, match="padded carry"):
            p.next_padded(64, nnz_bucket=64 * 40)
        # before_first resets the mode; padded then works
        p.before_first()
        assert p.next_padded(64, nnz_bucket=64 * 40) is not None
        with pytest.raises(DMLCError, match="within one"):
            p.next()
        p.destroy()

    def test_before_first_after_destroy_is_noop(self, tmp_path):
        # regression: before_first() on a destroyed sharded parser must
        # stay the safe no-op it was pre-gang (it used to dereference
        # the freed gang handle in C)
        from dmlc_tpu.native import bindings
        path = tmp_path / "dd.rec"
        _write_dense(path, rows=50, seed=2)
        p = bindings.NativeShardedTextParser(
            str(path), shards=2, format="recordio_dense")
        assert p.next_padded(16, nnz_bucket=16 * 40) is not None
        p.destroy()
        p.before_first()  # must not crash
        assert not p.next()
        assert p.outstanding() == 0

    def test_gang_epoch_restart_byte_identical(self, tmp_path):
        from dmlc_tpu.native import bindings
        path = tmp_path / "ep.rec"
        _write_dense(path, rows=300, seed=6)
        p = bindings.NativeShardedTextParser(
            str(path), shards=2, format="recordio_dense")

        def epoch():
            p.before_first()
            h = hashlib.sha256()
            while True:
                b = p.next_padded(64, nnz_bucket=64 * 40)
                if b is None:
                    return h.hexdigest()
                for k in sorted(b):
                    h.update(np.ascontiguousarray(b[k]).tobytes())

        assert epoch() == epoch()
        p.destroy()
