"""Image-record decode (ABI 8): the frozen HWC u8 payload contract,
the Python golden parser, native/python byte parity (incl.
escaped-magic pixel runs and sharded parses), the fused padded
pipeline producing DECODED fixed-shape batches, and the corruption
contract (EngineError / DMLCError, never a crash or shifted pixels)."""

import hashlib
import struct

import numpy as np
import pytest

from dmlc_tpu.io.recordio import (
    RECORDIO_MAGIC, ImageRecordWriter, decode_image_record,
    encode_image_record,
)
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.utils.logging import DMLCError

MAGIC_BYTES = np.frombuffer(struct.pack("<I", RECORDIO_MAGIC), np.uint8)


def _write_images(path, records=200, shape=(8, 10, 3), seed=0,
                  magic_every=13, ragged=False):
    """Image corpus, optionally ragged shapes; every ``magic_every``-th
    record carries the frame magic at a 4-aligned pixel offset (the
    16-byte payload header keeps pixel offsets 4-aligned), so the
    escaped multi-frame path runs inside the corpus."""
    rng = np.random.default_rng(seed)
    expect = []
    with create_stream(str(path), "w") as s:
        w = ImageRecordWriter(s)
        for i in range(records):
            hwc = shape
            if ragged and i % 3 == 0:
                hwc = (4 + i % 5, 6, 1 + i % 3)
            px = rng.integers(0, 256, hwc, dtype=np.uint8)
            if magic_every and i % magic_every == 0:
                px.reshape(-1)[4:8] = MAGIC_BYTES
            label = float(i % 7) - 3.0
            w.write(label, px)
            expect.append((np.float32(label), px))
        escaped = w.escaped_magic_count
    return expect, escaped


def _stream_content(parser):
    hs = {k: hashlib.sha256() for k in ("nnz", "label", "index", "value")}
    rows = 0
    parser.before_first()
    while parser.next():
        b = parser.value()
        hs["nnz"].update(
            np.diff(np.asarray(b.offset)).astype("<i8").tobytes())
        hs["label"].update(np.ascontiguousarray(b.label).tobytes())
        hs["index"].update(
            np.ascontiguousarray(b.index).astype("<u4").tobytes())
        hs["value"].update(np.ascontiguousarray(b.value).tobytes())
        rows += b.size
    if hasattr(parser, "destroy"):
        parser.destroy()
    return {k: h.hexdigest() for k, h in hs.items()}, rows


def _have_native():
    from dmlc_tpu import native
    return native.native_available()


class TestImagePayload:
    def test_round_trip(self):
        rng = np.random.default_rng(1)
        px = rng.integers(0, 256, (5, 7, 3), dtype=np.uint8)
        label, got = decode_image_record(encode_image_record(2.5, px))
        assert label == np.float32(2.5)
        np.testing.assert_array_equal(got, px)

    def test_grayscale_gains_channel_axis(self):
        px = np.arange(12, dtype=np.uint8).reshape(3, 4)
        _, got = decode_image_record(encode_image_record(0.0, px))
        assert got.shape == (3, 4, 1)
        np.testing.assert_array_equal(got.reshape(3, 4), px)

    def test_strict_length_contract(self):
        payload = encode_image_record(1.0, np.zeros((2, 2, 3), np.uint8))
        with pytest.raises(DMLCError, match="disagrees"):
            decode_image_record(payload[:-1])
        with pytest.raises(DMLCError, match="shorter"):
            decode_image_record(payload[:10])
        # shape lies: bump the declared width
        bad = bytearray(payload)
        bad[4:8] = struct.pack("<I", 5)
        with pytest.raises(DMLCError, match="disagrees"):
            decode_image_record(bytes(bad))

    def test_magic_bits_escape_and_stitch(self, tmp_path):
        p = tmp_path / "m.rec"
        expect, escaped = _write_images(p, records=40, magic_every=2)
        assert escaped > 0
        from dmlc_tpu.data.parser import Parser
        parser = Parser.create(str(p), 0, 1, format="recordio_image",
                               engine="python")
        rows = []
        for b in parser:
            for r in range(b.size):
                lo, hi = b.offset[r], b.offset[r + 1]
                rows.append((b.label[r], b.value[lo:hi]))
        assert len(rows) == len(expect)
        for (lab, vals), (elab, epx) in zip(rows, expect):
            assert lab == elab
            np.testing.assert_array_equal(
                vals, epx.reshape(-1).astype(np.float32))


class TestGoldenParser:
    def test_decode_matches_writer(self, tmp_path):
        p = tmp_path / "g.rec"
        expect, _ = _write_images(p, records=60, ragged=True)
        from dmlc_tpu.data.parser import Parser
        parser = Parser.create(str(p), 0, 1, format="recordio_image",
                               engine="python")
        seen = 0
        for b in parser:
            for r in range(b.size):
                lo, hi = b.offset[r], b.offset[r + 1]
                elab, epx = expect[seen]
                assert b.label[r] == elab
                np.testing.assert_array_equal(
                    b.value[lo:hi], epx.reshape(-1).astype(np.float32))
                np.testing.assert_array_equal(
                    b.index[lo:hi], np.arange(hi - lo, dtype=np.uint32))
                seen += 1
        assert seen == 60

    def test_split_type_guard(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        p = tmp_path / "g.rec"
        _write_images(p, records=5)
        with pytest.raises(DMLCError, match="split_type"):
            Parser.create(str(p), 0, 1, format="recordio_image",
                          engine="python", split_type="text")


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestNativeParity:
    def test_byte_parity(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        p = tmp_path / "n.rec"
        _write_images(p, records=300, ragged=True)
        g, grows = _stream_content(
            Parser.create(str(p), 0, 1, format="recordio_image",
                          engine="python"))
        n, nrows = _stream_content(
            Parser.create(str(p), 0, 1, format="recordio_image",
                          engine="native"))
        assert grows == nrows == 300
        assert g == n

    @pytest.mark.parametrize("shards", [2, 4])
    def test_sharded_byte_parity(self, tmp_path, shards):
        from dmlc_tpu.data.parser import Parser
        p = tmp_path / "s.rec"
        _write_images(p, records=240)
        one, _ = _stream_content(
            Parser.create(str(p), 0, 1, format="recordio_image",
                          engine="native"))
        sh, rows = _stream_content(
            Parser.create(str(p), 0, 1, format="recordio_image",
                          engine="native", shards=shards))
        assert rows == 240
        assert sh == one

    def test_part_split_parity(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        p = tmp_path / "p.rec"
        _write_images(p, records=200)
        for k in range(3):
            g, grows = _stream_content(
                Parser.create(str(p), k, 3, format="recordio_image",
                              engine="python"))
            n, nrows = _stream_content(
                Parser.create(str(p), k, 3, format="recordio_image",
                              engine="native"))
            assert g == n and grows == nrows

    def test_corrupt_payload_rejected_both_engines(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        from dmlc_tpu.io.recordio import RecordIOWriter
        p = tmp_path / "bad.rec"
        with create_stream(str(p), "w") as s:
            w = RecordIOWriter(s)
            w.write_record(encode_image_record(
                1.0, np.zeros((4, 4, 3), np.uint8)))
            # a payload whose declared shape disagrees with its length
            good = encode_image_record(0.0, np.zeros((2, 2, 1), np.uint8))
            w.write_record(good[:-2])
        for engine in ("python", "native"):
            parser = Parser.create(str(p), 0, 1,
                                   format="recordio_image",
                                   engine=engine)
            with pytest.raises(DMLCError,
                               match="disagrees|shorter"):
                for _ in parser:
                    pass
            if hasattr(parser, "destroy"):
                parser.destroy()

    def test_leak_probe_outstanding_zero(self, tmp_path):
        from dmlc_tpu.data.parser import Parser
        p = tmp_path / "l.rec"
        _write_images(p, records=60)
        parser = Parser.create(str(p), 0, 1, format="recordio_image",
                               engine="native")
        for _ in range(2):
            parser.before_first()
            while parser.next():
                pass
            assert parser.outstanding() == 0
        parser.destroy()


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestPaddedPipeline:
    def test_decoded_batches_fuse_and_match(self, tmp_path):
        """The config-3 acceptance shape: uniform-shape .rec -> padded
        device-layout batches, python-fused and native-padded
        byte-identical; the native lowering must actually fuse."""
        from dmlc_tpu.pipeline import Pipeline
        p = tmp_path / "pipe.rec"
        h, w, c = 6, 8, 3
        _write_images(p, records=150, shape=(h, w, c))
        rows = 32
        nnz = rows * h * w * c

        def run(engine):
            built = (Pipeline.from_uri(str(p))
                     .parse(format="recordio_image", engine=engine)
                     .batch(rows, pad=True, nnz_bucket=nnz)
                     .build())
            hh = hashlib.sha256()
            shapes = []
            for b in built:
                for k in sorted(b):
                    hh.update(k.encode())
                    hh.update(np.ascontiguousarray(b[k]).tobytes())
                shapes.append(int(b["num_rows"]))
            snap = built.stats()
            ap = next((x["assembly_path"] for s in snap["stages"]
                       if (x := s.get("extra") or {}).get(
                           "assembly_path")), None)
            built.close()
            return hh.hexdigest(), shapes, ap

        hg, sg, apg = run("python")
        hn, sn, apn = run("native")
        assert apg == "python-fused" and apn == "native-padded"
        assert sg == sn
        assert hg == hn
        # decoded batches: the padded value block reshapes to images
        built = (Pipeline.from_uri(str(p))
                 .parse(format="recordio_image", engine="native")
                 .batch(rows, pad=True, nnz_bucket=nnz).build())
        batch = next(iter(built))
        imgs = np.asarray(batch["value"]).reshape(rows, h, w, c)
        assert imgs.dtype == np.float32
        assert imgs.min() >= 0.0 and imgs.max() <= 255.0
        built.close()
