"""ABI-5 native batch assembly (ISSUE 7): byte-parity of
``dtp_parser_next_padded`` with the Python fused golden across the edge
cases (empty rows, short last batch, mid-file schema flip,
release-after-EOF), padded-lease leak discipline (source arenas return
to the free list the moment a batch is cut), sharded single-file parse
byte-identity, and the double-buffered staging overlap proof
(device.xfer spans intersecting the next batch's device.assemble)."""

import numpy as np
import pytest

from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.rowblock import RowBlockContainer
from dmlc_tpu.pipeline import Pipeline
from dmlc_tpu.utils.logging import DMLCError

from tests.test_native import _ensure_native

pytestmark = pytest.mark.skipif(not _ensure_native(),
                                reason="native engine not buildable")


def _write_libsvm(tmp_path, name="a.libsvm", rows=3000, seed=0,
                  qid_from=None, max_nnz=9, min_nnz=0):
    """libsvm corpus with zero-nnz rows and an optional mid-file qid
    schema flip (rows >= qid_from carry qid:)."""
    rng = np.random.RandomState(seed)
    lines = []
    for i in range(rows):
        nnz = rng.randint(min_nnz, max_nnz + 1)
        idx = np.sort(rng.choice(2000, nnz, replace=False))
        feats = " ".join(f"{j}:{v:.6f}" for j, v in zip(idx, rng.rand(nnz)))
        qid = (f"qid:{i // 50} " if qid_from is not None and i >= qid_from
               else "")
        lines.append(f"{(-1) ** i} {qid}{feats}".rstrip())
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _write_libfm(tmp_path, rows=1500, seed=3):
    rng = np.random.RandomState(seed)
    lines = []
    for i in range(rows):
        nnz = rng.randint(1, 7)
        idx = np.sort(rng.choice(900, nnz, replace=False))
        feats = " ".join(f"{rng.randint(0, 12)}:{j}:{v:.6f}"
                         for j, v in zip(idx, rng.rand(nnz)))
        lines.append(f"{i % 2} {feats}")
    p = tmp_path / "a.libfm"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _drain_padded(uri, engine, rows, nnz_bucket, fmt="libsvm",
                  chunk_size=32 << 10, parse_kw=None, **kw):
    """Run uri through parse(engine).batch(pad=True); return the list
    of deep-copied padded dicts plus the assembly_path the stage
    reported."""
    built = (Pipeline.from_uri(uri)
             .parse(format=fmt, engine=engine, chunk_size=chunk_size,
                    **(parse_kw or {}))
             .batch(rows, pad=True, nnz_bucket=nnz_bucket, **kw)
             .build())
    out = []
    for b in built:
        out.append({k: np.array(v, copy=True) for k, v in b.items()})
    snap = built.stats()
    built.close()
    path = None
    for st in snap["stages"]:
        path = st.get("extra", {}).get("assembly_path") or path
    return out, path


def _assert_batches_equal(native, python):
    assert len(native) == len(python)
    for i, (n, p) in enumerate(zip(native, python)):
        assert set(n.keys()) == set(p.keys()), f"batch {i} key set"
        for k in p:
            np.testing.assert_array_equal(
                np.asarray(n[k]), np.asarray(p[k]),
                err_msg=f"batch {i} key {k}")
            assert np.asarray(n[k]).dtype == np.asarray(p[k]).dtype, \
                f"batch {i} key {k} dtype"


class TestPaddedParity:
    """Native next_padded vs the Python fused golden, batch for batch:
    same key sets, dtypes, and bytes — the ABI-5 parity pin."""

    def test_libsvm_with_empty_rows_and_short_last(self, tmp_path):
        # min_nnz=0 exercises empty rows; 3000 % 128 != 0 exercises the
        # short last batch (num_rows < rows under the padding)
        uri = _write_libsvm(tmp_path, rows=3000, min_nnz=0)
        nat, nat_path = _drain_padded(uri, "native", 128, 128 * 12)
        py, py_path = _drain_padded(uri, "python", 128, 128 * 12)
        assert nat_path == "native-padded", \
            "batch() on a native parse must lower onto the engine"
        assert py_path == "python-fused"
        _assert_batches_equal(nat, py)
        last = nat[-1]
        assert int(last["num_rows"]) == 3000 % 128  # really short
        assert last["label"].shape == nat[0]["label"].shape  # same bucket

    def test_qid_schema_flip_mid_file(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=2000, qid_from=1000)
        nat, nat_path = _drain_padded(uri, "native", 100, 100 * 12)
        py, _ = _drain_padded(uri, "python", 100, 100 * 12)
        assert nat_path == "native-padded"
        _assert_batches_equal(nat, py)
        assert any("qid" in b for b in nat)

    def test_want_qid_forces_presence_everywhere(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=600, qid_from=None)
        nat, _ = _drain_padded(uri, "native", 64, 64 * 12, want_qid=True)
        py, _ = _drain_padded(uri, "python", 64, 64 * 12, want_qid=True)
        _assert_batches_equal(nat, py)
        assert all("qid" in b and np.all(np.asarray(b["qid"]) == -1)
                   for b in nat)

    def test_libfm_field_parity(self, tmp_path):
        uri = _write_libfm(tmp_path)
        nat, nat_path = _drain_padded(uri, "native", 96, 96 * 8,
                                      fmt="libfm")
        py, _ = _drain_padded(uri, "python", 96, 96 * 8, fmt="libfm")
        assert nat_path == "native-padded"
        _assert_batches_equal(nat, py)
        assert all("field" in b for b in nat)

    def test_csv_parity(self, tmp_path):
        rng = np.random.RandomState(7)
        lines = [f"{i % 2}," + ",".join(f"{v:.5f}" for v in rng.rand(6))
                 for i in range(1100)]
        p = tmp_path / "a.csv"
        p.write_text("\n".join(lines) + "\n")
        nat, nat_path = _drain_padded(str(p), "native", 80, 80 * 8,
                                      fmt="csv")
        py, _ = _drain_padded(str(p), "python", 80, 80 * 8, fmt="csv")
        assert nat_path == "native-padded"
        _assert_batches_equal(nat, py)

    def test_row_bucket_wider_than_rows(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=500)
        nat, _ = _drain_padded(uri, "native", 64, 64 * 12, row_bucket=96)
        py, _ = _drain_padded(uri, "python", 64, 64 * 12, row_bucket=96)
        _assert_batches_equal(nat, py)
        assert nat[0]["label"].shape[-1] == 96

    def test_blank_only_file_yields_nothing(self, tmp_path):
        # chunks that parse to ZERO rows (blank lines) must not emit an
        # empty padded batch — the stream ends with None, no lease held
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        p = tmp_path / "blank.libsvm"
        p.write_bytes(b"\n\n\n")
        parser = NativeLibSVMParser(str(p), 0, 1)
        assert parser.next_padded(64, 64, 512) is None
        assert parser.outstanding() == 0
        parser.destroy()

    def test_blank_runs_between_rows_parity(self, tmp_path):
        p = tmp_path / "gaps.libsvm"
        p.write_text("\n".join(
            ("" if i % 3 else f"{i % 2} {i % 40}:{i}.25")
            for i in range(400)) + "\n")
        nat, _ = _drain_padded(str(p), "native", 32, 64)
        py, _ = _drain_padded(str(p), "python", 32, 64)
        _assert_batches_equal(nat, py)


class TestPaddedLease:
    """Lease lifetime and the leak probe: padded emission must hand the
    source CSR arenas straight back to the free list (the PR 2
    RSS-retention class), with the padded block the ONLY outstanding
    lease."""

    def _parser(self, tmp_path, rows=1200):
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        uri = _write_libsvm(tmp_path, rows=rows, name="lease.libsvm")
        return NativeLibSVMParser(uri, 0, 1, chunk_size=8 << 10)

    def test_arena_returns_to_free_list_after_emission(self, tmp_path):
        parser = self._parser(tmp_path)
        n_batches = 0
        while True:
            b = parser.next_padded(64, 64, 64 * 12)
            if b is None:
                break
            n_batches += 1
            # the padded lease is the ONLY thing outstanding: every
            # source arena the batch was cut from is back in the pool
            # even while the batch's views are live
            assert parser.outstanding() == 1
        assert n_batches >= 10
        # EOF released the last padded lease too
        assert parser.outstanding() == 0
        parser.destroy()

    def test_release_after_eof(self, tmp_path):
        parser = self._parser(tmp_path, rows=900)
        held = []
        while True:
            b = parser.next_padded(64, 64, 64 * 12)
            if b is None:
                break
            snap = {k: np.array(v, copy=True) for k, v in b.items()}
            held.append((snap, b, parser.detach()))
        assert len(held) >= 2
        assert parser.next_padded(64, 64, 64 * 12) is None  # EOF sticky
        # every detached padded block survives EOF byte-for-byte
        assert parser.outstanding() == len(held)
        for snap, b, _lease in held:
            for k, v in snap.items():
                np.testing.assert_array_equal(np.asarray(b[k]), v)
        for _snap, _b, lease in held:
            lease.release()
        assert parser.outstanding() == 0
        parser.destroy()

    def test_mode_guard_next_then_padded(self, tmp_path):
        parser = self._parser(tmp_path, rows=400)
        assert parser.next()
        with pytest.raises(DMLCError, match="before_first"):
            parser.next_padded(64, 64, 64 * 12)
        parser.destroy()

    def test_before_first_recycles_carry(self, tmp_path):
        # a partially consumed arena (the padded carry) goes back to
        # the pool on before_first and the re-read stream is intact
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        uri = _write_libsvm(tmp_path, rows=1000, name="carry.libsvm")
        parser = NativeLibSVMParser(uri, 0, 1, chunk_size=8 << 10)
        assert parser.next_padded(32, 32, 32 * 12) is not None
        parser.before_first()
        assert parser.outstanding() == 0
        c = RowBlockContainer(np.uint32)
        while parser.next():
            c.push_block(parser.value())
        parser.destroy()
        ref = RowBlockContainer(np.uint32)
        p = Parser.create(uri, 0, 1, format="libsvm", engine="python")
        for blk in p:
            ref.push_block(blk)
        assert c.get_block().content_hash() == ref.get_block().content_hash()


def _hash_parse(uri, engine, fmt="libsvm", **kw):
    c = RowBlockContainer(np.uint32)
    p = Parser.create(uri, 0, 1, format=fmt, engine=engine, **kw)
    for b in p:
        c.push_block(b)
    if hasattr(p, "destroy"):
        p.destroy()
    return c.get_block().content_hash()


class TestShardedSingleFile:
    """shards=N splits ONE file across N native parsers on aligned
    byte ranges; the reassembled stream must be byte-identical to the
    1-parser stream (and the python golden)."""

    def test_byte_identity_vs_one_parser(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=6000, name="big.libsvm")
        base = _hash_parse(uri, "native", chunk_size=16 << 10)
        assert base == _hash_parse(uri, "python")
        for shards in (2, 3, 4):
            assert _hash_parse(uri, "native", shards=shards,
                               chunk_size=16 << 10) == base, \
                f"shards={shards} reordered or corrupted the stream"

    def test_dispatch_returns_sharded_parser(self, tmp_path):
        from dmlc_tpu.native.bindings import NativeShardedTextParser
        uri = _write_libsvm(tmp_path, rows=300)
        p = Parser.create(uri, 0, 1, format="libsvm", engine="native",
                          shards=2)
        assert isinstance(p, NativeShardedTextParser)
        p.destroy()

    def test_tiny_file_more_shards_than_content(self, tmp_path):
        # shards beyond the file's aligned ranges leave trailing
        # sub-parsers empty; the stream is still exactly the input
        uri = _write_libsvm(tmp_path, rows=40, name="tiny.libsvm")
        assert (_hash_parse(uri, "native", shards=8)
                == _hash_parse(uri, "python"))

    def test_nested_split_runs_unsharded(self, tmp_path):
        # under an outer part/num_parts split, shards= is a no-op (the
        # alignment rule must not apply twice) — parity per part
        uri = _write_libsvm(tmp_path, rows=2000, name="parts.libsvm")
        for k in (0, 1):
            c1 = RowBlockContainer(np.uint32)
            p = Parser.create(uri, k, 2, format="libsvm", engine="native")
            for b in p:
                c1.push_block(b)
            p.destroy()
            c2 = RowBlockContainer(np.uint32)
            p = Parser.create(uri, k, 2, format="libsvm", engine="native",
                              shards=4)
            for b in p:
                c2.push_block(b)
            p.destroy()
            assert c1.get_block().content_hash() == \
                c2.get_block().content_hash()

    def test_sharded_padded_parity(self, tmp_path):
        # sharded parse under padded assembly (ABI 6): the GANG handle
        # cuts padded batches across the sub-parsers' shard-ordered
        # arena streams in C (dtp_gang_next_padded), so the lowering
        # fuses — assembly_path is native-padded — and batches stay
        # byte-identical to the unsharded python golden (a batch MAY
        # straddle the shard boundary; the gang cuts it exactly where
        # the 1-parser stream would)
        uri = _write_libsvm(tmp_path, rows=4000, name="sp.libsvm")
        nat, nat_path = _drain_padded(uri, "native", 128, 128 * 12,
                                      chunk_size=16 << 10,
                                      parse_kw={"shards": 3})
        py, _ = _drain_padded(uri, "python", 128, 128 * 12)
        assert nat_path == "native-padded"
        _assert_batches_equal(nat, py)


class TestSteadyPathEndToEnd:
    """Padded leases must survive the downstream stages: prefetch
    detaches them (release-on-next-pull), to_device routes the batch
    through a staging slot and frees the lease at copy time."""

    def test_padded_through_prefetch_parity(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=2000)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="native",
                        chunk_size=32 << 10)
                 .batch(100, pad=True, nnz_bucket=100 * 12)
                 .prefetch(depth=3).build())
        nat = [{k: np.array(v, copy=True) for k, v in b.items()}
               for b in built]
        snap = built.stats()
        built.close()
        path = None
        for st in snap["stages"]:
            path = st.get("extra", {}).get("assembly_path") or path
        assert path == "native-padded"
        py, _ = _drain_padded(uri, "python", 100, 100 * 12)
        _assert_batches_equal(nat, py)

    def test_full_steady_path_to_device(self, tmp_path):
        uri = _write_libsvm(tmp_path, rows=1500)
        built = (Pipeline.from_uri(uri)
                 .parse(format="libsvm", engine="native",
                        chunk_size=32 << 10)
                 .batch(128, pad=True, nnz_bucket=128 * 12)
                 .to_device(window=2).build())
        dev = [{k: np.asarray(v) for k, v in b.items()} for b in built]
        built.close()
        py, _ = _drain_padded(uri, "python", 128, 128 * 12)
        # values only: jax with x64 off canonicalizes int64 device
        # arrays to int32, so the device batches' dtypes legitimately
        # differ from the host layout
        assert len(dev) == len(py)
        for i, (d, p) in enumerate(zip(dev, py)):
            assert set(d.keys()) == set(p.keys()), f"batch {i} key set"
            for k in p:
                np.testing.assert_array_equal(
                    d[k], np.asarray(p[k]), err_msg=f"batch {i} key {k}")


class TestStagingOverlap:
    """Double-buffered staging: batch N's H2D window must overlap batch
    N+1's staged assembly on one trace timeline — THE acceptance
    criterion's span-intersection assert."""

    def _batches(self, n=6, side=192):
        return [{"x": np.full((side, side), i, np.float32),
                 "y": np.full((side,), i, np.float32)} for i in range(n)]

    def test_xfer_overlaps_next_assemble(self):
        from dmlc_tpu.obs import trace as obs_trace
        from dmlc_tpu.parallel.device_iter import device_prefetch
        batches = self._batches()
        rec = obs_trace.start()
        try:
            out = list(device_prefetch(iter(batches), size=2,
                                       staging=True))
        finally:
            obs_trace.stop()
        assert len(out) == len(batches)
        spans = {"device.xfer": [], "device.assemble": []}
        for ph, name, _cat, t, d, _tid, _args in rec.events():
            if ph == "X" and name in spans:
                spans[name].append((t, t + d))
        assert len(spans["device.xfer"]) == len(batches)
        assert len(spans["device.assemble"]) == len(batches)
        # non-empty intersection with an assemble that STARTED inside
        # the transfer's enqueue→ready window: the overlap is real, not
        # a pair of adjacent spans
        overlapping = [
            (x, a)
            for x in spans["device.xfer"]
            for a in spans["device.assemble"]
            if x[0] < a[0] < x[1]
        ]
        assert overlapping, \
            "no H2D transfer window overlapped a later staged assembly"

    def test_staged_batches_faithful(self):
        from dmlc_tpu.parallel.device_iter import device_prefetch
        batches = self._batches(n=5, side=32)
        out = list(device_prefetch(iter(batches), size=2, staging=True))
        assert len(out) == 5
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b["x"]),
                                          batches[i]["x"])
            np.testing.assert_array_equal(np.asarray(b["y"]),
                                          batches[i]["y"])

    def test_slot_reuse_and_gauge(self):
        from dmlc_tpu.obs.metrics import REGISTRY
        from dmlc_tpu.parallel.device_iter import HostStaging
        pool = HostStaging(slots=2, alias_unsafe=False)
        a = {"x": np.arange(64, dtype=np.float32)}
        s1 = pool.stage(a)
        assert s1["x"] is not a["x"]
        np.testing.assert_array_equal(s1["x"], a["x"])
        assert pool.in_flight == 1
        assert REGISTRY.gauge("device.staging").value == 1
        pool.release(s1)
        assert REGISTRY.gauge("device.staging").value == 0
        # fixed-shape steady state: the SAME buffer serves batch 2
        s2 = pool.stage({"x": np.zeros(64, np.float32)})
        assert s2["x"] is s1["x"]
        pool.release(s2)

    def test_alias_unsafe_never_reuses(self):
        from dmlc_tpu.parallel.device_iter import HostStaging
        pool = HostStaging(slots=2, alias_unsafe=True)
        a = {"x": np.arange(16, dtype=np.float32)}
        s1 = pool.stage(a)
        pool.release(s1)
        s2 = pool.stage(a)
        assert s2["x"] is not s1["x"]  # consumer may alias s1's memory
        pool.release(s2)
