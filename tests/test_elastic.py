"""Elastic recovery (VERDICT r1 #9; SURVEY §5.3).

The framework's recovery story is DETERMINISM: a shard stream is a pure
function of (uri, part, num_parts, seed, epoch), so a worker that dies
mid-epoch is recovered by restarting it with the same coordinates — the
replacement replays the byte-identical record stream from the top (or
from a batch checkpoint, since batch order is deterministic too). The
reference reaches the same property via its `recover` handshake +
DMLC_NUM_ATTEMPT rejoin (tracker.py); here jax.distributed restart +
deterministic InputSplit make data-side recovery trivial — these tests
make that claim executable. Documented in docs/ARCHITECTURE.md.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

# the worker prints one line per block: "<blocks_done> <running_hash>"
_WORKER = r"""
import hashlib, sys
import jax
jax.config.update("jax_platforms", "cpu")
from dmlc_tpu.data.parser import Parser
uri, part, nparts, seed, epoch = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]),
                                  int(sys.argv[5]))
h = hashlib.sha256()
p = Parser.create(uri, part, nparts, format="libsvm", chunk_size=65536)
n = 0
for _ in range(epoch + 1):       # deterministic epoch replay
    p.before_first()
    while p.next():
        h.update(p.value().copy().content_hash().encode())
        n += 1
        print(f"{n} {h.hexdigest()}", flush=True)
if hasattr(p, "destroy"):
    p.destroy()
"""

_SHUFFLE_WORKER = r"""
import hashlib, sys
import jax
jax.config.update("jax_platforms", "cpu")
from dmlc_tpu.io.input_split_shuffle import InputSplitShuffle
uri, part, nparts, seed, epoch = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]),
                                  int(sys.argv[5]))
sp = InputSplitShuffle.create(uri, part, nparts, "text",
                              num_shuffle_parts=4, seed=seed)
h = hashlib.sha256()
for e in range(epoch + 1):       # epoch-reshuffled but seed-deterministic
    sp.before_first()
    n = 0
    while True:
        rec = sp.next_record()
        if rec is None:
            break
        h.update(rec)
        n += 1
        print(f"{n} {h.hexdigest()}", flush=True)
"""


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    rng = np.random.RandomState(3)
    lines = [f"{i % 2} " + " ".join(
        f"{j}:{rng.rand():.5f}"
        for j in np.sort(rng.choice(500, rng.randint(1, 9), replace=False)))
        for i in range(30000)]
    p = tmp_path_factory.mktemp("el") / "d.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


def _run_worker(code, args, kill_after_lines=None, timeout=120):
    """Run the worker; optionally SIGKILL it after N progress lines.
    Returns the progress lines seen."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join(
               [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
               + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
    proc = subprocess.Popen([sys.executable, "-c", code] + [str(a) for a in args],
                            env=env, stdout=subprocess.PIPE, text=True)
    lines = []
    try:
        deadline = time.monotonic() + timeout
        for line in proc.stdout:
            lines.append(line.strip())
            if kill_after_lines and len(lines) >= kill_after_lines:
                os.kill(proc.pid, signal.SIGKILL)  # die mid-epoch, hard
                break
            if time.monotonic() > deadline:
                raise TimeoutError("worker too slow")
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    return lines


class TestElasticRecovery:
    def test_killed_worker_replacement_replays_identical_stream(
            self, data_file):
        # clean run: the golden stream hash for (uri, part=1, nparts=3)
        clean = _run_worker(_WORKER, [data_file, 1, 3, 0, 0])
        assert len(clean) >= 3, "fixture should produce several blocks"
        # kill a worker HARD mid-epoch (SIGKILL: no cleanup, no flush)
        killed = _run_worker(_WORKER, [data_file, 1, 3, 0, 0],
                             kill_after_lines=1)
        assert len(killed) >= 1 and killed[0] == clean[0]
        # elastic recovery: a REPLACEMENT worker with the same
        # (uri, part, nparts, seed, epoch) replays the identical stream
        replay = _run_worker(_WORKER, [data_file, 1, 3, 0, 0])
        assert replay == clean, \
            "replacement worker diverged from the killed worker's stream"

    def test_partial_progress_is_a_prefix(self, data_file):
        # mid-stream kill leaves a PREFIX of the deterministic stream:
        # a restart can also fast-forward past already-consumed batches
        clean = _run_worker(_WORKER, [data_file, 0, 3, 0, 0])
        killed = _run_worker(_WORKER, [data_file, 0, 3, 0, 0],
                             kill_after_lines=2)
        assert killed == clean[:len(killed)]

    def test_second_epoch_stream_is_deterministic(self, data_file):
        a = _run_worker(_WORKER, [data_file, 2, 3, 0, 1])
        b = _run_worker(_WORKER, [data_file, 2, 3, 0, 1])
        assert a and a == b

    def test_shuffled_split_recovers_by_seed(self, data_file):
        # shuffled reads are ALSO recoverable: same seed => same order,
        # across a hard kill and restart
        clean = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1])
        assert len(clean) > 10
        _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1],
                    kill_after_lines=3)
        replay = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1])
        assert replay == clean
        # different seed => different order (the shuffle is real)
        other = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 8, 1])
        assert other != clean
