"""Elastic recovery (VERDICT r1 #9; SURVEY §5.3) + the rendezvous
membership plane (ROADMAP item 1).

The framework's recovery story has two layers. The DETERMINISM layer
(TestElasticRecovery): a shard stream is a pure function of (uri,
part, num_parts, seed, epoch), so a worker that dies mid-epoch can be
recovered by restarting it with the same coordinates — the
replacement replays the byte-identical record stream from the top.
The MEMBERSHIP layer (dmlc_tpu.rendezvous, the reference's
tracker.py gone elastic): a gang that loses or gains a member does
NOT restart — the rendezvous service bumps the membership epoch, the
survivors re-derive shard ownership as a pure function of (num_parts,
world, rank), and each adopted part RESUMES from the committed
progress prefix instead of replaying. Epoch-fenced progress commits
make the coverage exactly-once across any interleaving of reshards
(TestRendezvousMembership, TestElasticGangAcceptance). Documented in
docs/rendezvous.md and docs/ARCHITECTURE.md.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

# the worker prints one line per block: "<blocks_done> <running_hash>"
_WORKER = r"""
import hashlib, sys
import jax
jax.config.update("jax_platforms", "cpu")
from dmlc_tpu.data.parser import Parser
uri, part, nparts, seed, epoch = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]),
                                  int(sys.argv[5]))
h = hashlib.sha256()
p = Parser.create(uri, part, nparts, format="libsvm", chunk_size=65536)
n = 0
for _ in range(epoch + 1):       # deterministic epoch replay
    p.before_first()
    while p.next():
        h.update(p.value().copy().content_hash().encode())
        n += 1
        print(f"{n} {h.hexdigest()}", flush=True)
if hasattr(p, "destroy"):
    p.destroy()
"""

_SHUFFLE_WORKER = r"""
import hashlib, sys
import jax
jax.config.update("jax_platforms", "cpu")
from dmlc_tpu.io.input_split_shuffle import InputSplitShuffle
uri, part, nparts, seed, epoch = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), int(sys.argv[4]),
                                  int(sys.argv[5]))
sp = InputSplitShuffle.create(uri, part, nparts, "text",
                              num_shuffle_parts=4, seed=seed)
h = hashlib.sha256()
for e in range(epoch + 1):       # epoch-reshuffled but seed-deterministic
    sp.before_first()
    n = 0
    while True:
        rec = sp.next_record()
        if rec is None:
            break
        h.update(rec)
        n += 1
        print(f"{n} {h.hexdigest()}", flush=True)
"""


@pytest.fixture(scope="module")
def data_file(tmp_path_factory):
    rng = np.random.RandomState(3)
    lines = [f"{i % 2} " + " ".join(
        f"{j}:{rng.rand():.5f}"
        for j in np.sort(rng.choice(500, rng.randint(1, 9), replace=False)))
        for i in range(30000)]
    p = tmp_path_factory.mktemp("el") / "d.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


def _run_worker(code, args, kill_after_lines=None, timeout=120):
    """Run the worker; optionally SIGKILL it after N progress lines.
    Returns the progress lines seen."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join(
               [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
               + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
    proc = subprocess.Popen([sys.executable, "-c", code] + [str(a) for a in args],
                            env=env, stdout=subprocess.PIPE, text=True)
    lines = []
    try:
        deadline = time.monotonic() + timeout
        for line in proc.stdout:
            lines.append(line.strip())
            if kill_after_lines and len(lines) >= kill_after_lines:
                os.kill(proc.pid, signal.SIGKILL)  # die mid-epoch, hard
                break
            if time.monotonic() > deadline:
                raise TimeoutError("worker too slow")
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    return lines


class TestElasticRecovery:
    def test_killed_worker_replacement_replays_identical_stream(
            self, data_file):
        # clean run: the golden stream hash for (uri, part=1, nparts=3)
        clean = _run_worker(_WORKER, [data_file, 1, 3, 0, 0])
        assert len(clean) >= 3, "fixture should produce several blocks"
        # kill a worker HARD mid-epoch (SIGKILL: no cleanup, no flush)
        killed = _run_worker(_WORKER, [data_file, 1, 3, 0, 0],
                             kill_after_lines=1)
        assert len(killed) >= 1 and killed[0] == clean[0]
        # elastic recovery: a REPLACEMENT worker with the same
        # (uri, part, nparts, seed, epoch) replays the identical stream
        replay = _run_worker(_WORKER, [data_file, 1, 3, 0, 0])
        assert replay == clean, \
            "replacement worker diverged from the killed worker's stream"

    def test_partial_progress_is_a_prefix(self, data_file):
        # mid-stream kill leaves a PREFIX of the deterministic stream:
        # a restart can also fast-forward past already-consumed batches
        clean = _run_worker(_WORKER, [data_file, 0, 3, 0, 0])
        killed = _run_worker(_WORKER, [data_file, 0, 3, 0, 0],
                             kill_after_lines=2)
        assert killed == clean[:len(killed)]

    def test_second_epoch_stream_is_deterministic(self, data_file):
        a = _run_worker(_WORKER, [data_file, 2, 3, 0, 1])
        b = _run_worker(_WORKER, [data_file, 2, 3, 0, 1])
        assert a and a == b

    def test_shuffled_split_recovers_by_seed(self, data_file):
        # shuffled reads are ALSO recoverable: same seed => same order,
        # across a hard kill and restart
        clean = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1])
        assert len(clean) > 10
        _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1],
                    kill_after_lines=3)
        replay = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 7, 1])
        assert replay == clean
        # different seed => different order (the shuffle is real)
        other = _run_worker(_SHUFFLE_WORKER, [data_file, 0, 2, 8, 1])
        assert other != clean


class TestRendezvousMembership:
    """The rendezvous service + client unit contracts: deterministic
    rank assignment, monotonic epoch delivery, heartbeat-grace flap
    suppression, and the epoch fence that makes progress commits
    exactly-once."""

    def test_rank_assignment_deterministic(self):
        from dmlc_tpu.rendezvous import elastic
        from dmlc_tpu.rendezvous.service import RendezvousService
        # two services fed the identical join sequence agree exactly
        with RendezvousService() as s1, RendezvousService() as s2:
            rosters = []
            for svc in (s1, s2):
                for m in ("a", "b", "c"):
                    resp = svc.handle({"op": "join", "gang": "det",
                                       "member": m, "host": "h",
                                       "port": None, "attempt": 0})
                    assert resp["ok"]
                rosters.append(resp["roster"])
            assert rosters[0] == rosters[1]
            assert [e["rank"] for e in rosters[0]] == [0, 1, 2]
        # ownership is a pure disjoint cover of the part space
        for num_parts in (1, 3, 7, 16):
            for world in (1, 2, 3, 5):
                covered = sorted(
                    p for r in range(world)
                    for p in elastic.assign_parts(num_parts, world, r))
                assert covered == list(range(num_parts))
                for r in range(world):
                    mine = elastic.assign_parts(num_parts, world, r)
                    assert mine == elastic.assign_parts(
                        num_parts, world, r)
                    for p in mine:
                        assert elastic.owner_of(p, world) == r
        # a reshard plan resumes every part exactly once, mid-prefix
        plan = elastic.reshard_plan(7, 3, {"0": 5, "3": 2})
        assert plan[0] == [(0, 5), (3, 2), (6, 0)]
        assert sorted(p for parts in plan.values()
                      for p, _ in parts) == list(range(7))

    def test_epoch_monotonic_roster_delivery(self):
        from dmlc_tpu.rendezvous.service import RendezvousService
        with RendezvousService() as svc:
            epochs = []
            script = [("join", "a"), ("join", "b"), ("join", "c"),
                      ("leave", "b"), ("report_death", "c"),
                      ("join", "a"),  # alive rejoin: NO flap
                      ("join", "d")]
            for op, member in script:
                resp = svc.handle({"op": op, "gang": "mono",
                                   "member": member, "host": "h",
                                   "port": None, "attempt": 0})
                assert resp["ok"]
                # every delivered roster has dense ranks 0..world-1
                assert [e["rank"] for e in resp["roster"]] == \
                    list(range(resp["world"]))
                epochs.append(resp["epoch"])
            # monotone, bumping on every REAL membership change and
            # holding still on the idempotent supervisor-restart rejoin
            assert epochs == [1, 2, 3, 4, 5, 5, 6]
            final = svc.handle({"op": "roster", "gang": "mono"})
            assert [e["member"] for e in final["roster"]] == ["a", "d"]

    def test_heartbeat_grace_flap_suppression(self, monkeypatch):
        from dmlc_tpu.rendezvous import RendezvousClient
        from dmlc_tpu.rendezvous import service as rsvc
        with rsvc.RendezvousService(heartbeat_grace_s=0.8) as svc:
            a = RendezvousClient("127.0.0.1", svc.port, gang="flap",
                                 member="a")
            b = RendezvousClient("127.0.0.1", svc.port, gang="flap",
                                 member="b")
            a.join()
            b.join()
            a.heartbeat()
            assert (a.epoch, a.world) == (2, 2)
            # a flaky wire: EVERY beat's first attempt fails — the
            # rendezvous.* retry seam must absorb it as a counted
            # retry, never as a membership flap
            real = rsvc.call
            calls = {"n": 0}

            def flaky(host, port, payload, timeout_s=2.0):
                calls["n"] += 1
                if calls["n"] % 2 == 1:
                    raise IOError("flaky wire")
                return real(host, port, payload, timeout_s=timeout_s)

            monkeypatch.setattr(rsvc, "call", flaky)
            for _ in range(5):
                assert a.heartbeat() and b.heartbeat()
                time.sleep(0.02)
            assert calls["n"] >= 20  # the flakiness was real
            assert (a.epoch, a.world) == (2, 2), \
                "a retried-but-delivered heartbeat flapped the roster"
            monkeypatch.setattr(rsvc, "call", real)
            # now b goes TRULY silent past the grace: one death, one
            # epoch bump, ranks compact
            deadline = time.monotonic() + 10
            while a.world != 1:
                assert time.monotonic() < deadline, \
                    "grace never reaped the silent member"
                time.sleep(0.05)
                a.heartbeat()
            assert (a.epoch, a.rank) == (3, 0)
            # the flapped member comes back: its next beat learns
            # "not in gang", auto-rejoins, and the epoch bumps again
            assert b.heartbeat()
            assert (b.epoch, b.world, b.rank) == (4, 2, 1)

    def test_fenced_commit_rejects_stale_epoch(self):
        from dmlc_tpu.rendezvous import RendezvousClient
        from dmlc_tpu.rendezvous.service import RendezvousService
        with RendezvousService() as svc:
            a = RendezvousClient("127.0.0.1", svc.port, gang="fence",
                                 member="a")
            b = RendezvousClient("127.0.0.1", svc.port, gang="fence",
                                 member="b")
            a.join()
            stale = a.epoch
            b.join()  # the roster moves; a's view is now stale
            assert a.commit(5, 10, epoch=stale) is False, \
                "a stale-fenced commit must NOT merge"
            # the rejection itself delivered the fresh view...
            assert a.epoch == b.epoch and a.world == 2
            assert a.progress.get("5", 0) == 0
            # ...under which the re-derived commit lands
            assert a.commit(5, 10, epoch=a.epoch) is True
            assert a.progress["5"] == 10

    def test_peer_tier_dead_rank_reassigns_to_survivors(self):
        from dmlc_tpu.io.objstore.peer import PeerTier
        t = PeerTier([7001, 7002, 7003], self_port=7001)
        assert t.owner_index(1) == 1
        t.mark_dead(1)
        # a dead rank costs zero probes...
        assert not t.available(1)
        # ...and its page groups round-robin over the survivors
        # [0, 2] (None == this process is the reassigned owner)
        assert [t.owner_index(g) for g in (1, 4, 7, 10)] == \
            [2, None, 2, None]
        # a roster refresh (rendezvous epoch bump) adopts the new
        # topology in place and fully resets breaker + dead state
        t.refresh([7001, 7003], self_port=7003)
        assert t.self_index == 1
        assert t.available(0) and t.available(1)
        assert t.owner_index(0) == 0 and t.owner_index(1) is None


def _consume_elastic(cli, records, out, stop, batch=3):
    """One gang member's elastic consume loop: derive ownership, the
    resume offset and the commit fence from ONE view snapshot per
    pass, read the batch, and count it consumed IFF the epoch-fenced
    commit lands — the discipline under which coverage is exactly-once
    across any interleaving of reshards."""
    from dmlc_tpu.rendezvous import elastic
    num_parts = len(records)
    while not stop.is_set():
        v = cli.view()
        if v["rank"] is None or v["epoch"] is None:
            return
        if all(int(v["progress"].get(str(p), 0)) >= len(records[p])
               for p in range(num_parts)):
            return
        progressed = False
        for p in elastic.assign_parts(num_parts, v["world"],
                                      v["rank"]):
            start = elastic.resume_skip(v["progress"], p)
            if start >= len(records[p]):
                continue
            end = min(start + batch, len(records[p]))
            chunk = records[p][start:end]
            if cli.commit(p, end, epoch=v["epoch"]):
                out.extend(chunk)
                progressed = True
            break  # one batch per pass: re-derive ownership
        if not progressed:
            cli.heartbeat()
            time.sleep(0.002)


class TestElasticGangAcceptance:
    """The two ROADMAP item-1 acceptance gangs: permanent loss →
    shrink → byte-identical exactly-once global coverage; mid-epoch
    grow → reshard visible on the merged trace, on /gang, and on the
    control ledger."""

    def test_shrink_gang_byte_identical_coverage(self):
        import hashlib
        import threading

        from dmlc_tpu.rendezvous import RendezvousClient
        from dmlc_tpu.rendezvous.service import RendezvousService
        records = {p: [f"{p}:{i}".encode() for i in range(40)]
                   for p in range(5)}
        want = sorted(r for recs in records.values() for r in recs)
        baseline = hashlib.sha256(b"\n".join(want)).hexdigest()
        outs = {m: [] for m in "abc"}
        stops = {m: threading.Event() for m in "abc"}
        # grace high: THIS gang's death is the supervisor's report,
        # deterministically timed, not a racy grace sweep
        with RendezvousService(heartbeat_grace_s=30.0) as svc:
            clis = {m: RendezvousClient("127.0.0.1", svc.port,
                                        gang="shrink", member=m)
                    for m in "abc"}
            threads = {}
            for m in "abc":
                clis[m].join()
                threads[m] = threading.Thread(
                    target=_consume_elastic,
                    args=(clis[m], records, outs[m], stops[m]),
                    daemon=True)
            for t in threads.values():
                t.start()
            # let the victim commit real mid-epoch progress, then
            # lose it PERMANENTLY: hard-stopped (a SIGKILLed process
            # commits nothing more), then reported dead by the
            # supervisor — the launch_local seam
            deadline = time.monotonic() + 30
            while len(outs["b"]) < 6:
                assert time.monotonic() < deadline, \
                    "victim never committed a batch"
                time.sleep(0.005)
            stops["b"].set()
            threads["b"].join(timeout=10)
            assert not threads["b"].is_alive()
            resp = svc.handle({"op": "report_death", "gang": "shrink",
                               "member": "b"})
            assert resp["ok"] and resp["world"] == 2
            for m in "ac":
                threads[m].join(timeout=60)
                assert not threads[m].is_alive(), \
                    f"survivor {m!r} hung after the shrink"
            assert clis["a"].world == 2
            assert clis["a"].epoch >= 4  # 3 joins + the death
            assert all(e["member"] != "b"
                       for e in clis["a"].roster)
        # the acceptance bound: byte-identical global coverage —
        # every record consumed EXACTLY once across the whole arc,
        # the victim's committed prefix reused (not replayed)
        got = sorted(outs["a"] + outs["b"] + outs["c"])
        assert got == want
        assert hashlib.sha256(b"\n".join(got)).hexdigest() == baseline
        assert outs["b"], "the victim's prefix should be real work"

    def test_grow_reshard_visible_on_trace_gang_and_ledger(self):
        import json as _json
        import urllib.request

        import dmlc_tpu.rendezvous as rndv
        from dmlc_tpu.obs import control as obs_control
        from dmlc_tpu.obs import trace as obs_trace
        from dmlc_tpu.obs.control import Controller
        from dmlc_tpu.obs.serve import StatusServer
        from dmlc_tpu.rendezvous import RendezvousClient
        from dmlc_tpu.rendezvous.service import RendezvousService

        rec = obs_trace.start()
        ctl = obs_control.install(Controller())
        svc = srv = None
        try:
            svc = RendezvousService(heartbeat_grace_s=30.0)
            a = RendezvousClient("127.0.0.1", svc.port, gang="grow",
                                 member="a", serve_port=7101)
            b = RendezvousClient("127.0.0.1", svc.port, gang="grow",
                                 member="b", serve_port=7102)
            a.join()
            b.join()
            a.heartbeat()
            assert (a.epoch, a.world) == (2, 2)
            rndv.install(client=a)  # a's membership IS /gang here
            srv = StatusServer(port=0)
            # the mid-epoch GROW: a third member joins the running
            # gang; a learns at its next beat and reshards
            c = RendezvousClient("127.0.0.1", svc.port, gang="grow",
                                 member="c", serve_port=7103)
            c.join()
            assert (c.world, c.rank) == (3, 2)
            a.heartbeat()
            assert a.world == 3
            # 1) the merged trace: service-side join instants AND the
            # member-side reshard instant, with the world transition
            names = [e[1] for e in rec.events()]
            assert "gang/member/join" in names
            assert "gang/member/reshard" in names
            ev = [e for e in rec.events()
                  if e[1] == "gang/member/reshard"][-1]
            assert ev[6]["old_world"] == 2
            assert ev[6]["new_world"] == 3
            # 2) /gang: the live roster over HTTP
            with urllib.request.urlopen(srv.url("/gang"),
                                        timeout=5) as r:
                doc = _json.loads(r.read())
            mem = doc["membership"]
            assert mem["world"] == 3 and mem["epoch"] == a.epoch
            assert [m["member"] for m in mem["roster"]] == \
                ["a", "b", "c"]
            # 3) the control ledger: a schema-valid membership record
            recs = [r for r in ctl.ledger.records()
                    if r["family"] == "gang"]
            assert recs and recs[-1]["outcome"] == "reshard"
            assert (recs[-1]["old"], recs[-1]["new"]) == (2, 3)
            assert recs[-1]["verdict_id"] == f"m{a.epoch}-grow"
        finally:
            rndv.uninstall()
            obs_control.uninstall()
            obs_trace.stop()
            if srv is not None:
                srv.close()
            if svc is not None:
                svc.close()
