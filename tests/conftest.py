"""Test config: force CPU JAX with 8 virtual devices BEFORE jax imports.

Mirrors the reference's test strategy (SURVEY.md §4): sharding invariants
are tested single-process by enumerating part_index; multi-chip sharding
is tested on a virtual CPU mesh so CI needs no TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# the env var alone is overridden by this machine's axon TPU plugin;
# the config update is authoritative
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def tmpfile(tmp_path):
    def _make(name: str, content: bytes) -> str:
        p = tmp_path / name
        p.write_bytes(content)
        return str(p)
    return _make


@pytest.fixture
def rng():
    return np.random.RandomState(42)
