"""ThreadedIter semantics (reference: unittest_threaditer,
unittest_threaditer_exc_handling — producer exception rethrow in Next,
BeforeFirst restart, clean shutdown)."""

import threading
import time

import pytest

from dmlc_tpu.data.threaded_iter import ThreadedIter
from dmlc_tpu.utils.concurrency import (
    ConcurrentBlockingQueue, PriorityBlockingQueue,
)


def make_counter_iter(n, capacity=4):
    state = {"i": 0}

    def next_fn():
        if state["i"] >= n:
            return None
        state["i"] += 1
        return state["i"]

    def before_first():
        state["i"] = 0

    it = ThreadedIter(max_capacity=capacity)
    it.init(next_fn, before_first)
    return it


class TestThreadedIter:
    def test_drains_in_order(self):
        it = make_counter_iter(100)
        try:
            assert list(it) == list(range(1, 101))
        finally:
            it.destroy()

    def test_end_is_sticky(self):
        it = make_counter_iter(3)
        try:
            assert list(it) == [1, 2, 3]
            assert it.next() is None
            assert it.next() is None
        finally:
            it.destroy()

    def test_before_first_restarts(self):
        it = make_counter_iter(10)
        try:
            assert list(it) == list(range(1, 11))
            it.before_first()
            assert list(it) == list(range(1, 11))
        finally:
            it.destroy()

    def test_before_first_mid_stream(self):
        it = make_counter_iter(1000)
        try:
            got = [it.next() for _ in range(5)]
            assert got == [1, 2, 3, 4, 5]
            it.before_first()
            assert it.next() == 1
        finally:
            it.destroy()

    def test_producer_exception_rethrown(self):
        calls = {"n": 0}

        def next_fn():
            calls["n"] += 1
            if calls["n"] == 3:
                raise ValueError("producer-died")
            return calls["n"]

        it = ThreadedIter(max_capacity=2)
        it.init(next_fn)
        try:
            assert it.next() == 1
            assert it.next() == 2
            with pytest.raises(ValueError, match="producer-died"):
                while True:
                    if it.next() is None:
                        break
        finally:
            it.destroy()

    def test_exception_then_before_first_recovers(self):
        state = {"fail": True, "i": 0}

        def next_fn():
            if state["fail"]:
                raise RuntimeError("first-pass-fails")
            if state["i"] >= 3:
                return None
            state["i"] += 1
            return state["i"]

        def before_first():
            state["fail"] = False
            state["i"] = 0

        it = ThreadedIter(max_capacity=2)
        it.init(next_fn, before_first)
        try:
            with pytest.raises(RuntimeError, match="first-pass-fails"):
                it.next()
            it.before_first()
            assert list(it.__iter__()) == [1, 2, 3] or [
                it.next(), it.next(), it.next()] == [1, 2, 3]
        finally:
            it.destroy()

    def test_bounded_capacity(self):
        produced = []

        def next_fn():
            produced.append(1)
            time.sleep(0.001)
            return len(produced)

        it = ThreadedIter(max_capacity=3)
        it.init(next_fn)
        try:
            time.sleep(0.3)
            # producer must stall at capacity (3 queued + 1 in flight)
            assert len(produced) <= 5
            assert it.next() == 1
        finally:
            it.destroy()

    def test_destroy_while_blocked_producer(self):
        it = ThreadedIter(max_capacity=1)
        it.init(lambda: 42)  # infinite producer
        assert it.next() == 42
        it.destroy()  # must not hang

    def test_destroy_idempotent(self):
        it = make_counter_iter(5)
        it.destroy()
        it.destroy()


class TestConcurrentBlockingQueue:
    def test_push_pop_order(self):
        q = ConcurrentBlockingQueue(max_size=10)
        for i in range(5):
            q.push(i)
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]
        assert q.size() == 0

    def test_kill_unblocks_consumer(self):
        q = ConcurrentBlockingQueue()
        results = []

        def consumer():
            results.append(q.pop())

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        q.signal_for_kill()
        t.join(timeout=2)
        assert not t.is_alive()
        assert results == [None]

    def test_kill_unblocks_producer(self):
        q = ConcurrentBlockingQueue(max_size=1)
        q.push(1)
        done = []

        def producer():
            done.append(q.push(2))

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.05)
        q.signal_for_kill()
        t.join(timeout=2)
        assert not t.is_alive()
        assert done == [False]

    def test_priority(self):
        q = PriorityBlockingQueue()
        q.push((1, "low"))
        q.push((9, "high"))
        q.push((5, "mid"))
        assert q.pop() == (9, "high")
        assert q.pop() == (5, "mid")
        assert q.pop() == (1, "low")
