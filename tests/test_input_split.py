"""The sharding acceptance gate (reference: unittest_inputsplit):
coverage and no-overlap of records across parts, for text and recordio,
across varying num_parts / chunk sizes / file layouts / newline styles."""

import os
import struct

import numpy as np
import pytest

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.input_split_shuffle import InputSplitShuffle
from dmlc_tpu.io.recordio import RECORDIO_MAGIC, RecordIOWriter
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.io.threaded_split import ThreadedInputSplit

MAGIC_BYTES = struct.pack("<I", RECORDIO_MAGIC)


def write_text_files(tmp_path, contents):
    paths = []
    for i, c in enumerate(contents):
        p = tmp_path / f"part{i:02d}.txt"
        p.write_bytes(c)
        paths.append(str(p))
    return ";".join(paths)


def gather_all_parts(uri, num_parts, split_type="text", **kw):
    """Concatenate records from every part, in part order."""
    all_records = []
    per_part = []
    for k in range(num_parts):
        split = InputSplit.create(uri, k, num_parts, split_type, **kw)
        recs = list(split)
        per_part.append(recs)
        all_records.extend(recs)
    return all_records, per_part


class TestTextSplitInvariant:
    def expected_records(self, blobs):
        out = []
        for blob in blobs:
            out.extend([l for l in blob.splitlines() if l])
        return out

    @pytest.mark.parametrize("num_parts", [1, 2, 3, 5, 8, 16])
    def test_coverage_no_overlap_single_file(self, tmp_path, num_parts, rng):
        lines = [f"line-{i}-{'x' * rng.randint(0, 30)}".encode()
                 for i in range(200)]
        blob = b"\n".join(lines) + b"\n"
        uri = write_text_files(tmp_path, [blob])
        got, _ = gather_all_parts(uri, num_parts)
        assert got == lines

    @pytest.mark.parametrize("num_parts", [1, 2, 4, 7])
    def test_multi_file(self, tmp_path, num_parts, rng):
        blobs = []
        for f in range(5):
            n = rng.randint(1, 60)
            blobs.append(b"".join(
                b"f%d-rec%d-%s\n" % (f, i, b"y" * rng.randint(0, 20))
                for i in range(n)))
        uri = write_text_files(tmp_path, blobs)
        got, _ = gather_all_parts(uri, num_parts)
        assert got == self.expected_records(blobs)

    def test_no_trailing_newline(self, tmp_path):
        blob = b"a\nb\nc"  # last record unterminated
        uri = write_text_files(tmp_path, [blob])
        for nparts in (1, 2, 3):
            got, _ = gather_all_parts(uri, nparts)
            assert got == [b"a", b"b", b"c"]

    def test_crlf_and_empty_lines(self, tmp_path):
        blob = b"a\r\n\r\nb\r\nc\n\n\nd"
        uri = write_text_files(tmp_path, [blob])
        for nparts in (1, 2, 3, 4):
            got, _ = gather_all_parts(uri, nparts)
            assert got == [b"a", b"b", b"c", b"d"], f"nparts={nparts}"

    @pytest.mark.parametrize("chunk_size", [64 * 1024])
    def test_small_chunks(self, tmp_path, chunk_size, rng):
        # chunk_size floors at 64KB; use many tiny records to force
        # several chunks per part with a big file
        lines = [b"r%06d" % i for i in range(30000)]
        blob = b"\n".join(lines) + b"\n"
        uri = write_text_files(tmp_path, [blob])
        got, _ = gather_all_parts(uri, 3, chunk_size=chunk_size)
        assert got == lines

    def test_more_parts_than_records(self, tmp_path):
        blob = b"only\ntwo\n"
        uri = write_text_files(tmp_path, [blob])
        got, per_part = gather_all_parts(uri, 8)
        assert got == [b"only", b"two"]
        # most parts must be empty, none duplicated
        assert sum(len(p) > 0 for p in per_part) <= 2

    def test_empty_file_skipped(self, tmp_path):
        (tmp_path / "a.txt").write_bytes(b"x\ny\n")
        (tmp_path / "b.txt").write_bytes(b"")
        (tmp_path / "c.txt").write_bytes(b"z\n")
        uri = str(tmp_path)  # directory expansion
        got, _ = gather_all_parts(uri, 2)
        assert got == [b"x", b"y", b"z"]

    def test_reset_partition(self, tmp_path):
        lines = [b"%d" % i for i in range(100)]
        uri = write_text_files(tmp_path, [b"\n".join(lines) + b"\n"])
        split = InputSplit.create(uri, 0, 4)
        first = list(split)
        split.reset_partition(1, 4)
        second = list(split)
        split.reset_partition(0, 4)
        assert list(split) == first
        assert set(first).isdisjoint(second)

    def test_before_first_replays(self, tmp_path):
        uri = write_text_files(tmp_path, [b"a\nb\nc\n"])
        split = InputSplit.create(uri, 0, 1)
        assert list(split) == [b"a", b"b", b"c"]
        assert list(split) == [b"a", b"b", b"c"]  # __iter__ calls before_first

    def test_total_size(self, tmp_path):
        blob = b"abc\ndef\n"
        uri = write_text_files(tmp_path, [blob, blob])
        split = InputSplit.create(uri, 0, 2)
        assert split.get_total_size() == 2 * len(blob)


def make_recordio_file(path, records):
    with create_stream(str(path), "w") as s:
        w = RecordIOWriter(s)
        for r in records:
            w.write_record(r)


class TestRecordIOSplitInvariant:
    @pytest.mark.parametrize("num_parts", [1, 2, 3, 5, 9])
    def test_coverage_no_overlap(self, tmp_path, num_parts, rng):
        records = []
        for i in range(300):
            n = rng.randint(0, 100)
            raw = rng.bytes(n)
            if n > 8 and rng.rand() < 0.3:
                pos = (rng.randint(0, n // 4)) * 4
                raw = raw[:pos] + MAGIC_BYTES + raw[pos + 4:]
            records.append(raw)
        p = tmp_path / "data.rec"
        make_recordio_file(p, records)
        got, _ = gather_all_parts(str(p), num_parts, "recordio")
        assert got == records

    @pytest.mark.parametrize("num_parts", [1, 2, 4])
    def test_multi_file(self, tmp_path, num_parts, rng):
        all_records = []
        paths = []
        for f in range(3):
            recs = [rng.bytes(rng.randint(1, 50)) for _ in range(40)]
            p = tmp_path / f"d{f}.rec"
            make_recordio_file(p, recs)
            paths.append(str(p))
            all_records.extend(recs)
        got, _ = gather_all_parts(";".join(paths), num_parts, "recordio")
        assert got == all_records

    def test_multiframe_records_stay_whole(self, tmp_path):
        # records containing escaped magic produce multi-frame encodings;
        # boundary realignment must not treat continuation frames as starts
        records = [MAGIC_BYTES * 10 + b"tail%d" % i for i in range(50)]
        p = tmp_path / "m.rec"
        make_recordio_file(p, records)
        for nparts in (1, 2, 3, 7):
            got, _ = gather_all_parts(str(p), nparts, "recordio")
            assert got == records, f"nparts={nparts}"


class TestShuffledSplit:
    def test_shuffle_covers_all(self, tmp_path, rng):
        lines = [b"%d" % i for i in range(500)]
        uri = write_text_files(tmp_path, [b"\n".join(lines) + b"\n"])
        split = InputSplitShuffle.create(uri, 0, 1, "text",
                                         num_shuffle_parts=5, seed=3)
        epoch1 = list(split)
        assert sorted(epoch1) == sorted(lines)
        epoch2 = list(split)
        assert sorted(epoch2) == sorted(lines)
        assert epoch1 != epoch2  # reshuffled across epochs

    def test_shuffle_deterministic_same_seed(self, tmp_path):
        lines = [b"%d" % i for i in range(200)]
        uri = write_text_files(tmp_path, [b"\n".join(lines) + b"\n"])
        a = list(InputSplitShuffle.create(uri, 0, 1, "text",
                                          num_shuffle_parts=4, seed=9))
        b = list(InputSplitShuffle.create(uri, 0, 1, "text",
                                          num_shuffle_parts=4, seed=9))
        assert a == b

    def test_multi_worker_coverage(self, tmp_path):
        lines = [b"%d" % i for i in range(300)]
        uri = write_text_files(tmp_path, [b"\n".join(lines) + b"\n"])
        got = []
        for k in range(3):
            got.extend(InputSplitShuffle.create(
                uri, k, 3, "text", num_shuffle_parts=4, seed=1))
        assert sorted(got) == sorted(lines)


class TestThreadedSplit:
    def test_same_records_as_plain(self, tmp_path):
        lines = [b"rec%d" % i for i in range(5000)]
        uri = write_text_files(tmp_path, [b"\n".join(lines) + b"\n"])
        plain = list(InputSplit.create(uri, 0, 2))
        threaded = ThreadedInputSplit(InputSplit.create(uri, 0, 2))
        try:
            got = list(threaded)
            assert got == plain
            got2 = list(threaded)  # before_first via __iter__
            assert got2 == plain
        finally:
            threaded.destroy()


class TestCachedSplit:
    def test_cache_replay_identical(self, tmp_path):
        lines = [b"c%d" % i for i in range(1000)]
        data = tmp_path / "d.txt"
        data.write_bytes(b"\n".join(lines) + b"\n")
        cache = tmp_path / "cache.bin"
        uri = f"{data}#{cache}"
        split = InputSplit.create(uri, 0, 1)
        first = list(split)
        assert first == lines
        # committed through the page store: entry + fingerprint stamp
        # (the pre-pagestore .done marker is gone)
        assert os.path.exists(str(cache) + ".p0-1")
        assert os.path.exists(str(cache) + ".p0-1.meta.json")
        second = list(split)
        assert second == lines
        # replay must also work from a fresh object (cache hit)
        third = list(InputSplit.create(uri, 0, 1))
        assert third == lines


class TestCachedSplitRegressions:
    def test_before_first_rewinds_records(self, tmp_path):
        data = tmp_path / "r.txt"
        data.write_bytes(b"r0\nr1\nr2\n")
        uri = f"{data}#{tmp_path / 'c.bin'}"
        s = InputSplit.create(uri, 0, 1)
        assert s.next_record() == b"r0"
        s.before_first()
        assert s.next_record() == b"r0"  # must restart, not resume

    def test_bytes_read_resets_per_epoch(self, tmp_path):
        data = tmp_path / "b.txt"
        data.write_bytes(b"x\n" * 100)
        uri = f"{data}#{tmp_path / 'c2.bin'}"
        s = InputSplit.create(uri, 0, 1)
        list(s)
        first = s.bytes_read
        list(s)  # second epoch (replay from cache)
        assert s.bytes_read == first  # not accumulated across epochs


def test_next_batch(tmp_path):
    p = tmp_path / "batch.txt"
    p.write_text("".join(f"line{i}\n" for i in range(10)))
    sp = InputSplit.create(str(p), 0, 1, "text")
    sp.before_first()
    b1 = sp.next_batch(4)
    assert [bytes(r) for r in b1] == [f"line{i}".encode() for i in range(4)]
    b2 = sp.next_batch(100)
    assert len(b2) == 6
    assert sp.next_batch(3) is None
