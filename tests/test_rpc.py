"""Cross-rank distributed request tracing (dmlc_tpu.obs.rpc): the
trace-context wire format, Perfetto flow-event golden keys, the
per-(peer, verb) RPC edge table and its /rpc endpoint, per-attempt
chaos spans, the traced rendezvous/scrape edges, the tracing-off
overhead gate, and THE acceptance — a real 2-process gang whose merged
timeline carries one flow-linked client/server span pair per edge
type (peer /pages, objstore GET, rendezvous commit)."""

import json
import os
import sys
import tempfile
import time
import urllib.request

import pytest

import dmlc_tpu.io.objstore as objstore
from dmlc_tpu.io.stream import create_seek_stream_for_read
from dmlc_tpu.obs import rpc
from dmlc_tpu.obs import trace as obs_trace
from dmlc_tpu.obs.export import chrome_events
from dmlc_tpu.resilience import inject


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts with a quiet tracing plane and an empty edge
    table, and cannot leak a live recorder into its neighbours. A
    REGISTRY.reset() elsewhere in the suite drops the import-time
    collector registration — restore it so snapshot-shape tests hold
    regardless of ordering."""
    from dmlc_tpu.obs.metrics import REGISTRY
    if "rpc" not in REGISTRY.snapshot()["collectors"]:
        REGISTRY.register("rpc", rpc.EDGES, rpc.RpcEdgeTable.stats)
    rpc.EDGES.reset()
    yield
    if obs_trace.active() is not None:
        obs_trace.stop()
    rpc.EDGES.reset()
    objstore.configure(None)


def _client_spans(evs, verb=None):
    out = [e for e in evs if e.get("cat") == rpc._trace.CAT_RPC_CLIENT]
    if verb is not None:
        out = [e for e in out if e["args"]["verb"] == verb]
    return out


def _server_spans(evs):
    return [e for e in evs if e.get("cat") == rpc._trace.CAT_RPC_SERVER]


def _settle(rec, pred, timeout_s=5.0):
    """Server spans land from the HANDLER thread after the response is
    on the wire — poll the live recorder until the pair shows up."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        evs = chrome_events(rec)
        if pred(evs):
            return evs
        time.sleep(0.01)
    return chrome_events(rec)


class TestTraceContext:
    def test_roundtrip_and_wire_form(self):
        ctx = rpc.new_context()
        assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 8
        wire = rpc.serialize(ctx)
        assert wire == f"{ctx.trace_id}-{ctx.span_id}"
        assert rpc.parse(wire) == ctx

    def test_operation_pins_trace_id(self):
        obs_trace.start()
        try:
            with rpc.operation("io.objstore.get") as tid:
                a = rpc.new_context(tid)
                b = rpc.new_context(tid)
            assert a.trace_id == b.trace_id == tid
            assert a.span_id != b.span_id
        finally:
            obs_trace.stop()

    def test_parse_tolerates_garbage(self):
        for junk in (None, 42, "", "nodash", "-", "a-", "-b",
                     b"aaaa-bbbb", ["x-y"]):
            assert rpc.parse(junk) is None

    def test_inject_extract_header_and_field(self):
        ctx = rpc.new_context()
        hdrs = {}
        rpc.inject(ctx, hdrs)
        assert hdrs == {rpc.TRACE_HEADER: rpc.serialize(ctx)}
        assert rpc.extract(hdrs) == ctx
        payload = {"op": "join"}
        rpc.inject(ctx, payload, key=rpc.TRACE_FIELD)
        assert rpc.extract(payload, key=rpc.TRACE_FIELD) == ctx
        # carriers without .get (or missing keys) are None, not raises
        assert rpc.extract(object()) is None
        assert rpc.extract({}) is None

    def test_off_cost_mints_nothing(self):
        assert obs_trace.active() is None
        with rpc.operation("io.objstore.get") as op:
            assert op is None
            with rpc.client_span("get", "emulator") as call:
                assert call is None
                assert rpc.active_call() is None
        assert rpc.EDGES.view()["edges"] == []


class TestFlowEventGolden:
    """Golden: the Perfetto flow-event shape is pinned like the PR 3
    chrome golden — ph "s" inside the client slice, ph "f" + bp "e"
    inside the server slice, both bound by id == trace_id."""

    def _trace_one_pair(self):
        rec = obs_trace.start()
        with rpc.operation("io.objstore.get"):
            with rpc.client_span("get", "127.0.0.1:9") as call:
                ctx = call.ctx
                with rpc.emulated_server("get"):
                    time.sleep(0.002)
        obs_trace.stop()
        return chrome_events(rec), ctx

    def test_flow_golden_keys(self):
        evs, ctx = self._trace_one_pair()
        flows = [e for e in evs if e.get("name") == "rpc.flow"]
        assert len(flows) == 2
        start = [f for f in flows if f["ph"] == "s"]
        finish = [f for f in flows if f["ph"] == "f"]
        assert len(start) == 1 and len(finish) == 1
        for f in flows:
            for key in ("name", "cat", "id", "pid", "tid", "ts", "ph"):
                assert key in f, (key, f)
            assert f["cat"] == "rpc"
            # bound by trace_id ONLY: retried attempts share the chain
            assert f["id"] == ctx.trace_id
        assert finish[0]["bp"] == "e"
        assert "bp" not in start[0]

    def test_flow_ts_matches_owning_slice(self):
        evs, ctx = self._trace_one_pair()
        cl = _client_spans(evs, "get")[0]
        sv = _server_spans(evs)[0]
        flows = {f["ph"]: f for f in evs if f.get("name") == "rpc.flow"}
        assert flows["s"]["ts"] == cl["ts"]
        assert flows["f"]["ts"] == sv["ts"]
        # the span pair itself is bound by the serialized context
        assert cl["args"][rpc.TRACE_FIELD] == sv["args"][rpc.TRACE_FIELD]

    def test_no_flow_without_context(self):
        rec = obs_trace.start()
        with obs_trace.span("stage", "pipeline"):
            pass
        obs_trace.stop()
        assert [e for e in chrome_events(rec)
                if e.get("name") == "rpc.flow"] == []


class TestEdgeTable:
    def test_percentiles_and_residual(self):
        t = rpc.RpcEdgeTable()
        for i in range(100):
            # client 1000..1099us, server flat 400us
            t.observe("peer:1", "get", 1000.0 + i, 400.0)
        (edge,) = t.view()["edges"]
        assert edge["count"] == 100 and edge["errors"] == 0
        assert edge["attributed"] == 100
        assert edge["client_us"]["p50"] == pytest.approx(1050, abs=2)
        assert edge["client_us"]["p99"] == pytest.approx(1099, abs=1)
        assert edge["server_us"]["p50"] == 400.0
        assert edge["residual_us"]["p50"] == pytest.approx(650, abs=2)

    def test_residual_clamped_at_zero(self):
        t = rpc.RpcEdgeTable()
        t.observe("p", "get", 100.0, 250.0)  # clock skew: server > client
        (edge,) = t.view()["edges"]
        assert edge["residual_us"]["p50"] == 0.0

    def test_unattributed_edge_has_no_server_stats(self):
        t = rpc.RpcEdgeTable()
        t.observe("p", "stat", 50.0)
        (edge,) = t.view()["edges"]
        assert edge["attributed"] == 0
        assert edge["server_us"] is None and edge["residual_us"] is None

    def test_bounded_cardinality_folds_to_other(self):
        t = rpc.RpcEdgeTable(max_edges=4)
        for i in range(10):
            t.observe(f"peer:{i}", "get", 10.0)
        doc = t.view()
        peers = {e["peer"] for e in doc["edges"]}
        assert len(doc["edges"]) == 5  # 4 tracked + the overflow bucket
        assert "other" in peers
        other = next(e for e in doc["edges"] if e["peer"] == "other")
        assert other["count"] == 6

    def test_stats_totals_ride_the_collector(self):
        from dmlc_tpu.obs.metrics import REGISTRY
        rpc.EDGES.observe("p", "get", 100.0, 60.0)
        rpc.EDGES.observe("p", "get", 200.0, 80.0, ok=False)
        snap = REGISTRY.snapshot()
        got = snap["collectors"]["rpc"]
        assert got["count"] == 2 and got["errors"] == 1
        assert got["attributed"] == 2
        assert got["client_us"] == pytest.approx(300.0)
        assert got["server_us"] == pytest.approx(140.0)
        assert got["residual_us"] == pytest.approx(160.0)


class TestRpcEndpoint:
    def test_get_rpc_serves_edge_table(self):
        from dmlc_tpu.obs.serve import StatusServer
        rpc.EDGES.observe("peer:1", "get", 123.0, 45.0)
        srv = StatusServer(port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/rpc") as resp:
                doc = json.load(resp)
        finally:
            srv.close()
        assert doc["schema"] == rpc.RPC_SCHEMA
        (edge,) = doc["edges"]
        assert (edge["peer"], edge["verb"]) == ("peer:1", "get")

    def test_scrape_is_a_traced_edge(self):
        """Satellite: every scrape poll is its own traced operation —
        a slow scrape shows as a flow-linked client/server pair with
        queue/handle/write phases on the server side."""
        from dmlc_tpu.obs.serve import StatusServer, scrape
        srv = StatusServer(port=0)
        rec = obs_trace.start()
        try:
            snap = scrape(srv.port)
            evs = _settle(rec, lambda es: _server_spans(es))
        finally:
            obs_trace.stop()
            srv.close()
        assert "counters" in snap
        (cl,) = _client_spans(evs, "scrape")
        sv = [e for e in _server_spans(evs)
              if e["args"][rpc.TRACE_FIELD] == cl["args"][rpc.TRACE_FIELD]]
        assert len(sv) == 1
        for phase in ("queue_us", "handle_us", "write_us"):
            assert phase in sv[0]["args"], phase
        assert cl["args"]["server_us"] == pytest.approx(
            sv[0]["args"]["handle_us"], abs=0.11)

    def test_gang_view_carries_rpc(self):
        """Satellite: GangAggregator polls surface each rank's edge
        totals under ranks.*.rpc (and the poll itself is traced)."""
        from dmlc_tpu.obs.aggregate import GangAggregator
        from dmlc_tpu.obs.serve import StatusServer
        rpc.EDGES.observe("peer:1", "get", 123.0, 45.0)
        srv = StatusServer(port=0)
        try:
            agg = GangAggregator(ports=[srv.port])
            agg.poll_once()
            view = agg.view()
        finally:
            srv.close()
        (rank,) = view["ranks"].values()
        assert rank["rpc"]["count"] >= 1


class TestEmulatorDecomposition:
    def test_server_handle_matches_modeled_delay(self, tmp_path):
        """Acceptance: the edge table decomposes client latency into
        server handle vs residual within ±20% of the emulator's
        modeled wire delay."""
        modeled_s = 0.02
        em = objstore.configure(root=str(tmp_path / "root"),
                                latency_s=modeled_s,
                                block_bytes=1 << 16)
        em.put("b", "k.bin", b"x" * (1 << 17))  # 2 blocks
        obs_trace.start()
        try:
            s = create_seek_stream_for_read("obj://b/k.bin")
            got = 0
            while True:
                chunk = s.read(1 << 20)
                if not chunk:
                    break
                got += len(chunk)
            s.close()
        finally:
            obs_trace.stop()
        assert got == 1 << 17
        edges = {(e["peer"], e["verb"]): e
                 for e in rpc.view()["edges"]}
        get = edges[("emulator", "get")]
        assert get["attributed"] == get["count"] >= 2
        modeled_us = modeled_s * 1e6
        assert get["server_us"]["p50"] == pytest.approx(
            modeled_us, rel=0.20)
        # the residual (client - server) is the non-modeled overhead:
        # far under the wire delay, so the decomposition is meaningful
        assert get["residual_us"]["p50"] < 0.2 * modeled_us
        # client ≈ server + residual by construction
        assert get["client_us"]["p50"] == pytest.approx(
            get["server_us"]["p50"] + get["residual_us"]["p50"],
            rel=0.25)


class TestChaosPerAttemptSpans:
    def test_injected_retries_are_countable_spans(self, tmp_path):
        """Satellite: a FaultPlan-injected retry at io.objstore.get
        produces one client span per ATTEMPT, all sharing the pinned
        trace_id — retries countable straight off the timeline."""
        em = objstore.configure(root=str(tmp_path / "root"))
        em.put("b", "k.bin", b"z" * (1 << 14))
        inject.install("site=io.objstore.get,fault=ioerror,times=2")
        rec = obs_trace.start()
        try:
            s = create_seek_stream_for_read("obj://b/k.bin")
            data = s.read(1 << 20)
            s.close()
        finally:
            obs_trace.stop()
            inject.uninstall()
        assert len(data) == 1 << 14
        spans = _client_spans(chrome_events(rec), "get")
        assert len(spans) == 3  # 2 injected failures + the success
        oks = sorted(e["args"]["ok"] for e in spans)
        assert oks == [False, False, True]
        tids = {e["args"][rpc.TRACE_FIELD].split("-")[0] for e in spans}
        assert len(tids) == 1, "attempts must share the trace_id"
        span_ids = {e["args"][rpc.TRACE_FIELD] for e in spans}
        assert len(span_ids) == 3, "each attempt is its own span"
        edge = next(e for e in rpc.view()["edges"]
                    if e["verb"] == "get")
        assert edge["errors"] == 2


class TestRendezvousTraced:
    def test_client_server_pair_over_the_line_protocol(self):
        from dmlc_tpu.rendezvous import RendezvousClient
        from dmlc_tpu.rendezvous.service import RendezvousService
        svc = RendezvousService(port=0)
        host, port = svc.address
        rec = obs_trace.start()
        try:
            c = RendezvousClient(host, port, gang="g", member="w0")
            assert c.join() == 0
            assert c.commit("p0", 10) is True
            c.leave()
            evs = _settle(rec, lambda es: len(_server_spans(es)) >= 3)
        finally:
            obs_trace.stop()
            svc.close()
        (commit,) = _client_spans(evs, "commit")
        assert commit["args"]["server_us"] is not None
        # the service handler recorded the paired server span (same
        # process here; the gang acceptance below proves cross-process)
        paired = [e for e in _server_spans(evs)
                  if e["args"][rpc.TRACE_FIELD]
                  == commit["args"][rpc.TRACE_FIELD]]
        assert len(paired) == 1
        assert paired[0]["args"]["handle_us"] == pytest.approx(
            commit["args"]["server_us"], abs=0.11)


class TestTracingOffOverhead:
    def test_off_overhead_smoke_under_2pct(self, tmp_path):
        """Tier-1 gate (PR 3 discipline): with tracing OFF the rpc
        seams cost one global read + branch per edge — judged against
        tracing ON on the quietest interleaved pair, the off epochs
        must stay within 2% (+ absolute slack for sub-100ms noise)."""
        em = objstore.configure(root=str(tmp_path / "root"),
                                block_bytes=1 << 20, hydrate=False)
        em.put("b", "big.bin", b"q" * (1 << 22))  # 4 x 1MiB GETs

        def epoch_wall():
            t0 = time.perf_counter()
            s = create_seek_stream_for_read("obj://b/big.bin")
            while s.read(1 << 20):
                pass
            s.close()
            return time.perf_counter() - t0

        epoch_wall()  # warm imports/caches outside the measurement
        off, on = [], []
        for _ in range(5):
            off.append(epoch_wall())
            obs_trace.start()
            try:
                on.append(epoch_wall())
            finally:
                obs_trace.stop()
        grace = 0.010 / min(off)  # flat 10 ms, scaled to the wall
        ratios = [a / b for a, b in zip(on, off)]
        assert min(ratios) <= 1.02 + grace, (on, off, ratios)


# ------------------------------------------------- THE gang acceptance

class TestGangTraceAcceptance:
    def test_two_rank_gang_merged_trace_is_flow_linked(self, tmp_path):
        """A REAL 2-process gang (bench_peer_worker, no jax) with
        tracing + rendezvous on: the merged timeline must contain at
        least one flow-linked client/server span pair for EVERY edge
        type — peer /pages (cross-process), objstore GET (emulator),
        and the rendezvous commit (worker -> launcher service)."""
        from dmlc_tpu.parallel.launch import launch_local

        payload = os.urandom(1 << 20)
        objroot = tmp_path / "objroot"
        em = objstore.configure(root=str(objroot))
        try:
            em.put("bench", "gang.bin", payload)
        finally:
            objstore.configure(None)
        worker = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "dmlc_tpu", "bench_peer_worker.py")
        out_dir = tmp_path / "gang"
        out_dir.mkdir()
        trace_dir = tmp_path / "traces"
        env = {
            "DMLC_TPU_OBJSTORE_ROOT": str(objroot),
            "PYTHONPATH": os.pathsep.join(
                [os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))]
                + [p for p in os.environ.get(
                    "PYTHONPATH", "").split(os.pathsep) if p]),
        }
        # the rendezvous service runs in THIS process: record here so
        # its server spans merge with the workers' trace files
        rec = obs_trace.start()
        try:
            codes = launch_local(
                2, [sys.executable, worker, "obj://bench/gang.bin",
                    str(out_dir), str(1 << 16), "2"],
                env=env, serve_ports=True, trace_dir=str(trace_dir),
                rendezvous=True, timeout=180)
        finally:
            obs_trace.stop()
        assert codes[:2] == [0, 0]
        merged_path = trace_dir / "trace-gang.json"
        assert merged_path.exists()
        evs = json.load(open(merged_path))["traceEvents"]
        evs += chrome_events(rec)  # + the launcher's service spans

        clients = _client_spans(evs)
        servers = {e["args"][rpc.TRACE_FIELD]: e
                   for e in _server_spans(evs)}
        flow_ids = {(f["ph"], f["id"]) for f in evs
                    if f.get("name") == "rpc.flow"}

        def linked_pairs(verb, cross_process=False):
            pairs = []
            for cl in clients:
                if cl["args"]["verb"] != verb or not cl["args"]["ok"]:
                    continue
                sv = servers.get(cl["args"][rpc.TRACE_FIELD])
                if sv is None:
                    continue
                if cross_process and sv["pid"] == cl["pid"]:
                    continue
                tid = cl["args"][rpc.TRACE_FIELD].split("-")[0]
                if ("s", tid) in flow_ids and ("f", tid) in flow_ids:
                    pairs.append((cl, sv))
            return pairs

        # edge type 1: peer /pages — MUST cross process rows
        assert linked_pairs("pages", cross_process=True), \
            "no flow-linked cross-process peer /pages pair"
        # edge type 2: objstore GET (emulator models the server half)
        assert linked_pairs("get"), \
            "no flow-linked objstore GET pair"
        # edge type 3: rendezvous commit (server span lives in the
        # launcher's recorder; the service names the op it dispatched)
        assert linked_pairs("commit"), \
            "no flow-linked rendezvous commit pair"

        # and the edge table made it into each rank's bench output
        # plane: /rpc on a live rank was exercised by the scrape test;
        # here every rank's trace must carry BOTH span categories
        for r in (0, 1):
            rank_evs = json.load(
                open(trace_dir / f"trace-rank{r}.json"))["traceEvents"]
            cats = {e.get("cat") for e in rank_evs}
            assert "rpc.client" in cats, f"rank {r} recorded no clients"
