"""Native Parquet page decode (ABI 8): byte parity with the pyarrow
golden across the supported matrix (i32/i64/f32/f64, def-level nulls,
PLAIN + RLE-dictionary, UNCOMPRESSED + SNAPPY + GZIP, multi-page
chunks),
row-group-aligned part splits and shards, the fused padded pipeline,
loud fallback for everything outside the matrix, and the corruption
contract."""

import hashlib

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from dmlc_tpu.data.parser import Parser  # noqa: E402
from dmlc_tpu.data.parquet_parser import ParquetParser  # noqa: E402
from dmlc_tpu.data.rowblock import RowBlockContainer  # noqa: E402
from dmlc_tpu.utils.logging import DMLCError  # noqa: E402


def _have_native():
    from dmlc_tpu import native
    return native.native_available()


def _with_nulls(rng, arr, frac=0.15):
    m = rng.rand(len(arr)) < frac
    a = arr.astype(object)
    a[m] = None
    return pa.array(list(a), type=pa.from_numpy_dtype(arr.dtype))


def _mixed_table(rng, n=3000, nulls=True):
    wrap = (lambda a: _with_nulls(rng, a)) if nulls \
        else (lambda a: pa.array(a))
    return pa.table({
        "label": pa.array(rng.rand(n).astype(np.float32)),
        "f0": wrap(rng.rand(n).astype(np.float32)),
        "f1": wrap(rng.randn(n).astype(np.float64)),
        "i0": wrap(rng.randint(-1000, 1000, n).astype(np.int32)),
        # big int64s pin the null-dependent double-rounding contract
        "i1": wrap((rng.randint(0, 2 ** 62, n) - 2 ** 61)
                   .astype(np.int64)),
        "w": pa.array(rng.rand(n).astype(np.float32)),
    })


def _drain(path, engine, fmt="parquet_native", k=0, n=1, **kw):
    c = RowBlockContainer(np.uint32)
    p = Parser.create(path, k, n, format=fmt, engine=engine,
                      label_column="label", **kw)
    for b in p:
        c.push_block(b)
    if hasattr(p, "destroy"):
        p.destroy()
    return c.get_block()


def _block_eq(a, b):
    """Bit-exact block comparison (values/labels compared as raw bits
    so NaNs participate)."""
    return (np.array_equal(a.offset, b.offset)
            and np.array_equal(a.label.view(np.uint32),
                               b.label.view(np.uint32))
            and np.array_equal(a.index, b.index)
            and np.array_equal(a.value.view(np.uint32),
                               b.value.view(np.uint32)))


def _stream_hash(parser):
    h = hashlib.sha256()
    rows = 0
    parser.before_first()
    while parser.next():
        b = parser.value()
        h.update(np.diff(np.asarray(b.offset)).astype("<i8").tobytes())
        h.update(np.ascontiguousarray(b.label).tobytes())
        h.update(np.ascontiguousarray(b.index).astype("<u4").tobytes())
        h.update(np.ascontiguousarray(b.value).tobytes())
        rows += b.size
    if hasattr(parser, "destroy"):
        parser.destroy()
    return h.hexdigest(), rows


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestNativeParity:
    @pytest.mark.parametrize("compression,use_dict,nulls", [
        ("NONE", False, False),
        ("NONE", True, True),
        ("GZIP", False, True),
        ("GZIP", True, False),
        ("SNAPPY", False, True),
        ("SNAPPY", True, True),
    ])
    def test_byte_parity(self, tmp_path, rng, compression, use_dict,
                         nulls):
        t = _mixed_table(rng, nulls=nulls)
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path, row_group_size=700,
                       compression=compression, use_dictionary=use_dict)
        g = _drain(path, "python")
        n = _drain(path, "native")
        assert g.size == n.size == 3000
        assert _block_eq(g, n)

    def test_multi_page_chunks(self, tmp_path, rng):
        t = _mixed_table(rng)
        path = str(tmp_path / "mp.parquet")
        # tiny data_page_size: several V1 pages per column chunk
        pq.write_table(t, path, row_group_size=1500,
                       compression="GZIP", data_page_size=2048)
        assert _block_eq(_drain(path, "python"), _drain(path, "native"))

    def test_weight_column(self, tmp_path, rng):
        t = _mixed_table(rng, n=500, nulls=False)
        path = str(tmp_path / "w.parquet")
        pq.write_table(t, path, compression="NONE")
        g = _drain(path, "python", weight_column="w")
        n = _drain(path, "native", weight_column="w")
        assert g.weight is not None and n.weight is not None
        assert np.array_equal(g.weight, n.weight)
        assert _block_eq(g, n)

    def test_part_split_parity_and_coverage(self, tmp_path, rng):
        t = _mixed_table(rng)
        path = str(tmp_path / "p.parquet")
        pq.write_table(t, path, row_group_size=400, compression="NONE")
        whole = _drain(path, "python")
        rows = 0
        labels = []
        for k in range(3):
            g = _drain(path, "python", k=k, n=3)
            n = _drain(path, "native", k=k, n=3)
            assert _block_eq(g, n)
            rows += g.size
            labels.append(g.label)
        assert rows == whole.size
        # contiguous ranges: parts concatenate in FILE order
        np.testing.assert_array_equal(np.concatenate(labels),
                                      whole.label)

    @pytest.mark.parametrize("shards", [2, 4])
    def test_sharded_byte_identical(self, tmp_path, rng, shards):
        t = _mixed_table(rng)
        path = str(tmp_path / "s.parquet")
        pq.write_table(t, path, row_group_size=300, compression="NONE")
        one, n1 = _stream_hash(
            Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="label"))
        sh, ns = _stream_hash(
            Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="label",
                          shards=shards))
        assert ns == n1 == 3000
        assert sh == one

    def test_directory_of_part_files(self, tmp_path, rng):
        d = tmp_path / "ds"
        d.mkdir()
        for k in range(3):
            t = _mixed_table(rng, n=400)
            pq.write_table(t, str(d / f"part-{k}.parquet"),
                           row_group_size=150, compression="NONE")
        g = _drain(str(d), "python")
        n = _drain(str(d), "native")
        assert g.size == n.size == 1200
        assert _block_eq(g, n)

    def test_buffered_fallback_parity(self, tmp_path, rng,
                                      monkeypatch):
        """DMLC_TPU_NO_MMAP=1 routes row-group chunks through the
        buffered reader (fread of the span) — byte-identical to the
        mmap-view path."""
        path = str(tmp_path / "b.parquet")
        pq.write_table(_mixed_table(rng, n=1000), path,
                       row_group_size=250, compression="GZIP")
        g = _drain(path, "native")
        monkeypatch.setenv("DMLC_TPU_NO_MMAP", "1")
        n = _drain(path, "native")
        assert _block_eq(g, n)

    def test_leak_probe_outstanding_zero(self, tmp_path, rng):
        path = str(tmp_path / "l.parquet")
        pq.write_table(_mixed_table(rng, n=800), path,
                       row_group_size=200, compression="NONE")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="label")
        for _ in range(2):
            p.before_first()
            while p.next():
                pass
            assert p.outstanding() == 0
        assert p.bytes_read() > 0
        p.destroy()


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestFallbackMatrix:
    """Everything outside the native matrix falls back to the pyarrow
    golden at CREATE (engine='auto'), loudly under engine='native'."""

    def _simple(self, tmp_path, rng, **write_kw):
        path = str(tmp_path / "f.parquet")
        t = pa.table({"label": pa.array(rng.rand(50).astype(np.float32)),
                      "f0": pa.array(rng.rand(50).astype(np.float32))})
        pq.write_table(t, path, **write_kw)
        return path

    def test_snappy_decodes_natively(self, tmp_path, rng):
        """SNAPPY left the fallback matrix: the engine grew a raw
        snappy page decoder (the most common parquet codec), so
        engine='auto' keeps the native path and the stream is
        byte-identical to the golden."""
        path = self._simple(tmp_path, rng, compression="SNAPPY")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="auto", label_column="label")
        assert not isinstance(p, ParquetParser)  # native, no fallback
        if hasattr(p, "destroy"):
            p.destroy()
        n = _drain(path, "native")
        g = _drain(path, "python")
        assert _block_eq(n, g)

    def test_zstd_falls_back(self, tmp_path, rng):
        """zstd stays OUT of the native matrix: create-time fallback
        under auto, a named error under engine='native'."""
        try:
            path = self._simple(tmp_path, rng, compression="ZSTD")
        except Exception:
            pytest.skip("pyarrow without zstd support")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="auto", label_column="label")
        assert isinstance(p, ParquetParser)  # the pyarrow golden
        p.destroy()
        with pytest.raises(DMLCError, match="codec|ZSTD|zstd|6"):
            Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="label")

    def test_v2_pages_fall_back(self, tmp_path, rng):
        path = self._simple(tmp_path, rng, compression="NONE",
                            data_page_version="2.0")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="auto", label_column="label")
        assert isinstance(p, ParquetParser)
        p.destroy()

    def test_string_column_falls_back(self, tmp_path, rng):
        path = str(tmp_path / "str.parquet")
        t = pa.table({"label": pa.array([0.0, 1.0]),
                      "name": pa.array(["a", "b"])})
        pq.write_table(t, path, compression="NONE")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="auto", label_column="label")
        assert isinstance(p, ParquetParser)
        p.destroy()
        with pytest.raises(DMLCError, match="physical type"):
            Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="label")

    def test_sparse_falls_back(self, tmp_path, rng):
        path = self._simple(tmp_path, rng, compression="NONE")
        p = Parser.create(path, 0, 1, format="parquet_native",
                          engine="auto", label_column="label",
                          sparse=True)
        assert isinstance(p, ParquetParser)
        p.destroy()

    def test_missing_label_column_errors(self, tmp_path, rng):
        path = self._simple(tmp_path, rng, compression="NONE")
        with pytest.raises(DMLCError, match="not in the schema"):
            Parser.create(path, 0, 1, format="parquet_native",
                          engine="native", label_column="nope")

    def test_v2_pages_loud_under_native(self, tmp_path, rng):
        """V2 pages pass footer parse (page type shows up at decode):
        the error is loud AT DECODE under engine='native'."""
        path = self._simple(tmp_path, rng, compression="NONE",
                            data_page_version="2.0")
        # engine="native" may fail at create (probe) or first decode;
        # either way it must NAME the V2 gap, never emit wrong bytes
        try:
            p = Parser.create(path, 0, 1, format="parquet_native",
                              engine="native", label_column="label")
        except DMLCError as e:
            assert "V2" in str(e)
            return
        with pytest.raises(DMLCError, match="V2"):
            for _ in p:
                pass
        p.destroy()


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestCorruption:
    def test_truncated_file_rejected(self, tmp_path, rng):
        path = str(tmp_path / "t.parquet")
        pq.write_table(_mixed_table(rng, n=200), path,
                       compression="NONE")
        data = open(path, "rb").read()
        bad = str(tmp_path / "bad.parquet")
        with open(bad, "wb") as f:
            f.write(data[:len(data) // 2])
        with pytest.raises(DMLCError):
            Parser.create(bad, 0, 1, format="parquet_native",
                          engine="native", label_column="label")

    def test_corrupt_page_run_rejected(self, tmp_path, rng):
        """Zeroing a column chunk's page bytes breaks the page-header
        walk: the decode must raise, never emit shifted values."""
        path = str(tmp_path / "c.parquet")
        pq.write_table(_mixed_table(rng, n=500, nulls=False), path,
                       row_group_size=500, compression="NONE",
                       use_dictionary=False)
        md = pq.ParquetFile(path).metadata.row_group(0).column(1)
        data = bytearray(open(path, "rb").read())
        off = md.data_page_offset
        data[off:off + 16] = b"\xff" * 16
        bad = str(tmp_path / "cbad.parquet")
        with open(bad, "wb") as f:
            f.write(bytes(data))
        p = Parser.create(bad, 0, 1, format="parquet_native",
                          engine="native", label_column="label")
        with pytest.raises(DMLCError):
            for _ in p:
                pass
        p.destroy()


@pytest.mark.skipif(not _have_native(), reason="native engine not built")
class TestPaddedPipeline:
    def test_fused_padded_parity(self, tmp_path, rng):
        from dmlc_tpu.pipeline import Pipeline
        path = str(tmp_path / "pipe.parquet")
        n = 2000
        t = pa.table({
            "label": pa.array(rng.rand(n).astype(np.float32)),
            **{f"f{i}": _with_nulls(rng, rng.rand(n).astype(np.float32))
               for i in range(6)}})
        pq.write_table(t, path, row_group_size=300, compression="GZIP")
        rows = 128
        nnz = rows * 6

        def run(engine):
            built = (Pipeline.from_uri(path)
                     .parse(format="parquet_native", engine=engine,
                            label_column="label")
                     .batch(rows, pad=True, nnz_bucket=nnz)
                     .build())
            h = hashlib.sha256()
            nb = 0
            for b in built:
                for k in sorted(b):
                    h.update(k.encode())
                    h.update(np.ascontiguousarray(b[k]).tobytes())
                nb += 1
            snap = built.stats()
            ap = next((x["assembly_path"] for s in snap["stages"]
                       if (x := s.get("extra") or {}).get(
                           "assembly_path")), None)
            built.close()
            return h.hexdigest(), nb, ap

        hg, ng, apg = run("python")
        hn, nn, apn = run("native")
        assert apg == "python-fused" and apn == "native-padded"
        assert (hg, ng) == (hn, nn)

    def test_sharded_padded_gang(self, tmp_path, rng):
        """shards=N under batch(pad=True): the ABI-6 gang cuts padded
        batches across the row-group-aligned sub-parsers, identical to
        the 1-parser padded stream."""
        from dmlc_tpu.pipeline import Pipeline
        path = str(tmp_path / "gang.parquet")
        n = 2400
        t = pa.table({
            "label": pa.array(rng.rand(n).astype(np.float32)),
            **{f"f{i}": pa.array(rng.rand(n).astype(np.float32))
               for i in range(5)}})
        pq.write_table(t, path, row_group_size=200, compression="NONE")

        def run(shards):
            kw = {"shards": shards} if shards else {}
            built = (Pipeline.from_uri(path)
                     .parse(format="parquet_native", engine="native",
                            label_column="label", **kw)
                     .batch(100, pad=True, nnz_bucket=500).build())
            h = hashlib.sha256()
            for b in built:
                for k in sorted(b):
                    h.update(k.encode())
                    h.update(np.ascontiguousarray(b[k]).tobytes())
            snap = built.stats()
            ap = next((x["assembly_path"] for s in snap["stages"]
                       if (x := s.get("extra") or {}).get(
                           "assembly_path")), None)
            built.close()
            return h.hexdigest(), ap

        h1, ap1 = run(None)
        h2, ap2 = run(2)
        assert ap1 == ap2 == "native-padded"
        assert h1 == h2


class TestDecodePathEvidence:
    """The obs/analyze decode-evidence satellite: the parse stage
    stamps which decode path ran, and a parse-bound verdict names it
    with its measured GB/s."""

    def test_stage_stamps_decode_path(self, tmp_path, rng):
        from dmlc_tpu.pipeline import Pipeline
        path = str(tmp_path / "d.parquet")
        t = pa.table({"label": pa.array(rng.rand(300).astype(np.float32)),
                      "f0": pa.array(rng.rand(300).astype(np.float32))})
        pq.write_table(t, path, compression="NONE")
        built = (Pipeline.from_uri(path)
                 .parse(format="parquet_native", engine="python",
                        label_column="label")
                 .batch(64).build())
        snap = built.run_epoch()
        built.close()
        extras = [s.get("extra") or {} for s in snap["stages"]]
        paths = [x.get("decode_path") for x in extras
                 if x.get("decode_path")]
        assert paths == ["pyarrow"]
        if _have_native():
            built = (Pipeline.from_uri(path)
                     .parse(format="parquet_native", engine="native",
                            label_column="label")
                     .batch(64).build())
            snap = built.run_epoch()
            built.close()
            extras = [s.get("extra") or {} for s in snap["stages"]]
            assert [x.get("decode_path") for x in extras
                    if x.get("decode_path")] == ["native-page"]

    def test_analyze_names_decode_path(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 10.0, "epoch": 3, "stages": [
            {"name": "parse", "kind": "parse", "wait_s": 8.0,
             "bytes": 5_000_000_000,
             "extra": {"decode_path": "pyarrow",
                       "bytes_read": 5_000_000_000}},
            {"name": "batch", "kind": "batch", "wait_s": 0.5},
        ]}
        v = attribute(snap)
        assert v["bound"] == "parse"
        decode_lines = [e for e in v["evidence"]
                        if "decode path" in e]
        assert len(decode_lines) == 1
        assert "pyarrow" in decode_lines[0]
        assert "GB/s" in decode_lines[0]

    def test_analyze_decode_line_absent_without_path(self):
        from dmlc_tpu.obs.analyze import attribute
        snap = {"wall_s": 10.0, "stages": [
            {"name": "parse", "kind": "parse", "wait_s": 8.0}]}
        v = attribute(snap)
        assert not any("decode path" in e for e in v["evidence"])
