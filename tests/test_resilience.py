"""Chaos suite for dmlc_tpu.resilience (ISSUE 5).

Pins the three pillars: RetryPolicy semantics (deterministic backoff,
classifier, shared budget, per-attempt timeout), the seeded fault-
injection plane (same seed => same faults; retry-until-success at
every instrumented seam), and elastic gang supervision (a REAL
2-process launch_local gang survives an injected mid-epoch worker
crash with byte-identical epoch output vs. the fault-free run, the
restart visible on /metrics and the merged gang trace; budget
exhausted = prompt teardown with a flight bundle, not a hang).
"""

import hashlib
import json
import os
import sys
import time

import numpy as np
import pytest

from dmlc_tpu.data.rowblock import RowBlock
from dmlc_tpu.resilience import (
    CRASH_EXIT, AttemptTimeout, FaultPlan, RestartPolicy, RetryBudget,
    RetryPolicy, guarded, inject, policy_for, reset_policies,
    retry_counts, set_policy,
)
from dmlc_tpu.utils.logging import DMLCError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _noop_sleep(_s):
    pass


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test leaves the process chaos-free and policy-default."""
    yield
    inject.uninstall()
    reset_policies()


def _gang_env(extra=None):
    env = {"JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join(
               [_REPO] + [p for p in
                          os.environ.get("PYTHONPATH", "").split(
                              os.pathsep) if p])}
    if extra:
        env.update(extra)
    return env


# ---------------------------------------------------------------- policy

class TestRetryPolicy:
    def test_retries_then_succeeds(self):
        calls = []
        pol = RetryPolicy(max_attempts=4, sleep=_noop_sleep)

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise IOError("transient")
            return "ok"

        assert pol.call("t.basic", fn) == "ok"
        assert len(calls) == 3
        assert retry_counts()["t.basic"] == 2

    def test_backoff_schedule_is_deterministic(self):
        slept_a, slept_b = [], []
        for slept in (slept_a, slept_b):
            calls = [0]
            pol = RetryPolicy(max_attempts=4, base_delay_s=0.1,
                              multiplier=2.0, jitter=0.2,
                              sleep=slept.append)

            def fn():
                calls[0] += 1
                if calls[0] < 4:
                    raise IOError("x")
                return calls[0]

            pol.call("t.backoff", fn)
        assert slept_a == slept_b  # jitter is seeded, not random
        assert slept_a == [pol.delay_for("t.backoff", a)
                           for a in (1, 2, 3)]
        # exponential shape survives the +-20% jitter
        assert slept_a[0] < slept_a[1] < slept_a[2]

    def test_non_retryable_raises_immediately(self):
        pol = RetryPolicy(sleep=_noop_sleep)
        calls = []

        def bad_value():
            calls.append(1)
            raise ValueError("parse error")

        with pytest.raises(ValueError):
            pol.call("t.cls", bad_value)
        assert len(calls) == 1

        def missing():
            calls.append(1)
            raise FileNotFoundError("gone")

        calls.clear()
        with pytest.raises(FileNotFoundError):
            pol.call("t.cls", missing)
        assert len(calls) == 1  # permanent OSError subclasses: no retry

    def test_attempts_exhausted_reraises_last(self):
        pol = RetryPolicy(max_attempts=3, sleep=_noop_sleep)
        calls = []

        def fn():
            calls.append(1)
            raise IOError(f"fail {len(calls)}")

        with pytest.raises(IOError, match="fail 3"):
            pol.call("t.exhaust", fn)
        assert len(calls) == 3

    def test_budget_shared_across_sites(self):
        budget = RetryBudget(1)
        pol = RetryPolicy(max_attempts=5, budget=budget,
                          sleep=_noop_sleep)
        a_calls, b_calls = [], []

        def flaky(calls, ok_after):
            calls.append(1)
            if len(calls) < ok_after:
                raise IOError("x")
            return True

        assert pol.call("pipe.a", lambda: flaky(a_calls, 2))
        assert budget.remaining == 0
        # the pool is spent: site B gets its first attempt, no retries
        with pytest.raises(IOError):
            pol.call("pipe.b", lambda: flaky(b_calls, 2))
        assert len(b_calls) == 1

    def test_attempt_timeout_retries_hung_attempt(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                time.sleep(0.5)  # a hung first attempt
            return "done"

        pol = RetryPolicy(max_attempts=2, attempt_timeout_s=0.05,
                          sleep=_noop_sleep)
        assert pol.call("t.hang", fn) == "done"
        assert len(calls) == 2

    def test_attempt_timeout_exhaustion_raises_timeout(self):
        pol = RetryPolicy(max_attempts=2, attempt_timeout_s=0.05,
                          sleep=_noop_sleep)
        with pytest.raises(AttemptTimeout):
            pol.call("t.hang2", lambda: time.sleep(0.5))

    def test_attempt_timeout_polices_first_attempt_via_guarded(self):
        # guarded()'s quiet fast path must yield to the policy when a
        # configured site carries attempt_timeout_s — the FIRST attempt
        # is the one most likely to hang, and without this the guard
        # never engaged unless chaos was armed or a retry had begun
        set_policy("t.firsthang",
                   RetryPolicy(max_attempts=2, attempt_timeout_s=0.05,
                               sleep=_noop_sleep))
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                time.sleep(2.0)  # would block guarded() for 2s
            return "ok"

        t0 = time.monotonic()
        assert guarded("t.firsthang", fn) == "ok"
        assert time.monotonic() - t0 < 1.0
        assert len(calls) == 2

    def test_env_only_timeout_polices_first_attempt(self, monkeypatch):
        # a timeout configured ONLY via DMLC_TPU_RETRY must engage on
        # the very first guarded() call of a fresh process — the lazy
        # env load cannot hide behind the fast path
        monkeypatch.setenv("DMLC_TPU_RETRY",
                           "site=t.envhang,timeout=0.05,attempts=2,"
                           "base=0.0,jitter=0.0")
        reset_policies()
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                time.sleep(2.0)
            return "ok"

        t0 = time.monotonic()
        assert guarded("t.envhang", fn) == "ok"
        assert time.monotonic() - t0 < 1.0
        assert len(calls) == 2

    def test_env_contract_configures_sites(self, monkeypatch):
        monkeypatch.setenv(
            "DMLC_TPU_RETRY",
            "attempts=7,base=0.01;site=obs.*,attempts=1")
        reset_policies()
        assert policy_for("io.stream.read").max_attempts == 7
        assert policy_for("io.stream.read").base_delay_s == 0.01
        assert policy_for("obs.scrape").max_attempts == 1

    def test_env_contract_rejects_unknown_key(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_RETRY", "nope=3")
        reset_policies()
        with pytest.raises(DMLCError, match="unknown key"):
            policy_for("any.site")

    def test_set_default_policy_flows_into_site_overrides(self):
        # site overrides are CHANGES over the current default: a
        # replaced default's sleep/backoff must reach sites that only
        # tweak attempts (obs.scrape's built-in fail-fast)
        from dmlc_tpu.resilience import set_default_policy
        slept = []
        record = slept.append
        set_default_policy(RetryPolicy(base_delay_s=0.0, sleep=record))
        pol = policy_for("obs.scrape")
        assert pol.max_attempts == 2       # the built-in change
        assert pol.sleep is record         # the new default's sleep
        # and a site with NO override is exactly the new default
        assert policy_for("io.stream.read").sleep is record
        assert policy_for("io.stream.read").base_delay_s == 0.0


# ---------------------------------------------------------------- inject

class TestFaultPlan:
    def test_parse_spec_roundtrip(self):
        spec = ("site=io.stream.read,fault=ioerror,times=2;"
                "site=gang.*,fault=crash,nth=3,rank=1,attempt=0")
        plan = FaultPlan.parse(spec, seed=5)
        assert plan.spec() == spec
        assert plan.seed == 5

    def test_parse_rejects_garbage(self):
        with pytest.raises(DMLCError, match="unknown fault"):
            FaultPlan.parse("site=x,fault=explode")
        with pytest.raises(DMLCError, match="unknown key"):
            FaultPlan.parse("site=x,fault=ioerror,frequency=2")
        with pytest.raises(DMLCError, match="site= and fault="):
            FaultPlan.parse("fault=ioerror")

    def test_times_trigger_fires_first_n(self):
        plan = FaultPlan.parse("site=a.b,fault=ioerror,times=2")
        for _ in range(2):
            with pytest.raises(IOError, match="injected fault"):
                plan.fire("a.b")
        plan.fire("a.b")  # third and later matches pass clean
        plan.fire("a.b")
        assert plan.injected == 2

    def test_nth_trigger_fires_exactly_once(self):
        plan = FaultPlan.parse("site=a.*,fault=ioerror,nth=3")
        plan.fire("a.x")
        plan.fire("a.y")
        with pytest.raises(IOError):
            plan.fire("a.z")
        plan.fire("a.x")
        assert plan.injected == 1

    def test_probability_trigger_is_seed_deterministic(self):
        def pattern(seed):
            plan = FaultPlan.parse("site=p.*,fault=ioerror,p=0.5",
                                   seed=seed)
            hits = []
            for _ in range(64):
                try:
                    plan.fire("p.x")
                    hits.append(0)
                except IOError:
                    hits.append(1)
            return hits

        assert pattern(7) == pattern(7)      # same seed => same faults
        assert pattern(7) != pattern(8)      # the seed is real
        assert 10 < sum(pattern(7)) < 54     # and it is ~a coin

    def test_rank_and_attempt_scoping(self, monkeypatch):
        monkeypatch.setenv("DMLC_TPU_TASK_ID", "1")
        monkeypatch.setenv("DMLC_TPU_ATTEMPT", "0")
        plan = FaultPlan.parse(
            "site=s,fault=ioerror,rank=1,attempt=0")
        with pytest.raises(IOError):
            plan.fire("s")
        # a restarted process (attempt bumped) runs clean
        monkeypatch.setenv("DMLC_TPU_ATTEMPT", "1")
        plan2 = FaultPlan.parse(
            "site=s,fault=ioerror,rank=1,attempt=0")
        plan2.fire("s")
        # another rank never matches
        monkeypatch.setenv("DMLC_TPU_ATTEMPT", "0")
        monkeypatch.setenv("DMLC_TPU_TASK_ID", "0")
        plan3 = FaultPlan.parse(
            "site=s,fault=ioerror,rank=1,attempt=0")
        plan3.fire("s")
        assert plan.injected == 1
        assert plan2.injected == plan3.injected == 0

    def test_delay_fault_sleeps_not_raises(self):
        plan = FaultPlan.parse(
            "site=d,fault=delay,delay_s=0.05,times=1")
        t0 = time.perf_counter()
        plan.fire("d")
        assert time.perf_counter() - t0 >= 0.04
        assert plan.events()[0]["fault"] == "delay"


# ---------------------------------------------------------------- seams

class TestInstrumentedSeams:
    def test_stream_open_retry_until_success(self, tmpfile):
        path = tmpfile("seam.bin", b"z" * 64)
        set_policy("io.stream.open",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        inject.install("site=io.stream.open,fault=ioerror,times=2")
        from dmlc_tpu.io.stream import create_stream
        with create_stream(path, "r") as s:
            assert s.read_all() == b"z" * 64
        assert retry_counts()["io.stream.open"] == 2

    def test_stream_read_retry_until_success(self, tmpfile):
        path = tmpfile("seam2.bin", b"q" * 128)
        set_policy("io.stream.read",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        from dmlc_tpu.io.stream import create_stream
        with create_stream(path, "r") as s:
            inject.install("site=io.stream.read,fault=ioerror,times=1")
            assert s.read_exact(128) == b"q" * 128
        assert retry_counts()["io.stream.read"] == 1

    def test_stream_read_truncation_surfaces_as_short_read(self,
                                                           tmpfile):
        path = tmpfile("seam3.bin", b"w" * 100)
        from dmlc_tpu.io.stream import create_stream
        with create_stream(path, "r") as s:
            inject.install(
                "site=io.stream.read,fault=truncate,times=1")
            # the torn read loses the tail; the framing layer's short-
            # read detection (read_exact) must catch it, not hang
            with pytest.raises(DMLCError, match="unexpected EOF"):
                s.read_exact(100)

    def test_midfile_truncation_is_eof_not_silent_shift(self, tmpfile):
        # truncation must pin the stream at EOF: with file bytes left
        # past the drop point, a mere shortening would let the next
        # read return SHIFTED bytes and read_exact would succeed with
        # silently wrong data — the exact corruption chaos exists to
        # surface, not create
        payload = bytes(range(200)) + bytes(range(56))
        path = tmpfile("seam3b.bin", payload)
        from dmlc_tpu.io.stream import create_stream
        with create_stream(path, "r") as s:
            inject.install(
                "site=io.stream.read,fault=truncate,times=1")
            with pytest.raises(DMLCError, match="unexpected EOF"):
                s.read_exact(100)

    def test_readinto_truncation_covered(self, tmpfile):
        # the in-place read path (pooled staging buffers) is part of
        # the seam too: truncation shortens the count and pins EOF
        path = tmpfile("seam3c.bin", b"r" * 100)
        from dmlc_tpu.io.stream import create_stream
        with create_stream(path, "r") as s:
            inject.install(
                "site=io.stream.read,fault=truncate,times=1")
            buf = bytearray(100)
            n = s.readinto(buf)
            assert n == 50 and bytes(buf[:n]) == b"r" * 50
            assert s.readinto(bytearray(50)) == 0  # EOF-pinned

    def test_read_retry_restores_file_position(self):
        # a buffered read that fails AFTER consuming bytes advances the
        # offset; the retried attempt must seek back or the stream
        # silently loses those bytes (shifted, wrong payloads)
        from dmlc_tpu.io.stream import FileStream

        class FlakyFile:
            def __init__(self, data):
                self.data = data
                self.pos = 0
                self.failed = False

            def read(self, n):
                if not self.failed:
                    self.failed = True
                    self.pos += 3  # consumed bytes, then the error
                    raise IOError("EIO mid-read")
                out = self.data[self.pos:self.pos + n]
                self.pos += len(out)
                return out

            def tell(self):
                return self.pos

            def seek(self, pos):
                self.pos = pos

        set_policy("io.stream.read",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        s = FileStream(FlakyFile(bytes(range(64))))
        assert s.read_exact(64) == bytes(range(64))

    def test_filesys_stat_retry(self, tmpfile):
        path = tmpfile("seam4.bin", b"s")
        set_policy("io.filesys.*",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        inject.install("site=io.filesys.stat,fault=ioerror,times=1")
        from dmlc_tpu.io.filesys import FileSystem, URI
        u = URI(path)
        info = FileSystem.get_instance(u).get_path_info(u)
        assert info.size == 1
        assert retry_counts()["io.filesys.stat"] == 1

    def test_spill_commit_retry(self, tmp_path):
        from dmlc_tpu.data.row_iter import RoundSpillWriter
        set_policy("spill.commit",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        block = RowBlock(offset=[0, 2], label=[1.0],
                         index=np.array([0, 3], np.uint32),
                         value=[0.5, 1.5])
        w = RoundSpillWriter(str(tmp_path / "r.pages"), nparts=1)
        w.add_row([block])
        inject.install("site=spill.commit,fault=ioerror,times=2")
        f = w.commit()
        assert os.path.exists(f.path) and f.rounds == 1
        rows = list(f.iter_rows())
        assert len(rows) == 1
        np.testing.assert_array_equal(rows[0][0].index, block.index)
        assert retry_counts()["spill.commit"] == 2

    def test_checkpoint_save_restore_retry(self, tmp_path):
        set_policy("checkpoint.*",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        from dmlc_tpu.io.checkpoint import load_pytree, save_pytree
        path = str(tmp_path / "ck.bin")
        inject.install("site=checkpoint.save,fault=ioerror,times=2;"
                       "site=checkpoint.restore,fault=ioerror,times=2")
        save_pytree({"a": np.arange(5)}, path)
        out = load_pytree(path)
        np.testing.assert_array_equal(out["a"], np.arange(5))
        counts = retry_counts()
        assert counts["checkpoint.save"] == 2
        assert counts["checkpoint.restore"] == 2

    def test_checkpoint_save_exhaustion_raises(self, tmp_path):
        set_policy("checkpoint.save",
                   RetryPolicy(max_attempts=2, sleep=_noop_sleep))
        from dmlc_tpu.io.checkpoint import save_pytree
        inject.install("site=checkpoint.save,fault=ioerror,times=9")
        with pytest.raises(IOError, match="injected fault"):
            save_pytree({"a": np.zeros(2)}, str(tmp_path / "ck2.bin"))

    def test_scrape_gang_retry_keeps_rank_visible(self):
        from dmlc_tpu.obs.serve import StatusServer, scrape_gang
        with StatusServer(port=0) as srv:
            inject.install("site=obs.scrape,fault=ioerror,times=1")
            merged = scrape_gang([srv.port])
            assert "unreachable" not in merged
            assert len(merged["workers"]) == 1
        assert retry_counts()["obs.scrape"] == 1

    def test_disk_row_iter_build_retries_transient_factory(
            self, tmpfile, tmp_path):
        # satellite: the page-cache build is the data-layer retry site,
        # now on resilience.RetryPolicy — a transiently failing source
        # re-parses instead of aborting the cache
        data = tmpfile("d.libsvm",
                       b"1 0:1 3:2\n0 1:1\n1 2:5 4:1\n" * 50)
        set_policy("data.pages.build",
                   RetryPolicy(max_attempts=3, sleep=_noop_sleep))
        from dmlc_tpu.data.parser import Parser
        from dmlc_tpu.data.row_iter import DiskRowIter
        calls = []

        def factory():
            calls.append(1)
            if len(calls) == 1:
                raise IOError("transient source")
            return Parser.create(data, 0, 1, format="libsvm")

        it = DiskRowIter(factory, str(tmp_path / "d.pages"))
        it.before_first()
        rows = 0
        while it.next():
            rows += it.value().size
        assert rows == 150
        assert len(calls) == 2
        assert retry_counts()["data.pages.build"] == 1

    def test_disk_row_iter_build_permanent_error_not_retried(
            self, tmp_path):
        from dmlc_tpu.data.row_iter import DiskRowIter
        calls = []

        def factory():
            calls.append(1)
            raise FileNotFoundError("no such corpus")

        with pytest.raises(FileNotFoundError):
            DiskRowIter(factory, str(tmp_path / "x.pages"))
        assert len(calls) == 1


# ------------------------------------------------------------ supervision

class TestGangSupervision:
    def test_worker_exit0_early_keeps_gang_running(self, tmp_path):
        # satellite: "exited 0 early" is a FINISHED member, not a dead
        # one — the slow worker still completes its write
        from dmlc_tpu.parallel.launch import launch_local
        script = tmp_path / "w.py"
        script.write_text(
            "import os, sys, time\n"
            "rank = os.environ['DMLC_TPU_TASK_ID']\n"
            "if rank == '0':\n"
            "    sys.exit(0)  # finishes immediately\n"
            "time.sleep(1.0)\n"
            f"open(os.path.join({str(tmp_path)!r}, 'slow-done'), "
            "'w').close()\n")
        t0 = time.monotonic()
        codes = launch_local(2, [sys.executable, str(script)],
                             timeout=60)
        assert codes == [0, 0]
        assert time.monotonic() - t0 >= 1.0
        assert (tmp_path / "slow-done").exists()

    def test_ps_roles_drained_after_workers_finish(self, tmp_path):
        # satellite: service roles wait for work forever by design;
        # "every worker exited 0" is their clean shutdown signal (the
        # pre-resilience poll loop hung on them)
        from dmlc_tpu.parallel.launch import launch_local
        script = tmp_path / "node.py"
        script.write_text(
            "import os, sys, time\n"
            "role = os.environ.get('DMLC_ROLE', 'worker')\n"
            "if role == 'worker':\n"
            "    sys.exit(0)\n"
            "time.sleep(300)  # a real scheduler/server never exits\n")
        t0 = time.monotonic()
        codes = launch_local(1, [sys.executable, str(script)],
                             num_servers=1)  # note: no timeout
        assert codes == [0, 0, 0]
        assert time.monotonic() - t0 < 60

    def test_ps_drain_beats_a_short_launch_timeout(self, tmp_path):
        # the grace window must clamp to the launch deadline: a run
        # whose every worker exited 0 must drain lingering service
        # roles and SUCCEED, not die as a misleading timeout failure
        from dmlc_tpu.parallel.launch import launch_local
        script = tmp_path / "node.py"
        script.write_text(
            "import os, sys, time\n"
            "if os.environ.get('DMLC_ROLE', 'worker') == 'worker':\n"
            "    sys.exit(0)\n"
            "time.sleep(300)\n")
        codes = launch_local(1, [sys.executable, str(script)],
                             num_servers=1, timeout=10)
        assert codes == [0, 0, 0]

    def test_restart_survives_injected_crash(self, tmp_path):
        from dmlc_tpu.parallel.launch import launch_local
        script = tmp_path / "w.py"
        script.write_text(
            "import os\n"
            "from dmlc_tpu.resilience import inject\n"
            "inject.install_if_env()\n"
            "inject.fire('work.step')\n"
            f"open(os.path.join({str(tmp_path)!r}, 'ok-'\n"
            "     + os.environ['DMLC_TPU_TASK_ID'] + '-'\n"
            "     + os.environ['DMLC_TPU_ATTEMPT']), 'w').close()\n")
        codes = launch_local(
            2, [sys.executable, str(script)], env=_gang_env(),
            faults="site=work.step,fault=crash,rank=1,attempt=0",
            restart_policy=RestartPolicy(max_restarts=2,
                                         backoff_base_s=0.05),
            timeout=120)
        assert codes == [0, 0]
        # rank 0 finished on attempt 0; rank 1 crashed (exit CRASH_EXIT)
        # and finished on attempt 1 with the same coordinates
        assert (tmp_path / "ok-0-0").exists()
        assert (tmp_path / "ok-1-1").exists()
        assert not (tmp_path / "ok-1-0").exists()
        assert CRASH_EXIT != 0

    def test_launch_faults_plan_seed_reaches_workers(self, tmp_path):
        # launch_local(faults=FaultPlan(seed=N)) must export the plan
        # seed (spec() carries clauses only) or every worker's p=
        # clauses would re-seed to 0 and the chaos schedule would not
        # reproduce the one the caller armed
        from dmlc_tpu.parallel.launch import launch_local
        script = tmp_path / "w.py"
        script.write_text(
            "import os\n"
            "from dmlc_tpu.resilience import inject\n"
            "plan = inject.install_if_env()\n"
            f"open(os.path.join({str(tmp_path)!r}, 'seed'), 'w')"
            ".write(str(plan.seed))\n")
        plan = FaultPlan.parse("site=never.fires,fault=ioerror,nth=999",
                               seed=42)
        codes = launch_local(1, [sys.executable, str(script)],
                             env=_gang_env(), faults=plan, timeout=60)
        assert codes == [0]
        assert (tmp_path / "seed").read_text() == "42"

    def test_budget_exhausted_tears_down_with_flight_bundle(
            self, tmp_path):
        from dmlc_tpu.parallel.launch import launch_local
        flight_dir = tmp_path / "flight"
        script = tmp_path / "w.py"
        script.write_text(
            "from dmlc_tpu.resilience import inject\n"
            "inject.install_if_env()\n"
            "inject.fire('work.step')\n")
        t0 = time.monotonic()
        with pytest.raises(DMLCError,
                           match="restart budget exhausted"):
            launch_local(
                1, [sys.executable, str(script)], env=_gang_env(),
                # every attempt crashes: no attempt= scope
                faults="site=work.step,fault=crash",
                restart_policy=RestartPolicy(max_restarts=1,
                                             backoff_base_s=0.05),
                flight_dir=str(flight_dir), timeout=120)
        assert time.monotonic() - t0 < 90  # teardown, not a hang
        bundles = [d for d in os.listdir(flight_dir)
                   if d.startswith("flight-")]
        assert bundles, "no launcher-side flight bundle written"
        reasons = []
        for b in bundles:
            with open(flight_dir / b / "MANIFEST.json") as f:
                reasons.append(json.load(f)["reason"])
        assert "gang_restart_budget_exhausted" in reasons


# ------------------------------------------------------- gang acceptance

_GANG_WORKER = r"""
import hashlib, os, sys
from dmlc_tpu.resilience import inject
inject.install_if_env()
from dmlc_tpu.data.parser import Parser
uri, out_dir = sys.argv[1], sys.argv[2]
rank = int(os.environ["DMLC_TPU_TASK_ID"])
nparts = int(os.environ["DMLC_TPU_NUM_WORKER"])
h = hashlib.sha256()
count = 0
p = Parser.create(uri, rank, nparts, format="libsvm", chunk_size=16384)
p.before_first()
while p.next():
    inject.fire("gang.block")      # the armed mid-epoch crash site
    h.update(p.value().copy().content_hash().encode())
    count += 1
if hasattr(p, "destroy"):
    p.destroy()
tmp = os.path.join(out_dir, f"out-{rank}.tmp")
with open(tmp, "w") as f:
    f.write(f"{count} {h.hexdigest()}\n")
os.replace(tmp, os.path.join(out_dir, f"out-{rank}.txt"))
"""


@pytest.fixture(scope="module")
def gang_data(tmp_path_factory):
    rng = np.random.RandomState(11)
    lines = [f"{i % 2} " + " ".join(
        f"{j}:{rng.rand():.5f}"
        for j in np.sort(rng.choice(400, rng.randint(2, 8),
                                    replace=False)))
        for i in range(20000)]
    p = tmp_path_factory.mktemp("resg") / "g.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


class TestGangCrashAcceptance:
    """ISSUE 5 acceptance: a real 2-process gang + injected mid-epoch
    crash -> auto-restart -> byte-identical epoch output, restart
    visible on /metrics and the merged gang trace."""

    def _run_gang(self, worker, data, out_dir, tmp_path, faults=None,
                  restart_policy=None, trace_dir=None):
        from dmlc_tpu.parallel.launch import launch_local
        os.makedirs(out_dir, exist_ok=True)
        return launch_local(
            2, [sys.executable, str(worker), data, out_dir],
            env=_gang_env(), faults=faults,
            restart_policy=restart_policy, trace_dir=trace_dir,
            timeout=300)

    def test_gang_survives_midepoch_crash_byte_identical(
            self, gang_data, tmp_path):
        from dmlc_tpu.obs.metrics import REGISTRY
        from dmlc_tpu.obs.serve import StatusServer
        worker = tmp_path / "gw.py"
        worker.write_text(_GANG_WORKER)
        clean_dir = str(tmp_path / "clean")
        chaos_dir = str(tmp_path / "chaos")
        trace_dir = str(tmp_path / "traces")

        # golden: the fault-free gang
        codes = self._run_gang(worker, gang_data, clean_dir, tmp_path)
        assert codes == [0, 0]
        clean = {r: open(os.path.join(clean_dir, f"out-{r}.txt"))
                 .read() for r in range(2)}
        assert all(clean.values())

        # chaos: rank 1 hard-crashes at its 3rd block, attempt 0 only
        before = REGISTRY.counter("resilience.restart").value
        codes = self._run_gang(
            worker, gang_data, chaos_dir, tmp_path,
            faults="site=gang.block,fault=crash,nth=3,rank=1,attempt=0",
            restart_policy=RestartPolicy(max_restarts=2,
                                         backoff_base_s=0.05),
            trace_dir=trace_dir)
        assert codes == [0, 0]
        chaos = {r: open(os.path.join(chaos_dir, f"out-{r}.txt"))
                 .read() for r in range(2)}
        # the restarted worker replayed its identical shard stream
        assert chaos == clean

        # the restart is visible in the launcher's /metrics ...
        assert REGISTRY.counter("resilience.restart").value \
            == before + 1
        with StatusServer(port=0) as srv:
            from urllib.request import urlopen
            with urlopen(srv.url("/metrics"), timeout=10) as resp:
                body = resp.read().decode()
        restart_lines = [
            line for line in body.splitlines()
            if line.startswith("dmlc_resilience_restart_total ")]
        assert restart_lines and \
            float(restart_lines[0].split()[1]) >= 1

        # ... and on the merged gang trace (supervisor track)
        with open(os.path.join(trace_dir, "trace-gang.json")) as f:
            merged = json.load(f)
        names = {e.get("name") for e in merged["traceEvents"]}
        assert "gang/restart/worker-1" in names
        assert any(n.startswith("gang/spawn/") for n in names)


# ---------------------------------------------------------- bench chaos

class TestBenchChaos:
    def test_bench_suite_chaos_degrades_not_aborts(
            self, tmpfile, monkeypatch, capsys):
        # --chaos arms the plan for the run; a config whose I/O rides
        # the guarded seams retries through injected faults and still
        # emits a SUCCESS line (with the chaos accounting), not an
        # "error" line
        from dmlc_tpu import bench_suite
        data = tmpfile("bench.bin", b"y" * 4096)
        set_policy("io.stream.*",
                   RetryPolicy(max_attempts=4, sleep=_noop_sleep))

        def chaos_probe(mb, dev):
            from dmlc_tpu.io.stream import create_stream
            t0 = time.perf_counter()
            with create_stream(data, "r") as s:
                payload = s.read_exact(4096)
            dt = time.perf_counter() - t0
            return {"config": "chaos_probe", "gbps": 4096 / dt / 1e9,
                    "bytes": len(payload)}

        def doomed(mb, dev):
            inject.fire("bench.doomed")  # always-armed ioerror below
            return {"config": "doomed", "gbps": 0.0}

        # one main() over BOTH configs (doomed first) so the per-config
        # delta baselines are exercised across a failing config
        monkeypatch.setattr(bench_suite, "CONFIGS",
                            {98: ("doomed", doomed),
                             99: ("chaos_probe", chaos_probe)})
        bench_suite.main([
            "--mb", "1", "--cold",
            "--chaos",
            "site=bench.doomed,fault=ioerror;"
            "site=io.stream.open,fault=ioerror,times=1;"
            "site=io.stream.read,fault=ioerror,times=1"])
        out = [json.loads(line) for line in
               capsys.readouterr().out.splitlines() if line.strip()]
        assert len(out) == 2
        # config 98 aborts (un-retryable by count: every fire raises)
        assert "error" in out[0]
        # config 99 degrades gracefully, and its chaos accounting is a
        # per-config DELTA: the doomed config's injected faults are
        # not credited to it
        assert "error" not in out[1]
        assert out[1]["bytes"] == 4096
        assert out[1]["chaos"]["injected"] == 2
        assert out[1]["chaos"]["retries"]["io.stream.open"] == 1
        assert "bench.doomed" not in out[1]["chaos"]["retries"]
