"""Continuous sampling profiler (PR 10): merged Python+native
flamegraphs, the /profile endpoint, and hot-frame verdict evidence.

Covers: wait classification and the byte-budgeted coarsening trie
(budget held, total sample weight conserved), collapsed/speedscope
exports, the sampler's install/env contract, the synthetic hot-loop
attribution gate (>=60% of the running thread's samples land on the
known hot function), on-CPU/off-CPU separation, the <2% tier-1
overhead gate with the sampler installed, /profile live + burst +
404-with-hint, the obsctl profile subcommand, hot_frames evidence in
the analyze verdict (schema 2, lint-pinned), watchdog stall reports
and flight crash bundles attaching a forced profile (a REAL
subprocess crash pins the bundle's profile.txt member), the native
phase beacons (fused epoch serves a /profile with BOTH Python frames
and native leaves; sampled parse share agrees with parse_busy_ns;
sharded sub-parsers carry shard tags), and a REAL 2-process gang
scraped via /profile during the run.
"""

import glob
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from dmlc_tpu.obs import analyze as obs_analyze
from dmlc_tpu.obs import flight as obs_flight
from dmlc_tpu.obs import log as obs_log
from dmlc_tpu.obs import profile as obs_prof
from dmlc_tpu.obs import timeseries as obs_ts
from dmlc_tpu.obs import trace as obs_trace
from dmlc_tpu.obs import watchdog as obs_watchdog
from dmlc_tpu.obs.export import (
    collapsed_lines, speedscope_doc, write_collapsed,
)
from dmlc_tpu.obs.serve import StatusServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import obsctl  # noqa: E402


def _native_ok() -> bool:
    from dmlc_tpu import native
    return native.native_available()


@pytest.fixture(autouse=True)
def _obs_clean():
    """No profiler, flight recorder, ring, or trace state leaks —
    including the duty-guard's cross-instance tick-cost prior (each
    test gets fresh-process semantics; the prior reflects whatever
    thread population the PREVIOUS test left)."""
    obs_prof.uninstall()
    obs_prof._tick_cost_prior_s = 0.0
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()
    yield
    obs_prof.uninstall()
    obs_flight.uninstall()
    obs_ts.uninstall()
    obs_trace.stop()
    obs_trace.clear_fallback()
    obs_log.reset()


def _get(url: str, timeout_s: float = 15.0):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.status, resp.read()


def _hot_spin(seconds: float) -> int:
    x = 0
    deadline = time.perf_counter() + seconds
    while time.perf_counter() < deadline:
        for i in range(2000):
            x += i * i
    return x


def _frame_weight(doc, predicate) -> int:
    """Total self weight over frames whose name satisfies predicate."""
    total = 0

    def visit(node):
        nonlocal total
        if predicate(node.get("name") or ""):
            total += int(node.get("self") or 0)
        for c in node.get("children") or []:
            visit(c)

    for root in (doc.get("threads") or {}).values():
        visit(root)
    return total


def _thread_weight(doc, label) -> int:
    root = (doc.get("threads") or {}).get(label)
    if root is None:
        return 0
    total = 0

    def visit(node):
        nonlocal total
        total += int(node.get("self") or 0) + int(node.get("folded")
                                                 or 0)
        for c in node.get("children") or []:
            visit(c)

    visit(root)
    return total


class TestWaitClassification:
    def test_stdlib_wait_sites(self):
        assert obs_prof.classify_wait("threading.py", "wait")
        assert obs_prof.classify_wait("queue.py", "get")
        assert obs_prof.classify_wait("selectors.py", "select")
        assert obs_prof.classify_wait("socket.py", "recv")

    def test_generic_wait_names(self):
        assert obs_prof.classify_wait("anything.py", "acquire")
        assert obs_prof.classify_wait("worker.py", "sleep")

    def test_hot_names_are_not_waits(self):
        assert not obs_prof.classify_wait("parser.py", "tokenize")
        assert not obs_prof.classify_wait("queue.py", "qsize")
        assert not obs_prof.classify_wait("x.py", "get_value")


class TestFrameTrie:
    def test_add_and_weights(self):
        t = obs_prof.FrameTrie()
        t.add("main", ["a.py:f", "a.py:g"])
        t.add("main", ["a.py:f", "a.py:g"])
        t.add("main", ["a.py:f", "a.py:h"], wait=True)
        doc = t.to_dict()
        assert doc["samples"] == 3 and doc["wait_samples"] == 1
        root = doc["threads"]["main"]
        (f,) = root["children"]
        assert f["name"] == "a.py:f" and f["self"] == 0
        kids = {c["name"]: c["self"] for c in f["children"]}
        assert kids == {"a.py:g": 2, "a.py:h": 1}

    def test_coarsen_holds_budget_and_conserves_weight(self):
        # thousands of unique cold paths against the floor budget:
        # the trie must stay under budget by FOLDING weight upward,
        # never by dropping samples
        t = obs_prof.FrameTrie(budget_bytes=16 << 10)
        n = 4000
        for i in range(n):
            t.add("main", [f"mod{i % 7}.py:f", f"leaf_{i}.py:g{i}"])
        for _ in range(50):
            t.add("main", ["mod0.py:f", "hot.py:hot"])  # the survivor
        doc = t.to_dict()
        assert doc["coarsenings"] > 0
        assert doc["approx_bytes"] <= doc["budget_bytes"]
        total = sum(w for _, w in _walk(doc))
        assert total == doc["samples"] == n + 50
        # the heavy path survives coarsening with its own name
        assert _frame_weight(doc, lambda s: s == "hot.py:hot") == 50

    def test_folded_weight_renders_as_coarsened_leaf(self):
        t = obs_prof.FrameTrie(budget_bytes=16 << 10)
        for i in range(4000):
            t.add("main", [f"leaf_{i}.py:g{i}"])
        lines = collapsed_lines(t.to_dict())
        assert any(obs_prof.FOLDED_FRAME in ln for ln in lines)


def _walk(doc):
    from dmlc_tpu.obs.export import _walk_profile
    return list(_walk_profile(doc))


class TestExports:
    def _doc(self):
        t = obs_prof.FrameTrie()
        t.add("main", ["a.py:f", "b.py:g"])
        t.add("main", ["a.py:f", "b.py:g"])
        t.add("io", ["c.py:h", "threading.py:wait",
                     obs_prof.WAIT_FRAME], wait=True)
        d = {"schema": obs_prof.PROFILE_SCHEMA, "hz": 10.0,
             "duration_s": 1.0, "burst": False}
        d.update(t.to_dict())
        return d

    def test_collapsed_lines(self):
        lines = collapsed_lines(self._doc())
        assert "main;a.py:f;b.py:g 2" in lines
        assert ("io;c.py:h;threading.py:wait;"
                f"{obs_prof.WAIT_FRAME} 1") in lines

    def test_write_collapsed(self, tmp_path):
        p = str(tmp_path / "prof.collapsed")
        write_collapsed(self._doc(), p)
        body = open(p).read().strip().splitlines()
        assert body == collapsed_lines(self._doc())

    def test_speedscope_golden_keys_and_weights(self):
        ss = speedscope_doc(self._doc())
        assert ss["$schema"] == \
            "https://www.speedscope.app/file-format-schema.json"
        prof = ss["profiles"][0]
        assert prof["type"] == "sampled"
        assert len(prof["samples"]) == len(prof["weights"])
        assert prof["endValue"] == sum(prof["weights"]) == 3
        names = [f["name"] for f in ss["shared"]["frames"]]
        for s in prof["samples"]:  # every index resolves
            for i in s:
                assert 0 <= i < len(names)
        assert "b.py:g" in names


class TestStackProfiler:
    def test_install_if_env(self, monkeypatch):
        monkeypatch.delenv(obs_prof.ENV_PROFILE_HZ, raising=False)
        assert obs_prof.install_if_env() is None
        monkeypatch.setenv(obs_prof.ENV_PROFILE_HZ, "0")
        assert obs_prof.install_if_env() is None  # 0 disables
        monkeypatch.setenv(obs_prof.ENV_PROFILE_HZ, "97")
        monkeypatch.setenv(obs_prof.ENV_PROFILE_BYTES, str(64 << 10))
        prof = obs_prof.install_if_env()
        assert prof is not None and obs_prof.active() is prof
        assert prof.hz == 97
        assert prof.trie.budget_bytes == 64 << 10
        # idempotent: a second hook call returns the SAME profiler
        assert obs_prof.install_if_env() is prof
        obs_prof.uninstall()
        assert obs_prof.active() is None
        # a malformed BUDGET falls back to the default — it must not
        # silently drop a valid rate request
        monkeypatch.setenv(obs_prof.ENV_PROFILE_BYTES, "512k")
        prof = obs_prof.install_if_env()
        assert prof is not None and prof.hz == 97
        assert prof.trie.budget_bytes == obs_prof.DEFAULT_BUDGET_BYTES
        obs_prof.uninstall()

    def test_hot_loop_attribution(self):
        """The ISSUE acceptance: >=60% of the running thread's samples
        land on the known hot function. Spins until the sampler has
        collected enough of this thread — under suite load the
        duty-cycle guard throttles the effective rate, so a fixed
        spin time has no guaranteed sample count."""
        prof = obs_prof.install(hz=250)
        me = threading.current_thread().name
        deadline = time.perf_counter() + 8.0
        doc = prof.to_dict()
        while time.perf_counter() < deadline:
            _hot_spin(0.3)
            doc = prof.to_dict()
            if _thread_weight(doc, me) >= 20:
                break
        obs_prof.uninstall()
        mine = _thread_weight(doc, me)
        hot = _frame_weight(doc, lambda s: s.endswith(":_hot_spin"))
        assert mine >= 10, doc["samples"]
        assert hot >= 0.6 * mine, (hot, mine)

    def test_wait_separation(self):
        prof = obs_prof.install(hz=250)
        ev = threading.Event()
        t = threading.Thread(target=lambda: ev.wait(20.0),
                             name="prof-waiter")
        t.start()
        deadline = time.perf_counter() + 8.0
        doc = prof.to_dict()
        while time.perf_counter() < deadline:
            time.sleep(0.1)
            doc = prof.to_dict()
            if _thread_weight(doc, "prof-waiter") >= 3:
                break
        ev.set()
        t.join()
        obs_prof.uninstall()
        assert doc["wait_samples"] > 0
        # the blocked thread's samples sit under the [off-cpu] leaf
        # (a stray bootstrap-phase sample may precede the block, so
        # the DOMINANT share is asserted, not every line)
        lines = [ln for ln in collapsed_lines(doc)
                 if ln.startswith("prof-waiter;")]
        assert lines, collapsed_lines(doc)
        offcpu = sum(int(ln.rsplit(" ", 1)[1]) for ln in lines
                     if obs_prof.WAIT_FRAME in ln)
        total = _thread_weight(doc, "prof-waiter")
        assert total > 0 and offcpu >= 0.8 * total, (offcpu, total)
        # and the Event.wait path is named: threading.py:wait
        assert any("threading.py:wait" in ln for ln in lines), lines

    def test_sample_now_rate_limited_unless_forced(self):
        prof = obs_prof.StackProfiler(hz=1)  # period 1 s, NOT started
        assert prof.sample_now() is True
        assert prof.sample_now() is False  # inside half a period
        assert prof.sample_now(force=True) is True  # the dump bypass
        assert prof.trie.samples >= 2

    def test_burst_is_fresh_and_continuous_keeps_accumulating(self):
        prof = obs_prof.install(hz=100)
        _hot_spin(0.15)
        before = prof.trie.samples
        assert before > 0
        # the burst runs on THIS thread and excludes itself (the
        # /profile handler shape) — give it a workload to observe
        spinner = threading.Thread(target=_hot_spin, args=(0.4,),
                                   name="burst-spinner")
        spinner.start()
        doc = prof.burst(0.2, hz=200)
        spinner.join()
        assert doc["burst"] is True
        assert doc["duration_s"] >= 0.2
        assert doc["samples"] > 0
        # the burst wrote a FRESH trie: the continuous one kept its
        # pre-burst weight (and may have grown — the sampler never
        # pauses), and the continuous dump still says burst=False
        assert prof.trie.samples >= before
        cont = prof.to_dict()
        assert cont["burst"] is False
        # the burst's own samples never land in the continuous trie:
        # its capture thread is excluded while the burst runs, so the
        # continuous trie carries no profile.py burst frames
        assert _frame_weight(
            cont, lambda s: s == "profile.py:burst") == 0
        obs_prof.uninstall()

    def test_overhead_smoke_under_2pct(self, tmp_path):
        """Tier-1 gate (the ISSUE acceptance number): the sampler at
        its default rate costs <2% of a pipeline epoch. Interleaved
        min-of-5, the history/tracing gate shape, so credit drift
        hits both sides symmetrically."""
        from dmlc_tpu.pipeline import Pipeline
        # epochs long enough (~0.4 s) that the flat 10 ms grace and
        # the box's climate noise are small against the wall being
        # compared — at 0.1 s the gate is all grace, no power
        lines = [f"{i % 2} 1:0.5 7:1.25 9:{i}.0"
                 for i in range(16000)]
        uri = tmp_path / "overhead.libsvm"
        uri.write_text("\n".join(lines) + "\n")
        built = (Pipeline.from_uri(str(uri))
                 .parse(format="libsvm", engine="python",
                        chunk_size=4096)
                 .batch(256)
                 .build())

        def epoch_wall():
            t0 = time.perf_counter()
            for _ in built:
                pass
            return time.perf_counter() - t0

        epoch_wall()  # warm caches/imports outside the measurement
        off, on = [], []
        sampled = 0
        # 7 rounds of adjacent (on, off) pairs, alternating which
        # side runs first: this burstable box swings epoch walls 2x
        # within a run (credit climate), so the gate judges the
        # QUIETEST PAIR — climate is shared inside a pair, and a real
        # >=2% sampler tax would show in every pair
        for i in range(7):
            first_on = i % 2 == 1
            for is_on in (first_on, not first_on):
                if is_on:
                    prof = obs_prof.install()  # DEFAULT_HZ contract
                    try:
                        on.append(epoch_wall())
                    finally:
                        sampled += prof.trie.samples
                        obs_prof.uninstall()
                else:
                    off.append(epoch_wall())
        built.close()
        assert sampled > 0  # sampling was actually on
        grace = 0.010 / min(off)  # flat 10 ms, scaled to the wall
        ratios = [a / b for a, b in zip(on, off)]
        assert min(ratios) <= 1.02 + grace, (on, off, ratios)


class TestProfileEndpoint:
    def test_404_with_hint_when_uninstalled(self):
        with StatusServer() as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(srv.url("/profile"))
            assert e.value.code == 404
            payload = json.load(e.value)
            assert "DMLC_TPU_PROFILE_HZ" in payload["hint"]

    def test_continuous_and_burst(self):
        prof = obs_prof.install(hz=200)
        _hot_spin(0.2)
        with StatusServer() as srv:
            doc = json.loads(_get(srv.url("/profile"))[1])
            assert doc["schema"] == obs_prof.PROFILE_SCHEMA
            assert doc["samples"] > 0 and doc["burst"] is False
            burst = json.loads(_get(
                srv.url("/profile?seconds=0.2&hz=100"))[1])
            assert burst["burst"] is True
            assert burst["duration_s"] >= 0.2
        assert prof is obs_prof.active()
        obs_prof.uninstall()


class TestObsctlProfile:
    def test_cli_surfaces_404_payload(self, capsys):
        """The uninstalled-server path: exit 2 and the server's
        enable hint printed, not a bare HTTP error (the PR 8 _fetch
        HTTPError contract)."""
        with StatusServer() as srv:
            rc = obsctl.main(["profile", "--port", str(srv.port)])
        out = capsys.readouterr().out
        assert rc == 2
        assert "DMLC_TPU_PROFILE_HZ" in out

    def test_cli_summary_and_out(self, tmp_path, capsys):
        obs_prof.install(hz=200)
        _hot_spin(0.25)
        with StatusServer() as srv:
            rc = obsctl.main(["profile", "--port", str(srv.port),
                              "--keys", "3"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "samples" in out and "%" in out
            dest = str(tmp_path / "p.collapsed")
            rc = obsctl.main(["profile", "--port", str(srv.port),
                              "--out", dest])
            assert rc == 0 and os.path.getsize(dest) > 0
            dest2 = str(tmp_path / "p.speedscope.json")
            rc = obsctl.main(["profile", "--port", str(srv.port),
                              "--out", dest2, "--format",
                              "speedscope"])
            assert rc == 0
            assert "$schema" in json.load(open(dest2))
        obs_prof.uninstall()


def _profile_doc(threads):
    return {"schema": obs_prof.PROFILE_SCHEMA, "hz": 100.0,
            "duration_s": 1.0, "burst": False,
            "samples": sum(_n(v) for v in threads.values()),
            "wait_samples": 0, "budget_bytes": 1 << 20,
            "approx_bytes": 1024, "coarsenings": 0, "min_fold": 2,
            "threads": threads}


def _n(node):
    return (int(node.get("self") or 0) + int(node.get("folded") or 0)
            + sum(_n(c) for c in node.get("children") or []))


def _leaf(name, n):
    return {"name": name, "self": n, "folded": 0, "children": []}


def _root(label, children):
    return {"name": label, "self": 0, "folded": 0,
            "children": children}


class TestVerdictHotFrames:
    def _parse_snap(self):
        return {"schema": 1, "epoch": 1, "wall_s": 2.0, "knobs": {},
                "stages": [{"name": "parse", "kind": "parse",
                            "wait_s": 1.5, "bytes": 10 ** 9}]}

    def test_hot_frames_filtered_to_bound_stage(self):
        doc = _profile_doc({"MainThread": _root("MainThread", [
            _leaf("libsvm_parser.py:tokenize", 60),
            _leaf("device_iter.py:xfer_drain", 40),
        ])})
        v = obs_analyze.attribute(self._parse_snap(), profile_doc=doc)
        assert v["bound"] == "parse"
        frames = [h["frame"] for h in v["hot_frames"]]
        assert "libsvm_parser.py:tokenize" in frames
        assert "device_iter.py:xfer_drain" not in frames
        assert any(e.startswith("hot frames (parse)")
                   for e in v["evidence"])

    def test_native_leaves_rank_for_parse(self):
        doc = _profile_doc({
            "native/worker-0": _root("native/worker-0", [
                _leaf("native:parse", 80),
                _leaf("native:worker_wait", 20),
            ])})
        v = obs_analyze.attribute(self._parse_snap(), profile_doc=doc)
        frames = [h["frame"] for h in v["hot_frames"]]
        assert frames == ["native:parse"]  # wait leaves never rank

    def test_fallback_to_overall_top_when_no_hint_matches(self):
        doc = _profile_doc({"MainThread": _root("MainThread", [
            _leaf("somewhere.py:unrelated", 10)])})
        v = obs_analyze.attribute(self._parse_snap(), profile_doc=doc)
        assert [h["frame"] for h in v["hot_frames"]] == \
            ["somewhere.py:unrelated"]
        # the evidence line must SAY these are overall-top frames,
        # not claim them as the parse stage's own
        line = next(e for e in v["evidence"]
                    if e.startswith("hot frames"))
        assert "overall" in line and "no sampled frame matched" in line

    def test_empty_without_profiler(self):
        assert obs_prof.active() is None
        v = obs_analyze.attribute(self._parse_snap())
        assert v["hot_frames"] == []
        assert sorted(v) == sorted(obs_analyze.VERDICT_KEYS)
        assert v["schema"] == obs_analyze.ANALYSIS_SCHEMA == 4

    def test_live_profiler_feeds_verdict(self):
        obs_prof.install(hz=250)
        _hot_spin(0.4)
        v = obs_analyze.attribute(self._parse_snap())
        obs_prof.uninstall()
        assert v["hot_frames"], "installed profiler produced no frames"
        for h in v["hot_frames"]:
            assert sorted(h) == ["frac", "frame", "samples"]


class TestStallAndCrashAttachments:
    def test_stall_report_attaches_profile(self):
        obs_prof.install(hz=100)
        wd = obs_watchdog.Watchdog(threshold_s=30.0)
        report = wd._build_report([])
        assert isinstance(report["profile"], list)
        assert report["profile"], "forced sample left no lines"
        obs_prof.uninstall()

    def test_stall_report_without_profiler_is_none(self):
        report = obs_watchdog.Watchdog(
            threshold_s=30.0)._build_report([])
        assert report["profile"] is None

    def test_subprocess_crash_bundle_pins_profile_txt(self, tmp_path):
        """A REAL worker crash under launch_local(profile_hz=...)
        leaves a bundle whose MANIFEST pins profile.txt, holding the
        run's collapsed stacks (env wiring included end to end)."""
        from dmlc_tpu.parallel.launch import launch_local
        from dmlc_tpu.utils.logging import DMLCError
        out = str(tmp_path / "flight")
        script = tmp_path / "crash.py"
        script.write_text(
            "import time\n"
            "from dmlc_tpu.obs.profile import install_if_env\n"
            "prof = install_if_env()\n"
            "assert prof is not None, 'profile env missing'\n"
            "from dmlc_tpu.obs.flight import install_if_env as fl\n"
            "assert fl() is not None\n"
            "deadline = time.perf_counter() + 0.4\n"
            "x = 0\n"
            "while time.perf_counter() < deadline:\n"
            "    for i in range(1000):\n"
            "        x += i\n"
            "raise RuntimeError('deliberate profile crash')\n"
        )
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "")
            .split(os.pathsep))}
        with pytest.raises(DMLCError):
            launch_local(1, [sys.executable, str(script)], env=env,
                         flight_dir=out, profile_hz=100, timeout=120)
        bundles = glob.glob(os.path.join(out, "flight-*"))
        assert len(bundles) == 1, bundles
        manifest = json.load(open(
            os.path.join(bundles[0], "MANIFEST.json")))
        assert manifest["files"].get("profile.txt") == "ok"
        body = open(os.path.join(bundles[0], "profile.txt")).read()
        lines = [ln for ln in body.splitlines() if ln.strip()]
        assert lines, "profile.txt is empty"
        # collapsed-stack shape: "thread;frame;... N"
        for ln in lines:
            head, _, weight = ln.rpartition(" ")
            assert head and weight.isdigit(), ln

    def test_clean_exit_leaves_nothing(self, tmp_path):
        """An uninstalled profiler + clean process: no profile.txt
        appears anywhere (flight's clean-exit contract holds)."""
        out = str(tmp_path / "flight")
        fl = obs_flight.install(out_dir=out)
        d = fl.dump("test_no_profiler")
        assert not os.path.exists(os.path.join(d, "profile.txt"))
        manifest = json.load(open(os.path.join(d, "MANIFEST.json")))
        assert "profile.txt" not in manifest["files"]
        obs_flight.uninstall()


@pytest.mark.skipif(not _native_ok(), reason="native engine not built")
class TestNativeBeacons:
    def _corpus(self, tmp_path, rows=120000):
        p = tmp_path / "beacon.libsvm"
        with open(p, "w") as f:
            for i in range(rows):
                f.write(f"{i % 2} {i % 97}:1.5 {(i * 7) % 89}:2.25 "
                        f"{(i * 3) % 53}:0.5\n")
        return str(p)

    def test_profile_serves_merged_python_and_native(self, tmp_path):
        """THE acceptance: a live run serves /profile with a merged
        flamegraph holding BOTH Python frames and native phase
        leaves."""
        from dmlc_tpu.native import bindings
        path = self._corpus(tmp_path)
        obs_prof.install(hz=250)
        par = bindings.NativeLibSVMParser(path, nthreads=2,
                                          chunk_size=16384)
        par.set_test_touch_rounds(60)  # real byte-touching work: the
        # epoch spans many sampler ticks without sleeping
        done = threading.Event()

        def consume():
            while par.next():
                pass
            done.set()

        def merged(d):
            labels = set(d.get("threads") or {})
            return (_frame_weight(
                d, lambda s: s == "native:parse") > 0
                and any(not lb.startswith("native/")
                        for lb in labels))

        t = threading.Thread(target=consume, name="beacon-consumer")
        doc = None
        with StatusServer() as srv:
            t.start()
            deadline = time.time() + 30.0
            while time.time() < deadline:
                doc = json.loads(_get(srv.url("/profile"))[1])
                # the trie is cumulative: a post-epoch fetch still
                # carries everything sampled during the run
                if merged(doc) or done.is_set():
                    break
                time.sleep(0.02)
            if doc is not None and not merged(doc):
                doc = json.loads(_get(srv.url("/profile"))[1])
            t.join(timeout=60)
        par.destroy()
        obs_prof.uninstall()
        assert doc is not None
        labels = set(doc["threads"])
        assert any(lb.startswith("native/worker") for lb in labels), \
            labels
        assert any(not lb.startswith("native/") for lb in labels), \
            labels
        assert _frame_weight(doc, lambda s: s == "native:parse") > 0

    def test_beacon_parity_with_busy_counters(self, tmp_path):
        """The sampled native:parse share of worker samples agrees
        with the engine's own parse_busy_ns busy share — the beacons
        attribute the same time the counters measure."""
        from dmlc_tpu.native import bindings
        path = self._corpus(tmp_path)
        nthreads = 2
        par = bindings.NativeLibSVMParser(path, nthreads=nthreads,
                                          chunk_size=16384)
        # heavy per-chunk byte-touching: the epoch spans enough
        # sampler ticks for the share comparison to have power even
        # under the duty-cycle guard's throttled effective rate —
        # and IDENTICAL epochs repeat until the floor is met (the
        # guard makes per-epoch sample counts load-dependent; the
        # busy SHARE is stationary across replays)
        par.set_test_touch_rounds(160)
        prof = obs_prof.install(hz=250)
        parse = wait = 0
        for _ in range(6):
            while par.next():
                pass
            doc = prof.to_dict()
            stats = par.stats()
            parse = _frame_weight(doc, lambda s: s == "native:parse")
            wait = _frame_weight(doc,
                                 lambda s: s == "native:worker_wait")
            if parse + wait >= 20:
                break
            par.before_first()
        par.destroy()
        obs_prof.uninstall()
        assert parse + wait >= 20, (parse, wait, doc["samples"])
        sampled_share = parse / (parse + wait)
        busy_share = stats["parse_busy_ns"] / (
            nthreads * max(1, stats["wall_ns"]))
        assert abs(sampled_share - busy_share) <= 0.35, \
            (sampled_share, busy_share, parse, wait, stats)
        # the busy side must dominate under touch-round load: the
        # beacons would fail this if parse/wait were swapped
        assert sampled_share > 0.5, (sampled_share, busy_share)

    def test_sharded_subs_carry_shard_tags(self, tmp_path):
        from dmlc_tpu.native import bindings
        path = self._corpus(tmp_path, rows=60000)
        sp = bindings.NativeShardedTextParser(
            path, shards=2, format="libsvm", nthreads=2,
            chunk_size=16384)
        shards = set()
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                for kind, _idx, _phase, shard in bindings.prof_read():
                    if kind in (1, 2):  # reader/worker slots
                        shards.add(shard)
                time.sleep(0.002)

        t = threading.Thread(target=poll)
        t.start()
        while sp.next_padded(4096, row_bucket=4096,
                             nnz_bucket=4096 * 3) is not None:
            pass
        stop.set()
        t.join()
        sp.destroy()
        assert {0, 1} <= shards, shards
        # slots release with the pipelines: nothing leaks after destroy
        assert bindings.prof_read() == []


class TestGangProfileLive:
    def test_two_process_gang_serves_profile(self, tmp_path):
        """Extends the PR 4/8 scrape-under-load pattern: a REAL
        2-process launch_local gang under profile_hz serves /profile
        on every rank DURING the run, samples rising."""
        from dmlc_tpu.parallel.launch import (
            find_free_ports, launch_local,
        )
        script = tmp_path / "gang_worker.py"
        stop_file = tmp_path / "stop"
        script.write_text(
            "import os, sys, time\n"
            "from dmlc_tpu.obs.serve import serve_if_env\n"
            "from dmlc_tpu.obs.profile import install_if_env\n"
            "assert serve_if_env() is not None\n"
            "assert install_if_env() is not None\n"
            "deadline = time.time() + 60\n"
            "x = 0\n"
            "while time.time() < deadline:\n"
            "    for i in range(20000):\n"
            "        x += i * i\n"
            "    if os.path.exists(sys.argv[1]):\n"
            "        break\n"
        )
        ports = find_free_ports(2)
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "")
            .split(os.pathsep))}
        result = {}

        def gang():
            try:
                result["codes"] = launch_local(
                    2, [sys.executable, str(script), str(stop_file)],
                    env=env, serve_ports=ports, profile_hz=97,
                    timeout=90)
            except Exception as e:  # noqa: BLE001
                result["error"] = e

        t = threading.Thread(target=gang, daemon=True)
        t.start()
        try:
            deadline = time.time() + 45.0
            docs = {}
            while time.time() < deadline and len(docs) < 2:
                for port in ports:
                    if port in docs:
                        continue
                    try:
                        doc = json.loads(_get(
                            f"http://127.0.0.1:{port}/profile",
                            timeout_s=2.0)[1])
                    except (OSError, urllib.error.URLError,
                            ValueError):
                        continue
                    if doc.get("samples"):
                        docs[port] = doc
                time.sleep(0.05)
            assert len(docs) == 2, \
                f"gang never served /profile: {result}"
            for doc in docs.values():
                assert doc["schema"] == obs_prof.PROFILE_SCHEMA
                assert doc["hz"] == 97
                assert doc["threads"], doc
        finally:
            stop_file.write_text("stop")
            t.join(timeout=60.0)
        assert result.get("codes") == [0, 0], result
