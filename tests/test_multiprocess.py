"""REAL multi-process jax.distributed tests (VERDICT r1 #3/#4).

launch_local forks 2 worker processes that each call init_from_env()
(actual rendezvous over a coordinator socket, CPU backend, 2 virtual
devices per process = 4 global devices), stream skew-sharded data
through ShardedRowBlockIter, train collectively, ShardedCheckpoint.save,
then a FRESH launch restores and continues — executing the
process_count()>1 branches in sharded.py/checkpoint.py/launch.py that
single-process tests cannot reach. A single-process run over the same
4-part mesh is the golden: batch counts and parameters must agree.

Reference mechanism being mirrored: tracker/dmlc_tracker/local.py
(the reference tests multi-node by forking local workers that truly
connect to the tracker).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dmlc_tpu.parallel.launch import launch_local

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")


@pytest.fixture(scope="module")
def skewed_file(tmp_path_factory):
    """Record sizes grow sharply along the file, so equal BYTE shards get
    very different ROW counts — the lockstep empty-padding branch in
    ShardedRowBlockIter must fire on the early-exhausted parts."""
    rng = np.random.RandomState(0)
    lines = []
    for i in range(1200):
        nnz = 2 if i < 900 else rng.randint(30, 60)  # tiny rows then huge
        idx = np.sort(rng.choice(2048, nnz, replace=False))
        lines.append(f"{i % 2} " + " ".join(
            f"{j}:{rng.rand():.4f}" for j in idx))
    p = tmp_path_factory.mktemp("mp") / "skew.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


def _worker_env(local_devices: int):
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={local_devices}",
        # workers must not inherit a TPU/axon binding from the test env
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))] +
            os.environ.get("PYTHONPATH", "").split(os.pathsep)),
    }


def _read_results(out_dir: str, phase: str, world: int):
    out = []
    for rank in range(world):
        path = os.path.join(out_dir, f"result-{phase}-{rank}.json")
        assert os.path.exists(path), f"worker {rank} wrote no result"
        with open(path) as f:
            out.append(json.load(f))
    return out


@pytest.mark.slow
class TestMultiProcessDistributed:
    def test_mixed_cache_vote_falls_back_consistently(self, skewed_file,
                                                      tmp_path):
        """One rank over the epoch-1 cache budget must vote BOTH ranks
        onto the legacy per-round protocol (mixing protocols across
        ranks would mismatch collectives and hang): the gang still
        agrees on batch counts, and epoch 1 shows the per-round
        collective cadence instead of the single allgather."""
        mp_dir = str(tmp_path / "mixed")
        os.makedirs(mp_dir)
        env = _worker_env(2)
        env["DMLC_TEST_CACHE_BYTES_RANK0"] = "0"  # rank 0 over budget
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "train"],
                     env=env, timeout=600)
        results = _read_results(mp_dir, "train", 2)
        assert results[0]["nbatches"] == results[1]["nbatches"] > 0
        assert results[0]["params_digest"] == results[1]["params_digest"]
        for r in results:
            # legacy protocol: one done-flag allgather per round (the
            # vote itself is the +1); steady state still collective-free
            assert r["epoch_collectives"][0] >= r["epoch_batches"][0], \
                f"expected per-round cadence: {r['epoch_collectives']}"
            assert r["epoch_collectives"][1] == 0
            assert r["epoch_collectives"][2] == 0
            # every epoch served identical bytes per rank, whichever
            # path (re-parse or teed replay) produced them
            assert len(set(r["epoch_digests"])) == 1, r["epoch_digests"]
        # rank 0 (budget 0) can never tee a replay cache; rank 1 tees
        # during epoch 2's re-parse and REPLAYS epoch 3 — MIXED paths
        # must stay in lockstep (no collectives in either), which the
        # batch-count and digest asserts above prove. Pin both sides so
        # the mixed scenario cannot silently stop being exercised.
        assert results[0]["replay_epochs"] == 0
        assert results[1]["replay_epochs"] == 1, results[1]["replay_epochs"]

    def test_two_process_train_matches_single_process(self, skewed_file,
                                                      tmp_path):
        mp_dir = str(tmp_path / "mp")
        sp_dir = str(tmp_path / "sp")
        os.makedirs(mp_dir)
        os.makedirs(sp_dir)
        # 2 processes x 2 local devices = 4 global devices
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "train"],
                     env=_worker_env(2), timeout=600)
        mp_results = _read_results(mp_dir, "train", 2)
        # golden: ONE process, 4 local devices — same mesh shape/parts
        proc = subprocess.run(
            [sys.executable, WORKER, skewed_file, sp_dir, "train"],
            env={**os.environ, **_worker_env(4),
                 # explicitly no coordinator env: single-process mode
                 "DMLC_TPU_COORDINATOR_URI": "",
                 "DMLC_TRACKER_URI": ""},
            capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-3000:]
        (sp,) = _read_results(sp_dir, "train", 1)

        # collective batch-count agreement across ranks AND vs golden
        assert mp_results[0]["nbatches"] == mp_results[1]["nbatches"]
        assert mp_results[0]["nbatches"] == sp["nbatches"]
        # round-count agreement is ONE collective in epoch 1 (the cached
        # counting pass, VERDICT r3 #6 — previously one per round);
        # steady-state epochs run with zero per-batch collectives
        # (VERDICT r2 #3) and identical batch cadence
        for r in mp_results:
            assert (r["epoch_batches"][0] == r["epoch_batches"][1]
                    == r["epoch_batches"][2])
            assert r["epoch_collectives"][0] == 1, \
                f"epoch 1 should agree in ONE collective: {r['epoch_collectives']}"
            assert r["epoch_collectives"][1:] == [0, 0], \
                f"steady-state epoch ran collectives: {r['epoch_collectives']}"
            # r5 steady replay: the cached epoch-1 pass commits the
            # rounds, so BOTH steady epochs serve from memory with the
            # exact epoch-1 bytes (per-rank local-shard digest)
            assert r["replay_epochs"] == 2, r["replay_epochs"]
            assert len(set(r["epoch_digests"])) == 1, r["epoch_digests"]
        # identical training result (same parts, same order, same psums)
        assert mp_results[0]["params_digest"] == mp_results[1]["params_digest"]
        np.testing.assert_allclose(mp_results[0]["w_head"], sp["w_head"],
                                   rtol=1e-5, atol=1e-7)
        assert mp_results[0]["loss"] == pytest.approx(sp["loss"], rel=1e-5)

        # phase 2: FRESH processes (simulated restart) restore + continue
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "restore"],
                     env=_worker_env(2), timeout=600)
        restored = _read_results(mp_dir, "restore", 2)
        for r in restored:
            assert r["restored_digest"] == mp_results[0]["params_digest"], \
                "restore did not reproduce the trained params"
            assert r["meta_nbatches"] == mp_results[0]["nbatches"]
            assert np.isfinite(r["post_restore_loss"])
            # shard-local restore: each process read about its own part
            # of the model, not nprocs copies of it
            assert r["restore_bytes"] > 0
        assert restored[0]["stepped_digest"] == restored[1]["stepped_digest"]

    def test_worker_failure_propagates(self, tmp_path):
        from dmlc_tpu.utils.logging import DMLCError
        with pytest.raises(DMLCError, match="exit codes"):
            launch_local(2, [sys.executable, "-c", "import sys; sys.exit(3)"],
                         timeout=60)
