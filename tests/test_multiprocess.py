"""REAL multi-process jax.distributed tests (VERDICT r1 #3/#4).

launch_local forks 2 worker processes that each call init_from_env()
(actual rendezvous over a coordinator socket, CPU backend, 2 virtual
devices per process = 4 global devices), stream skew-sharded data
through ShardedRowBlockIter, train collectively, ShardedCheckpoint.save,
then a FRESH launch restores and continues — executing the
process_count()>1 branches in sharded.py/checkpoint.py/launch.py that
single-process tests cannot reach. A single-process run over the same
4-part mesh is the golden: batch counts and parameters must agree.

Reference mechanism being mirrored: tracker/dmlc_tracker/local.py
(the reference tests multi-node by forking local workers that truly
connect to the tracker).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dmlc_tpu.parallel.launch import launch_local

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")


@pytest.fixture(scope="module")
def skewed_file(tmp_path_factory):
    """Record sizes grow sharply along the file, so equal BYTE shards get
    very different ROW counts — the lockstep empty-padding branch in
    ShardedRowBlockIter must fire on the early-exhausted parts."""
    rng = np.random.RandomState(0)
    lines = []
    for i in range(1200):
        nnz = 2 if i < 900 else rng.randint(30, 60)  # tiny rows then huge
        idx = np.sort(rng.choice(2048, nnz, replace=False))
        lines.append(f"{i % 2} " + " ".join(
            f"{j}:{rng.rand():.4f}" for j in idx))
    p = tmp_path_factory.mktemp("mp") / "skew.libsvm"
    p.write_bytes(("\n".join(lines) + "\n").encode())
    return str(p)


def _worker_env(local_devices: int):
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={local_devices}",
        # workers must not inherit a TPU/axon binding from the test env
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))] +
            os.environ.get("PYTHONPATH", "").split(os.pathsep)),
    }


def _read_results(out_dir: str, phase: str, world: int):
    out = []
    for rank in range(world):
        path = os.path.join(out_dir, f"result-{phase}-{rank}.json")
        assert os.path.exists(path), f"worker {rank} wrote no result"
        with open(path) as f:
            out.append(json.load(f))
    return out


_PROBE = (
    "import os, jax, numpy as np\n"
    "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
    "from dmlc_tpu.parallel.launch import init_from_env, finalize\n"
    "pid, n = init_from_env()\n"
    "mesh = Mesh(np.array(jax.devices()), ('data',))\n"
    "from dmlc_tpu.parallel.sharded import make_replicated\n"
    "g = make_replicated({'x': np.ones(2, np.float32)}, mesh)\n"
    "sh = NamedSharding(mesh, P())\n"
    "jax.block_until_ready(\n"
    "    jax.jit(lambda a: a['x'] * 2, out_shardings=sh)(g))\n"
    "finalize()\n")


@pytest.fixture(scope="module")
def mp_computations():
    """Skip the gang tests when this host's jaxlib cannot run ANY
    multiprocess computation on the CPU backend (XlaRuntimeError
    'Multiprocess computations aren't implemented on the CPU backend'
    from a minimal 2-process jit) — every collective train step below
    needs them. On such hosts the tests are unfulfillable by
    construction, not failing code."""
    from dmlc_tpu.utils.logging import DMLCError
    try:
        launch_local(2, [sys.executable, "-c", _PROBE],
                     env=_worker_env(2), timeout=240)
    except DMLCError:
        pytest.skip("jaxlib lacks multiprocess CPU computations on "
                    "this host")


@pytest.mark.slow
@pytest.mark.usefixtures("mp_computations")
class TestMultiProcessDistributed:
    def test_mixed_cache_vote_falls_back_consistently(self, skewed_file,
                                                      tmp_path):
        """One rank over the epoch-1 cache budget must vote BOTH ranks
        onto the legacy per-round protocol (mixing protocols across
        ranks would mismatch collectives and hang): the gang still
        agrees on batch counts, and epoch 1 shows the per-round
        collective cadence instead of the single allgather."""
        mp_dir = str(tmp_path / "mixed")
        os.makedirs(mp_dir)
        env = _worker_env(2)
        env["DMLC_TEST_CACHE_BYTES_RANK0"] = "0"  # rank 0 over budget
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "train"],
                     env=env, timeout=600)
        results = _read_results(mp_dir, "train", 2)
        assert results[0]["nbatches"] == results[1]["nbatches"] > 0
        assert results[0]["params_digest"] == results[1]["params_digest"]
        for r in results:
            # legacy protocol: one done-flag allgather per round (the
            # vote itself is the +1); steady state still collective-free
            assert r["epoch_collectives"][0] >= r["epoch_batches"][0], \
                f"expected per-round cadence: {r['epoch_collectives']}"
            assert r["epoch_collectives"][1] == 0
            assert r["epoch_collectives"][2] == 0
            # every epoch served identical bytes per rank, whichever
            # path (re-parse or teed replay) produced them
            assert len(set(r["epoch_digests"])) == 1, r["epoch_digests"]
        # rank 0 (budget 0) can never tee a replay cache; rank 1 tees
        # its legacy epoch-1 stream (r6: the local tee is not part of
        # the protocol) and REPLAYS epochs 2 and 3 — MIXED paths must
        # stay in lockstep (no collectives in either), which the
        # batch-count and digest asserts above prove. Pin both sides so
        # the mixed scenario cannot silently stop being exercised.
        assert results[0]["replay_epochs"] == 0
        assert results[1]["replay_epochs"] == 2, results[1]["replay_epochs"]

    def test_gang_page_spill_replays_byte_identical(self, skewed_file,
                                                    tmp_path):
        """ISSUE 2 acceptance on a REAL 2-process gang: with
        agreement_cache_bytes far below the shard's round bytes, every
        rank spills its epoch's rounds to the page cache and serves ALL
        steady epochs from pages — collective-free, with per-rank
        epoch digests (every field of every batch) identical to epoch 1
        and batch counts in lockstep across ranks."""
        mp_dir = str(tmp_path / "spill")
        os.makedirs(mp_dir)
        env = _worker_env(2)
        env["DMLC_TEST_CACHE_BYTES_ALL"] = "4096"  # >0 but << shard
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "train"],
                     env=env, timeout=600)
        results = _read_results(mp_dir, "train", 2)
        assert results[0]["nbatches"] == results[1]["nbatches"] > 0
        assert results[0]["params_digest"] == results[1]["params_digest"]
        for r in results:
            # over-budget epoch 1 runs the legacy per-round agreement;
            # steady epochs are PAGE replay: zero collectives, same
            # bytes (the digest covers every field incl. padding)
            assert r["epoch_collectives"][1:] == [0, 0], \
                r["epoch_collectives"]
            assert len(set(r["epoch_digests"])) == 1, r["epoch_digests"]
            assert r["replay_tier"] == "pages", r["replay_tier"]
            assert r["replay_epochs"] == 2, r["replay_epochs"]
            assert r["page_replay_epochs"] == 2, r["page_replay_epochs"]

    def test_two_process_train_matches_single_process(self, skewed_file,
                                                      tmp_path):
        mp_dir = str(tmp_path / "mp")
        sp_dir = str(tmp_path / "sp")
        os.makedirs(mp_dir)
        os.makedirs(sp_dir)
        # 2 processes x 2 local devices = 4 global devices
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "train"],
                     env=_worker_env(2), timeout=600)
        mp_results = _read_results(mp_dir, "train", 2)
        # golden: ONE process, 4 local devices — same mesh shape/parts
        proc = subprocess.run(
            [sys.executable, WORKER, skewed_file, sp_dir, "train"],
            env={**os.environ, **_worker_env(4),
                 # explicitly no coordinator env: single-process mode
                 "DMLC_TPU_COORDINATOR_URI": "",
                 "DMLC_TRACKER_URI": ""},
            capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-3000:]
        (sp,) = _read_results(sp_dir, "train", 1)

        # collective batch-count agreement across ranks AND vs golden
        assert mp_results[0]["nbatches"] == mp_results[1]["nbatches"]
        assert mp_results[0]["nbatches"] == sp["nbatches"]
        # round-count agreement is ONE collective in epoch 1 (the cached
        # counting pass, VERDICT r3 #6 — previously one per round);
        # steady-state epochs run with zero per-batch collectives
        # (VERDICT r2 #3) and identical batch cadence
        for r in mp_results:
            assert (r["epoch_batches"][0] == r["epoch_batches"][1]
                    == r["epoch_batches"][2])
            assert r["epoch_collectives"][0] == 1, \
                f"epoch 1 should agree in ONE collective: {r['epoch_collectives']}"
            assert r["epoch_collectives"][1:] == [0, 0], \
                f"steady-state epoch ran collectives: {r['epoch_collectives']}"
            # r5 steady replay: the cached epoch-1 pass commits the
            # rounds, so BOTH steady epochs serve from memory with the
            # exact epoch-1 bytes (per-rank local-shard digest)
            assert r["replay_epochs"] == 2, r["replay_epochs"]
            assert len(set(r["epoch_digests"])) == 1, r["epoch_digests"]
        # identical training result (same parts, same order, same psums)
        assert mp_results[0]["params_digest"] == mp_results[1]["params_digest"]
        np.testing.assert_allclose(mp_results[0]["w_head"], sp["w_head"],
                                   rtol=1e-5, atol=1e-7)
        assert mp_results[0]["loss"] == pytest.approx(sp["loss"], rel=1e-5)

        # phase 2: FRESH processes (simulated restart) restore + continue
        launch_local(2, [sys.executable, WORKER, skewed_file, mp_dir,
                         "restore"],
                     env=_worker_env(2), timeout=600)
        restored = _read_results(mp_dir, "restore", 2)
        for r in restored:
            assert r["restored_digest"] == mp_results[0]["params_digest"], \
                "restore did not reproduce the trained params"
            assert r["meta_nbatches"] == mp_results[0]["nbatches"]
            assert np.isfinite(r["post_restore_loss"])
            # shard-local restore: each process read about its own part
            # of the model, not nprocs copies of it
            assert r["restore_bytes"] > 0
        assert restored[0]["stepped_digest"] == restored[1]["stepped_digest"]

@pytest.mark.slow
def test_worker_failure_propagates(tmp_path):
    # outside the gated class: launch_local's failure propagation needs
    # no multiprocess computations, so it must run even on hosts whose
    # jaxlib lacks them
    from dmlc_tpu.utils.logging import DMLCError
    with pytest.raises(DMLCError, match="exit codes"):
        launch_local(2, [sys.executable, "-c", "import sys; sys.exit(3)"],
                     timeout=60)
