"""Sharded ingest + CSR device ops + model training on the 8-device
CPU mesh (the multi-chip contract, SURVEY.md §5.8)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.rowblock import RowBlock, RowBlockContainer
from dmlc_tpu.models import SparseFMModel, SparseLinearModel
from dmlc_tpu.ops import (
    csr_to_dense, csr_to_padded_rows, sdot_rows, segment_spmv, sharded_spmv,
    spmv,
)
from dmlc_tpu.parallel import (
    DeviceIter, ShardedRowBlockIter, device_prefetch, empty_block,
    ensure_schema, make_global_batch, next_pow2_bucket, pad_to_bucket,
    stack_device_batches,
)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))


def random_block(rng, rows=64, ncol=50, max_nnz=8):
    c = RowBlockContainer(np.uint32)
    for i in range(rows):
        nnz = rng.randint(0, max_nnz)
        idx = np.sort(rng.choice(ncol, nnz, replace=False))
        c.push(float(rng.randint(0, 2) * 2 - 1), idx,
               rng.rand(nnz).astype(np.float32))
    return c.get_block()


class TestCsrOps:
    def test_spmv_matches_numpy(self, rng):
        block = random_block(rng)
        w = rng.rand(50).astype(np.float32)
        y = spmv(block.offset, block.index, block.value, w)
        gold = np.array([row.sdot(w) for row in block], np.float32)
        np.testing.assert_allclose(np.asarray(y), gold, rtol=1e-5)

    def test_spmv_with_padding_neutral(self, rng):
        block = random_block(rng, rows=10)
        padded = pad_to_bucket(block, 16, 128)
        y = segment_spmv(jnp.asarray(padded["offset"]),
                         jnp.asarray(padded["index"]),
                         jnp.asarray(padded["value"]),
                         jnp.ones(50, jnp.float32), num_rows=16)
        gold = np.zeros(16, np.float32)
        for i, row in enumerate(block):
            gold[i] = row.sdot(np.ones(50, np.float32))
        np.testing.assert_allclose(np.asarray(y), gold, rtol=1e-5)

    def test_csr_to_dense(self, rng):
        block = random_block(rng, rows=7, ncol=9)
        dense = csr_to_dense(jnp.asarray(block.offset),
                             jnp.asarray(block.index),
                             jnp.asarray(block.value), 7, 9)
        gold = np.zeros((7, 9), np.float32)
        for i, row in enumerate(block):
            for j in range(row.length):
                gold[i, int(row.index[j])] += float(row.value[j])
        np.testing.assert_allclose(np.asarray(dense), gold, rtol=1e-6)

    def test_padded_rows_sdot(self, rng):
        block = random_block(rng, rows=12)
        pi, pv, mask = csr_to_padded_rows(block.offset, block.index,
                                          block.value)
        w = rng.rand(50).astype(np.float32)
        y = sdot_rows(pi, pv, w)
        gold = np.array([row.sdot(w) for row in block], np.float32)
        np.testing.assert_allclose(np.asarray(y), gold, rtol=1e-5)
        assert mask.sum() == block.nnz


class TestPadAndStack:
    def test_pad_contract(self, rng):
        block = random_block(rng, rows=5)
        out = pad_to_bucket(block, 8, 64)
        assert out["offset"].shape == (9,)
        assert out["label"].shape == (8,)
        assert out["index"].shape == (64,)
        assert out["num_rows"] == 5
        # padded rows empty + weight 0
        assert out["offset"][5] == out["offset"][8] == block.nnz
        assert (out["weight"][5:] == 0).all()
        assert (out["value"][block.nnz:] == 0).all()

    def test_bucket_too_small(self, rng):
        block = random_block(rng, rows=5)
        with pytest.raises(Exception):
            pad_to_bucket(block, 2, 64)

    def test_next_pow2(self):
        assert next_pow2_bucket(5) == 8
        assert next_pow2_bucket(8) == 8
        assert next_pow2_bucket(9) == 16
        assert next_pow2_bucket(0) == 8

    def test_stack(self, rng):
        blocks = [pad_to_bucket(random_block(rng, rows=4), 8, 64)
                  for _ in range(3)]
        stacked = stack_device_batches(blocks)
        assert stacked["label"].shape == (3, 8)
        assert stacked["num_rows"].shape == (3,)

    def test_fused_stack_matches_composed_path(self, rng):
        # stack_padded_rows is the replay serve-thread hot loop: it must
        # be BYTE-identical to pad_to_bucket + ensure_schema +
        # stack_device_batches on every column combination (plain,
        # qid-bearing, field-bearing, weighted, empty pads, forced keys)
        from dmlc_tpu.parallel.sharded import stack_padded_rows

        def qid_block(rows):
            c = RowBlockContainer(np.uint32)
            for i in range(rows):
                nnz = rng.randint(1, 5)
                idx = np.sort(rng.choice(50, nnz, replace=False))
                c.push(float(i % 3), idx, rng.rand(nnz), qid=i // 2,
                       weight=0.5 + rng.rand())
            return c.get_block()

        def field_block(rows):
            c = RowBlockContainer(np.uint32)
            for i in range(rows):
                nnz = rng.randint(1, 5)
                idx = np.sort(rng.choice(50, nnz, replace=False))
                c.push(float(i % 2), idx, rng.rand(nnz),
                       fields=rng.randint(0, 4, nnz))
            return c.get_block()

        cases = [
            ([random_block(rng, rows=5), random_block(rng, rows=3),
              empty_block()], False, False),
            ([qid_block(4), empty_block(), random_block(rng, rows=2)],
             True, False),
            ([field_block(3), empty_block()], False, True),
            ([random_block(rng, rows=2)], True, True),  # forced keys
        ]
        for blocks, want_qid, want_field in cases:
            fused = stack_padded_rows(blocks, 8, 64, want_qid, want_field)
            composed = stack_device_batches(
                [ensure_schema(pad_to_bucket(b, 8, 64), 8, 64,
                               want_qid
                               or any(x.qid is not None for x in blocks),
                               want_field
                               or any(x.field is not None
                                      for x in blocks))
                 for b in blocks])
            assert set(fused) == set(composed)
            for k in fused:
                assert fused[k].dtype == composed[k].dtype, k
                np.testing.assert_array_equal(fused[k], composed[k],
                                              err_msg=k)


class TestGlobalBatch:
    def test_sharding_layout(self, mesh, rng):
        locals_ = [pad_to_bucket(random_block(rng, rows=4), 8, 64)
                   for _ in range(8)]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        assert gb["offset"].shape == (8, 9)
        assert gb["offset"].sharding.spec == P("data", None)
        assert len(gb["offset"].addressable_shards) == 8

    def test_sharded_spmv_matches_local(self, mesh, rng):
        blocks = [random_block(rng, rows=6) for _ in range(8)]
        locals_ = [pad_to_bucket(b, 8, 64) for b in blocks]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        w = rng.rand(50).astype(np.float32)
        y = sharded_spmv(gb, w, mesh)
        assert y.shape == (8, 8)
        for d, b in enumerate(blocks):
            gold = np.array([row.sdot(w) for row in b], np.float32)
            np.testing.assert_allclose(np.asarray(y)[d, :b.size], gold,
                                       rtol=1e-5)


class TestShardedRowBlockIter:
    def test_coverage_across_devices(self, mesh, tmp_path, rng):
        lines = [f"{i % 2} {rng.randint(0, 50)}:{rng.rand():.6f}".encode()
                 for i in range(333)]
        p = tmp_path / "d.libsvm"
        p.write_bytes(b"\n".join(lines) + b"\n")
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False)
        total = 0
        for gb in it:
            total += int(np.asarray(gb["num_rows"]).sum())
            assert gb["label"].sharding.spec == P("data", None)
        assert total == 333

    def test_empty_block_padding(self):
        b = empty_block()
        assert b.size == 0 and b.nnz == 0
        padded = pad_to_bucket(b, 4, 16)
        assert (padded["weight"] == 0).all()

    @staticmethod
    def _write_libsvm(path, rng, n):
        lines = [f"{i % 2} {rng.randint(0, 50)}:{rng.rand():.6f}".encode()
                 for i in range(n)]
        path.write_bytes(b"\n".join(lines) + b"\n")

    def _collect(self, it):
        out = []
        for gb in it:
            out.append({k: np.asarray(v) for k, v in gb.items()})
        return out

    def test_over_budget_fallback_matches_cached_path(self, mesh, tmp_path,
                                                      rng):
        # agreement_cache_bytes=0 forces the legacy per-round protocol;
        # the batch stream must be identical to the cached fast path
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 200)
        kw = dict(format="libsvm", row_bucket=32, nnz_bucket=64,
                  prefetch=False)
        fast = self._collect(ShardedRowBlockIter(
            str(p), mesh, first_epoch_cache="always", **kw))
        slow = self._collect(ShardedRowBlockIter(
            str(p), mesh, first_epoch_cache="always",
            agreement_cache_bytes=0, **kw))
        assert len(fast) == len(slow) > 0
        for a, b in zip(fast, slow):
            assert set(a) == set(b)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    @pytest.mark.parametrize("cache_mode", ["always", "never"])
    def test_epoch_replay_detects_truncated_file(self, mesh, tmp_path, rng,
                                                 cache_mode):
        # VERDICT r3 #7: steady-state epochs trust the epoch-1 round
        # count; a file truncated between epochs must raise loudly, not
        # silently desynchronize the collective batch contract.
        # Truncation lands on a line boundary so every remaining byte
        # parses cleanly — only the replay-length check can catch it.
        from dmlc_tpu.utils.logging import DMLCError
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 300)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=8, nnz_bucket=16,
                                 prefetch=False,
                                 first_epoch_cache=cache_mode)
        n1 = len(self._collect(it))
        assert n1 > 0
        data = p.read_bytes()
        cut = data.index(b"\n", len(data) // 4) + 1
        p.write_bytes(data[:cut])  # clean truncation at a line boundary
        with pytest.raises(DMLCError, match="changed between epochs"):
            self._collect(it)

    def test_epoch_replay_detects_rewritten_file(self, mesh, tmp_path, rng):
        # a rewrite with different bytes typically breaks mid-token at
        # the old shard boundaries; the replay wraps the parse error
        # with the file-mutation context
        from dmlc_tpu.utils.logging import DMLCError
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 300)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=8, nnz_bucket=16,
                                 prefetch=False)
        assert len(self._collect(it)) > 0
        self._write_libsvm(p, rng, 40)  # rewrite, much shorter
        with pytest.raises(DMLCError, match="changed between epochs"):
            self._collect(it)

    def test_epoch_replay_ignores_appended_data(self, mesh, tmp_path, rng):
        # shard byte-ranges are captured at creation, so data APPENDED
        # after the iterator was built is invisible: replay stays loyal
        # to epoch 1 (documented behavior, not a hazard)
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 150)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False)
        e1 = self._collect(it)
        with open(p, "ab") as f:
            f.write(b"1 3:0.5\n" * 200)
        e2 = self._collect(it)
        assert len(e1) == len(e2)
        for a, b in zip(e1, e2):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    def test_steady_replay_serves_from_memory(self, mesh, tmp_path, rng):
        # VERDICT r4 #2: with the epoch-1 cache on, steady epochs must
        # REPLAY the retained rounds (no re-parse) and still match
        # epoch 1 exactly
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 150)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        assert it.replay_epochs == 0
        e2 = self._collect(it)
        assert it.replay_epochs == 1  # epoch 2 came from memory
        e3 = self._collect(it)
        assert it.replay_epochs == 2
        for a, b in zip(e1, e2):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        for a, b in zip(e1, e3):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    def test_single_process_auto_tees_then_replays(self, mesh, tmp_path,
                                                   rng):
        # single-process "auto" streams epoch 1 (no cache), re-parses +
        # tees epoch 2, replays epoch 3+ — all identical
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 150)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False)
        e1 = self._collect(it)
        e2 = self._collect(it)
        assert it.replay_epochs == 0  # epoch 2 re-parsed (the tee)
        e3 = self._collect(it)
        assert it.replay_epochs == 1  # epoch 3 replayed the tee
        for a, b in zip(e1, e3):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        assert len(e1) == len(e2) == len(e3)

    def test_append_after_replay_reparses_then_reearns(self, mesh,
                                                       tmp_path, rng):
        # appended bytes are invisible (byte-ranges captured at
        # creation): a replay-armed iterator must notice the stat
        # change, fall back to one clean re-parse epoch, and re-earn
        # replay — never serve an error, never serve the appended rows
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 150)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        with open(p, "ab") as f:
            f.write(b"1 3:0.5\n" * 200)
        e2 = self._collect(it)
        assert it.replay_epochs == 0  # stat changed: epoch 2 re-parsed
        e3 = self._collect(it)
        assert it.replay_epochs == 1  # stable again: epoch 3 replayed
        for a, b in zip(e1, e2):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        for a, b in zip(e1, e3):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    @staticmethod
    def _epoch_hash(batches):
        """Content hash of one epoch's batch stream (order- and
        key-sensitive) — the byte-parity probe for replay tiers."""
        import hashlib
        h = hashlib.sha256()
        for gb in batches:
            for k in sorted(gb):
                h.update(k.encode())
                h.update(np.ascontiguousarray(gb[k]).tobytes())
        return h.hexdigest()

    def test_page_spill_serves_steady_epochs_byte_identical(
            self, mesh, tmp_path, rng):
        # ISSUE 2 tentpole: an 8-device gang whose rounds exceed a
        # deliberately tiny agreement_cache_bytes must SPILL the rounds
        # to the binary page cache instead of abandoning replay, and
        # every steady epoch must serve from pages with batches
        # content-hash-identical to epoch 1
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 300)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 agreement_cache_bytes=2048,  # << shard
                                 spill_dir=str(tmp_path / "spill"),
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        assert it.replay_tier == "parse"
        assert it._round_store is not None
        assert it._round_store.tier == "pages"
        spill_path = it._round_store.file.path
        assert os.path.exists(spill_path)
        e2 = self._collect(it)
        assert it.replay_tier == "pages"
        assert (it.replay_epochs, it.page_replay_epochs) == (1, 1)
        e3 = self._collect(it)
        assert (it.replay_epochs, it.page_replay_epochs) == (2, 2)
        assert (self._epoch_hash(e1) == self._epoch_hash(e2)
                == self._epoch_hash(e3))
        it.close()
        assert not os.path.exists(spill_path), \
            "close() must delete the spill file"

    def test_page_spill_mutation_reparses_then_reearns(self, mesh,
                                                       tmp_path, rng):
        # the mutation contract is tier-independent: a page-armed
        # iterator must notice the stat change, fall back to one clean
        # asserting re-parse epoch (appends stay invisible), and
        # re-earn PAGE replay — never serve stale pages
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 300)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 agreement_cache_bytes=2048,
                                 spill_dir=str(tmp_path / "spill"),
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        first_spill = it._round_store.file.path
        with open(p, "ab") as f:
            f.write(b"1 3:0.5\n" * 200)
        e2 = self._collect(it)
        assert it.replay_tier == "parse"      # stat change: re-parse
        assert it.page_replay_epochs == 0
        assert not os.path.exists(first_spill), \
            "stale spill file must be dropped with its store"
        e3 = self._collect(it)
        assert it.replay_tier == "pages"      # stable again: re-earned
        assert it.page_replay_epochs == 1
        assert (self._epoch_hash(e1) == self._epoch_hash(e2)
                == self._epoch_hash(e3))
        it.close()

    def test_page_spill_truncation_still_raises(self, mesh, tmp_path,
                                                rng):
        # page tier must not weaken the hazard detection: truncating
        # the backing file under a page-armed iterator raises the
        # mutation error on the fallback re-parse, same as r5
        from dmlc_tpu.utils.logging import DMLCError
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 300)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=8, nnz_bucket=16,
                                 prefetch=False,
                                 agreement_cache_bytes=2048,
                                 spill_dir=str(tmp_path / "spill"),
                                 first_epoch_cache="always")
        assert len(self._collect(it)) > 0
        assert it._round_store is not None
        data = p.read_bytes()
        cut = data.index(b"\n", len(data) // 4) + 1
        p.write_bytes(data[:cut])
        with pytest.raises(DMLCError, match="changed between epochs"):
            self._collect(it)

    def test_raw_rounds_beat_padded_on_short_rows(self, mesh, tmp_path,
                                                  rng):
        # the RSS model's multiplier: on a short-row corpus the raw
        # retained rounds must sit WELL below the padded bytes the r5
        # tee held (nnz_bucket sized for the worst row, short rows
        # leave most of it as pad) — the reason the r6 tee retains raw
        p = tmp_path / "short.libsvm"
        self._write_libsvm(p, rng, 400)  # 1 feature per row
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=64, nnz_bucket=1 << 10,
                                 prefetch=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        store = it._round_store
        assert store is not None and store.tier == "memory"
        padded_bytes = sum(int(v.nbytes) for gb in e1
                           for v in gb.values())
        assert store.nbytes < padded_bytes / 4, (
            store.nbytes, padded_bytes)

    def test_page_spill_off_abandons_over_budget(self, mesh, tmp_path,
                                                 rng):
        # page_spill=False restores the pre-r6 behavior: over-budget
        # rounds abandon replay and every epoch re-parses (identically)
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 200)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 agreement_cache_bytes=2048,
                                 page_spill=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        e2 = self._collect(it)
        assert it.replay_epochs == 0
        assert it._round_store is None
        assert self._epoch_hash(e1) == self._epoch_hash(e2)

    def test_steady_replay_off_reparses_every_epoch(self, mesh, tmp_path,
                                                    rng):
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 100)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False, steady_replay=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        e2 = self._collect(it)
        assert it.replay_epochs == 0
        for a, b in zip(e1, e2):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])

    @pytest.mark.parametrize("cache_mode", ["always", "never"])
    def test_skewed_qid_parts_pad_consistent_schema(self, mesh, tmp_path,
                                                    rng, cache_mode):
        # ADVICE r4 (medium): on a qid-bearing source, a part that
        # exhausts before the global round count pads with empty blocks
        # — those pads must carry the SAME key set (qid = -1) or
        # stack_device_batches raises 'inconsistent batch keys'. Row
        # lengths vary wildly so equal BYTE shards hold very different
        # row counts: early parts replay many more rounds than late
        # ones (verified: part_rounds like [16, 11, 3, ...]).
        lines = []
        for i in range(200):
            lines.append(f"{i % 2} qid:{i // 3} {rng.randint(0, 9)}:1")
        for i in range(20):
            feats = " ".join(
                f"{j}:{rng.rand():.6f}"
                for j in sorted(rng.choice(500, 40, replace=False)))
            lines.append(f"{i % 2} qid:{100 + i} {feats}")
        p = tmp_path / "rank.libsvm"
        p.write_bytes("\n".join(lines).encode() + b"\n")
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=8, nnz_bucket=64,
                                 prefetch=False,
                                 first_epoch_cache=cache_mode)
        for epoch in range(2):
            batches = self._collect(it)
            assert len(batches) > 0
            for gb in batches:
                assert "qid" in gb  # every batch carries the schema
                q = gb["qid"]
                n = gb["num_rows"]
                for d in range(q.shape[0]):
                    assert (q[d, int(n[d]):] == -1).all()  # neutral pad
        assert len(set(it._part_rounds)) > 1  # the skew actually happened

    def test_skewed_field_parts_pad_consistent_schema(self, mesh,
                                                      tmp_path, rng):
        # same hazard for the libfm field[] column (field pads 0):
        # short rows first, long rows last, so byte shards skew
        lines = []
        for i in range(200):
            lines.append(f"{i % 2} 1:{rng.randint(0, 9)}:1")
        for i in range(20):
            toks = " ".join(
                f"{rng.randint(0, 6)}:{j}:{rng.rand():.6f}"
                for j in sorted(rng.choice(500, 40, replace=False)))
            lines.append(f"{i % 2} {toks}")
        p = tmp_path / "f.libfm"
        p.write_bytes("\n".join(lines).encode() + b"\n")
        it = ShardedRowBlockIter(str(p), mesh, format="libfm",
                                 row_bucket=8, nnz_bucket=64,
                                 prefetch=False)
        for epoch in range(2):
            batches = self._collect(it)
            assert len(batches) > 0
            for gb in batches:
                assert "field" in gb
        assert len(set(it._part_rounds)) > 1  # the skew actually happened

    def test_second_epoch_matches_first(self, mesh, tmp_path, rng):
        # the steady-state replay (no collectives, counted rounds) must
        # reproduce epoch 1's batches exactly
        p = tmp_path / "d.libsvm"
        self._write_libsvm(p, rng, 150)
        it = ShardedRowBlockIter(str(p), mesh, format="libsvm",
                                 row_bucket=32, nnz_bucket=64,
                                 prefetch=False,
                                 first_epoch_cache="always")
        e1 = self._collect(it)
        e2 = self._collect(it)
        assert len(e1) == len(e2)
        for a, b in zip(e1, e2):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])


class TestSparseRankingModel:
    """Pairwise RankNet loss — the consumer of the libsvm qid column:
    loss must match a brute-force pairwise golden, training must raise
    pairwise accuracy on a planted scorer, and sharded == flat when qid
    groups stay within device blocks."""

    @staticmethod
    def _ranking_block(rng, nqueries, ncol, docs_per_q=6):
        c = RowBlockContainer(np.uint32)
        w_true = np.random.RandomState(5).randn(ncol).astype(np.float32)
        for q in range(nqueries):
            for _ in range(docs_per_q):
                nnz = rng.randint(2, 6)
                idx = np.sort(rng.choice(ncol, nnz, replace=False))
                val = rng.rand(nnz).astype(np.float32)
                score = float((val * w_true[idx]).sum())
                # graded relevance from the hidden scorer (0/1/2)
                c.push(float(np.digitize(score, [0.5, 1.2])), idx, val,
                       qid=q)
        return c.get_block()

    @staticmethod
    def _brute_force_loss(params, batch):
        """The objective verbatim: softplus(-(m_i - m_j)) over same-qid
        pairs with label_i > label_j, weight-weighted mean."""
        from dmlc_tpu.models import SparseRankingModel
        w = np.asarray(params["w"]).astype(np.float64)
        b = float(params["b"])
        off = np.asarray(batch["offset"])
        idx = np.asarray(batch["index"]).astype(int)
        val = np.asarray(batch["value"]).astype(np.float64)
        lab = np.asarray(batch["label"])
        qid = np.asarray(batch["qid"])
        wt = np.asarray(batch["weight"]).astype(np.float64)
        n = lab.shape[0]
        m = np.array([b + (val[off[i]:off[i + 1]]
                           * w[idx[off[i]:off[i + 1]]]).sum()
                      for i in range(n)])
        num = den = 0.0
        for i in range(n):
            for j in range(n):
                if qid[i] >= 0 and qid[i] == qid[j] and lab[i] > lab[j]:
                    pw = wt[i] * wt[j]
                    num += pw * np.log1p(np.exp(-(m[i] - m[j])))
                    den += pw
        # true weighted mean (the production max(den, 1) clamp was
        # removed in r4; den == 0 means no pairs)
        return num / den if den > 0 else 0.0

    def test_loss_matches_brute_force(self, rng):
        from dmlc_tpu.models import SparseRankingModel
        block = self._ranking_block(rng, nqueries=5, ncol=20)
        batch = pad_to_bucket(block, 64, 512)
        model = SparseRankingModel(20)
        params = {"w": np.asarray(rng.randn(20), np.float32),
                  "b": np.float32(0.1)}
        got = float(model.loss(params, batch))
        want = self._brute_force_loss(params, batch)
        assert got == pytest.approx(want, rel=1e-5)

    def test_training_improves_pairwise_accuracy(self, rng):
        from dmlc_tpu.models import SparseRankingModel
        block = self._ranking_block(rng, nqueries=24, ncol=24)
        batch = pad_to_bucket(block, 256, 2048)
        model = SparseRankingModel(24, learning_rate=1.0)
        params = model.init_params()
        acc0 = model.pairwise_accuracy(params, batch)
        for _ in range(60):
            params, loss = model.train_step(params, batch)
        acc1 = model.pairwise_accuracy(params, batch)
        assert np.isfinite(float(loss))
        assert acc1 > max(acc0, 0.8), (acc0, acc1)

    def test_sharded_step_matches_single_chip(self, mesh, rng):
        from dmlc_tpu.models import SparseRankingModel
        ncol = 18
        # one block per device, DISTINCT qids per device: no group
        # straddles a shard, so within-block pairs == all pairs and
        # sharded must equal flat exactly
        blocks = []
        for d in range(8):
            c = RowBlockContainer(np.uint32)
            w_true = np.random.RandomState(5).randn(ncol)
            for q in range(2):
                for _ in range(4):
                    nnz = rng.randint(2, 5)
                    idx = np.sort(rng.choice(ncol, nnz, replace=False))
                    val = rng.rand(nnz).astype(np.float32)
                    s = float((val * w_true[idx]).sum())
                    c.push(float(s > 0.8), idx, val, qid=d * 2 + q)
            blocks.append(c.get_block())
        locals_ = [pad_to_bucket(b, 8, 64) for b in blocks]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        model = SparseRankingModel(ncol, learning_rate=0.2)
        params = model.init_params()
        p1, loss_sharded = model.make_sharded_train_step(mesh)(params, gb)

        c = RowBlockContainer(np.uint32)
        for b in blocks:
            c.push_block(b)
        flat = pad_to_bucket(c.get_block(), 64, 512)
        p2, loss_flat = model.train_step(params, flat)
        assert float(loss_sharded) == pytest.approx(float(loss_flat),
                                                    rel=1e-5)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                                   rtol=1e-4, atol=1e-6)

    def test_missing_qid_raises_named_error(self, mesh, rng):
        # a qid-less batch must fail with the real cause, not a bare
        # KeyError inside a jit trace — on BOTH the flat and the
        # sharded path
        from dmlc_tpu.models import SparseRankingModel
        from dmlc_tpu.utils.logging import DMLCError
        block = random_block(rng, rows=8)
        batch = pad_to_bucket(block, 8, 64)  # no qid column
        model = SparseRankingModel(50)
        with pytest.raises(DMLCError, match="qid"):
            model.loss(model.init_params(), batch)
        locals_ = [pad_to_bucket(random_block(rng, rows=4), 8, 64)
                   for _ in range(8)]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        with pytest.raises(DMLCError, match="qid"):
            model.make_sharded_train_step(mesh)(model.init_params(), gb)

    def test_sub_unit_weights_use_true_weighted_mean(self, rng):
        # pair weights are PRODUCTS of instance weights: with weights
        # 0.1 the total pair weight is << 1, and the old max(wsum, 1)
        # clamp would silently shrink the loss; the weighted mean must
        # be invariant to a uniform instance-weight rescale
        from dmlc_tpu.models import SparseRankingModel
        block = self._ranking_block(rng, nqueries=4, ncol=16)
        b1 = pad_to_bucket(block, 32, 256)
        b2 = {k: (v.copy() if hasattr(v, "copy") else v)
              for k, v in b1.items()}
        b2["weight"] = b2["weight"] * 0.1
        model = SparseRankingModel(16)
        params = {"w": np.asarray(rng.randn(16), np.float32),
                  "b": np.float32(0.0)}
        l1 = float(model.loss(params, b1))
        l2 = float(model.loss(params, b2))
        assert l1 == pytest.approx(l2, rel=1e-5), (l1, l2)

    def test_oversized_row_bucket_raises_at_trace(self, rng):
        # the pairwise loss is O(n^2) memory: an oversized batch must
        # fail loudly at trace time, not OOM on device
        from dmlc_tpu.models import SparseRankingModel
        from dmlc_tpu.utils.logging import DMLCError
        block = self._ranking_block(rng, nqueries=3, ncol=12)
        batch = pad_to_bucket(block, 64, 512)
        model = SparseRankingModel(12, max_row_bucket=32)
        with pytest.raises(DMLCError, match="max_row_bucket"):
            model.loss(model.init_params(), batch)

    def test_libsvm_qid_to_training(self, tmp_path, rng):
        """End-to-end: libsvm text WITH qid tokens → Parser → padded
        batch → ranking step — qid flows to the device and is
        consumed."""
        from dmlc_tpu.models import SparseRankingModel
        ncol = 16
        lines = []
        for q in range(30):
            for _ in range(5):
                nnz = rng.randint(2, 6)
                idx = np.sort(rng.choice(ncol, nnz, replace=False))
                feats = " ".join(f"{j}:{rng.rand():.4f}" for j in idx)
                lines.append(f"{rng.randint(0, 3)} qid:{q} {feats}")
        p = tmp_path / "rank.libsvm"
        p.write_text("\n".join(lines) + "\n")
        c = RowBlockContainer(np.uint32)
        parser = Parser.create(str(p), 0, 1, format="libsvm")
        for b in parser:
            c.push_block(b)
        if hasattr(parser, "destroy"):
            parser.destroy()
        block = c.get_block()
        assert block.qid is not None
        batch = pad_to_bucket(block, next_pow2_bucket(block.size),
                              next_pow2_bucket(block.nnz))
        assert "qid" in batch
        model = SparseRankingModel(ncol, learning_rate=0.5)
        params = model.init_params()
        losses = []
        for _ in range(20):
            params, loss = model.train_step(params, batch)
            losses.append(float(loss))
        assert np.isfinite(losses[-1]) and losses[-1] <= losses[0]


class TestDevicePrefetch:
    def test_preserves_order_and_values(self, rng):
        batches = [{"x": rng.rand(4).astype(np.float32)} for _ in range(7)]
        out = list(device_prefetch(iter(batches), size=3))
        assert len(out) == 7
        for a, b in zip(batches, out):
            np.testing.assert_array_equal(a["x"], np.asarray(b["x"]))
            assert isinstance(b["x"], jax.Array)

    def test_device_iter_protocol(self, rng):
        batches = [{"x": np.full(2, i, np.float32)} for i in range(4)]
        it = DeviceIter(lambda: iter(batches), size=2)
        got = [float(np.asarray(b["x"])[0]) for b in it]
        assert got == [0.0, 1.0, 2.0, 3.0]
        got2 = [float(np.asarray(b["x"])[0]) for b in it]  # replay
        assert got2 == got


class TestSparseLinearModel:
    def test_single_chip_training_decreases_loss(self, rng):
        ncol = 32
        c = RowBlockContainer(np.uint32)
        w_true = rng.randn(ncol).astype(np.float32)
        for _ in range(256):
            nnz = rng.randint(1, 8)
            idx = np.sort(rng.choice(ncol, nnz, replace=False))
            val = rng.rand(nnz).astype(np.float32)
            margin = (val * w_true[idx]).sum()
            c.push(1.0 if margin > 0 else -1.0, idx, val)
        block = c.get_block()
        batch = pad_to_bucket(block, 256, 2048)
        model = SparseLinearModel(ncol, learning_rate=0.5)
        params = model.init_params()
        losses = []
        for _ in range(20):
            params, loss = model.train_step(params, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9

    def test_sharded_step_matches_single_chip(self, mesh, rng):
        ncol = 24
        blocks = [random_block(rng, rows=8, ncol=ncol) for _ in range(8)]
        locals_ = [pad_to_bucket(b, 8, 64) for b in blocks]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        model = SparseLinearModel(ncol, learning_rate=0.1)
        params = model.init_params()
        sharded_step = model.make_sharded_train_step(mesh)
        p1, loss_sharded = sharded_step(params, gb)

        # single-chip equivalent: all 64 rows in one flat batch
        c = RowBlockContainer(np.uint32)
        for b in blocks:
            c.push_block(b)
        flat = pad_to_bucket(c.get_block(), 64, 512)
        p2, loss_flat = model.train_step(params, flat)
        assert float(loss_sharded) == pytest.approx(float(loss_flat),
                                                    rel=1e-5)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                                   rtol=1e-4, atol=1e-6)


class TestSparseFMModel:
    """Second-order FM (the libfm-family consumer): must fit pure
    feature INTERACTIONS a linear model provably cannot, and its sharded
    step must match the flat single-chip step."""

    @staticmethod
    def _xor_blocks(rng, rows, npairs=4):
        """Label = XOR of which feature of a pair fires — zero linear
        signal, pure pairwise signal."""
        c = RowBlockContainer(np.uint32)
        for _ in range(rows):
            a = rng.randint(npairs)          # pair id
            b = rng.randint(2)               # which side of the pair
            cbit = rng.randint(2)
            # features: 2 per pair + 2 "context" features
            idx = np.array(sorted({2 * a + b, 2 * npairs + cbit}), np.uint32)
            label = 1.0 if b == cbit else -1.0   # interaction-only rule
            c.push(label, idx, np.ones(len(idx), np.float32))
        return c.get_block()

    def test_fm_learns_interactions_linear_cannot(self, rng):
        ncol = 10
        block = self._xor_blocks(rng, rows=512)
        batch = pad_to_bucket(block, 512, 2048)
        fm = SparseFMModel(ncol, num_factors=4, learning_rate=0.5)
        lin = SparseLinearModel(ncol, learning_rate=0.5)
        fparams, lparams = fm.init_params(seed=3), lin.init_params()
        flosses, llosses = [], []
        for _ in range(150):
            fparams, fl = fm.train_step(fparams, batch)
            flosses.append(float(fl))
            lparams, ll = lin.train_step(lparams, batch)
            llosses.append(float(ll))
        assert flosses[-1] < 0.45, flosses[-1]           # FM fits XOR
        assert llosses[-1] > 0.6, llosses[-1]            # linear cannot
        # and prediction accuracy beats chance decisively
        proba = np.asarray(fm.predict_proba(fparams, batch))
        y = np.asarray(batch["label"]) > 0
        acc = ((proba > 0.5) == y)[: block.size].mean()
        assert acc > 0.9, acc

    def test_sharded_step_matches_single_chip(self, mesh, rng):
        ncol = 24
        blocks = [random_block(rng, rows=8, ncol=ncol) for _ in range(8)]
        locals_ = [pad_to_bucket(b, 8, 64) for b in blocks]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        model = SparseFMModel(ncol, num_factors=4, learning_rate=0.1)
        params = model.init_params(seed=1)
        sharded_step = model.make_sharded_train_step(mesh)
        p1, loss_sharded = sharded_step(params, gb)

        c = RowBlockContainer(np.uint32)
        for b in blocks:
            c.push_block(b)
        flat = pad_to_bucket(c.get_block(), 64, 512)
        p2, loss_flat = model.train_step(params, flat)
        assert float(loss_sharded) == pytest.approx(float(loss_flat),
                                                    rel=1e-5)
        np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(p1["V"]), np.asarray(p2["V"]),
                                   rtol=1e-4, atol=1e-6)

    def test_libfm_file_to_training(self, tmp_path, rng):
        """End-to-end: libfm text → Parser → padded batch → FM step (the
        format family's canonical consumer loop)."""
        ncol = 16
        lines = []
        for i in range(200):
            nnz = rng.randint(1, 6)
            idx = np.sort(rng.choice(ncol, nnz, replace=False))
            toks = " ".join(
                f"{rng.randint(0, 4)}:{j}:{rng.rand():.4f}" for j in idx)
            lines.append(f"{i % 2} {toks}")
        p = tmp_path / "d.libfm"
        p.write_text("\n".join(lines) + "\n")
        c = RowBlockContainer(np.uint32)
        parser = Parser.create(str(p), 0, 1, format="libfm")
        for b in parser:
            c.push_block(b)
        if hasattr(parser, "destroy"):
            parser.destroy()
        block = c.get_block()
        assert block.field is not None  # libfm parsed fields
        batch = pad_to_bucket(block, next_pow2_bucket(block.size),
                              next_pow2_bucket(block.nnz))
        model = SparseFMModel(ncol, num_factors=2, learning_rate=0.2)
        params = model.init_params()
        _, l0 = model.train_step(params, batch)
        assert np.isfinite(float(l0))


class TestSparseFFMModel:
    """Field-aware FM — the consumer of the libfm field[] column
    (VERDICT r3 #8): forward must match the brute-force pairwise FFM
    definition (which proves the field pairing is real, not FM in
    disguise), and the sharded step must match the flat step."""

    @staticmethod
    def _ffm_batch(rng, rows, ncol, nfields, row_bucket, nnz_bucket):
        c = RowBlockContainer(np.uint32)
        fields = rng.randint(0, nfields, size=ncol)  # feature -> field
        for _ in range(rows):
            nnz = rng.randint(1, 6)
            idx = np.sort(rng.choice(ncol, nnz, replace=False))
            c.push(float(rng.randint(0, 2) * 2 - 1), idx,
                   rng.rand(nnz).astype(np.float32),
                   fields=fields[idx].astype(np.int64))
        block = c.get_block()
        assert block.field is not None
        return pad_to_bucket(block, row_bucket, nnz_bucket), block

    @staticmethod
    def _brute_force_margins(params, block):
        """The FFM definition verbatim: b + Σ w_i x_i +
        Σ_{i<j} <v_{i,f_j}, v_{j,f_i}> x_i x_j, row by row."""
        w = np.asarray(params["w"])
        V = np.asarray(params["V"])
        bias = float(params["b"])
        out = []
        for r in range(block.size):
            s, e = int(block.offset[r]), int(block.offset[r + 1])
            idx = block.index[s:e].astype(int)
            val = block.value[s:e].astype(np.float64)
            fld = block.field[s:e].astype(int)
            m = bias + float((w[idx] * val).sum())
            for a in range(len(idx)):
                for b2 in range(a + 1, len(idx)):
                    m += float(np.dot(V[idx[a], fld[b2]],
                                      V[idx[b2], fld[a]])
                               * val[a] * val[b2])
            out.append(m)
        return np.array(out, np.float64)

    def test_forward_matches_brute_force(self, rng):
        from dmlc_tpu.models import SparseFFMModel
        ncol, nfields = 20, 3
        batch, block = self._ffm_batch(rng, 64, ncol, nfields, 64, 512)
        model = SparseFFMModel(ncol, nfields, num_factors=4)
        params = model.init_params(seed=1)
        got = np.asarray(model.forward(params, batch))[: block.size]
        want = self._brute_force_margins(params, block)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_training_fits_planted_ffm_signal(self, rng):
        # labels come from a TEACHER FFM (brute-force margins of random
        # teacher params): a learnable field-aware signal, so training
        # must fit it well — random labels would only allow memorization
        from dmlc_tpu.models import SparseFFMModel
        ncol, nfields = 16, 4
        batch, block = self._ffm_batch(rng, 256, ncol, nfields, 256, 2048)
        teacher = SparseFFMModel(ncol, nfields, num_factors=4,
                                 init_scale=1.0)
        margins = self._brute_force_margins(teacher.init_params(seed=9),
                                            block)
        batch["label"][: block.size] = np.where(margins > np.median(
            margins), 1.0, -1.0).astype(np.float32)
        model = SparseFFMModel(ncol, nfields, num_factors=4,
                               learning_rate=2.0, init_scale=0.1)
        params = model.init_params(seed=2)
        losses = []
        for _ in range(200):
            params, loss = model.train_step(params, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.72, losses[::40]

    def test_sharded_step_matches_single_chip(self, mesh, rng):
        from dmlc_tpu.models import SparseFFMModel
        ncol, nfields = 18, 3
        per_dev = [self._ffm_batch(rng, 8, ncol, nfields, 8, 64)
                   for _ in range(8)]
        locals_ = [b for b, _ in per_dev]
        gb = make_global_batch(stack_device_batches(locals_), mesh)
        model = SparseFFMModel(ncol, nfields, num_factors=2,
                               learning_rate=0.1)
        params = model.init_params(seed=4)
        p1, loss_sharded = model.make_sharded_train_step(mesh)(params, gb)

        c = RowBlockContainer(np.uint32)
        for _, blk in per_dev:
            c.push_block(blk)
        flat = pad_to_bucket(c.get_block(), 64, 512)
        p2, loss_flat = model.train_step(params, flat)
        assert float(loss_sharded) == pytest.approx(float(loss_flat),
                                                    rel=1e-5)
        np.testing.assert_allclose(np.asarray(p1["V"]), np.asarray(p2["V"]),
                                   rtol=1e-4, atol=1e-6)

    def test_validate_batch_rejects_out_of_range_fields(self, rng):
        # the jitted forward CLIPS out-of-range field ids (XLA gather
        # must be in-bounds) — the host-side validator is what turns a
        # num_fields misconfiguration into an error instead of silent
        # field merging
        from dmlc_tpu.models import SparseFFMModel
        from dmlc_tpu.utils.logging import DMLCError
        batch, _ = self._ffm_batch(rng, 16, 12, 5, 16, 128)
        model = SparseFFMModel(12, num_fields=2, num_factors=2)
        with pytest.raises(DMLCError, match="num_fields"):
            model.validate_batch(batch)
        SparseFFMModel(12, num_fields=5).validate_batch(batch)  # fits
        batch["field"][0] = -1  # negative sentinel: also clipped → error
        with pytest.raises(DMLCError, match="field ids"):
            SparseFFMModel(12, num_fields=5).validate_batch(batch)

    def test_libfm_file_to_ffm_training(self, tmp_path, rng):
        """End-to-end: libfm text → Parser → padded batch WITH field →
        FFM step — field[] flows to the device and is consumed."""
        from dmlc_tpu.models import SparseFFMModel
        ncol, nfields = 16, 4
        lines = []
        for i in range(200):
            nnz = rng.randint(1, 6)
            idx = np.sort(rng.choice(ncol, nnz, replace=False))
            toks = " ".join(
                f"{rng.randint(0, nfields)}:{j}:{rng.rand():.4f}"
                for j in idx)
            lines.append(f"{i % 2} {toks}")
        p = tmp_path / "d.libfm"
        p.write_text("\n".join(lines) + "\n")
        c = RowBlockContainer(np.uint32)
        parser = Parser.create(str(p), 0, 1, format="libfm")
        for b in parser:
            c.push_block(b)
        if hasattr(parser, "destroy"):
            parser.destroy()
        block = c.get_block()
        batch = pad_to_bucket(block, next_pow2_bucket(block.size),
                              next_pow2_bucket(block.nnz))
        assert "field" in batch
        model = SparseFFMModel(ncol, nfields, num_factors=2,
                               learning_rate=0.3)
        params = model.init_params()
        losses = []
        for _ in range(15):
            params, loss = model.train_step(params, batch)
            losses.append(float(loss))
        assert np.isfinite(losses[-1]) and losses[-1] <= losses[0]
