"""ThreadGroup, memory pools, profiler, indexed recordio writer, stdin
split, cluster backend generators (reference: thread_group.h, memory.h,
timer.h, indexed_recordio, single_file_split, tracker backends)."""

import subprocess
import sys
import time

import numpy as np
import pytest

from dmlc_tpu.io.input_split import InputSplit
from dmlc_tpu.io.recordio import IndexedRecordIOWriter, RECORDIO_MAGIC
from dmlc_tpu.io.stream import create_stream
from dmlc_tpu.parallel.backends import (
    kubernetes_manifest, mpi_command, sge_script, slurm_script,
)
from dmlc_tpu.utils.logging import DMLCError
from dmlc_tpu.utils.memory import BufferPool, thread_local_pool
from dmlc_tpu.obs.trace import Profiler
from dmlc_tpu.utils.thread_group import ManualEvent, ThreadGroup


class TestThreadGroup:
    def test_create_join(self):
        g = ThreadGroup()
        results = []
        g.create("worker-a", lambda: results.append("a"))
        g.create("worker-b", lambda x: results.append(x), "b")
        g.join_all()
        assert sorted(results) == ["a", "b"]
        assert g.size() == 0

    def test_cooperative_shutdown(self):
        g = ThreadGroup()
        stopped = []

        def worker():
            t = g.thread("loop")
            while not t.shutdown_requested:
                time.sleep(0.005)
            stopped.append(True)

        g.create("loop", worker)
        time.sleep(0.05)
        assert g.size() == 1
        g.request_shutdown_all()
        g.join_all(timeout_per_thread=2)
        assert stopped == [True]

    def test_duplicate_name_raises(self):
        g = ThreadGroup()
        ev = ManualEvent()
        g.create("x", ev.wait, 5)
        with pytest.raises(DMLCError, match="already running"):
            g.create("x", lambda: None)
        ev.signal()
        g.join_all()

    def test_manual_event(self):
        ev = ManualEvent()
        assert not ev.is_set()
        assert not ev.wait(0.01)
        ev.signal()
        assert ev.wait(0.01)
        ev.reset()
        assert not ev.is_set()


class TestBufferPool:
    def test_reuse(self):
        pool = BufferPool()
        a = pool.acquire(1000)
        assert len(a) == 1024  # size class
        pool.release(a)
        b = pool.acquire(900)
        assert b is a  # recycled
        assert pool.stats() == (1, 1)

    def test_distinct_classes(self):
        pool = BufferPool()
        a = pool.acquire(100)
        b = pool.acquire(10000)
        assert len(a) != len(b)

    def test_thread_local(self):
        assert thread_local_pool() is thread_local_pool()


class TestBenchTransferProbe:
    """CI smoke for the transfer-ceiling probe (BASELINE.md "Transfer
    ceiling" cites it as rerunnable evidence): every cell runs tiny on
    the CPU backend and returns a positive rate."""

    def test_cells_run_tiny(self):
        import jax
        from dmlc_tpu import bench_transfer as bt
        dev = jax.devices()[0]
        assert bt.memcpy_gauge(mb=2) > 0
        assert bt.cell_single(dev, 1, 2, 4) > 0
        assert bt.cell_threads(dev, 2, 1, 1, 4) > 0
        assert bt.cell_mono(dev, 2) > 0
        # enqueue_cpu_share is process_time/wall: process-WIDE CPU, so
        # inside a full-suite run (XLA pools + spin-waiting helpers) it
        # legitimately exceeds the old quiet-process bound of 2.0 over
        # a tiny wall window. The principled ceiling is the live thread
        # count (process CPU rate cannot exceed it, modulo clock
        # granularity — hence the slightly larger window and +2 slack).
        import threading
        share = bt.enqueue_cpu_share(dev, chunk_mb=1, total_mb=8)
        assert 0.0 <= share <= threading.active_count() + 2, share
        rate, copied = bt.cell_under_cpu_load(dev, 1, 1, 2)
        assert rate > 0 and copied >= 0


class TestProfiler:
    def test_stage_accumulation(self):
        p = Profiler()
        with p.stage("parse", nbytes=1000, items=10):
            time.sleep(0.01)
        with p.stage("parse", nbytes=500, items=5):
            pass
        st = p.stats()["parse"]
        assert st.calls == 2 and st.bytes == 1500 and st.items == 15
        assert st.seconds >= 0.01
        assert "parse" in p.report()

    def test_disabled(self):
        p = Profiler()
        p.enabled = False
        with p.stage("x"):
            pass
        assert p.stats() == {}


class TestIndexedRecordIOWriter:
    def test_roundtrip_via_indexed_split(self, tmp_path, rng):
        data = tmp_path / "d.rec"
        records = [rng.bytes(rng.randint(1, 60)) for _ in range(40)]
        # make some records contain the magic (multi-frame + index offsets)
        records[5] = np.uint32(RECORDIO_MAGIC).tobytes() * 3
        with create_stream(str(data), "w") as ds, \
                create_stream(str(data) + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(ds, ix)
            for r in records:
                w.write_record(r)
        split = InputSplit.create(str(data), 0, 1, "indexed_recordio")
        assert list(split) == records
        # sharded coverage at record granularity
        got = []
        for k in range(3):
            got.extend(InputSplit.create(str(data), k, 3,
                                         "indexed_recordio"))
        assert sorted(got) == sorted(records)

    def test_explicit_keys(self, tmp_path):
        data = tmp_path / "k.rec"
        with create_stream(str(data), "w") as ds, \
                create_stream(str(data) + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(ds, ix)
            w.write_record(b"rec-a", key=100)
            w.write_record(b"rec-b", key=200)
        idx_text = (tmp_path / "k.rec.idx").read_text()
        assert idx_text.startswith("100\t0\n")
        split = InputSplit.create(str(data), 0, 1, "indexed_recordio")
        assert split.keys() == [100, 200]

    def test_shuffled_indexed_read(self, tmp_path, rng):
        data = tmp_path / "s.rec"
        records = [b"r%03d" % i for i in range(100)]
        with create_stream(str(data), "w") as ds, \
                create_stream(str(data) + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(ds, ix)
            for r in records:
                w.write_record(r)
        split = InputSplit.create(str(data), 0, 1, "indexed_recordio",
                                  shuffle=True, seed=3, batch_size=10)
        e1 = list(split)
        e2 = list(split)
        assert sorted(e1) == records and sorted(e2) == records
        assert e1 != records  # actually shuffled
        assert e1 != e2       # epoch reshuffle


class TestStdinSplit:
    def test_stdin_records(self):
        code = (
            "import sys; sys.path.insert(0, '/root/repo')\n"
            "from dmlc_tpu.io.input_split import InputSplit\n"
            "s = InputSplit.create('-', 0, 1)\n"
            "print([r.decode() for r in s])\n")
        out = subprocess.run([sys.executable, "-c", code],
                             input=b"a\nbb\n\nccc\n", capture_output=True)
        assert out.returncode == 0, out.stderr.decode()
        assert "['a', 'bb', 'ccc']" in out.stdout.decode()


class TestClusterBackends:
    def test_mpi_command(self):
        line = mpi_command(4, ["python", "w.py"], "h:9")
        assert line.startswith("mpirun -n 4")
        assert "OMPI_COMM_WORLD_RANK" in line
        assert "DMLC_TPU_COORDINATOR_URI=h:9" in line

    def test_slurm_script(self):
        s = slurm_script(8, ["python", "w.py"], "h:9", partition="tpu")
        assert "#SBATCH --ntasks=8" in s
        assert "--partition=tpu" in s
        assert "SLURM_PROCID" in s

    def test_sge_script(self):
        s = sge_script(3, ["python", "w.py"], "h:9")
        assert "#$ -t 1-3" in s and "SGE_TASK_ID" in s

    def test_k8s_manifest(self):
        m = kubernetes_manifest(5, ["python", "w.py"], "h:9",
                                image="my/img:1")
        assert m["spec"]["completions"] == 5
        assert m["spec"]["completionMode"] == "Indexed"
        assert m["spec"]["template"]["spec"]["containers"][0][
            "image"] == "my/img:1"
        names = [e["name"] for e in
                 m["spec"]["template"]["spec"]["containers"][0]["env"]]
        assert "DMLC_TPU_COORDINATOR_URI" in names


class TestStdinRegressions:
    def test_recordio_on_stdin_raises(self):
        with pytest.raises(DMLCError, match="text"):
            InputSplit.create("-", 0, 1, "recordio")

    def test_sharded_stdin_raises(self):
        with pytest.raises(DMLCError, match="one part"):
            InputSplit.create("-", 1, 4)

    def test_streaming_chunks_bounded(self):
        # 3 MB piped through a 64 KB-chunk stdin split: many chunks,
        # records intact
        code = (
            "import sys; sys.path.insert(0, '/root/repo')\n"
            "from dmlc_tpu.io.input_split import InputSplit\n"
            "s = InputSplit.create('-', 0, 1, chunk_size=1)\n"  # floors 64KB
            "chunks = 0; recs = 0\n"
            "while True:\n"
            "    c = s.next_chunk()\n"
            "    if c is None: break\n"
            "    chunks += 1; recs += len(list(s.extract_records(c)))\n"
            "print(chunks, recs)\n")
        payload = b"".join(b"line-%06d\n" % i for i in range(200000))
        out = subprocess.run([sys.executable, "-c", code], input=payload,
                             capture_output=True)
        assert out.returncode == 0, out.stderr.decode()
        chunks, recs = map(int, out.stdout.split())
        assert recs == 200000
        assert chunks > 10  # streamed, not slurped


class TestBufferPoolRegression:
    def test_foreign_view_not_pooled(self):
        pool = BufferPool()
        a = pool.acquire(1024)
        view = a[:300]
        pool.release(view)  # dropped silently
        b = pool.acquire(300)
        assert len(b) == 512 and b is not view


class TestK8sTaskIdCompat:
    def test_both_task_id_names_injected(self):
        m = kubernetes_manifest(2, ["w"], "h:9", image="img")
        env = m["spec"]["template"]["spec"]["containers"][0]["env"]
        names = [e["name"] for e in env]
        assert "DMLC_TPU_TASK_ID" in names and "DMLC_TASK_ID" in names
