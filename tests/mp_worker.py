"""Distributed worker for tests/test_multiprocess.py.

Runs as a REAL separate OS process under launch_local (reference
mechanism: tracker/dmlc_tracker/local.py forking workers that actually
connect to the tracker): calls init_from_env() to join the
jax.distributed rendezvous, builds a global mesh over all processes'
devices, streams skew-sharded data through ShardedRowBlockIter, trains a
SparseLinearModel for two epochs, saves a ShardedCheckpoint, and (in the
"restore" phase, a fresh launch simulating restart) restores it and
verifies byte-identical params before taking one more step.

Usage: mp_worker.py <data_uri> <out_dir> <train|restore>
Writes <out_dir>/result-<phase>-<rank>.json with what the test asserts.
"""

import hashlib
import json
import os
import sys

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # this machine's axon TPU plugin overrides the env var; the config
    # update is authoritative (same dance as tests/conftest.py)
    import jax
    jax.config.update("jax_platforms", "cpu")


NUM_FEATURES = 2048
ROW_BUCKET = 64
NNZ_BUCKET = 1024


def main() -> int:
    data_uri, out_dir, phase = sys.argv[1], sys.argv[2], sys.argv[3]
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from dmlc_tpu.io.checkpoint import ShardedCheckpoint
    from dmlc_tpu.models.linear import SparseLinearModel
    from dmlc_tpu.parallel.launch import init_from_env, finalize
    from dmlc_tpu.parallel.sharded import (
        ShardedRowBlockIter, make_replicated,
    )

    pid, nprocs = init_from_env()
    assert jax.process_count() == nprocs, (jax.process_count(), nprocs)
    mesh = Mesh(np.array(jax.devices()), ("data",))

    model = SparseLinearModel(num_features=NUM_FEATURES, learning_rate=0.5)
    # make_replicated, not device_put-to-global-sharding: the latter
    # runs an assert_equal collective per leaf (and cannot run at all
    # on the multiprocess CPU backend)
    params = make_replicated(model.init_params(), mesh)
    step_fn = model.make_sharded_train_step(mesh)
    # DMLC_TEST_CACHE_BYTES_RANK0: force THIS rank over/under the
    # epoch-1 cache budget to exercise the mixed-vote path — one rank
    # over budget must vote EVERY rank onto the legacy per-round
    # protocol (protocols may never mix across ranks).
    # DMLC_TEST_CACHE_BYTES_ALL: force EVERY rank's budget (the r6
    # page-spill gang test sets it tiny-but-positive so steady epochs
    # must serve from spilled round pages on all ranks).
    cache_bytes = 1 << 30
    if os.environ.get("DMLC_TEST_CACHE_BYTES_ALL"):
        cache_bytes = int(os.environ["DMLC_TEST_CACHE_BYTES_ALL"])
    if pid == 0 and os.environ.get("DMLC_TEST_CACHE_BYTES_RANK0"):
        cache_bytes = int(os.environ["DMLC_TEST_CACHE_BYTES_RANK0"])
    it = ShardedRowBlockIter(data_uri, mesh, format="libsvm",
                             row_bucket=ROW_BUCKET, nnz_bucket=NNZ_BUCKET,
                             agreement_cache_bytes=cache_bytes)
    ck = ShardedCheckpoint(os.path.join(out_dir, "ckpt"))

    def digest(p):
        h = hashlib.sha256()
        h.update(np.asarray(p["w"]).tobytes())
        h.update(np.asarray(p["b"]).tobytes())
        return h.hexdigest()

    if phase == "train":
        # count host collectives per epoch: epoch 1 agrees on the round
        # count (one done-flag allgather per round), later epochs must
        # run with ZERO per-batch collectives (VERDICT r2 #3 — the
        # reference has no cross-worker comm at all during iteration).
        # 3 epochs since r5: epoch 2+ may REPLAY retained rounds (or
        # re-parse when this rank's budget forbids caching — ranks may
        # MIX paths, both are collective-free and batch-identical); the
        # per-epoch local-shard digest proves every epoch served the
        # same bytes whichever path produced them.
        from jax.experimental import multihost_utils
        orig_ag = multihost_utils.process_allgather
        ag_calls = [0]

        def _counting_ag(*a, **k):
            ag_calls[0] += 1
            return orig_ag(*a, **k)

        multihost_utils.process_allgather = _counting_ag
        nbatches = 0
        last_loss = None
        epoch_batches = []
        epoch_collectives = []
        epoch_digests = []
        try:
            for _epoch in range(3):
                nb0, ag0 = nbatches, ag_calls[0]
                eh = hashlib.sha256()
                for batch in it:
                    for key in sorted(batch):  # EVERY field, incl. the
                        # weight column and the num_rows/num_nnz true-
                        # size masks — "same bytes" must mean all of them
                        for sh in batch[key].addressable_shards:
                            eh.update(np.asarray(sh.data).tobytes())
                    params, loss = step_fn(params, batch)
                    nbatches += 1
                    last_loss = float(loss)
                epoch_batches.append(nbatches - nb0)
                epoch_collectives.append(ag_calls[0] - ag0)
                epoch_digests.append(eh.hexdigest())
        finally:
            multihost_utils.process_allgather = orig_ag
        ck.save(nbatches, params, metadata={"nbatches": nbatches})
        result = {"rank": pid, "world": nprocs, "nbatches": nbatches,
                  "loss": last_loss, "params_digest": digest(params),
                  "epoch_batches": epoch_batches,
                  "epoch_collectives": epoch_collectives,
                  "epoch_digests": epoch_digests,
                  "replay_epochs": it.replay_epochs,
                  "page_replay_epochs": it.page_replay_epochs,
                  "replay_tier": it.replay_tier,
                  "w_head": np.asarray(params["w"])[:8].tolist()}
    elif phase == "restore":
        restored, user = ck.restore(like=params)
        # exercise the restored params: one more global step must run
        batch = next(iter(it))
        stepped, loss = step_fn(restored, batch)
        result = {"rank": pid, "world": nprocs,
                  "restored_digest": digest(restored),
                  "restore_bytes": ck.last_restore_bytes_read,
                  "meta_nbatches": user["nbatches"],
                  "post_restore_loss": float(loss),
                  "stepped_digest": digest(stepped)}
    else:
        raise SystemExit(f"unknown phase {phase!r}")

    with open(os.path.join(out_dir, f"result-{phase}-{pid}.json"),
              "w") as f:
        json.dump(result, f)
    finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
