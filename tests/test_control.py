"""dmlc_tpu.obs.control: the verdict-driven control plane.

The observe→act loop, end to end: the ExplorationRail (accept /
revert / cooldown / budget / regime gates shared with the autotuner),
the bound→family policy (parse grows parse knobs, wire automates the
remote-io advice, credit-limited FREEZES everything), the immutable
byte-budgeted decision ledger, the /control endpoint + obsctl control
rendering, flight-bundle attachment, pipeline adoption, chaos
interplay under a deterministic-seed FaultPlan, and a REAL 2-process
gang serving per-rank ledgers live."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from dmlc_tpu.obs import control as obs_control
from dmlc_tpu.obs.control import (
    ControlKnob, Controller, DecisionLedger, RECORD_KEYS,
)
from dmlc_tpu.pipeline.autotune import ExplorationRail

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPTS = os.path.join(REPO, "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


def _snap(stages, wall_s=2.0, epoch=1, bytes_=1 << 30):
    """A pipeline stats snapshot whose sink carries ``bytes_`` (the
    rail's throughput objective = sink bytes / wall)."""
    stages = [dict(s) for s in stages]
    stages[-1].setdefault("bytes", bytes_)
    return {"schema": 1, "epoch": epoch, "wall_s": wall_s,
            "stages": stages, "knobs": {}}


def _parse_bound(epoch=1, wall_s=2.0, bytes_=1 << 30):
    return _snap([
        {"name": "parse", "kind": "parse", "wait_s": 0.9 * wall_s,
         "bytes": bytes_},
    ], wall_s=wall_s, epoch=epoch, bytes_=bytes_)


def _store_knob(store, name="k", family="parse", lo=1, hi=64):
    return ControlKnob(name, family,
                       get=lambda: store[name],
                       set=lambda n: store.__setitem__(name, n),
                       lo=lo, hi=hi)


class TestExplorationRail:
    def test_accept_updates_reference(self):
        rail = ExplorationRail()
        store = {"v": 4}
        rail.observe(100.0)  # reference epoch
        rail.begin("k", 4, 8, lambda n: store.__setitem__("v", n))
        out = rail.observe(150.0)
        assert out["outcome"] == "accepted"
        assert rail.best() == 150.0
        assert store["v"] == 4  # accept never touches the knob

    def test_revert_restores_freezes_and_charges_budget(self):
        rail = ExplorationRail(cooldown=3, revert_budget=2)
        store = {"v": 8}
        rail.observe(100.0)
        rail.begin("k", 4, 8, lambda n: store.__setitem__("v", n),
                   group="parse")
        out = rail.observe(50.0)  # < 0.9 * 100
        assert out["outcome"] == "reverted"
        assert store["v"] == 4          # restored
        assert rail.frozen("k")         # cooldown gate
        assert rail.reverts("parse") == 1
        assert not rail.exhausted("parse")
        rail.advance()
        rail.begin("k2", 1, 2, lambda n: None, group="parse")
        rail.observe(10.0)
        assert rail.exhausted("parse")  # budget of 2 spent

    def test_regime_change_discards_without_freeze_or_charge(self):
        rail = ExplorationRail(revert_budget=1)
        store = {"v": 8}
        rail.note_regime((("cache", "parse"),))
        rail.observe(100.0)
        rail.begin("k", 4, 8, lambda n: store.__setitem__("v", n),
                   group="parse")
        trial = rail.note_regime((("cache", "pages"),))
        assert trial["outcome"] == "discarded (replay tier changed)"
        assert store["v"] == 4          # restored...
        assert not rail.frozen("k")     # ...but no cooldown
        assert rail.reverts("parse") == 0  # and no budget charge
        assert rail.best() is None      # reference reset

    def test_drop_source_restores_pending_and_releases_charges(self):
        # a source dying mid-trial must not strand a process-global
        # knob at its unjudged trial value, and its revert charges die
        # with it — a ghost's reverts must not exhaust the family for
        # every future pipeline in the process
        rail = ExplorationRail(revert_budget=1, cooldown=0)
        a, b = {"v": 8}, {"v": 2}
        rail.observe(100.0, source="s")
        rail.begin("a", 4, 8, lambda n: a.__setitem__("v", n),
                   group="wire", source="s")
        rail.observe(10.0, source="s")   # reverted: charge (wire, s)
        assert rail.exhausted("wire", source="s")
        rail.advance()
        rail.begin("b", 1, 2, lambda n: b.__setitem__("v", n),
                   group="wire", source="s")
        rail.drop_source("s")
        assert b["v"] == 1               # pending trial restored
        assert rail.pending is None
        assert rail.best("s") is None
        assert not rail.exhausted("wire", source="s")
        assert rail.reverts_total("wire") == 0

    def test_cooldown_expires(self):
        rail = ExplorationRail(cooldown=2)
        rail.freeze("k")
        assert rail.frozen("k")
        rail.advance()
        assert rail.frozen("k")
        rail.advance()
        assert not rail.frozen("k")


class TestLedger:
    def _rec(self, i):
        return {"epoch": i, "verdict_id": f"v{i}-x", "bound": "parse",
                "band": "unknown", "evidence": [f"parse wait {i}s"],
                "family": "parse", "knob": "k", "old": 1, "new": 2,
                "outcome": "trial", "reverted": False}

    def test_coarsens_under_budget_keeping_ends(self):
        led = DecisionLedger(budget_bytes=2 << 10)
        for i in range(300):
            led.append(self._rec(i))
        d = led.to_dict()
        assert d["offered"] == 300
        assert d["kept"] < 300
        assert d["coarsenings"] >= 1
        assert d["approx_bytes"] <= d["budget_bytes"]
        recs = d["records"]
        assert recs[0]["epoch"] == 0      # the oldest survives
        assert recs[-1]["epoch"] == 299   # the newest survives
        assert [r["epoch"] for r in recs] == \
            sorted(r["epoch"] for r in recs)

    def test_last_trims(self):
        led = DecisionLedger()
        for i in range(10):
            led.append(self._rec(i))
        assert [r["epoch"] for r in led.records(last=3)] == [7, 8, 9]
        assert len(led.to_dict(last=2)["records"]) == 2


class TestControllerPolicy:
    def _controller(self, knobs, **kw):
        kw.setdefault("revert_budget", 2)
        return Controller(knobs, **kw)

    def test_parse_bound_grows_parse_family(self):
        store = {"k": 4}
        ctl = self._controller([_store_knob(store)])
        try:
            rec = ctl.observe(_parse_bound(epoch=1))
            assert rec["outcome"] == "trial"
            assert rec["family"] == "parse" and rec["knob"] == "k"
            assert (rec["old"], rec["new"]) == (4, 8)
            assert store["k"] == 8
            # the record cites the exact verdict (epoch + digest)
            assert rec["epoch"] == 1
            assert rec["verdict_id"].startswith("v1-")
            assert rec["bound"] == "parse" and rec["evidence"]
            assert sorted(rec) == sorted(RECORD_KEYS)
        finally:
            ctl.close()

    def test_xfer_bound_moves_transfer_family_only(self):
        store = {"k": 4, "w": 2}
        ctl = self._controller([
            _store_knob(store, "k", "parse"),
            ControlKnob("w", "transfer",
                        get=lambda: store["w"],
                        set=lambda n: store.__setitem__("w", n),
                        lo=1, hi=32)])
        try:
            rec = ctl.observe(_snap([
                {"name": "parse", "kind": "parse", "wait_s": 0.1},
                {"name": "to_device", "kind": "to_device",
                 "wait_s": 1.5, "extra": {"xfer_wait_s": 1.5}},
            ]))
            assert rec["bound"] == "xfer"
            assert rec["family"] == "transfer" and rec["knob"] == "w"
            assert store == {"k": 4, "w": 4}
        finally:
            ctl.close()

    def test_wire_bound_automates_remote_io_advice(self):
        # wire-bound + cold pagestore: the controller escalates the
        # wire family in the documented order — coalesce first
        opts = {"coalesce": 4, "parallel": 4, "codec": 0}
        knobs = [
            ControlKnob("wire.coalesce", "wire",
                        lambda: opts["coalesce"],
                        lambda n: opts.__setitem__("coalesce", n),
                        lo=1, hi=16),
            ControlKnob("wire.codec_level", "wire",
                        lambda: opts["codec"],
                        lambda n: opts.__setitem__("codec", n),
                        lo=0, hi=9,
                        grow=lambda cur: 6 if cur == 0 else cur),
        ]
        ctl = self._controller(knobs)
        try:
            metrics = {"counters": {
                "pagestore.hit": 0, "pagestore.miss": 40,
                "objstore.get": 40, "objstore.bytes": 1 << 30}}
            rec = ctl.observe(_snap([
                {"name": "parse", "kind": "parse", "wait_s": 1.0,
                 "bytes": 1 << 30}]), metrics=metrics)
            assert rec["bound"] == "wire"
            assert rec["knob"] == "wire.coalesce"
            assert opts["coalesce"] == 8 and opts["codec"] == 0
            # coalesce trial regresses hard -> reverted + cooldown;
            # the NEXT wire epoch escalates to the codec flip
            rec = ctl.observe(_snap([
                {"name": "parse", "kind": "parse", "wait_s": 1.0,
                 "bytes": 1 << 24}]), metrics=metrics)
            assert rec["outcome"] == "reverted"
            assert opts["coalesce"] == 4
            rec = ctl.observe(_snap([
                {"name": "parse", "kind": "parse", "wait_s": 1.0,
                 "bytes": 1 << 30}]), metrics=metrics)
            assert rec["outcome"] == "trial"
            assert rec["knob"] == "wire.codec_level"
            assert (rec["old"], rec["new"]) == (0, 6)
            assert opts["codec"] == 6
        finally:
            ctl.close()

    def test_credit_limited_freezes_all_knobs_never_thrashes(self):
        store = {"k": 4, "w": 2}
        ctl = self._controller([
            _store_knob(store, "k", "parse"),
            _store_knob(store, "w", "transfer")])
        try:
            for epoch in range(1, 6):
                rec = ctl.observe(_parse_bound(epoch=epoch),
                                  epoch_gauges=[0.3, 0.4, 0.5])
                assert rec["outcome"] == "freeze"
                assert rec["band"] == "drained"
                assert rec["knob"] is None and rec["new"] is None
                assert any("drained" in e for e in rec["evidence"])
            # the whole point: five drained epochs, zero knob motion
            assert store == {"k": 4, "w": 2}
            assert ctl.to_dict()["counts"]["freezes"] == 5
        finally:
            ctl.close()

    def test_consumer_bound_is_an_explicit_noop(self):
        store = {"k": 4}
        ctl = self._controller([_store_knob(store)])
        try:
            rec = ctl.observe(_snap([
                {"name": "parse", "kind": "parse", "wait_s": 0.01,
                 "bytes": 1 << 30}]))
            assert rec["bound"] == "consumer"
            assert rec["outcome"] == "no-op"
            assert store["k"] == 4
        finally:
            ctl.close()

    def test_revert_budget_disables_family(self):
        store = {"k": 4}
        ctl = self._controller([_store_knob(store)], revert_budget=1,
                               cooldown=0)
        try:
            ctl.observe(_parse_bound(epoch=1))            # trial 4->8
            rec = ctl.observe(_parse_bound(epoch=2, bytes_=1 << 20))
            assert rec["outcome"] == "reverted" and store["k"] == 4
            rec = ctl.observe(_parse_bound(epoch=3))
            assert rec["outcome"] == "family-exhausted"
            assert store["k"] == 4  # the family stays put for good
        finally:
            ctl.close()

    def test_credit_drain_discards_pending_trial_without_charge(self):
        # a drained epoch judges NOTHING: the pending trial must be
        # DISCARDED (restored, no freeze, no budget charge) — never
        # reverted by the credit scheduler's throughput, which would
        # burn the family's revert budget on climate noise
        store = {"k": 4}
        ctl = self._controller([_store_knob(store)], revert_budget=1)
        try:
            ctl.observe(_parse_bound(epoch=1))            # trial 4->8
            assert store["k"] == 8
            rec = ctl.observe(_parse_bound(epoch=2, bytes_=1 << 20),
                              epoch_gauges=[0.3, 0.4])
            assert store["k"] == 4                        # restored
            outcomes = [r["outcome"] for r in ctl.ledger.records()]
            assert outcomes == ["trial", "discarded", "freeze"]
            assert rec["outcome"] == "freeze"
            # no budget charge: the family can still explore after
            assert not ctl.rail.exhausted("parse")
            assert ctl.to_dict()["counts"]["reverted"] == 0
        finally:
            ctl.close()

    def test_reverted_epoch_arms_no_new_trial(self):
        # the double-count fix, on the controller's rails: the revert
        # epoch's stats ran under the BAD value — its record IS the
        # decision, and no second knob moves from it
        store = {"k": 4, "k2": 2}
        ctl = self._controller([
            _store_knob(store, "k"), _store_knob(store, "k2")])
        try:
            ctl.observe(_parse_bound(epoch=1))
            ctl.observe(_parse_bound(epoch=2, bytes_=1 << 20))
            assert store == {"k": 4, "k2": 2}
            outcomes = [r["outcome"] for r in ctl.ledger.records()]
            assert outcomes == ["trial", "reverted"]
        finally:
            ctl.close()

    def test_collector_rides_the_registry(self):
        from dmlc_tpu.obs.metrics import REGISTRY
        store = {"k": 4}
        ctl = self._controller([_store_knob(store)])
        try:
            ctl.observe(_parse_bound())
            snap = REGISTRY.snapshot()
            col = snap["collectors"].get("control")
            assert col is not None
            assert col["decisions"] == 1 and col["trials"] == 1
            assert col["knobs"]["k"] == 8
        finally:
            ctl.close()
        assert "control" not in REGISTRY.snapshot()["collectors"]


class TestAutotunerDoubleCountFix:
    """Satellite pin: a reverted trial's epoch stats (measured under
    the bad knob value) must not seed the NEXT trial — before the
    rail extraction, the revert epoch immediately proposed the next
    knob from its own polluted snapshot."""

    def _snap(self, bytes_=10 ** 9):
        stages = [
            {"name": "prefetch", "kind": "prefetch", "items": 10,
             "rows": 100, "nnz": 0, "bytes": bytes_, "wait_s": 0.5,
             "wait_frac": 0.5, "throughput_gbps": None,
             "rows_per_s": None, "queue_depth_mean": None,
             "queue_cap": 4, "queue_occupancy": 0.9},
            {"name": "to_device", "kind": "to_device", "items": 10,
             "rows": 100, "nnz": 0, "bytes": bytes_, "wait_s": 0.1,
             "wait_frac": 0.1, "throughput_gbps": None,
             "rows_per_s": None, "queue_depth_mean": None,
             "queue_cap": None, "queue_occupancy": None,
             "extra": {"xfer_wait_s": 0.5}},
        ]
        return {"schema": 1, "epoch": 1, "wall_s": 1.0,
                "stages": stages, "knobs": {}}

    def test_no_proposal_from_reverted_epoch(self):
        from dmlc_tpu.pipeline.autotune import Autotuner, Knob
        store = {"a": 4, "b": 4}
        knobs = [
            Knob("prefetch.depth", "prefetch",
                 lambda: store["a"],
                 lambda n: store.__setitem__("a", n), lo=1, hi=64),
            Knob("device.window", "to_device",
                 lambda: store["b"],
                 lambda n: store.__setitem__("b", n), lo=1, hi=32),
        ]
        t = Autotuner(knobs)
        t.after_epoch(self._snap())                 # trial a: 4 -> 8
        assert store["a"] == 8
        t.after_epoch(self._snap(bytes_=10 ** 7))   # collapse: revert
        assert store["a"] == 4
        assert t.report()["decisions"][-1]["outcome"] == "reverted"
        # the fix: knob b must NOT have been armed from the polluted
        # epoch (before the fix it was proposed immediately)
        assert store["b"] == 4
        assert t.rail.pending is None
        t.after_epoch(self._snap())                 # clean epoch:
        assert store["b"] == 8                      # b proposes now


class TestChaosInterplay:
    """ISSUE satellite: under a deterministic-seed FaultPlan that
    injects objstore faults while the credit climate is drained, the
    controller must emit FREEZE decisions (never knob thrash) and the
    ledger must carry the credit-band evidence."""

    def test_freeze_under_chaos_and_drained_credits(self, tmp_path):
        from dmlc_tpu.io import objstore
        from dmlc_tpu.io.input_split import InputSplit
        from dmlc_tpu.resilience import inject

        payload = b"x" * (256 << 10)
        em = objstore.configure(root=str(tmp_path / "objroot"))
        plan = inject.install(
            "site=io.objstore.get,fault=ioerror,times=2", seed=11)
        store = {"k": 4, "wire.coalesce": 4}
        ctl = Controller([
            _store_knob(store, "k", "parse"),
            _store_knob(store, "wire.coalesce", "wire")])
        try:
            em.put("bucket", "train/x.bin", payload)
            for epoch in range(1, 4):
                # a real remote read under the armed plan: the seam
                # retries the injected faults, bytes stay identical
                split = InputSplit.create("obj://bucket/train/x.bin",
                                          0, 1)
                got = b"".join(iter(split.next_chunk, None))
                assert got == payload
                rec = ctl.observe(
                    _parse_bound(epoch=epoch),
                    epoch_gauges=[0.4, 0.5, 0.3])  # drained climate
                assert rec["outcome"] == "freeze", rec
                assert rec["band"] == "drained"
                assert any("drained" in e for e in rec["evidence"])
            # chaos really ran (deterministic: times=2 exactly) and
            # the controller never chased it with a knob move
            assert plan.injected == 2
            assert store == {"k": 4, "wire.coalesce": 4}
            assert all(r["outcome"] == "freeze"
                       for r in ctl.ledger.records())
        finally:
            ctl.close()
            inject.uninstall()
            objstore.configure(None)


class TestPipelineAdoption:
    def _corpus(self, tmp_path, rows=800):
        import numpy as np
        rng = np.random.RandomState(0)
        lines = []
        for i in range(rows):
            nnz = rng.randint(3, 9)
            idx = np.sort(rng.choice(500, nnz, replace=False))
            feats = " ".join(f"{j}:{v:.4f}"
                             for j, v in zip(idx, rng.rand(nnz)))
            lines.append(f"{i % 2} {feats}")
        p = tmp_path / "data.libsvm"
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def test_installed_controller_subsumes_autotuner(self, tmp_path):
        from dmlc_tpu.pipeline import Pipeline
        uri = self._corpus(tmp_path)
        built = (Pipeline.from_uri(uri).parse(format="libsvm")
                 .batch(64).prefetch(depth="auto")
                 .build(autotune=True))
        ctl = obs_control.install(Controller())
        try:
            for _ in range(3):
                built.run_epoch()
            # the pipeline's "auto" knobs joined the families...
            knobs = ctl.to_dict()["knobs"]
            assert "prefetch.depth" in knobs
            assert knobs["prefetch.depth"]["family"] == "assemble"
            # ...one decision per epoch landed in the ledger...
            assert len(ctl.ledger.records()) >= 3
            assert ctl.to_dict()["epoch"] == 3
            # ...and the blind hill-climber stood down (one mover)
            assert built.autotune_report()["decisions"] == []
        finally:
            obs_control.uninstall()
            built.close()
        assert obs_control.active() is None

    def test_adopted_knobs_move_only_for_their_pipeline(self, tmp_path):
        # pipeline B's verdict must never trial pipeline A's knob: A's
        # knob cannot affect B's throughput, so the rail would judge
        # the move by rates it cannot change (accepts forever). Name
        # collisions across live pipelines get the stable source-token
        # prefix, never apostrophe mangling.
        from dmlc_tpu.pipeline import Pipeline
        uri = self._corpus(tmp_path, rows=400)

        def build():
            return (Pipeline.from_uri(uri).parse(format="libsvm")
                    .batch(64).prefetch(depth="auto").build())

        a, b = build(), build()
        ctl = Controller()
        try:
            tok_a = ctl.adopt_pipeline(a)
            tok_b = ctl.adopt_pipeline(b)
            knobs = ctl.to_dict()["knobs"]
            assert "prefetch.depth" in knobs            # A's, bare
            assert f"{tok_b}.prefetch.depth" in knobs   # B's, stable
            assert not any("'" in k for k in knobs)
            # an assemble-bound epoch observed FOR B moves B's knob
            asm = _snap([{"name": "batch", "kind": "assemble",
                          "wait_s": 1.0, "bytes": 1 << 30,
                          "extra": {"assemble_s": 0.9}}])
            rec = ctl.observe(asm, source=tok_b)
            assert rec["outcome"] == "trial"
            assert rec["knob"] == f"{tok_b}.prefetch.depth"
            vals = ctl.knob_values()
            assert vals["prefetch.depth"] == 4          # A untouched
            assert vals[f"{tok_b}.prefetch.depth"] == 8
        finally:
            ctl.close()
            a.close()
            b.close()

    def test_closed_pipeline_knobs_retire(self, tmp_path):
        # a rebuilt pipeline must not leave the controller trialing a
        # DEAD pipeline's knobs (or growing name' name'' aliases): the
        # adopted knobs ride the pipeline's lifetime and retire with
        # it, cancelling any pending trial without a budget charge
        import gc
        from dmlc_tpu.pipeline import Pipeline
        uri = self._corpus(tmp_path, rows=400)

        def build():
            return (Pipeline.from_uri(uri).parse(format="libsvm")
                    .batch(64).prefetch(depth="auto")
                    .build(autotune=True))

        ctl = obs_control.install(Controller())
        try:
            built = build()
            built.run_epoch()
            assert "prefetch.depth" in ctl.to_dict()["knobs"]
            built.close()
            del built
            gc.collect()
            built = build()
            built.run_epoch()
            knobs = ctl.to_dict()["knobs"]
            assert "prefetch.depth" in knobs
            assert "prefetch.depth'" not in knobs  # no alias growth
            assert len([k for k in knobs
                        if k.startswith("prefetch.depth")]) == 1
            built.close()
        finally:
            obs_control.uninstall()

    def test_delta_metrics_scoped_per_source(self):
        # two interleaved sources: each epoch's wire counters are
        # delta-scoped against that SOURCE's previous epoch, never the
        # other one's (A must not absorb B's traffic)
        from dmlc_tpu.obs.metrics import MetricsRegistry
        reg = MetricsRegistry()
        ctl = Controller([], registry=reg)
        try:
            reg.counter("objstore.bytes").inc(100)
            ctl._delta_metrics("A")   # A's baseline: 100
            reg.counter("objstore.bytes").inc(50)
            ctl._delta_metrics("B")   # B's baseline: 150
            reg.counter("objstore.bytes").inc(7)
            dA = ctl._delta_metrics("A")
            assert dA["counters"]["objstore.bytes"] == 57
            dB = ctl._delta_metrics("B")
            assert dB["counters"]["objstore.bytes"] == 7
        finally:
            ctl.close()

    def test_detach_suspends_without_closing(self):
        from dmlc_tpu.obs.metrics import REGISTRY
        ctl = obs_control.install(Controller())
        try:
            assert "control" in REGISTRY.snapshot()["collectors"]
            suspended = obs_control.detach()
            assert suspended is ctl
            assert obs_control.active() is None
            # the "control" collector name is FREE while suspended —
            # a probe's own controller owns the gang/metrics surface
            assert "control" not in REGISTRY.snapshot()["collectors"]
            probe = Controller([])
            assert "control" in REGISTRY.snapshot()["collectors"]
            probe.close()
            # reinstall resumes the collector, ledger intact
            assert obs_control.install(suspended) is ctl
            assert obs_control.active() is ctl
            assert "control" in REGISTRY.snapshot()["collectors"]
        finally:
            obs_control.uninstall()

    def test_install_if_env(self, monkeypatch):
        monkeypatch.delenv(obs_control.ENV_CONTROL, raising=False)
        assert obs_control.install_if_env() is None
        monkeypatch.setenv(obs_control.ENV_CONTROL, "0")
        assert obs_control.install_if_env() is None
        monkeypatch.setenv(obs_control.ENV_CONTROL, "1")
        try:
            ctl = obs_control.install_if_env()
            assert ctl is not None
            # the default controller owns the wire family (the
            # automated docs/remote_io.md advice)
            fams = {k["family"] for k in ctl.to_dict()["knobs"].values()}
            assert fams == {"wire"}
        finally:
            obs_control.uninstall()


class TestServeAndCli:
    def _get(self, url, timeout_s=5.0):
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_control_endpoint_and_obsctl(self, capsys):
        from dmlc_tpu.obs.serve import StatusServer
        import obsctl
        store = {"k": 4}
        srv = StatusServer(port=0)
        try:
            # no controller yet: 404 with the enable hint
            status, body = self._get(srv.url("/control"))
            assert status == 404
            assert b"DMLC_TPU_CONTROL" in body
            assert obsctl.main(["control", "--port",
                                str(srv.port)]) == 2
            capsys.readouterr()
            ctl = obs_control.install(
                Controller([_store_knob(store)]))
            ctl.observe(_parse_bound(epoch=1))
            ctl.observe(_parse_bound(epoch=2, bytes_=2 << 30))
            status, body = self._get(srv.url("/control"))
            assert status == 200
            doc = json.loads(body)
            assert doc["schema"] == obs_control.CONTROL_SCHEMA
            recs = doc["ledger"]["records"]
            assert [r["outcome"] for r in recs] == \
                ["trial", "accepted", "trial"]
            assert doc["knobs"]["k"]["value"] == 16
            # ?last=N trims the ledger, state stays whole
            doc = json.loads(self._get(
                srv.url("/control?last=1"))[1])
            assert len(doc["ledger"]["records"]) == 1
            # the operator CLI renders decision + evidence, exit 0
            assert obsctl.main(["control", "--port",
                                str(srv.port)]) == 0
            out = capsys.readouterr().out
            assert "trial" in out and "accepted" in out
            assert "parse wait" in out      # the evidence line
            assert "knob k = 16" in out
        finally:
            obs_control.uninstall()
            srv.close()

    def test_flight_bundle_attaches_control_json(self, tmp_path):
        from dmlc_tpu.obs import flight as obs_flight
        store = {"k": 4}
        ctl = obs_control.install(Controller([_store_knob(store)]))
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "flight")).install()
        try:
            ctl.observe(_parse_bound())
            d = fl.dump("unit_test")
            doc = json.load(open(os.path.join(d, "control.json")))
            assert doc["schema"] == obs_control.CONTROL_SCHEMA
            assert doc["ledger"]["records"][0]["outcome"] == "trial"
            manifest = json.load(
                open(os.path.join(d, "MANIFEST.json")))
            assert manifest["files"]["control.json"] == "ok"
        finally:
            fl.uninstall()
            obs_control.uninstall()


class TestGangControlLive:
    """Acceptance: a REAL 2-process launch_local(control=True) gang —
    every rank runs the controller over its own pipeline and serves
    its decision ledger at /control WHILE the gang runs."""

    def test_two_process_gang_serves_control(self, tmp_path):
        from dmlc_tpu.parallel.launch import find_free_ports, launch_local
        corpus = TestPipelineAdoption()._corpus(tmp_path, rows=1200)
        script = tmp_path / "control_worker.py"
        stop_file = tmp_path / "stop"
        script.write_text(
            "import os, sys, time\n"
            "from dmlc_tpu.obs.serve import serve_if_env\n"
            "from dmlc_tpu.obs.control import install_if_env\n"
            "from dmlc_tpu.pipeline import Pipeline\n"
            "srv = serve_if_env()\n"
            "assert srv is not None, 'serve port env missing'\n"
            "ctl = install_if_env()\n"
            "assert ctl is not None, 'control env missing'\n"
            "built = (Pipeline.from_uri(sys.argv[1])\n"
            "         .parse(format='libsvm')\n"
            "         .batch(64).prefetch(depth='auto')\n"
            "         .build(autotune=True))\n"
            "for _ in range(4):\n"
            "    built.run_epoch()\n"
            "built.close()\n"
            "deadline = time.time() + 30\n"
            "while not os.path.exists(sys.argv[2]) "
            "and time.time() < deadline:\n"
            "    time.sleep(0.05)\n"
        )
        ports = find_free_ports(2)
        env = {"PYTHONPATH": os.pathsep.join(
            [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
        result = {}

        def gang():
            try:
                result["codes"] = launch_local(
                    2, [sys.executable, str(script), corpus,
                        str(stop_file)],
                    env=env, serve_ports=ports, control=True,
                    timeout=120)
            except Exception as e:  # noqa: BLE001
                result["error"] = e

        t = threading.Thread(target=gang, daemon=True)
        t.start()
        try:
            # poll until BOTH ranks serve a non-empty decision ledger
            # — the controller is running and citable DURING the run
            deadline = time.time() + 60.0
            ledgers = {}
            while len(ledgers) < 2 and time.time() < deadline:
                for rank, port in enumerate(ports):
                    if rank in ledgers:
                        continue
                    try:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{port}/control",
                                timeout=2.0) as r:
                            doc = json.load(r)
                    except (OSError, ValueError,
                            urllib.error.URLError):
                        time.sleep(0.05)
                        continue
                    if doc.get("ledger", {}).get("records"):
                        ledgers[rank] = doc
                time.sleep(0.05)
            assert len(ledgers) == 2, f"gang never served: {result}"
            for rank, doc in ledgers.items():
                recs = doc["ledger"]["records"]
                assert all(sorted(r) == sorted(RECORD_KEYS)
                           for r in recs), recs
                assert all(r["verdict_id"] for r in recs)
                # the adopted pipeline knob is visible per rank
                assert "prefetch.depth" in doc["knobs"]
        finally:
            stop_file.write_text("stop")
            t.join(timeout=60.0)
        assert result.get("codes") == [0, 0], result
