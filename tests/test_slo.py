"""SLO objectives, error budgets, burn-rate alerts (the SLO PR).

Covers: SLO-aware histogram bucket bounds (explicit bounds, the
target ON a bucket edge so judgment error at the target is zero and
bounded by one bucket width elsewhere), the SloEngine's windowed
attainment / budget / multi-rate burn arithmetic on a deterministic
clock, the gang rollup (merge_views + scrape_gang with a dead rank
marking the merged objective INCOMPLETE), the /slo endpoint (404
hint / live view) and /analyze slo_verdicts ride-along, the obsctl
slo renderer, slo.json in flight bundles, declarations at the
scheduler (add_tenant(slo=...) + the DMLC_TPU_SCHED / DMLC_TPU_SLO
grammars), /rpc edge retirement on gang shrink, and the <2%
off-cost smoke gate for an installed engine with no objectives.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

from dmlc_tpu.obs import analyze as obs_analyze
from dmlc_tpu.obs import flight as obs_flight
from dmlc_tpu.obs import rpc as obs_rpc
from dmlc_tpu.obs import slo as obs_slo
from dmlc_tpu.obs.metrics import MetricsRegistry
from dmlc_tpu.obs.serve import StatusServer, scrape_gang
from dmlc_tpu.pipeline import scheduler as sched_mod
from dmlc_tpu.utils.logging import DMLCError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import obsctl  # noqa: E402


@pytest.fixture(autouse=True)
def _slo_clean():
    """No installed engine/scheduler leaks across tests; the rpc
    roster diff starts from scratch."""
    obs_slo.uninstall()
    sched_mod.uninstall()
    obs_rpc._roster_peers = set()
    yield
    obs_slo.uninstall()
    sched_mod.uninstall()
    obs_rpc._roster_peers = set()


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ------------------------------------------- SLO-aware bucket bounds

class TestLatencyBounds:
    def test_target_sits_on_a_bucket_edge(self):
        for t in (0.001, 0.05, 0.15, 2.0):
            assert t in obs_slo.latency_bounds(t)

    def test_rejects_nonpositive_target(self):
        with pytest.raises(DMLCError):
            obs_slo.latency_bounds(0)

    def test_judgment_exact_when_target_on_edge(self):
        """The satellite pin: with latency_bounds the cumulative
        bucket walk judges observation <= target EXACTLY — no value
        on either side of the target is misclassified."""
        reg = MetricsRegistry()
        target = 0.1
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(target))
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("o", metric="lat", target_s=target, window_s=60)
        values = [0.0124, 0.05, 0.0999, 0.1, 0.10001, 0.13, 0.79, 1.0]
        for v in values:
            h.observe(v)
        good, total = eng._counts(eng._objectives["o"])
        assert total == len(values)
        assert good == sum(1 for v in values if v <= target)

    def test_straddling_bucket_error_bounded_by_one_width(self):
        """A target INSIDE a bucket (log2 default buckets) judges the
        straddling bucket as bad — the error is at most that one
        bucket's population, never more."""
        reg = MetricsRegistry()
        # log2 buckets double from 1e-6: ..., 0.065536, 0.131072
        h = reg.histogram("lat")
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("o", metric="lat", target_s=0.07, window_s=60)
        h.observe(0.06)    # bucket ub 0.065536 <= target: good
        h.observe(0.07)    # bucket ub 0.131072 straddles: judged bad
        h.observe(0.3)     # bad
        good, total = eng._counts(eng._objectives["o"])
        assert (good, total) == (1, 3)
        exact = 2  # 0.06 and 0.07 really are <= target
        assert exact - good <= h._buckets.get(0.131072, 0)


class TestHistogramBounds:
    def test_explicit_bounds_placement_and_overflow(self):
        reg = MetricsRegistry()
        h = reg.histogram("b", bounds=[1.0, 2.0, 4.0])
        for v in (0.5, 1.0, 1.5, 4.0, 5.0):
            h.observe(v)
        buckets = {float(k): n for k, n
                   in h.summary()["buckets"].items()}
        assert buckets == {1.0: 2, 2.0: 1, 4.0: 1, float("inf"): 1}

    def test_quantile_interpolates_explicit_edges(self):
        reg = MetricsRegistry()
        h = reg.histogram("b", bounds=[1.0, 2.0, 4.0])
        for _ in range(100):
            h.observe(1.5)
        p50 = h.summary()["p50"]
        assert 1.0 <= p50 <= 2.0

    def test_overflow_bucket_quantile_clamps_to_max(self):
        reg = MetricsRegistry()
        h = reg.histogram("b", bounds=[1.0])
        h.observe(10.0)
        assert h.summary()["p99"] == 10.0

    def test_invalid_bounds_rejected(self):
        reg = MetricsRegistry()
        for bad in ([0.0, 1.0], [-1.0, 2.0], [2.0, 1.0], [1.0, 1.0]):
            with pytest.raises(ValueError):
                reg.histogram(f"bad{bad}", bounds=bad)

    def test_bounds_apply_at_creation_only(self):
        reg = MetricsRegistry()
        h1 = reg.histogram("once", bounds=[1.0, 2.0])
        h2 = reg.histogram("once", bounds=[9.0])
        assert h2 is h1
        h1.observe(1.5)
        assert "2.0" in h1.summary()["buckets"]

    def test_peek_histogram_never_creates(self):
        reg = MetricsRegistry()
        assert reg.peek_histogram("ghost") is None
        h = reg.histogram("real")
        assert reg.peek_histogram("real") is h
        assert reg.peek_histogram("ghost") is None


# ------------------------------------------------ engine judgment

class TestEngineJudgment:
    def _engine(self, window_s=72.0, budget=0.01, target=0.1):
        reg = MetricsRegistry()
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(target))
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("api", metric="lat", target_s=target,
                     window_s=window_s, budget=budget, tenant="t0")
        return reg, h, eng

    def test_registration_baseline_excludes_prior_traffic(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(0.1))
        for _ in range(100):
            h.observe(5.0)  # all bad, BEFORE the declaration
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("api", metric="lat", target_s=0.1, window_s=60)
        for _ in range(10):
            h.observe(0.01)
        eng.sample()
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["attainment"] == 1.0
        assert row["windows"]["long"]["total"] == 10

    def test_empty_window_judges_nothing(self):
        """Silence is not attainment: zero observations -> burn None,
        no alert can fire."""
        _, _, eng = self._engine()
        eng.sample()
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["attainment"] is None
        assert row["budget_remaining"] is None
        assert row["windows"]["long"]["burn"] is None
        assert row["alerts"] == {"fast": False, "slow": False,
                                 "firing": False}

    def test_burn_fire_and_clear_arc(self):
        """The deterministic fire/clear arc on an explicit clock:
        window 72 s -> pairs (72, 6) and (12, 1). Good traffic, a bad
        burst (both pairs over their rates), recovery (the SHORT fast
        window resets fast immediately; slow clears once the short
        slow window drains)."""
        _, h, eng = self._engine()
        t0 = time.monotonic()
        for _ in range(100):
            h.observe(0.01)
        eng.sample(now=t0 + 1)
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["attainment"] == 1.0
        assert row["budget_remaining"] == 1.0
        assert not row["alerts"]["firing"]

        for _ in range(50):
            h.observe(0.5)  # 50 bad: attainment 100/150
        eng.sample(now=t0 + 2)
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["attainment"] == pytest.approx(0.666667)
        assert row["budget_remaining"] == pytest.approx(-32.3333,
                                                        abs=0.01)
        # fast_short saw ONLY the bad second: burn (1-0)/0.01 = 100
        assert row["windows"]["fast_short"]["burn"] == 100.0
        assert row["alerts"]["fast"] and row["alerts"]["slow"]

        for _ in range(500):
            h.observe(0.01)  # recovery flood
        eng.sample(now=t0 + 3)
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["windows"]["fast_short"]["burn"] == 0.0
        assert not row["alerts"]["fast"]  # short window = reset edge
        assert row["alerts"]["slow"]      # long windows still burned

        eng.sample(now=t0 + 10)
        row = eng.view(sample=False)["objectives"]["api"]
        # the 6 s slow-short window drained: burn None -> slow clears
        assert row["windows"]["short"]["burn"] is None
        assert not row["alerts"]["firing"]

    def test_window_expiry_and_sample_pruning(self):
        _, h, eng = self._engine()
        t0 = time.monotonic()
        for _ in range(10):
            h.observe(0.5)
        eng.sample(now=t0 + 1)
        eng.sample(now=t0 + 2)
        eng.sample(now=t0 + 80)  # everything aged out of the window
        row = eng.view(sample=False)["objectives"]["api"]
        assert row["attainment"] is None
        assert not row["alerts"]["firing"]
        # pruning keeps ONE sample older than the long window as the
        # base, not the whole history
        assert len(eng._objectives["api"].samples) <= 3

    def test_gauges_exported_per_objective(self):
        reg, h, eng = self._engine()
        for _ in range(10):
            h.observe(0.01)
        eng.sample()
        snap = reg.snapshot()
        assert snap["gauges"]["slo.api.attainment"] == 1.0
        assert snap["gauges"]["slo.api.fast_burn"] is False
        coll = snap["collectors"]["slo"]
        assert coll["schema"] == obs_slo.SLO_SCHEMA
        assert coll["count"] == 1 and coll["firing"] == 0
        assert "api" in coll["objectives"]

    def test_objective_name_and_spec_validation(self):
        _, _, eng = self._engine()
        with pytest.raises(DMLCError):
            eng.register("Bad Name!", metric="lat", target_s=0.1)
        with pytest.raises(DMLCError):
            eng.register("ok", metric="lat", target_s=0.1, budget=1.5)
        with pytest.raises(DMLCError):
            eng.register("ok", metric="lat", target_s=-1)
        eng.unregister("api")
        assert eng.objectives() == []


# ------------------------------------------------ gang rollup

def _fabricated_view(good: int, total: int, *, window_s=60.0,
                     budget=0.01, name="api") -> dict:
    reg = MetricsRegistry()
    h = reg.histogram("lat", bounds=obs_slo.latency_bounds(0.1))
    eng = obs_slo.SloEngine(registry=reg)
    eng.register(name, metric="lat", target_s=0.1, window_s=window_s,
                 budget=budget, tenant="t0")
    for _ in range(good):
        h.observe(0.01)
    for _ in range(total - good):
        h.observe(0.5)
    eng.sample()
    return eng.view(sample=False)


class TestGangRollup:
    def test_merge_views_sums_counts_and_rejudges(self):
        a = _fabricated_view(100, 100)
        b = _fabricated_view(0, 100)  # one rank fully burning
        merged = obs_slo.merge_views([a, b])
        assert merged["incomplete"] is False and merged["ranks"] == 2
        row = merged["objectives"]["api"]
        assert row["ranks"] == 2 and row["incomplete"] is False
        # judged on MERGED counts (0.5), not a vote of rank verdicts
        assert row["attainment"] == pytest.approx(0.5)
        assert row["windows"]["long"]["total"] == 200
        assert row["alerts"]["fast"]  # burn 50 >= 14.4 on both fasts

    def test_unreachable_rank_marks_incomplete(self):
        merged = obs_slo.merge_views([_fabricated_view(50, 50)],
                                     unreachable=["rank1"])
        assert merged["incomplete"] is True
        assert merged["unreachable"] == ["rank1"]
        assert merged["objectives"]["api"]["incomplete"] is True

    def test_scrape_gang_dead_rank_incomplete(self):
        """The satellite pin: scrape_gang over one live rank and one
        dead port -> the gang objective renders from the subset,
        flagged incomplete, never dressed up as the gang."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(0.1))
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("api", metric="lat", target_s=0.1, window_s=60)
        for _ in range(20):
            h.observe(0.01)
        eng.sample()
        dead = _free_port()
        with StatusServer(registry=reg) as srv:
            merged = scrape_gang([srv.port, dead], timeout_s=1.0)
        gv = obs_slo.gang_view(merged)
        assert gv is not None and gv["incomplete"] is True
        assert gv["unreachable"] == [str(dead)]
        row = gv["objectives"]["api"]
        assert row["incomplete"] is True
        assert row["attainment"] == 1.0 and row["ranks"] == 1

    def test_gang_view_none_when_no_slo_anywhere(self):
        reg = MetricsRegistry()
        reg.counter("x").inc()
        with StatusServer(registry=reg) as srv:
            merged = scrape_gang([srv.port])
        assert obs_slo.gang_view(merged) is None


# ---------------------------------------- /slo endpoint + obsctl

def _get_json(port: int, path: str):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5.0) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


class TestSloEndpoint:
    def test_404_with_hint_when_nothing_declared(self):
        with StatusServer(registry=MetricsRegistry()) as srv:
            code, doc = _get_json(srv.port, "/slo")
        assert code == 404
        assert doc["error"] == "no SLO objectives registered"
        assert "DMLC_TPU_SLO" in doc["hint"]
        assert "add_tenant" in doc["hint"]

    def test_live_view_and_analyze_ride_along(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(0.1))
        eng = obs_slo.install(obs_slo.SloEngine(registry=reg))
        eng.register("api", metric="lat", target_s=0.1, window_s=60,
                     tenant="t0")
        for _ in range(20):
            h.observe(0.5)  # fully burning
        eng.sample()
        with StatusServer(registry=reg) as srv:
            code, doc = _get_json(srv.port, "/slo")
            assert code == 200
            assert doc["schema"] == obs_slo.SLO_SCHEMA
            assert doc["fast_burn_rate"] == obs_slo.FAST_BURN_RATE
            assert doc["objectives"]["api"]["alerts"]["fast"]
            # no pipeline stats -> the stage verdict is None, but the
            # burning objective still surfaces on /analyze
            code, doc = _get_json(srv.port, "/analyze")
            assert code == 200
            (v,) = doc["slo_verdicts"]
            assert v["bound"] == "slo" and v["band"] == "fast-burn"

    def test_obsctl_slo_renderer_and_exit_codes(self, capsys):
        doc = _fabricated_view(0, 40)  # firing
        doc["objectives"]["api"]["incomplete"] = True
        doc["incomplete"] = True
        doc["unreachable"] = ["4001"]
        out = obsctl.render_slo(doc)
        assert "FAST-BURN (incomplete)" in out
        assert "INCOMPLETE gang rollup" in out and "4001" in out
        assert "api" in out and "t0" in out
        # exit 2 + the server's hint when nothing is declared
        with StatusServer(registry=MetricsRegistry()) as srv:
            rc = obsctl.main(["slo", "--port", str(srv.port)])
        assert rc == 2
        assert "hint" in capsys.readouterr().out
        # exit 0 + the table against a live declared engine
        reg = MetricsRegistry()
        reg.histogram("lat",
                      bounds=obs_slo.latency_bounds(0.1)).observe(0.01)
        eng = obs_slo.install(obs_slo.SloEngine(registry=reg))
        eng.register("api", metric="lat", target_s=0.1, window_s=60)
        with StatusServer(registry=reg) as srv:
            rc = obsctl.main(["slo", "--port", str(srv.port)])
        assert rc == 0
        assert "attain" in capsys.readouterr().out


class TestSloVerdicts:
    def test_verdict_shape_pinned_to_analyze_contract(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", bounds=obs_slo.latency_bounds(0.1))
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("api", metric="lat", target_s=0.1, window_s=60,
                     tenant="t0")
        for _ in range(200):
            h.observe(0.5)
        eng.sample()
        (v,) = eng.verdicts(epoch=7)
        assert tuple(v) == obs_analyze.VERDICT_KEYS
        assert v["schema"] == obs_analyze.ANALYSIS_SCHEMA
        assert v["epoch"] == 7 and v["tenant"] == "t0"
        assert v["bound"] == "slo" and v["band"] == "fast-burn"
        assert v["verdict_id"].startswith("v7-")
        assert any("burn" in e for e in v["evidence"])
        assert "slo" in obs_analyze.BOUNDS

    def test_healthy_objective_yields_no_verdict(self):
        reg = MetricsRegistry()
        reg.histogram("lat",
                      bounds=obs_slo.latency_bounds(0.1)).observe(0.01)
        eng = obs_slo.SloEngine(registry=reg)
        eng.register("api", metric="lat", target_s=0.1, window_s=60)
        eng.sample()
        assert eng.verdicts() == []


class TestFlightBundle:
    def test_slo_json_rides_when_objectives_declared(self, tmp_path):
        reg = MetricsRegistry()
        reg.histogram("lat",
                      bounds=obs_slo.latency_bounds(0.1)).observe(0.5)
        eng = obs_slo.install(obs_slo.SloEngine(registry=reg))
        eng.register("api", metric="lat", target_s=0.1, window_s=60)
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "flight")).install()
        try:
            d = fl.dump("test")
        finally:
            fl.uninstall()
        doc = json.load(open(os.path.join(d, "slo.json")))
        assert doc["schema"] == obs_slo.SLO_SCHEMA
        assert "api" in doc["objectives"]

    def test_no_slo_json_without_objectives(self, tmp_path):
        fl = obs_flight.FlightRecorder(
            out_dir=str(tmp_path / "flight")).install()
        try:
            d = fl.dump("test")
        finally:
            fl.uninstall()
        assert not os.path.exists(os.path.join(d, "slo.json"))


# ------------------------------------------- scheduler declarations

class TestSchedulerDeclaration:
    def test_add_tenant_declares_objective_and_bounds(self):
        reg = MetricsRegistry()
        sched = sched_mod.PipelineScheduler(registry=reg)
        sched_mod.install(sched)
        sched.add_tenant("victim", weight=2.0,
                         slo={"target_s": 0.15, "window_s": 60.0,
                              "budget": 0.02})
        eng = obs_slo.active()
        assert eng is not None
        assert eng.objectives() == ["tenant.victim"]
        # the declaration picked SLO-aware bounds for the judged
        # histogram BEFORE any observation landed
        h = reg.peek_histogram("tenant.victim.batch_s")
        assert h is not None
        assert h._bounds == obs_slo.latency_bounds(0.15)
        row = sched.to_dict()["tenants"]["victim"]
        assert row["slo"] == {"target_s": 0.15, "window_s": 60.0,
                              "budget": 0.02}

    def test_float_shorthand_and_bad_specs(self):
        sched = sched_mod.PipelineScheduler(registry=MetricsRegistry())
        sched_mod.install(sched)
        sched.add_tenant("t", slo=0.25)  # target-only shorthand
        row = sched.to_dict()["tenants"]["t"]
        assert row["slo"]["target_s"] == 0.25
        with pytest.raises(DMLCError):
            sched.add_tenant("bad", slo={"target_s": -1})
        with pytest.raises(DMLCError):
            sched.add_tenant("bad", slo={"target_s": 0.1,
                                         "nope": True})

    def test_sched_env_grammar_declares_slo(self, monkeypatch):
        monkeypatch.setenv(sched_mod.ENV_SCHED,
                           "quantum=2,slo.victim=0.15:60:0.02")
        sched = sched_mod.install_if_env()
        assert sched is not None
        row = sched.to_dict()["tenants"]["victim"]
        assert row["slo"] == {"target_s": 0.15, "window_s": 60.0,
                              "budget": 0.02}
        assert obs_slo.active() is not None

    def test_slo_env_grammar_and_malformed_degrade(self, monkeypatch):
        monkeypatch.setenv(
            obs_slo.ENV_SLO,
            "name=api,metric=lat,target=0.1,window=60,budget=0.02")
        eng = obs_slo.install_if_env()
        assert eng is not None and eng.objectives() == ["api"]
        obs_slo.uninstall()
        # malformed: warn + EMPTY engine, never an exception
        monkeypatch.setenv(obs_slo.ENV_SLO, "target=nope")
        eng = obs_slo.install_if_env()
        assert eng is not None and eng.objectives() == []
        obs_slo.uninstall()
        monkeypatch.setenv(obs_slo.ENV_SLO, "0")
        assert obs_slo.install_if_env() is None

    def test_parse_objectives_grammar(self):
        specs = obs_slo.parse_objectives(
            "name=a,metric=m,target=0.1;"
            "name=b,metric=n,target=0.2,window=30,budget=0.05,"
            "tenant=t")
        assert [s["name"] for s in specs] == ["a", "b"]
        assert specs[1] == {"name": "b", "metric": "n",
                            "target_s": 0.2, "window_s": 30.0,
                            "budget": 0.05, "tenant": "t"}
        for bad in ("name=a", "name=a,metric=m,target=x",
                    "name=a,metric=m,target=0.1,bogus=1"):
            with pytest.raises(ValueError):
                obs_slo.parse_objectives(bad)


# ------------------------------------------- /rpc edge retirement

class TestEdgeRetirement:
    def test_retire_drops_all_verbs_for_departed_peers(self):
        t = obs_rpc.RpcEdgeTable()
        t.observe("h1:1", "get", 10.0)
        t.observe("h1:1", "put", 10.0)
        t.observe("h2:2", "get", 10.0)
        assert t.retire(["h1:1"]) == 2
        peers = {e["peer"] for e in t.view()["edges"]}
        assert peers == {"h2:2"}
        assert t.retire(["ghost"]) == 0

    def test_membership_shrink_retires_departed_edges(self):
        """The satellite pin: a 2->1 shrink drops the departed
        member's rows from the process edge table; the rendezvous
        service endpoint and emulator rows (never roster members)
        survive every membership change."""
        from dmlc_tpu.obs.metrics import REGISTRY
        obs_rpc.EDGES.reset()
        try:
            obs_rpc.EDGES.observe("h1:1", "pages", 10.0)
            obs_rpc.EDGES.observe("h2:2", "pages", 10.0)
            obs_rpc.EDGES.observe("h2:2", "commit", 10.0)
            obs_rpc.EDGES.observe("emulator", "get", 10.0)
            obs_rpc.EDGES.observe("h9:99", "join", 10.0)  # the service
            roster2 = {"roster": [{"host": "h1", "port": 1},
                                  {"host": "h2", "port": 2}]}
            assert obs_rpc.membership_changed(roster2) == 0
            before = REGISTRY.counter("rpc.edges_retired").value
            roster1 = {"roster": [{"host": "h1", "port": 1}]}
            assert obs_rpc.membership_changed(roster1) == 2
            after = REGISTRY.counter("rpc.edges_retired").value
            assert after - before == 2
            peers = {e["peer"] for e in obs_rpc.view()["edges"]}
            assert peers == {"h1:1", "emulator", "h9:99"}
            # a peer never seen in a roster is NEVER retired, even
            # once the roster is empty
            assert obs_rpc.membership_changed({"roster": []}) == 1
            peers = {e["peer"] for e in obs_rpc.view()["edges"]}
            assert peers == {"emulator", "h9:99"}
        finally:
            obs_rpc.EDGES.reset()


# --------------------------------------------------- off-cost gate

class TestOffOverhead:
    def test_installed_empty_engine_under_2pct(self):
        """Tier-1 gate: an installed engine with NO objectives must
        cost under 2% on a histogram-observe hot loop (its sampler
        tick is a no-op; judged on the quietest interleaved pair,
        test_rpc discipline)."""
        def epoch(reg):
            h = reg.histogram("smoke.lat")
            t0 = time.perf_counter()
            for i in range(20000):
                h.observe(0.001 * (i % 7))
            return time.perf_counter() - t0

        epoch(MetricsRegistry())  # warm imports/caches
        off, on = [], []
        for _ in range(5):
            off.append(epoch(MetricsRegistry()))
            reg = MetricsRegistry()
            obs_slo.install(obs_slo.SloEngine(registry=reg,
                                              period_s=0.005))
            try:
                on.append(epoch(reg))
            finally:
                obs_slo.uninstall()
        grace = 0.010 / min(off)  # flat 10 ms, scaled to the wall
        ratios = [a / b for a, b in zip(on, off)]
        assert min(ratios) <= 1.02 + grace, (on, off, ratios)
