"""Parser correctness (reference: unittest_parser, libsvm_parser_test) +
RowBlock semantics + row iterators."""

import os

import numpy as np
import pytest

from dmlc_tpu.data.parser import Parser
from dmlc_tpu.data.rowblock import RowBlock, RowBlockContainer
from dmlc_tpu.data.row_iter import RowBlockIter
from dmlc_tpu.io.stream import MemoryStream
from dmlc_tpu.utils.logging import DMLCError

A1A_SAMPLE = b"""-1 3:1 11:1 14:1 19:1 39:1 42:1 55:1 64:1 67:1 73:1 75:1 76:1 80:1 83:1
-1 3:1 6:1 17:1 27:1 35:1 40:1 57:1 63:1 69:1 73:1 74:1 76:1 81:1 103:1
+1 4:1 6:1 15:1 21:1 35:1 40:1 57:1 63:1 67:1 73:1 74:1 76:1 80:1 83:1
-1 5:1 17:1 22:1 36:1 40:1 51:1 61:1 67:1 72:1 74:1 76:1 80:1 95:1
"""


def drain(parser):
    blocks = []
    parser.before_first()
    while parser.next():
        v = parser.value()
        # native blocks are zero-copy views valid until the next next();
        # retaining them across calls requires a copy (the contract)
        blocks.append(v.copy() if v.lease is not None else v)
    return blocks


def concat_blocks(blocks):
    c = RowBlockContainer(blocks[0].index.dtype if blocks else np.uint32)
    for b in blocks:
        c.push_block(b)
    return c.get_block()


class TestLibSVM:
    def test_basic(self, tmpfile):
        path = tmpfile("a1a.libsvm", A1A_SAMPLE)
        parser = Parser.create(path, 0, 1, format="libsvm", prefetch=False)
        block = concat_blocks(drain(parser))
        assert block.size == 4
        np.testing.assert_array_equal(block.label, [-1, -1, 1, -1])
        assert block.offset[1] - block.offset[0] == 14
        assert block.index.dtype == np.uint32
        row0 = block[0]
        assert list(row0.index[:3]) == [3, 11, 14]
        np.testing.assert_array_equal(row0.value, np.ones(14, np.float32))

    def test_qid(self, tmpfile):
        content = b"1 qid:7 1:0.5 2:0.25\n0 qid:9 3:1.5\n"
        path = tmpfile("q.libsvm", content)
        parser = Parser.create(path, 0, 1, format="libsvm", prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_array_equal(block.qid, [7, 9])
        np.testing.assert_allclose(block.value, [0.5, 0.25, 1.5])

    def test_float_values_parity(self, tmpfile):
        vals = [b"1.5", b"-2.75", b"1e-3", b"3.14159265358979",
                b"1.0000000000000002", b"2.2250738585072014e-308",
                b"9007199254740993", b".5", b"5.", b"1e20"]
        content = b"1 " + b" ".join(
            b"%d:%s" % (i + 1, v) for i, v in enumerate(vals)) + b"\n"
        path = tmpfile("f.libsvm", content)
        parser = Parser.create(path, 0, 1, format="libsvm", prefetch=False)
        block = concat_blocks(drain(parser))
        expect = np.array([np.float32(float(v)) for v in vals], np.float32)
        np.testing.assert_array_equal(block.value, expect)

    def test_indexing_mode_one_based(self, tmpfile):
        path = tmpfile("one.libsvm", b"1 1:2.0 5:3.0\n")
        parser = Parser.create(path, 0, 1, format="libsvm",
                               indexing_mode=1, prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_array_equal(block.index, [0, 4])

    def test_indexing_mode_auto(self, tmpfile):
        path = tmpfile("auto.libsvm", b"1 1:2.0\n0 3:1.0\n")
        parser = Parser.create(path, 0, 1, format="libsvm",
                               indexing_mode=-1, prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_array_equal(block.index, [0, 2])  # detected 1-based

    def test_uri_format_arg(self, tmpfile):
        path = tmpfile("u.libsvm", b"1 1:1\n")
        parser = Parser.create(path + "?format=libsvm", prefetch=False)
        block = concat_blocks(drain(parser))
        assert block.size == 1

    def test_bad_token_raises(self, tmpfile):
        path = tmpfile("bad.libsvm", b"1 nonsense\n")
        parser = Parser.create(path, 0, 1, format="libsvm", prefetch=False)
        with pytest.raises(DMLCError):
            drain(parser)

    def test_sharded_parse_consistent(self, tmpfile, rng):
        lines = []
        for i in range(500):
            nnz = rng.randint(1, 10)
            idxs = np.sort(rng.choice(1000, nnz, replace=False))
            feats = " ".join(f"{j}:{rng.rand():.6f}" for j in idxs)
            lines.append(f"{rng.randint(0, 2)} {feats}".encode())
        path = tmpfile("s.libsvm", b"\n".join(lines) + b"\n")
        whole = concat_blocks(drain(
            Parser.create(path, 0, 1, format="libsvm", prefetch=False)))
        sharded = concat_blocks(sum(
            (drain(Parser.create(path, k, 4, format="libsvm",
                                 prefetch=False)) for k in range(4)), []))
        assert whole.content_hash() == sharded.content_hash()


class TestCSV:
    def test_basic_with_label(self, tmpfile):
        content = b"1.0,2.0,3.0\n0.0,5.0,6.5\n"
        path = tmpfile("d.csv", content)
        parser = Parser.create(path, 0, 1, format="csv", label_column=0,
                               prefetch=False)
        block = concat_blocks(drain(parser))
        assert block.size == 2
        np.testing.assert_array_equal(block.label, [1.0, 0.0])
        np.testing.assert_array_equal(block.index, [0, 1, 0, 1])
        np.testing.assert_allclose(block.value, [2.0, 3.0, 5.0, 6.5])

    def test_no_label(self, tmpfile):
        path = tmpfile("n.csv", b"1,2\n3,4\n")
        parser = Parser.create(path, 0, 1, format="csv", prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_array_equal(block.label, [0.0, 0.0])
        np.testing.assert_allclose(block.value, [1, 2, 3, 4])

    def test_weight_column(self, tmpfile):
        path = tmpfile("w.csv", b"1,0.5,9\n0,2.0,8\n")
        parser = Parser.create(path, 0, 1, format="csv", label_column=0,
                               weight_column=1, prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_allclose(block.weight, [0.5, 2.0])
        np.testing.assert_allclose(block.value, [9, 8])

    def test_tab_delimiter(self, tmpfile):
        path = tmpfile("t.tsv", b"1\t2\n3\t4\n")
        parser = Parser.create(path, 0, 1, format="csv", delimiter="\t",
                               prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_allclose(block.value, [1, 2, 3, 4])

    def test_ragged_raises(self, tmpfile):
        path = tmpfile("r.csv", b"1,2\n3\n")
        parser = Parser.create(path, 0, 1, format="csv", prefetch=False)
        with pytest.raises(DMLCError, match="column"):
            drain(parser)


class TestLibFM:
    def test_basic(self, tmpfile):
        content = b"1 0:3:1.5 2:7:0.5\n-1 1:4:2.0\n"
        path = tmpfile("x.libfm", content)
        parser = Parser.create(path, 0, 1, format="libfm", prefetch=False)
        block = concat_blocks(drain(parser))
        np.testing.assert_array_equal(block.label, [1, -1])
        np.testing.assert_array_equal(block.field, [0, 2, 1])
        np.testing.assert_array_equal(block.index, [3, 7, 4])
        np.testing.assert_allclose(block.value, [1.5, 0.5, 2.0])


class TestRowBlock:
    def test_slice(self, tmpfile):
        path = tmpfile("a.libsvm", A1A_SAMPLE)
        block = concat_blocks(drain(
            Parser.create(path, 0, 1, format="libsvm", prefetch=False)))
        sl = block.slice(1, 3)
        assert sl.size == 2
        np.testing.assert_array_equal(sl.label, block.label[1:3])
        np.testing.assert_array_equal(sl[0].index, block[1].index)

    def test_page_save_load(self, rng):
        c = RowBlockContainer(np.uint32)
        for i in range(20):
            nnz = rng.randint(0, 8)
            c.push(float(i), rng.choice(100, nnz, replace=False),
                   rng.rand(nnz).astype(np.float32),
                   weight=float(rng.rand()), qid=i % 3)
        block = c.get_block()
        s = MemoryStream()
        RowBlockContainer.save_block(block, s)
        RowBlockContainer.save_block(block, s)  # two pages
        s.seek(0)
        p1 = RowBlockContainer.load_block(s)
        p2 = RowBlockContainer.load_block(s)
        p3 = RowBlockContainer.load_block(s)
        assert p3 is None
        assert p1.content_hash() == block.content_hash()
        assert p2.content_hash() == block.content_hash()

    def test_sdot(self):
        c = RowBlockContainer(np.uint32)
        c.push(1.0, [0, 2], [2.0, 3.0])
        block = c.get_block()
        w = np.array([1.0, 10.0, 100.0], np.float32)
        assert block[0].sdot(w) == pytest.approx(302.0)

    def test_memory_cost(self):
        c = RowBlockContainer(np.uint32)
        c.push(1.0, [0], [1.0])
        assert c.get_block().memory_cost_bytes() > 0


class TestRowBlockIter:
    def test_basic_iter(self, tmpfile):
        path = tmpfile("a.libsvm", A1A_SAMPLE)
        it = RowBlockIter.create(path, 0, 1, format="libsvm", prefetch=False)
        blocks = list(it)
        assert len(blocks) == 1
        assert blocks[0].size == 4
        assert it.num_col() == 104
        assert list(it)[0].size == 4  # replay

    def test_disk_cache_iter(self, tmp_path, rng):
        lines = []
        for i in range(200):
            lines.append(f"{i % 2} {rng.randint(1, 50)}:{rng.rand():.4f}"
                         .encode())
        data = tmp_path / "big.libsvm"
        data.write_bytes(b"\n".join(lines) + b"\n")
        cache = tmp_path / "pages.cache"
        uri = f"{data}#{cache}"
        it = RowBlockIter.create(uri, 0, 1, format="libsvm", prefetch=False)
        total1 = sum(b.size for b in it)
        assert total1 == 200
        assert os.path.exists(str(cache) + ".pages.p0-1")  # shard-namespaced
        # fresh object replays from cache without the source
        data.unlink()
        it2 = RowBlockIter.create(uri, 0, 1, format="libsvm", prefetch=False)
        total2 = sum(b.size for b in it2)
        assert total2 == 200


class TestDiskIterShardIsolation:
    def test_parts_do_not_share_cache(self, tmp_path, rng):
        lines = [f"{i} {i + 1}:1.0".encode() for i in range(100)]
        data = tmp_path / "s.libsvm"
        data.write_bytes(b"\n".join(lines) + b"\n")
        uri = f"{data}#{tmp_path / 'shared.cache'}"
        it0 = RowBlockIter.create(uri, 0, 2, format="libsvm", prefetch=False)
        it1 = RowBlockIter.create(uri, 1, 2, format="libsvm", prefetch=False)
        lab0 = np.concatenate([b.label for b in it0])
        lab1 = np.concatenate([b.label for b in it1])
        assert set(lab0).isdisjoint(set(lab1))
        assert len(lab0) + len(lab1) == 100

    def test_rows_per_page_respected(self, tmp_path):
        lines = [f"{i} 1:1.0".encode() for i in range(100)]
        data = tmp_path / "p.libsvm"
        data.write_bytes(b"\n".join(lines) + b"\n")
        uri = f"{data}#{tmp_path / 'pg.cache'}"
        from dmlc_tpu.data.row_iter import DiskRowIter
        from dmlc_tpu.data.parser import Parser
        it = DiskRowIter(
            lambda: Parser.create(str(data), 0, 1, format="libsvm",
                                  prefetch=False),
            str(tmp_path / "pg.cache"), rows_per_page=16)
        sizes = [b.size for b in it]
        assert sum(sizes) == 100
        assert all(s == 16 for s in sizes[:-1])


class TestRoundSpillStore:
    """The round spill store backing page-tier steady replay: rounds of
    raw blocks survive the disk round-trip byte-identical, commit is
    atomic, and the stale sweep honors the fingerprint contract."""

    @staticmethod
    def _blocks(rng, n, rows=6):
        out = []
        for _ in range(n):
            c = RowBlockContainer(np.uint32)
            for i in range(rows):
                nnz = rng.randint(1, 4)
                idx = np.sort(rng.choice(40, nnz, replace=False))
                c.push(float(i % 2), idx, rng.rand(nnz), qid=i)
            out.append(c.get_block())
        return out

    def test_round_trip_byte_identical(self, tmp_path, rng):
        from dmlc_tpu.data.row_iter import RoundSpillWriter
        from dmlc_tpu.parallel.sharded import empty_block
        path = str(tmp_path / "r.pages")
        w = RoundSpillWriter(path, nparts=3, meta={"fingerprint": None})
        rows = [self._blocks(rng, 2) + [empty_block()] for _ in range(4)]
        for row in rows:
            w.add_row(row)
        f = w.commit()
        assert f.rounds == 4 and os.path.exists(path)
        got = list(f.iter_rows())
        assert len(got) == 4
        for want_row, got_row in zip(rows, got):
            for a, b in zip(want_row, got_row):
                assert a.content_hash() == b.content_hash()
        f.delete()
        assert not os.path.exists(path)

    def test_abort_leaves_nothing(self, tmp_path, rng):
        from dmlc_tpu.data.row_iter import RoundSpillWriter
        path = str(tmp_path / "a.pages")
        w = RoundSpillWriter(path, nparts=1)
        w.add_row(self._blocks(rng, 1))
        w.abort()
        assert not os.path.exists(path)
        assert not os.path.exists(path + ".tmp")

    def test_sweep_deletes_stale_keeps_fresh(self, tmp_path, rng):
        from dmlc_tpu.data.row_iter import (
            RoundSpillWriter, read_spill_meta, sweep_stale_spill,
        )
        src = tmp_path / "src.txt"
        src.write_bytes(b"hello\n")
        st = os.stat(src)
        fresh_fp = [[str(src), st.st_size, st.st_mtime_ns]]
        stale_fp = [[str(src), st.st_size + 7, st.st_mtime_ns]]
        d = str(tmp_path / "spill")
        for name, fp in (("fresh.pages", fresh_fp),
                         ("stale.pages", stale_fp)):
            w = RoundSpillWriter(os.path.join(d, name), nparts=1,
                                 meta={"fingerprint": fp})
            w.add_row(self._blocks(rng, 1))
            w.commit()
        # an orphaned old .tmp (crashed writer) is swept too
        orphan = os.path.join(d, "dead.pages.tmp")
        open(orphan, "wb").close()
        os.utime(orphan, (1, 1))
        removed = sweep_stale_spill(d)
        assert removed == 2, removed
        assert os.path.exists(os.path.join(d, "fresh.pages"))
        assert not os.path.exists(os.path.join(d, "stale.pages"))
        assert not os.path.exists(orphan)
        assert read_spill_meta(
            os.path.join(d, "fresh.pages"))["fingerprint"] == fresh_fp

    def test_sweep_ignores_unknown_files(self, tmp_path):
        from dmlc_tpu.data.row_iter import sweep_stale_spill
        d = str(tmp_path / "spill")
        os.makedirs(d)
        alien = os.path.join(d, "not-ours.pages")
        with open(alien, "wb") as f:
            f.write(b"arbitrary bytes, no spill header")
        assert sweep_stale_spill(d) == 0
        assert os.path.exists(alien)  # never delete what we can't read
