"""Soak: memory stability of the native pipeline at 100s-of-MB scale.

The arena/chunk pools + bounded queues must keep RSS flat across epochs
(no per-chunk large alloc leak, no lease leak): parse a ~256MB dataset
for three epochs and assert RSS growth after warm-up stays bounded.
Also soaks the native RecordIO reader. Sizes are chosen so the test
stays O(30s) even on a throttled single-core host.
"""

import os

import numpy as np
import pytest


def _native_built() -> bool:
    from dmlc_tpu import native
    return native.native_available()


pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not _native_built(),
                       reason="native engine not built"),
    pytest.mark.skipif(not os.path.exists("/proc/self/status"),
                       reason="needs /proc for RSS accounting"),
]


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


@pytest.fixture(scope="module")
def big_libsvm(tmp_path_factory):
    rng = np.random.RandomState(0)
    rows = []
    for i in range(4000):
        idx = np.sort(rng.choice(10 ** 6, rng.randint(20, 40),
                                 replace=False))
        rows.append(f"{i % 2} " + " ".join(
            f"{j}:{v:.6f}" for j, v in zip(idx, rng.rand(len(idx)))))
    block = ("\n".join(rows) + "\n").encode()
    p = tmp_path_factory.mktemp("soak") / "big.libsvm"
    with open(p, "wb") as f:
        for _ in range(max(1, (256 << 20) // len(block))):
            f.write(block)
    return str(p), os.path.getsize(p)


class TestSoak:
    def test_parse_pipeline_rss_flat(self, big_libsvm):
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        path, size = big_libsvm
        parser = NativeLibSVMParser(path, 0, 1, nthreads=2)

        def epoch():
            parser.before_first()
            rows = nnz = 0
            while parser.next():
                b = parser.value()
                rows += b.size
                nnz += b.nnz
            return rows, nnz

        first = epoch()
        assert parser.bytes_read() == size
        warm = _rss_mb()
        for _ in range(2):
            assert epoch() == first  # byte-stable replay
        grown = _rss_mb() - warm
        parser.destroy()
        assert grown < 128, f"RSS grew {grown:.0f} MB across warm epochs"

    def test_leased_blocks_bound_memory(self, big_libsvm):
        # holding a few leases is fine; releasing them returns arenas to
        # the pool (not the OS necessarily, but RSS must not grow per
        # epoch when leases are cycled)
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        path, size = big_libsvm
        parser = NativeLibSVMParser(path, 0, 1, nthreads=2)

        def epoch():
            parser.before_first()
            held = []
            n = 0
            while parser.next():
                held.append(parser.detach())
                n += 1
                if len(held) > 3:
                    held.pop(0).release()
            for lease in held:
                lease.release()
            return n

        n0 = epoch()
        warm = _rss_mb()
        assert epoch() == n0
        grown = _rss_mb() - warm
        parser.destroy()
        assert grown < 128, f"RSS grew {grown:.0f} MB with lease cycling"

    def test_indexed_shuffled_soak(self, tmp_path):
        """Shuffled random-access reads across many epochs: the shared
        RecBatchPool and the single long-lived mapping must keep RSS
        flat (every epoch touches the whole file in a fresh order)."""
        from dmlc_tpu.io.recordio import IndexedRecordIOWriter
        from dmlc_tpu.io.stream import create_stream
        from dmlc_tpu.native.bindings import NativeIndexedRecordIOReader
        rng = np.random.RandomState(5)
        path = str(tmp_path / "soak_idx.rec")
        with create_stream(path, "w") as s, \
                create_stream(path + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(s, ix)
            written = 0
            while written < (96 << 20):
                rec = rng.bytes(rng.randint(50_000, 150_000))
                w.write_record(rec)
                written += len(rec) + 8
        reader = NativeIndexedRecordIOReader(path, 0, 1, shuffle=True,
                                             seed=9, batch_size=32)

        def epoch(first: bool) -> int:
            if not first:
                reader.before_first()  # next epoch's permutation
            n = 0
            while True:
                batch = reader.next_batch()
                if batch is None:
                    return n
                n += len(batch[1])

        n0 = epoch(True)
        warm = _rss_mb()
        for _ in range(3):
            assert epoch(False) == n0
        grown = _rss_mb() - warm
        reader.destroy()
        assert grown < 64, f"RSS grew {grown:.0f} MB across shuffled epochs"

    def test_sharded_replay_caches_at_default_budgets(self, big_libsvm,
                                                      tmp_path):
        """VERDICT r4 #8 + ISSUE 2: ShardedRowBlockIter with the
        DEFAULT cache budgets (agreement_cache_bytes 1 GB, BlockCache
        512 MB) over a 256 MB corpus and several epochs: RSS must step
        up ONCE for the retained replay rounds — which since r6 are
        RAW blocks, so the step is bounded by raw block bytes plus ONE
        round of serve-time padding, NOT the padded-dataset size the
        r5 tee retained (several× larger; the raw-vs-padded multiplier
        is asserted below) — and then PLATEAU: replay epochs allocate
        nothing beyond the one in-flight padded round.

        Runs in a SUBPROCESS: RSS accounting is only meaningful in a
        process this test owns (inside the full suite, 300 earlier
        tests' allocator state perturbs the deltas).
        """
        import json
        import subprocess
        import sys

        path, size = big_libsvm
        driver = tmp_path / "soak_driver.py"
        out = tmp_path / "soak_out.json"
        driver.write_text(f"""
import json, os, time
import numpy as np
import jax
if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon TPU plugin overrides the env var; the config update is
    # authoritative (same dance as tests/conftest.py / bench_mp_worker)
    jax.config.update("jax_platforms", "cpu")
from jax.sharding import Mesh
from dmlc_tpu.parallel.sharded import ShardedRowBlockIter

def rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0

mesh = Mesh(np.array(jax.devices()), ("data",))
it = ShardedRowBlockIter({str(path)!r}, mesh, format="libsvm",
                         row_bucket=1 << 12, nnz_bucket=1 << 17,
                         first_epoch_cache="always")

round_mb = [0.0]  # one stacked round's PADDED bytes (serve-time pad)

def epoch():
    n = 0
    for batch in it:
        jax.block_until_ready(batch["value"])
        if not round_mb[0]:
            round_mb[0] = sum(int(v.nbytes) for v in batch.values()) \
                / (1 << 20)
        n += 1
    return n

base = rss_mb()
n0 = epoch()
store = it._round_store
cache_mb = (store.nbytes / (1 << 20)
            if store is not None and store.tier == "memory" else None)
after_build = rss_mb()
walls = []
ok = True
for _ in range(3):
    t0 = time.perf_counter()
    ok = ok and epoch() == n0
    walls.append(time.perf_counter() - t0)
json.dump({{"base": base, "after_build": after_build,
           "final": rss_mb(), "cache_mb": cache_mb,
           "round_padded_mb": round_mb[0],
           "padded_total_mb": round_mb[0] * n0,
           "replay_tier": it.replay_tier,
           "replay_epochs": it.replay_epochs, "counts_ok": ok,
           "walls": walls}}, open({str(out)!r}, "w"))
""")
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   PYTHONPATH=os.pathsep.join(
                       [os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__)))]
                       + [p for p in
                          os.environ.get("PYTHONPATH", "").split(os.pathsep)
                          if p]))
        subprocess.run([sys.executable, str(driver)], check=True, env=env,
                       timeout=600)
        r = json.load(open(out))
        assert r["counts_ok"] and r["replay_epochs"] == 3
        assert r["replay_tier"] == "memory", r["replay_tier"]
        assert r["cache_mb"] is not None, "replay rounds not retained"
        # ISSUE 2 RSS model: the retained rounds are RAW blocks — never
        # more than the padded rounds the r5 tee held. (On THIS
        # criteo-shaped corpus the buckets are well matched, so raw ≈
        # padded; the several-× multiplier shows on short-row corpora —
        # asserted by test_parallel_ops'
        # test_raw_rounds_beat_padded_on_short_rows and recorded in
        # BASELINE.md.)
        assert r["cache_mb"] <= r["padded_total_mb"] * 1.05, (
            f"raw rounds {r['cache_mb']:.0f} MB exceed the padded "
            f"dataset {r['padded_total_mb']:.0f} MB")
        # the one-time step is bounded by the DOCUMENTED budgets: the
        # retained RAW rounds (measured, <= agreement_cache_bytes) plus
        # ONE in-flight padded round (serve-time padding) plus the
        # BlockCache warm set (<= its 512 MB default cap — a fresh
        # process pays it during the parse epoch) plus pool/XLA slack.
        # The cache pass hands its blocks to the tee (no second copy),
        # so the step must not reflect two copies of the rounds.
        step = r["after_build"] - r["base"]
        budget_mb = (r["cache_mb"] + 2 * r["round_padded_mb"]
                     + 512 + 400)
        assert step < budget_mb, (
            f"epoch-1 RSS step {step:.0f} MB vs {r['cache_mb']:.0f} MB "
            f"raw rounds + {r['round_padded_mb']:.0f} MB round pad "
            f"+ 512 MB BlockCache cap")
        grown = r["final"] - r["after_build"]
        assert grown < 96, (
            f"RSS grew {grown:.0f} MB across replay epochs "
            f"(plateau violated)")

    def test_recordio_soak(self, tmp_path):
        from dmlc_tpu.io.recordio import RecordIOWriter
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        rng = np.random.RandomState(1)
        path = tmp_path / "soak.rec"
        with open(path, "wb") as fh:
            w = RecordIOWriter(fh)
            written = 0
            while written < (96 << 20):
                rec = rng.bytes(rng.randint(50_000, 150_000))
                w.write_record(rec)
                written += len(rec) + 8
        reader = NativeRecordIOReader(str(path), 0, 1)

        def epoch():
            reader.before_first()
            n = 0
            while True:
                batch = reader.next_batch()
                if batch is None:
                    return n
                n += len(batch[1])

        n0 = epoch()
        warm = _rss_mb()
        assert epoch() == n0
        grown = _rss_mb() - warm
        reader.destroy()
        assert grown < 64, f"RSS grew {grown:.0f} MB across recordio epochs"
