"""Soak: memory stability of the native pipeline at 100s-of-MB scale.

The arena/chunk pools + bounded queues must keep RSS flat across epochs
(no per-chunk large alloc leak, no lease leak): parse a ~256MB dataset
for three epochs and assert RSS growth after warm-up stays bounded.
Also soaks the native RecordIO reader. Sizes are chosen so the test
stays O(30s) even on a throttled single-core host.
"""

import os

import numpy as np
import pytest


def _native_built() -> bool:
    from dmlc_tpu import native
    return native.native_available()


pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not _native_built(),
                       reason="native engine not built"),
    pytest.mark.skipif(not os.path.exists("/proc/self/status"),
                       reason="needs /proc for RSS accounting"),
]


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


@pytest.fixture(scope="module")
def big_libsvm(tmp_path_factory):
    rng = np.random.RandomState(0)
    rows = []
    for i in range(4000):
        idx = np.sort(rng.choice(10 ** 6, rng.randint(20, 40),
                                 replace=False))
        rows.append(f"{i % 2} " + " ".join(
            f"{j}:{v:.6f}" for j, v in zip(idx, rng.rand(len(idx)))))
    block = ("\n".join(rows) + "\n").encode()
    p = tmp_path_factory.mktemp("soak") / "big.libsvm"
    with open(p, "wb") as f:
        for _ in range(max(1, (256 << 20) // len(block))):
            f.write(block)
    return str(p), os.path.getsize(p)


class TestSoak:
    def test_parse_pipeline_rss_flat(self, big_libsvm):
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        path, size = big_libsvm
        parser = NativeLibSVMParser(path, 0, 1, nthreads=2)

        def epoch():
            parser.before_first()
            rows = nnz = 0
            while parser.next():
                b = parser.value()
                rows += b.size
                nnz += b.nnz
            return rows, nnz

        first = epoch()
        assert parser.bytes_read() == size
        warm = _rss_mb()
        for _ in range(2):
            assert epoch() == first  # byte-stable replay
        grown = _rss_mb() - warm
        parser.destroy()
        assert grown < 128, f"RSS grew {grown:.0f} MB across warm epochs"

    def test_leased_blocks_bound_memory(self, big_libsvm):
        # holding a few leases is fine; releasing them returns arenas to
        # the pool (not the OS necessarily, but RSS must not grow per
        # epoch when leases are cycled)
        from dmlc_tpu.native.bindings import NativeLibSVMParser
        path, size = big_libsvm
        parser = NativeLibSVMParser(path, 0, 1, nthreads=2)

        def epoch():
            parser.before_first()
            held = []
            n = 0
            while parser.next():
                held.append(parser.detach())
                n += 1
                if len(held) > 3:
                    held.pop(0).release()
            for lease in held:
                lease.release()
            return n

        n0 = epoch()
        warm = _rss_mb()
        assert epoch() == n0
        grown = _rss_mb() - warm
        parser.destroy()
        assert grown < 128, f"RSS grew {grown:.0f} MB with lease cycling"

    def test_indexed_shuffled_soak(self, tmp_path):
        """Shuffled random-access reads across many epochs: the shared
        RecBatchPool and the single long-lived mapping must keep RSS
        flat (every epoch touches the whole file in a fresh order)."""
        from dmlc_tpu.io.recordio import IndexedRecordIOWriter
        from dmlc_tpu.io.stream import create_stream
        from dmlc_tpu.native.bindings import NativeIndexedRecordIOReader
        rng = np.random.RandomState(5)
        path = str(tmp_path / "soak_idx.rec")
        with create_stream(path, "w") as s, \
                create_stream(path + ".idx", "w") as ix:
            w = IndexedRecordIOWriter(s, ix)
            written = 0
            while written < (96 << 20):
                rec = rng.bytes(rng.randint(50_000, 150_000))
                w.write_record(rec)
                written += len(rec) + 8
        reader = NativeIndexedRecordIOReader(path, 0, 1, shuffle=True,
                                             seed=9, batch_size=32)

        def epoch(first: bool) -> int:
            if not first:
                reader.before_first()  # next epoch's permutation
            n = 0
            while True:
                batch = reader.next_batch()
                if batch is None:
                    return n
                n += len(batch[1])

        n0 = epoch(True)
        warm = _rss_mb()
        for _ in range(3):
            assert epoch(False) == n0
        grown = _rss_mb() - warm
        reader.destroy()
        assert grown < 64, f"RSS grew {grown:.0f} MB across shuffled epochs"

    def test_recordio_soak(self, tmp_path):
        from dmlc_tpu.io.recordio import RecordIOWriter
        from dmlc_tpu.native.bindings import NativeRecordIOReader
        rng = np.random.RandomState(1)
        path = tmp_path / "soak.rec"
        with open(path, "wb") as fh:
            w = RecordIOWriter(fh)
            written = 0
            while written < (96 << 20):
                rec = rng.bytes(rng.randint(50_000, 150_000))
                w.write_record(rec)
                written += len(rec) + 8
        reader = NativeRecordIOReader(str(path), 0, 1)

        def epoch():
            reader.before_first()
            n = 0
            while True:
                batch = reader.next_batch()
                if batch is None:
                    return n
                n += len(batch[1])

        n0 = epoch()
        warm = _rss_mb()
        assert epoch() == n0
        grown = _rss_mb() - warm
        reader.destroy()
        assert grown < 64, f"RSS grew {grown:.0f} MB across recordio epochs"
