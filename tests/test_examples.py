"""Smoke-run every example as a real subprocess — examples are the
user-facing contract and must not rot. Each runs on the CPU backend
(virtual devices) exactly as examples/README.md documents."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")
REPO = os.path.dirname(EXAMPLES)


def _run(name, extra_env=None, timeout=420):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "PYTHONPATH": os.pathsep.join(
               [REPO] + os.environ.get("PYTHONPATH", "").split(os.pathsep))}
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, name)],
        env=env, cwd=EXAMPLES, capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.slow
class TestExamples:
    def test_data_pipeline(self):
        r = _run("data_pipeline.py")
        assert r.returncode == 0, r.stderr[-3000:]

    def test_pipeline_quickstart(self):
        r = _run("pipeline_quickstart.py")
        assert r.returncode == 0, r.stderr[-3000:]
        assert "pipeline quickstart OK" in r.stdout

    def test_train_sparse_linear(self):
        r = _run("train_sparse_linear.py")
        assert r.returncode == 0, r.stderr[-3000:]

    def test_train_fm(self):
        r = _run("train_fm.py")
        assert r.returncode == 0, r.stderr[-3000:]
        assert "OK" in r.stdout

    def test_train_ranking(self):
        r = _run("train_ranking.py")
        assert r.returncode == 0, r.stderr[-3000:]
        assert "OK" in r.stdout

    def test_tpu_device_ingest(self):
        r = _run("tpu_device_ingest.py")
        assert r.returncode == 0, r.stderr[-3000:]
        assert "checksum OK" in r.stdout

    def test_distributed_launch(self):
        r = _run("distributed_launch.py")
        if (r.returncode != 0
                and "Multiprocess computations aren't implemented"
                in (r.stderr or "")):
            # same environmental gap the test_multiprocess probe skips
            # on: this host's jaxlib cannot run ANY multiprocess CPU
            # computation, so the example is unfulfillable here
            import pytest
            pytest.skip("jaxlib lacks multiprocess CPU computations")
        assert r.returncode == 0, r.stderr[-3000:]
        assert "parent restored" in r.stdout
