"""Elastic resharding: deterministic N→M shard re-agreement.

The repo's recovery story has always been determinism — a shard
stream is a pure function of ``(uri, part, num_parts, seed, epoch)``,
proven by tests/test_elastic.py — but until this module the WORLD was
fixed: a dead member could only ever be replaced at identical
coordinates. Here the same contract goes elastic. Ownership of the
``num_parts`` input parts is itself a pure function of ``(num_parts,
world, rank)`` (:func:`assign_parts`), so when the rendezvous service
bumps the membership epoch from N to M members, every survivor
independently computes the SAME new partition — no negotiation, no
state migration, just new inputs to the same function.

Mid-epoch resume (:func:`reshard_plan`): the service's merged
progress map says, per part, how many records the previous owner had
already consumed. Because a killed consumer's progress is a PREFIX of
the deterministic stream (``test_partial_progress_is_a_prefix``), the
new owner resumes by skipping exactly that prefix — the skipped
records' bytes are already committed to the unified page store (and
peer-servable), so the resume costs page reads, not wire bytes, and
global coverage stays exactly-once: every record consumed by exactly
one member across the membership change.

Checkpoint integration: :func:`gang_metadata` is the membership stamp
``ShardedCheckpoint.save`` writes into ``meta.json`` — a restore
after a world change knows which (gang, epoch, world, rank) produced
each shard and re-derives ownership the same way.

Everything here is pure and stdlib-only; the I/O lives in
:mod:`dmlc_tpu.rendezvous.service` and the consumers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

from dmlc_tpu.utils.logging import check

__all__ = ["assign_parts", "owner_of", "reshard_plan", "resume_skip",
           "content_owner", "gang_metadata"]


def assign_parts(num_parts: int, world: int, rank: int) -> List[int]:
    """The parts rank ``rank`` owns in a ``world``-member gang: the
    strided partition ``{p : p % world == rank}`` — the same modular
    contract the peer tier uses for page-group ownership, so data
    locality survives reshards for the parts a member keeps."""
    check(num_parts >= 1, "assign_parts needs num_parts >= 1")
    check(world >= 1, "assign_parts needs world >= 1")
    check(0 <= rank < world,
          f"rank {rank} outside world {world}")
    return [p for p in range(num_parts) if p % world == rank]


def owner_of(part: int, world: int) -> int:
    """The rank owning ``part`` — the inverse view of
    :func:`assign_parts` (pure, shared by tests and the planner)."""
    check(world >= 1, "owner_of needs world >= 1")
    return part % world


def content_owner(digest: str, world: int) -> int:
    """The rank owning a content-addressed page in a ``world``-member
    gang: the digest's leading 48 bits mod world. This is the restore
    fanout's re-cut — pages were written by the SAVING world (any N),
    and every RESTORING member (any M) independently maps each digest
    to the same owner, who wire-fetches it while everyone else takes
    it from the owner's ``/pages`` tier. Pure, uniform (digests are
    cryptographic, so leading bits are), and world-size agnostic — the
    different-world restore needs no negotiation, just this function
    at the new M."""
    check(bool(digest), "content_owner needs a digest")
    return owner_of(int(digest[:12], 16), world)


def resume_skip(progress: Optional[Mapping[Any, Any]],
                part: int) -> int:
    """Records of ``part`` already consumed gang-wide (0 when the
    part was never started). The service keys its progress map by
    stringified part (JSON object keys); accept both."""
    if not progress:
        return 0
    v = progress.get(str(part), progress.get(part, 0))
    return max(0, int(v)) if isinstance(v, (int, float)) else 0


def reshard_plan(num_parts: int, world: int,
                 progress: Optional[Mapping[Any, Any]] = None,
                 ) -> Dict[int, List[Tuple[int, int]]]:
    """The full post-reshard work plan: rank -> ``[(part,
    skip_records), ...]`` for the NEW world. ``skip_records`` is the
    committed prefix the part's (possibly previous) owner already
    consumed — the new owner fast-forwards past it over the page
    store instead of re-emitting records a dead member already
    counted. Every part appears exactly once across all ranks —
    exactly-once coverage is the plan's invariant, asserted here
    rather than trusted."""
    plan = {rank: [(p, resume_skip(progress, p))
                   for p in assign_parts(num_parts, world, rank)]
            for rank in range(world)}
    covered = sorted(p for parts in plan.values() for p, _ in parts)
    check(covered == list(range(num_parts)),
          f"reshard plan lost coverage: {covered} != "
          f"0..{num_parts - 1}")
    return plan


def gang_metadata(client: Any = None) -> Optional[Dict[str, Any]]:
    """The membership stamp for checkpoint metadata: ``{"gang",
    "member", "rank", "epoch", "world"}`` from the active (or given)
    rendezvous client; None outside a rendezvous gang — callers store
    it only when it exists."""
    if client is None:
        from dmlc_tpu import rendezvous as _rndv
        client = _rndv.active()
    if client is None or client.rank is None:
        return None
    return {"gang": client.gang, "member": client.member,
            "rank": client.rank, "epoch": client.epoch,
            "world": client.world}
