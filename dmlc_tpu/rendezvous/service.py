"""The rendezvous service: a threaded TCP accept loop owning gang
membership (reference: tracker/dmlc_tracker/tracker.py RabitTracker).

The reference tracker's job — workers connect, get a rank, learn the
roster — plus the one thing it never did: a **membership epoch**. The
service keeps, per gang, an ordered roster of alive members; rank IS
the roster index, so ranks are always dense ``0..world-1``. Any
membership change — a join, a clean leave, a supervisor-reported
death, a member silent past the heartbeat grace — bumps the gang's
monotonically increasing epoch, and every member learns the new
roster (and possibly a NEW rank) from its next heartbeat. Shard
ownership is then re-derived deterministically from ``(num_parts,
world, rank)`` by :mod:`dmlc_tpu.rendezvous.elastic` — no state
migrates, only the pure function's inputs change.

Wire protocol (docs/rendezvous.md): one line-delimited JSON request
per TCP connection, one JSON line back. Ops: ``join``, ``heartbeat``,
``leave``, ``report_death``, ``roster``. The transport is bounded —
requests above ``MAX_LINE`` bytes are rejected, every socket carries
a timeout — and the accept loop reuses the ``obs/serve.py``
ThreadingHTTPServer discipline (daemon handler threads, never block
process exit).

This module is the package's ONE home for raw ``socket`` /
``socketserver`` construction (``scripts/lint.py`` socket gate):
the client transport (:func:`call`) and the free-port probe
(:func:`probe_free_ports`, re-exported by ``parallel.launch``) live
here so every other module stays socket-free.

Progress exchange: heartbeats may carry a ``{part: records_consumed}``
map. The service folds each gang's maps together (max per part), and
hands the merged view back — so after a reshard the NEW owner of a
part knows the committed prefix length and resumes mid-epoch
(prefix-skip over the deterministic stream, bytes re-read from the
committed page/peer tier, never the wire) instead of replaying from
record zero. Exactly-once coverage follows from the determinism
contract: a dead member's progress is a PREFIX of the part's stream
(tests/test_elastic.py ``test_partial_progress_is_a_prefix``).
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from dmlc_tpu.utils.logging import check

__all__ = ["RendezvousService", "call", "probe_free_ports",
           "MAX_LINE", "DEFAULT_GRACE_S"]

# one request line must fit here — join/heartbeat payloads are tiny;
# a progress map over even 10^4 parts stays well under this
MAX_LINE = 1 << 20

# a member silent past this many seconds is declared dead (epoch
# bump); heartbeats ride the rendezvous.* retry seam, so a flaky
# connection costs counted retries well inside the grace window —
# a retry is never a membership flap
DEFAULT_GRACE_S = 3.0


def probe_free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    """``n`` distinct free ports, chosen while ALL probe sockets are
    held open (ADVICE r5): closing a probe before the next bind lets
    the OS hand the same port out twice. Only guaranteed distinct from
    each other; as with any probe-then-bind scheme another process can
    still grab one before the real bind."""
    check(n >= 1, "probe_free_ports needs n >= 1")
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind((host, 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def call(host: str, port: int, payload: Dict[str, Any],
         timeout_s: float = 2.0) -> Dict[str, Any]:
    """One client request: connect, send one JSON line, read one JSON
    line back. Raises OSError/ValueError on transport or protocol
    failure — callers wrap it in ``resilience.guarded()`` at a
    ``rendezvous.*`` site so flakes are counted retries."""
    with socket.create_connection((host, int(port)),
                                  timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        s.sendall(json.dumps(payload).encode("utf-8") + b"\n")
        buf = bytearray()
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
            if len(buf) > MAX_LINE:
                raise IOError("rendezvous: oversized response line")
    if not buf:
        raise IOError("rendezvous: empty response (service gone?)")
    resp = json.loads(buf.decode("utf-8"))
    if not isinstance(resp, dict):
        raise IOError(f"rendezvous: non-object response {resp!r}")
    return resp


class _Member:
    __slots__ = ("name", "host", "port", "attempt", "last_seen",
                 "joined_epoch")

    def __init__(self, name: str, host: str, port: Optional[int],
                 attempt: int, now: float, epoch: int):
        self.name = name
        self.host = host
        self.port = port
        self.attempt = attempt
        self.last_seen = now
        self.joined_epoch = epoch


class _Gang:
    """One gang's membership state (under the service lock)."""

    def __init__(self, grace_s: float):
        self.grace_s = grace_s
        self.epoch = 0
        self.members: Dict[str, _Member] = {}
        self.order: List[str] = []         # roster order; rank = index
        self.progress: Dict[str, int] = {}  # part -> consumed prefix
        self.events: List[Dict[str, Any]] = []  # bounded history


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True          # the obs/serve.py discipline:
    allow_reuse_address = True     # handlers never block process exit
    rendezvous: "RendezvousService"


class _Handler(socketserver.StreamRequestHandler):
    timeout = 10.0

    def handle(self) -> None:  # noqa: D102 — socketserver contract
        from dmlc_tpu.obs import rpc as _rpc
        try:
            line = self.rfile.readline(MAX_LINE + 1)
            if not line or len(line) > MAX_LINE:
                return
            ctx = None
            op = "?"
            t0 = time.perf_counter()
            try:
                req = json.loads(line.decode("utf-8"))
                check(isinstance(req, dict), "request must be an object")
                # an inbound trace context (obs.rpc) rides as an extra
                # field the op dispatch below simply ignores
                ctx = _rpc.extract(req, key=_rpc.TRACE_FIELD)
                op = str(req.get("op", "?"))
                resp = self.server.rendezvous.handle(req)
            except Exception as e:  # noqa: BLE001 — one bad request
                # must not take the accept loop down; the client sees
                # a typed error line instead of a dropped connection
                resp = {"ok": False, "error": repr(e)}
            if ctx is not None:
                dur_s = time.perf_counter() - t0
                _rpc.inject(ctx, resp, key=_rpc.TRACE_FIELD)
                resp[_rpc.HANDLE_FIELD] = round(dur_s * 1e6, 1)
                _rpc.record_server_span(
                    op, _rpc.serialize(ctx), t0, dur_s,
                    args={"peer": str(self.client_address[0]),
                          "handle_us": round(dur_s * 1e6, 1)})
            self.wfile.write(json.dumps(resp).encode("utf-8") + b"\n")
        except OSError:
            pass  # client went away mid-exchange; nothing to answer


class RendezvousService:
    """The gang membership service (module docstring). Threaded accept
    loop on a daemon thread; :meth:`handle` is also callable directly
    for in-process tests (same dispatch, no socket)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_grace_s: float = DEFAULT_GRACE_S,
                 max_events: int = 256):
        self.host = host
        self.heartbeat_grace_s = float(heartbeat_grace_s)
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._gangs: Dict[str, _Gang] = {}
        self._srv = _Server((host, port), _Handler)
        self._srv.rendezvous = self
        self.port = int(self._srv.server_address[1])
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="dmlc-tpu-rendezvous", daemon=True)
        self._thread.start()

    # -- lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "RendezvousService":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- dispatch

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        gang = str(req.get("gang") or "default")
        with self._lock:
            g = self._gangs.setdefault(gang,
                                       _Gang(self.heartbeat_grace_s))
            now = time.monotonic()
            if op == "join":
                return self._join(gang, g, req, now)
            if op == "heartbeat":
                return self._heartbeat(gang, g, req, now)
            if op == "leave":
                return self._remove(gang, g, str(req.get("member")),
                                    "leave", now)
            if op == "report_death":
                return self._remove(gang, g, str(req.get("member")),
                                    "death", now)
            if op == "roster":
                self._sweep(gang, g, now)
                return self._view(gang, g)
            return {"ok": False, "error": f"unknown op {op!r}"}

    # -- state transitions (under the lock)

    def _roster(self, g: _Gang) -> List[Dict[str, Any]]:
        return [{"member": n, "rank": i,
                 "host": g.members[n].host, "port": g.members[n].port,
                 "attempt": g.members[n].attempt}
                for i, n in enumerate(g.order)]

    def _view(self, gang: str, g: _Gang,
              member: Optional[str] = None) -> Dict[str, Any]:
        out = {"ok": True, "gang": gang, "epoch": g.epoch,
               "world": len(g.order), "roster": self._roster(g),
               "progress": dict(g.progress)}
        if member is not None and member in g.order:
            out["rank"] = g.order.index(member)
        return out

    def _bump(self, gang: str, g: _Gang, kind: str, member: str,
              old_world: int) -> None:
        g.epoch += 1
        event = {"kind": kind, "member": member, "epoch": g.epoch,
                 "old_world": old_world, "new_world": len(g.order)}
        g.events.append(event)
        del g.events[:-self.max_events]
        self._emit(gang, g, event)

    def _join(self, gang: str, g: _Gang, req: Dict[str, Any],
              now: float) -> Dict[str, Any]:
        self._sweep(gang, g, now)
        member = str(req.get("member"))
        check(bool(member) and member != "None",
              "join needs a member name")
        host = str(req.get("host") or "127.0.0.1")
        port = req.get("port")
        port = int(port) if port is not None else None
        attempt = int(req.get("attempt") or 0)
        old_world = len(g.order)
        m = g.members.get(member)
        if m is not None and member in g.order:
            # a supervisor RESTART at the same coordinates: the slot
            # is still alive on the roster, so membership (and the
            # epoch) does not change — the reference's recover
            # handshake (DMLC_NUM_ATTEMPT bumped, same rank)
            m.host, m.port, m.attempt = host, port, attempt
            m.last_seen = now
            return self._view(gang, g, member)
        g.members[member] = _Member(member, host, port, attempt, now,
                                    g.epoch + 1)
        g.order.append(member)
        self._bump(gang, g, "join", member, old_world)
        return self._view(gang, g, member)

    def _heartbeat(self, gang: str, g: _Gang, req: Dict[str, Any],
                   now: float) -> Dict[str, Any]:
        member = str(req.get("member"))
        m = g.members.get(member)
        if m is None or member not in g.order:
            # declared dead (grace or a supervisor report) — the
            # member must re-join; until then it is not in the gang
            self._sweep(gang, g, now)
            out = self._view(gang, g)
            out["ok"] = False
            out["error"] = f"member {member!r} not in gang (rejoin)"
            return out
        m.last_seen = now
        prog = req.get("progress")
        rejected = False
        if isinstance(prog, dict):
            # epoch-fenced commit: progress is merged ONLY when the
            # sender's view of the membership epoch is current —
            # ownership of a part is unique within one epoch, so a
            # fenced commit can never overlap a post-reshard owner's
            # resume (the exactly-once half of the elastic contract);
            # a stale sender learns the new roster from this very
            # response and re-derives what it owns
            fence = req.get("epoch")
            if fence is not None and int(fence) != g.epoch:
                rejected = True
            else:
                for part, consumed in prog.items():
                    if isinstance(consumed, (int, float)):
                        k = str(part)
                        g.progress[k] = max(g.progress.get(k, 0),
                                            int(consumed))
        self._sweep(gang, g, now)
        out = self._view(gang, g, member)
        if rejected:
            out["progress_rejected"] = True
        return out

    def _remove(self, gang: str, g: _Gang, member: str, kind: str,
                now: float) -> Dict[str, Any]:
        self._sweep(gang, g, now)
        if member in g.order:
            old_world = len(g.order)
            g.order.remove(member)
            self._bump(gang, g, kind, member, old_world)
        return self._view(gang, g)

    def _sweep(self, gang: str, g: _Gang, now: float) -> None:
        """Lazy grace check: any member silent past the gang's grace
        window is declared dead (one epoch bump each — the events
        list says exactly who fell when)."""
        for name in list(g.order):
            if now - g.members[name].last_seen > g.grace_s:
                old_world = len(g.order)
                g.order.remove(name)
                self._bump(gang, g, "grace_death", name, old_world)

    # -- telemetry (launcher-side; members emit their own on epoch
    #    delivery — both sides of the story land on the merged trace)

    def _emit(self, gang: str, g: _Gang,
              event: Dict[str, Any]) -> None:
        try:
            from dmlc_tpu.obs import trace
            from dmlc_tpu.obs.metrics import REGISTRY
            trace.instant(f"gang/member/{event['kind']}", "rendezvous",
                          {"gang": gang, **event})
            REGISTRY.counter(
                f"rendezvous.{event['kind']}".replace("grace_death",
                                                      "death")).inc()
            REGISTRY.gauge("rendezvous.epoch").set(g.epoch)
            REGISTRY.gauge("rendezvous.world").set(len(g.order))
        except Exception:  # noqa: BLE001 — telemetry must not break
            pass           # membership bookkeeping
